"""wirelint: wire-protocol compatibility verifier.

The seventh linter leg (jaxlint / locklint / shapelint / cachelint /
planlint / statelint / wirelint — shared scaffolding in
tools/lintcore.py).  The runtime twin is
cyclonus_tpu/worker/wireregistry.py: a declarative, VERSIONED registry
of every wire message (Batch/Request/Result/Delta/FlowQuery/Verdict
and the serve Reply envelope) recording per key its JSON type,
optionality, the protocol version that introduced it, its emit guard,
its float canonicalization, and whether its value is portable across
peers.  wirelint extracts the registry from the AST (no import — a
package syntax error cannot take the linter down) and cross-checks the
scanned worker/ + serve/ modules plus the frozen committed golden
worker/wire_schema.json:

  WR001  emit site writes an undeclared wire key, or violates the
         declared emit guard (a required key emitted conditionally, an
         optional key emitted unconditionally, a `with=K` key emitted
         outside an emit branch that also writes K).
  WR002  optional-key read without a default or presence guard: an old
         peer's payload (key absent) would KeyError a new reader.
  WR003  schema evolution violation against the frozen golden —
         removed key, re-typed key, optional<->required flip, version
         pin drift, or a new key/version without a row.  Additive-
         optional is the ONLY legal change; regenerating the golden
         (`python -m cyclonus_tpu.worker.wireregistry --write-golden`)
         is the explicit, diffable act of changing the protocol.
  WR004  reply-epoch discipline: a reply carrying verdicts must stamp
         exactly one Epoch, taken from the verdicts' own batch (an
         `.epoch` / `["epoch"]` read, never an unrelated constant);
         an epoch="stamp" message must be constructed with an explicit
         epoch= at every call site — the replica-read invariant
         ROADMAP item 1 stands on.
  WR005  non-portable value on the wire: a float key with no declared
         canonicalization, or a pid/timestamp/identity value written
         into a key declared comparable across peers.

Emit/read sites wirelint cannot attribute to a model class carry
trailing markers: `# wire-emit: <Message>` on the statement creating
the reply dict, `# wire-read: <Message>` on the parse statement.

Suppress a finding with `# wirelint: ignore[WR00X]` on the offending
line.

Run: python tools/wirelint.py [paths...]
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from lintcore import Finding, ignore_regex, iter_py_files, run_cli, suppress

_IGNORE_RE = ignore_regex("wirelint")

DEFAULT_PATHS = [
    "cyclonus_tpu/worker",
    "cyclonus_tpu/serve",
]

REGISTRY_BASENAME = "wireregistry.py"
GOLDEN_BASENAME = "wire_schema.json"

_EMIT_MARK_RE = re.compile(r"#\s*wire-emit:\s*([A-Za-z_][A-Za-z0-9_]*)")
_READ_MARK_RE = re.compile(r"#\s*wire-read:\s*([A-Za-z_][A-Za-z0-9_]*)")

#: call leaves whose value is process-local by construction (WR005)
_NONPORTABLE_CALLS = {
    "time", "time_ns", "monotonic", "monotonic_ns", "perf_counter",
    "perf_counter_ns", "getpid", "id", "hash", "uuid1", "uuid4",
}


# --------------------------------------------------------------------------
# Registry extraction (statelint's discipline: literal Message(...) /
# Key(...) calls read off the AST, never imported).
# --------------------------------------------------------------------------

@dataclass
class KeyDecl:
    name: str
    type: str
    optional: bool
    since: int
    guard: str
    canon: str
    portable: bool
    ref: str
    sample: object
    note: str
    line: int

    def effective_guard(self) -> str:
        return self.guard or ("set" if self.optional else "always")

    def guard_tokens(self) -> List[str]:
        return [t.strip() for t in self.effective_guard().split(",") if t]


@dataclass
class MessageDecl:
    name: str
    since: int
    epoch: str
    keys: List[KeyDecl] = field(default_factory=list)
    note: str = ""
    line: int = 0

    def key_by_name(self, name: str) -> Optional[KeyDecl]:
        for k in self.keys:
            if k.name == name:
                return k
        return None


@dataclass
class Registry:
    path: str = ""
    protocol_version: int = 0
    versions: Dict[int, str] = field(default_factory=dict)
    messages: List[MessageDecl] = field(default_factory=list)

    def message(self, name: str) -> Optional[MessageDecl]:
        for m in self.messages:
            if m.name == name:
                return m
        return None


def _literal(node: ast.AST):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _call_name(call: ast.Call) -> str:
    fn = call.func
    return fn.id if isinstance(fn, ast.Name) else getattr(fn, "attr", "")


def _extract_key(call: ast.Call) -> KeyDecl:
    kw: Dict[str, object] = {}
    positional = ["name", "type"]
    for i, a in enumerate(call.args):
        if i < len(positional):
            kw[positional[i]] = _literal(a)
    for k in call.keywords:
        if k.arg:
            kw[k.arg] = _literal(k.value)
    return KeyDecl(
        name=str(kw.get("name") or ""),
        type=str(kw.get("type") or ""),
        optional=bool(kw.get("optional", False)),
        since=int(kw.get("since") or 1),
        guard=str(kw.get("guard") or ""),
        canon=str(kw.get("canon") or ""),
        portable=bool(kw.get("portable", True)),
        ref=str(kw.get("ref") or ""),
        sample=kw.get("sample"),
        note=str(kw.get("note") or ""),
        line=call.lineno,
    )


def _extract_message(call: ast.Call) -> MessageDecl:
    kw: Dict[str, object] = {}
    keys_node: Optional[ast.AST] = None
    for i, a in enumerate(call.args):
        if i == 0:
            kw["name"] = _literal(a)
    for k in call.keywords:
        if k.arg == "keys":
            keys_node = k.value
        elif k.arg:
            kw[k.arg] = _literal(k.value)
    keys: List[KeyDecl] = []
    if isinstance(keys_node, ast.Tuple):
        for el in keys_node.elts:
            if isinstance(el, ast.Call) and _call_name(el) == "Key":
                keys.append(_extract_key(el))
    return MessageDecl(
        name=str(kw.get("name") or ""),
        since=int(kw.get("since") or 1),
        epoch=str(kw.get("epoch") or ""),
        keys=keys,
        note=str(kw.get("note") or ""),
        line=call.lineno,
    )


def load_registry(registry_path: str) -> Optional[Registry]:
    try:
        with open(registry_path, "r") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return None
    reg = Registry(path=registry_path)
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            tgts = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for tgt in tgts:
                if not isinstance(tgt, ast.Name) or node.value is None:
                    continue
                if tgt.id == "PROTOCOL_VERSION":
                    val = _literal(node.value)
                    if isinstance(val, int):
                        reg.protocol_version = val
                elif tgt.id == "VERSIONS":
                    val = _literal(node.value)
                    if isinstance(val, dict):
                        reg.versions = {
                            int(k): str(v) for k, v in val.items()
                        }
                elif tgt.id == "MESSAGES" and isinstance(
                    node.value, ast.Tuple
                ):
                    for el in node.value.elts:
                        if isinstance(el, ast.Call) and (
                            _call_name(el) == "Message"
                        ):
                            reg.messages.append(_extract_message(el))
    return reg


def find_registry(paths: List[str]) -> Optional[str]:
    """Locate wireregistry.py: inside a scanned directory, else
    relative to the repo root the scanned paths live under."""
    for p in paths:
        if os.path.isdir(p):
            cand = os.path.join(p, REGISTRY_BASENAME)
            if os.path.exists(cand):
                return cand
        elif os.path.basename(p) == REGISTRY_BASENAME:
            return p
    anchor = os.path.abspath(paths[0]) if paths else os.getcwd()
    cur = anchor if os.path.isdir(anchor) else os.path.dirname(anchor)
    for _ in range(6):
        cand = os.path.join(
            cur, "cyclonus_tpu", "worker", REGISTRY_BASENAME
        )
        if os.path.exists(cand):
            return cand
        cur = os.path.dirname(cur)
    return None


def golden_path_for(registry_path: str) -> str:
    return os.path.join(os.path.dirname(registry_path), GOLDEN_BASENAME)


# --------------------------------------------------------------------------
# Emit/read site collection.
# --------------------------------------------------------------------------

@dataclass
class Write:
    """One `var["Key"] = ...` store (or dict-literal entry) with the If
    nodes lexically enclosing it."""
    key: str
    line: int
    col: int
    value: Optional[ast.AST]
    if_stack: Tuple[ast.AST, ...]


def _target_writes(stmt: ast.AST, var: str,
                   stack: Tuple[ast.AST, ...]) -> List[Write]:
    out: List[Write] = []
    if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        tgts = (
            stmt.targets if isinstance(stmt, ast.Assign)
            else [stmt.target]
        )
        for tgt in tgts:
            if (
                isinstance(tgt, ast.Subscript)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == var
            ):
                key = _const_str(tgt.slice)
                if key is not None:
                    out.append(Write(
                        key, stmt.lineno, stmt.col_offset, stmt.value,
                        stack,
                    ))
            elif (
                isinstance(tgt, ast.Name)
                and tgt.id == var
                and isinstance(stmt.value, ast.Dict)
            ):
                for k, v in zip(stmt.value.keys, stmt.value.values):
                    key = _const_str(k) if k is not None else None
                    if key is not None:
                        out.append(Write(
                            key, stmt.lineno, stmt.col_offset, v, stack,
                        ))
    return out


def collect_writes(func: ast.AST, var: str) -> List[Write]:
    """Every store of a constant string key into `var` within `func`,
    each with its lexical If context (for emit-guard checks)."""
    writes: List[Write] = []

    def visit(stmt: ast.AST, stack: Tuple[ast.AST, ...]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(stmt, ast.If):
            for s in stmt.body:
                visit(s, stack + (stmt,))
            for s in stmt.orelse:
                visit(s, stack + (stmt,))
            return
        writes.extend(_target_writes(stmt, var, stack))
        for fld in ("body", "orelse", "finalbody"):
            for s in getattr(stmt, fld, []) or []:
                visit(s, stack)
        for handler in getattr(stmt, "handlers", []) or []:
            for s in handler.body:
                visit(s, stack)

    for s in func.body:
        visit(s, ())
    return writes


def _if_writes_key(if_node: ast.AST, var: str, key: str) -> bool:
    """Does any statement in this If's subtree store `key` into
    `var`?  (the `with=K` anchor check: ParentSpan's enclosing
    `if self.trace_id:` block also writes TraceId.)"""
    for sub in ast.walk(if_node):
        for w in _target_writes(sub, var, ()):
            if w.key == key:
                return True
    return False


def _emit_var(func: ast.AST) -> Optional[str]:
    """The result-dict variable of an emit function: the target of the
    first dict-literal assignment (`d = {...}` / `reply: dict = {}`)."""
    for sub in ast.walk(func):
        if isinstance(sub, (ast.Assign, ast.AnnAssign)):
            tgts = (
                sub.targets if isinstance(sub, ast.Assign)
                else [sub.target]
            )
            if sub.value is None or not isinstance(sub.value, ast.Dict):
                continue
            for tgt in tgts:
                if isinstance(tgt, ast.Name):
                    return tgt.id
    return None


def _value_nonportable_call(value: Optional[ast.AST]) -> Optional[str]:
    if value is None:
        return None
    for sub in ast.walk(value):
        if isinstance(sub, ast.Call):
            leaf = _call_name(sub)
            if leaf in _NONPORTABLE_CALLS:
                return leaf
    return None


def _epoch_sourced(value: Optional[ast.AST]) -> bool:
    """Is the written epoch value derived from an epoch accessor
    (`verdicts[0].epoch`, `report["epoch"]`, `service.epoch`) rather
    than an unrelated constant/counter?"""
    if value is None:
        return False
    for sub in ast.walk(value):
        if isinstance(sub, ast.Attribute) and sub.attr == "epoch":
            return True
        if isinstance(sub, ast.Subscript):
            s = _const_str(sub.slice)
            if s is not None and s.lower() == "epoch":
                return True
    return False


def _epoch_fallback_guarded(w: Write, var: str) -> bool:
    """Is this Epoch write guarded by `"Epoch" not in <var>` (the
    exactly-one-stamp fallback pattern)?"""
    for if_node in w.if_stack:
        test = getattr(if_node, "test", None)
        if not isinstance(test, ast.Compare):
            continue
        if not any(isinstance(op, ast.NotIn) for op in test.ops):
            continue
        if _const_str(test.left) == "Epoch":
            return True
    return False


def _enclosing_func(tree: ast.Module, line: int) -> Optional[ast.AST]:
    best: Optional[ast.AST] = None
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        end = getattr(node, "end_lineno", node.lineno)
        if node.lineno <= line <= end:
            if best is None or node.lineno > best.lineno:
                best = node
    return best


# --------------------------------------------------------------------------
# Per-check logic.
# --------------------------------------------------------------------------

def _check_class_emit(path: str, msg: MessageDecl, func: ast.AST,
                      findings: List[Finding]) -> bool:
    """WR001 + WR005 over one model-class emit function (to_dict /
    to_json).  Returns True when the function emits (has a result
    dict)."""
    var = _emit_var(func)
    if var is None:
        return False
    writes = collect_writes(func, var)
    for w in writes:
        decl = msg.key_by_name(w.key)
        if decl is None:
            findings.append(Finding(
                path, w.line, w.col, "WR001",
                f"{msg.name} emit writes undeclared wire key {w.key!r} "
                f"(not in the wireregistry declaration)",
            ))
            continue
        tokens = decl.guard_tokens()
        if "always" in tokens and w.if_stack:
            findings.append(Finding(
                path, w.line, w.col, "WR001",
                f"required wire key {msg.name}.{w.key} emitted "
                f"conditionally (declared guard 'always': an old reader "
                f"relies on it)",
            ))
        if "set" in tokens and not w.if_stack:
            findings.append(Finding(
                path, w.line, w.col, "WR001",
                f"optional wire key {msg.name}.{w.key} emitted "
                f"unconditionally (declared guard 'set': emit only when "
                f"set, so old payloads stay byte-stable)",
            ))
        for tok in tokens:
            if tok.startswith("with="):
                anchor = tok[len("with="):]
                if not any(
                    _if_writes_key(if_node, var, anchor)
                    for if_node in w.if_stack
                ):
                    findings.append(Finding(
                        path, w.line, w.col, "WR001",
                        f"wire key {msg.name}.{w.key} declares guard "
                        f"{tok!r} but its emit branch never writes "
                        f"{anchor!r}",
                    ))
        if decl.portable:
            leaf = _value_nonportable_call(w.value)
            if leaf is not None:
                findings.append(Finding(
                    path, w.line, w.col, "WR005",
                    f"wire key {msg.name}.{w.key} is declared portable "
                    f"but its value calls {leaf}() (process-local: "
                    f"peers could never compare it)",
                ))
    return True


def _check_marker_emit(path: str, msg: MessageDecl, func: ast.AST,
                       var: str, findings: List[Finding]) -> None:
    """WR001 (undeclared keys) + WR004 (reply-epoch discipline) +
    WR005 over one marker-annotated emit function.  Guard
    conditionality is NOT enforced here: a reply builder legally
    branches (which is why it carries a marker, not a class)."""
    writes = collect_writes(func, var)
    for w in writes:
        decl = msg.key_by_name(w.key)
        if decl is None:
            findings.append(Finding(
                path, w.line, w.col, "WR001",
                f"{msg.name} emit writes undeclared wire key {w.key!r} "
                f"(not in the wireregistry declaration)",
            ))
            continue
        if decl.portable:
            leaf = _value_nonportable_call(w.value)
            if leaf is not None:
                findings.append(Finding(
                    path, w.line, w.col, "WR005",
                    f"wire key {msg.name}.{w.key} is declared portable "
                    f"but its value calls {leaf}() (process-local: "
                    f"peers could never compare it)",
                ))
    if msg.epoch != "from-verdicts":
        return
    verdict_writes = [w for w in writes if w.key == "Verdicts"]
    epoch_writes = sorted(
        (w for w in writes if w.key == "Epoch"), key=lambda w: w.line
    )
    if verdict_writes and not epoch_writes:
        w = verdict_writes[0]
        findings.append(Finding(
            path, w.line, w.col, "WR004",
            f"{msg.name} reply carries Verdicts but never stamps an "
            f"Epoch (epoch='from-verdicts': every verdict-bearing reply "
            f"anchors its staleness)",
        ))
    if len(epoch_writes) > 1:
        last = epoch_writes[-1]
        if not _epoch_fallback_guarded(last, var):
            findings.append(Finding(
                path, last.line, last.col, "WR004",
                f"{msg.name} reply may stamp Epoch more than once: the "
                f"final write is not guarded by '\"Epoch\" not in "
                f"{var}' (want exactly one stamp per reply)",
            ))
    for w in epoch_writes:
        if not _epoch_sourced(w.value):
            findings.append(Finding(
                path, w.line, w.col, "WR004",
                f"{msg.name}.Epoch is not taken from an epoch accessor "
                f"(want the verdicts' own batch epoch: an `.epoch` "
                f"attribute or ['epoch'] read, never a constant)",
            ))


def _check_parse_reads(path: str, msg: MessageDecl, func: ast.AST,
                       findings: List[Finding]) -> None:
    """WR002: an optional key subscripted without a presence guard
    inside a parse function — an old peer's payload would KeyError."""
    optional = {k.name for k in msg.keys if k.optional}
    if not optional:
        return

    def guarded(stack: Tuple[ast.AST, ...], key: str) -> bool:
        for if_node in stack:
            test = getattr(if_node, "test", None)
            if test is None:
                continue
            for sub in ast.walk(test):
                if isinstance(sub, ast.Compare) and any(
                    isinstance(op, ast.In) for op in sub.ops
                ):
                    if _const_str(sub.left) == key:
                        return True
        return False

    def visit(stmt: ast.AST, stack: Tuple[ast.AST, ...]) -> None:
        if isinstance(stmt, ast.If):
            for s in stmt.body:
                visit(s, stack + (stmt,))
            for s in stmt.orelse:
                visit(s, stack + (stmt,))
            return
        for sub in ast.walk(stmt):
            if not isinstance(sub, ast.Subscript):
                continue
            if not isinstance(sub.ctx, ast.Load):
                continue
            key = _const_str(sub.slice)
            if key in optional and not guarded(stack, key):
                findings.append(Finding(
                    path, sub.lineno, sub.col_offset, "WR002",
                    f"optional wire key {msg.name}.{key} read by "
                    f"subscript without a default or presence guard "
                    f"(an old peer omits it: use .get or 'if "
                    f"{key!r} in ...')",
                ))
        for fld in ("body", "orelse", "finalbody"):
            for s in getattr(stmt, fld, []) or []:
                visit(s, stack)
        for handler in getattr(stmt, "handlers", []) or []:
            for s in handler.body:
                visit(s, stack)

    for s in func.body:
        visit(s, ())


def _check_golden(reg: Registry, golden_path: str) -> List[Finding]:
    """WR003: the live registry vs the frozen committed golden.
    Anything but byte-equality of the evolution projection fires — the
    legal additive-optional change regenerates the golden, which shows
    up as a reviewable wire_schema.json diff, never as silence."""
    out: List[Finding] = []
    rp = reg.path

    def f(line: int, msg: str) -> None:
        out.append(Finding(rp, line, 0, "WR003", msg))

    # registry-internal version discipline first (these hold even
    # before a golden exists)
    for m in reg.messages:
        if m.since not in reg.versions:
            f(m.line,
              f"message {m.name!r} since=v{m.since} has no VERSIONS "
              f"row (a new version needs a declared history entry)")
        for k in m.keys:
            if k.since not in reg.versions:
                f(k.line,
                  f"wire key {m.name}.{k.name} since=v{k.since} has no "
                  f"VERSIONS row (a new key needs a version entry)")
            if k.since > m.since and not k.optional:
                f(k.line,
                  f"wire key {m.name}.{k.name} was added at v{k.since} "
                  f"(after the message's v{m.since}) but is required: "
                  f"a v{m.since} peer could never have emitted it")
    if reg.protocol_version not in reg.versions:
        f(0, f"PROTOCOL_VERSION v{reg.protocol_version} has no VERSIONS "
             f"row")

    try:
        with open(golden_path) as fh:
            golden = json.load(fh)
    except (OSError, ValueError) as e:
        f(0, f"frozen golden {golden_path} unreadable "
             f"({type(e).__name__}: {e}): commit it via "
             f"`python -m cyclonus_tpu.worker.wireregistry "
             f"--write-golden`")
        return out

    if golden.get("schema_version") != reg.protocol_version:
        f(0, f"registry PROTOCOL_VERSION v{reg.protocol_version} != "
             f"golden schema_version "
             f"v{golden.get('schema_version')}: regenerate the golden "
             f"to make the protocol change explicit")
    gmessages = golden.get("messages") or {}
    for name in sorted(set(gmessages) - {m.name for m in reg.messages}):
        f(0, f"wire message {name!r} was removed from the registry but "
             f"is frozen in the golden (removal breaks every old peer)")
    for m in reg.messages:
        gm = gmessages.get(m.name)
        if gm is None:
            f(m.line,
              f"wire message {m.name!r} has no golden row: regenerate "
              f"the golden to commit the protocol change")
            continue
        if gm.get("since") != m.since:
            f(m.line,
              f"message {m.name!r} since flipped v{gm.get('since')} -> "
              f"v{m.since} against the frozen golden")
        if gm.get("epoch", "") != m.epoch:
            f(m.line,
              f"message {m.name!r} epoch rule changed "
              f"{gm.get('epoch', '')!r} -> {m.epoch!r} against the "
              f"frozen golden")
        gkeys = gm.get("keys") or {}
        for kname in sorted(set(gkeys) - {k.name for k in m.keys}):
            f(m.line,
              f"wire key {m.name}.{kname} was removed from the "
              f"registry but is frozen in the golden (removal breaks "
              f"every old peer)")
        for k in m.keys:
            gk = gkeys.get(k.name)
            if gk is None:
                f(k.line,
                  f"wire key {m.name}.{k.name} has no golden row: "
                  f"regenerate the golden to commit the additive "
                  f"change")
                continue
            if gk.get("type") != k.type:
                f(k.line,
                  f"wire key {m.name}.{k.name} re-typed "
                  f"{gk.get('type')!r} -> {k.type!r} against the "
                  f"frozen golden (re-typing breaks old readers)")
            if bool(gk.get("optional")) != k.optional:
                flip = (
                    "optional -> required" if k.optional is False
                    else "required -> optional"
                )
                f(k.line,
                  f"wire key {m.name}.{k.name} optionality flipped "
                  f"({flip}) against the frozen golden")
            if gk.get("since") != k.since:
                f(k.line,
                  f"wire key {m.name}.{k.name} version pin drifted "
                  f"v{gk.get('since')} -> v{k.since} against the "
                  f"frozen golden")
    return out


def _check_registry_wr005(reg: Registry) -> List[Finding]:
    out: List[Finding] = []
    for m in reg.messages:
        for k in m.keys:
            if k.type == "float" and not k.canon:
                out.append(Finding(
                    reg.path, k.line, 0, "WR005",
                    f"float wire key {m.name}.{k.name} declares no "
                    f"canonicalization (canon=''): raw floats drift "
                    f"across peers — declare e.g. canon='round-ms'",
                ))
    return out


# --------------------------------------------------------------------------
# The lint proper.
# --------------------------------------------------------------------------

def lint_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, object]]:
    files = iter_py_files(paths)
    registry_path = find_registry(paths)
    findings: List[Finding] = []
    empty_stats = {
        "files": len(files), "messages": 0, "keys": 0,
        "emit_sites": 0, "read_sites": 0, "annotations": 0,
        "findings": 1,
    }
    if registry_path is None:
        findings.append(Finding(
            paths[0] if paths else ".", 0, 0, "WR001",
            "cyclonus_tpu/worker/wireregistry.py not found: the wire "
            "protocol has no declared registry to lint against",
        ))
        return findings, empty_stats
    reg = load_registry(registry_path)
    if reg is None or not reg.messages:
        findings.append(Finding(
            registry_path, 0, 0, "WR001",
            "wire registry unparseable or empty",
        ))
        return findings, empty_stats

    msg_names = {m.name for m in reg.messages}
    stamp_msgs = {m.name for m in reg.messages if m.epoch == "stamp"}
    annotations = len(reg.messages) + sum(
        len(m.keys) for m in reg.messages
    )
    emit_sites = 0
    read_sites = 0

    # registry-side findings (anchored at declaration lines, so the
    # registry file's own ignore comments apply)
    reg_findings = _check_golden(reg, golden_path_for(registry_path))
    reg_findings.extend(_check_registry_wr005(reg))
    try:
        with open(reg.path) as f:
            reg_lines = f.read().splitlines()
    except OSError:
        reg_lines = []
    findings.extend(suppress(reg_findings, reg_lines, _IGNORE_RE))

    for path in files:
        if os.path.basename(path) == REGISTRY_BASENAME:
            continue  # the declarations are not emit/read sites
        try:
            with open(path, "r") as f:
                source = f.read()
            tree = ast.parse(source)
        except (OSError, SyntaxError):
            findings.append(Finding(path, 0, 0, "WR000", "syntax error"))
            continue
        lines = source.splitlines()
        file_findings: List[Finding] = []

        # model classes named after registered messages: to_dict /
        # to_json are emit sites, from_dict / from_json are read sites
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            msg = reg.message(node.name)
            if msg is None:
                continue
            for sub in node.body:
                if not isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if sub.name in ("to_dict", "to_json"):
                    if _check_class_emit(path, msg, sub, file_findings):
                        emit_sites += 1
                elif sub.name in ("from_dict", "from_json"):
                    _check_parse_reads(path, msg, sub, file_findings)
                    read_sites += 1

        # marker-annotated sites (reply builders, peer-line parsers)
        seen_emit_funcs = set()
        for lineno, text in enumerate(lines, 1):
            em = _EMIT_MARK_RE.search(text)
            if em is not None:
                annotations += 1
                name = em.group(1)
                msg = reg.message(name)
                func = _enclosing_func(tree, lineno)
                if msg is None:
                    file_findings.append(Finding(
                        path, lineno, 0, "WR001",
                        f"wire-emit marker names unregistered message "
                        f"{name!r}",
                    ))
                elif func is not None and (
                    (func.name, name) not in seen_emit_funcs
                ):
                    seen_emit_funcs.add((func.name, name))
                    var = _emit_var(func)
                    if var is not None:
                        emit_sites += 1
                        _check_marker_emit(
                            path, msg, func, var, file_findings
                        )
            rm = _READ_MARK_RE.search(text)
            if rm is not None:
                annotations += 1
                name = rm.group(1)
                msg = reg.message(name)
                func = _enclosing_func(tree, lineno)
                if msg is None:
                    file_findings.append(Finding(
                        path, lineno, 0, "WR001",
                        f"wire-read marker names unregistered message "
                        f"{name!r}",
                    ))
                elif func is not None:
                    read_sites += 1
                    _check_parse_reads(path, msg, func, file_findings)

        # WR004 stamp discipline + live-annotation census over calls
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = _call_name(node)
            if leaf in stamp_msgs:
                kwargs = {kw.arg for kw in node.keywords if kw.arg}
                if "epoch" not in kwargs:
                    file_findings.append(Finding(
                        path, node.lineno, node.col_offset, "WR004",
                        f"{leaf} is an epoch-stamped message "
                        f"(epoch='stamp') but this constructor call "
                        f"passes no epoch= (every instance must carry "
                        f"the batch epoch it was computed at)",
                    ))
            elif leaf == "wire_table":
                arg = _const_str(node.args[0]) if node.args else None
                if arg in msg_names:
                    annotations += 1
            elif leaf in ("check_wire", "check_wire_read"):
                annotations += 1

        findings.extend(suppress(file_findings, lines, _IGNORE_RE))

    stats = {
        "files": len(files),
        "messages": len(reg.messages),
        "keys": sum(len(m.keys) for m in reg.messages),
        "emit_sites": emit_sites,
        "read_sites": read_sites,
        "annotations": annotations,
        "findings": len(findings),
        "registry": reg,
        "registry_path": registry_path,
    }
    return (
        sorted(findings, key=lambda f: (f.path, f.line, f.col, f.code)),
        stats,
    )


# --------------------------------------------------------------------------
# Manifest (pinned byte-identical to wireregistry.manifest()).
# --------------------------------------------------------------------------

def build_manifest(reg: Registry) -> Dict:
    return {
        "version": 1,
        "protocol_version": reg.protocol_version,
        "versions": {
            str(v): note for v, note in sorted(reg.versions.items())
        },
        "messages": [
            {
                "name": m.name,
                "since": m.since,
                "epoch": m.epoch,
                "note": m.note,
                "keys": [
                    {
                        "name": k.name,
                        "type": k.type,
                        "optional": k.optional,
                        "since": k.since,
                        "guard": k.effective_guard(),
                        "canon": k.canon,
                        "portable": k.portable,
                        "ref": k.ref,
                        "sample": k.sample,
                        "note": k.note,
                    }
                    for k in m.keys
                ],
            }
            for m in reg.messages
        ],
    }


def _post(args, findings, stats) -> None:
    stats.pop("registry", None)
    stats.pop("registry_path", None)


def main(argv: Optional[List[str]] = None) -> int:
    return run_cli(
        "wirelint",
        __doc__,
        lint_paths,
        DEFAULT_PATHS,
        lambda findings, stats: (
            f"wirelint: {len(findings)} finding(s), "
            f"{stats['messages']} message / {stats['keys']} key "
            f"declaration(s), {stats['emit_sites']}+{stats['read_sites']} "
            f"emit/read site(s), {stats['annotations']} live "
            f"annotation(s) in {stats['files']} file(s)"
        ),
        argv,
        post=_post,
    )


if __name__ == "__main__":
    import sys

    sys.exit(main())
