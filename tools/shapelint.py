#!/usr/bin/env python
"""Tensor-contract static lint: shape/dtype/sentinel checks for the
encoding -> kernel pipeline (third leg of the linter family next to
tools/jaxlint.py and tools/locklint.py).

The dense tensors engine/encoding.py produces mean exactly what the
scalar oracle assumes — `-1` pad ids, `-2` never-match sentinels,
`uint32` IPs beside `int32` ids, lane/sublane tile round-ups — but
those meanings used to live only in comments.  This lint reads the
contracts where the tensors are born (`contracts.tensor(...)` dataclass
descriptors, `@contracts.args(...)` decorators, and trailing
`# shape: (N, L) int32` / legacy `# [N, L] int32, pad -1` comments on
fields and parameters), propagates symbolic shapes/dtypes through
np/jnp constructors, reshape/stack/broadcast and one level of same-run
call-site return inference, and reports:

  SC001  shape-contract violation: a declared field/parameter built or
         passed with rank, literal dims, or dtype inconsistent with its
         declaration (including rank-changing implicit broadcast of two
         declared arrays, and wire-contract drift in worker/model.py)
  SC002  dtype-promotion hazard: cross-signedness comparison/bitop
         (uint32 vs int32 silently widens to int64), arithmetic on two
         bool arrays (upcasts; use logical ops), or an array literal
         with bare float elements and no dtype (poisons to float64
         under x64)
  SC003  sentinel misuse: a field declared with a validity mask
         (`mask="pod_ip_valid"`) compared without its mask in the same
         statement, or a declared-sentinel array filled with a negative
         fill outside its sentinel set
  SC004  tile alignment: a dim reaching a pallas `pl.BlockSpec` lane
         axis (or asserted by a trailing `# tile: <k>` comment) that
         cannot be proven a multiple of the tile — flags hand-rolled
         round-up math the prover can't discharge and misaligned
         literals

Contracts declared in ANY linted file are visible to every other file
in the same run (the registry is keyed by field name), so kernel.py's
`enc["ip_mask"]` picks up the dtype `_DirectionEncoding.ip_mask`
declares in encoding.py.

Suppress a finding with `# shapelint: ignore` or
`# shapelint: ignore[SC001,...]` on the offending line (same convention
as jaxlint/locklint).

Usage: python tools/shapelint.py [paths...]  (default: cyclonus_tpu/engine)
Exit status 1 iff findings remain.
"""

from __future__ import annotations

import ast
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from lintcore import Finding, ignore_regex, iter_py_files, run_cli, suppress

SIGNED = {"int8", "int16", "int32", "int64"}
UNSIGNED = {"uint8", "uint16", "uint32", "uint64"}
FLOATS = {"float32", "float64", "bfloat16"}
DTYPES = SIGNED | UNSIGNED | FLOATS | {"bool"}
ARRAY_MODULES = {"np", "numpy", "jnp"}
LANE = 128

_IGNORE_RE = ignore_regex("shapelint")
_CANON_RE = re.compile(
    r"#\s*shape:\s*[(\[]([^)\]]*)[)\]]\s*([A-Za-z_][A-Za-z0-9_]*)?"
)
_SENTINEL_RE = re.compile(r"sentinel:\s*([-0-9=a-zA-Z_,\s]+?)(?:;|$)")
_MASK_RE = re.compile(r"mask:\s*([A-Za-z_][A-Za-z0-9_]*)")
_LEGACY_RE = re.compile(
    r"#\s*\[([A-Za-z0-9_,\s]+)\]\s*([A-Za-z_][A-Za-z0-9_]*)?"
)
_LEGACY_PAD_RE = re.compile(r"\bpad\s+(-?\d+)")
_TILE_RE = re.compile(r"#\s*tile:\s*(\d+)")


@dataclass(frozen=True)
class Spec:
    """A declared tensor contract (static twin of contracts.TensorSpec)."""

    dims: Tuple[object, ...]  # int literals or str symbols
    dtype: Optional[str] = None
    sentinel: Tuple[int, ...] = ()
    mask: Optional[str] = None

    def render(self) -> str:
        return f"({', '.join(str(d) for d in self.dims)}) {self.dtype or ''}".strip()


_NOFILL = object()


@dataclass
class SI:
    """Inferred shape info for one expression."""

    rank: Optional[int] = None
    dims: Optional[Tuple[object, ...]] = None
    dtype: Optional[str] = None
    fill: object = _NOFILL


def _spec_si(spec: Spec) -> SI:
    return SI(rank=len(spec.dims), dims=spec.dims, dtype=spec.dtype)


def _parse_dims(raw: str) -> Optional[Tuple[object, ...]]:
    dims: List[object] = []
    for tok in raw.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok.lstrip("-").isdigit():
            dims.append(int(tok))
        elif re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", tok):
            dims.append(tok)
        else:
            return None
    return tuple(dims)


def parse_comment_spec(line_src: str) -> Optional[Spec]:
    """Trailing-comment contract: canonical `# shape: (N, L) int32;
    sentinel: -1=pad; mask: m` or legacy `# [N, L] int32, pad -1`."""
    m = _CANON_RE.search(line_src)
    legacy = False
    if m is None:
        m = _LEGACY_RE.search(line_src)
        legacy = True
    if m is None:
        return None
    dims = _parse_dims(m.group(1))
    if dims is None:
        return None
    dtype = m.group(2)
    if dtype is not None and dtype not in DTYPES:
        if not legacy:
            return None  # canonical grammar: a bad dtype is a typo
        dtype = None  # legacy comments carry prose after the dims
    rest = line_src[m.end():]
    sentinel: List[int] = []
    mask = None
    if legacy:
        pm = _LEGACY_PAD_RE.search(rest)
        if pm:
            sentinel.append(int(pm.group(1)))
    else:
        sm = _SENTINEL_RE.search(rest)
        if sm:
            for part in sm.group(1).split(","):
                val = part.strip().split("=")[0].strip()
                if val.lstrip("-").isdigit():
                    sentinel.append(int(val))
        km = _MASK_RE.search(rest)
        if km:
            mask = km.group(1)
    return Spec(dims, dtype, tuple(sentinel), mask)


def _const_int(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _const_int(node.operand)
        return -v if v is not None else None
    return None


def _dim_of(node: ast.AST) -> object:
    c = _const_int(node)
    if c is not None:
        return c
    if isinstance(node, ast.Name):
        return node.id
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover
        return None


def resolve_dtype(node: ast.AST) -> Optional[str]:
    """np.int32 / jnp.uint32 / 'int32' / bool / np.bool_ -> canonical."""
    if isinstance(node, ast.Attribute):
        name = node.attr
        if name == "bool_":
            return "bool"
        if name in DTYPES:
            return name
        return None
    if isinstance(node, ast.Name):
        if node.id == "bool":
            return "bool"
        if node.id in DTYPES:
            return node.id
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in DTYPES else None
    return None


def _attr_root(node: ast.AST) -> Optional[str]:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _contracts_tensor_call(node: ast.AST) -> Optional[Spec]:
    """`contracts.tensor("(N, L) int32", sentinel=..., mask=...)` ->
    Spec (the static read of utils/contracts.tensor)."""
    if not (isinstance(node, ast.Call) and node.args):
        return None
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None
    )
    if name != "tensor":
        return None
    arg = node.args[0]
    if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
        return None
    m = re.match(
        r"^\s*[(\[]([^)\]]*)[)\]]\s*([A-Za-z_][A-Za-z0-9_]*)?\s*$", arg.value
    )
    if not m:
        return None
    dims = _parse_dims(m.group(1))
    if dims is None:
        return None
    dtype = m.group(2) if m.group(2) in DTYPES else None
    sentinel: List[int] = []
    mask = None
    for kw in node.keywords:
        if kw.arg == "sentinel" and isinstance(kw.value, ast.Constant):
            for part in str(kw.value.value).split(","):
                val = part.strip().split("=")[0].strip()
                if val.lstrip("-").isdigit():
                    sentinel.append(int(val))
        elif kw.arg == "mask" and isinstance(kw.value, ast.Constant):
            mask = str(kw.value.value)
    return Spec(dims, dtype, tuple(sentinel), mask)


@dataclass
class ModuleScan:
    path: str
    tree: ast.Module
    lines: List[str]
    # class name -> ordered {field: Spec}
    class_contracts: Dict[str, Dict[str, Spec]] = field(default_factory=dict)
    # function name -> {param: Spec}
    func_contracts: Dict[str, Dict[str, Spec]] = field(default_factory=dict)
    # class name -> {wire key: optional?}
    wire_contracts: Dict[str, Dict[str, bool]] = field(default_factory=dict)
    functions: Dict[str, ast.FunctionDef] = field(default_factory=dict)
    classes: Dict[str, ast.ClassDef] = field(default_factory=dict)
    # `from X import Y [as Z]` aliases (alias -> original name): the
    # SC004 prover resolves these through the cross-file registry, so a
    # round-up helper or a packing constant (encoding.PACK_BITS,
    # pallas_kernel.lane_round_up) proves in every module that imports
    # it, not just where it is defined
    imports: Dict[str, str] = field(default_factory=dict)
    # module-level integer-literal constants (PACK_BITS = 32, BS = 512)
    int_consts: Dict[str, int] = field(default_factory=dict)
    n_annotations: int = 0


def _param_specs(scan: ModuleScan, fn: ast.FunctionDef) -> Dict[str, Spec]:
    """@contracts.args(...) kwargs + trailing comments on param lines."""
    out: Dict[str, Spec] = {}
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            name = dec.func.attr if isinstance(dec.func, ast.Attribute) else (
                dec.func.id if isinstance(dec.func, ast.Name) else None
            )
            if name == "args":
                for kw in dec.keywords:
                    if kw.arg and isinstance(kw.value, ast.Constant) \
                            and isinstance(kw.value.value, str):
                        sp = parse_comment_spec(f"# shape: {kw.value.value}")
                        if sp:
                            out[kw.arg] = sp
    a = fn.args
    for arg in a.posonlyargs + a.args + a.kwonlyargs:
        if arg.arg in out:
            continue
        if 0 < arg.lineno <= len(scan.lines):
            sp = parse_comment_spec(scan.lines[arg.lineno - 1])
            if sp:
                out[arg.arg] = sp
    return out


def scan_module(path: str, source: str) -> Optional[ModuleScan]:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return None
    scan = ModuleScan(path, tree, source.splitlines())
    for stmt in tree.body:
        if isinstance(stmt, ast.ImportFrom):
            for alias in stmt.names:
                scan.imports[alias.asname or alias.name] = alias.name
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and (
            isinstance(stmt.targets[0], ast.Name)
        ):
            c = _const_int(stmt.value)
            if c is not None:
                scan.int_consts[stmt.targets[0].id] = c
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            scan.functions.setdefault(node.name, node)
            specs = _param_specs(scan, node)
            if specs:
                scan.func_contracts[node.name] = specs
                scan.n_annotations += len(specs)
        elif isinstance(node, ast.ClassDef):
            scan.classes[node.name] = node
            fields: Dict[str, Spec] = {}
            wire: Dict[str, bool] = {}
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    sp = None
                    if stmt.value is not None:
                        sp = _contracts_tensor_call(stmt.value)
                    if sp is None and 0 < stmt.lineno <= len(scan.lines):
                        sp = parse_comment_spec(scan.lines[stmt.lineno - 1])
                    if sp:
                        fields[stmt.target.id] = sp
                targets = []
                if isinstance(stmt, ast.Assign):
                    targets = stmt.targets
                elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                    targets = [stmt.target]
                for t in targets:
                    if (
                        isinstance(t, ast.Name)
                        and t.id == "WIRE"
                        and isinstance(stmt.value, ast.Dict)
                    ):
                        for k, v in zip(stmt.value.keys, stmt.value.values):
                            if not (
                                isinstance(k, ast.Constant)
                                and isinstance(k.value, str)
                            ):
                                continue
                            optional = False
                            if isinstance(v, ast.Call):
                                for kw in v.keywords:
                                    if kw.arg == "optional" and isinstance(
                                        kw.value, ast.Constant
                                    ):
                                        optional = bool(kw.value.value)
                                if (
                                    len(v.args) > 1
                                    and isinstance(v.args[1], ast.Constant)
                                ):
                                    optional = bool(v.args[1].value)
                            wire[k.value] = optional
            if fields:
                scan.class_contracts[node.name] = fields
                scan.n_annotations += len(fields)
            if wire:
                scan.wire_contracts[node.name] = wire
                scan.n_annotations += len(wire)
    return scan


@dataclass
class Registry:
    """Contracts merged across every file in the run."""

    class_contracts: Dict[str, Dict[str, Spec]] = field(default_factory=dict)
    func_contracts: Dict[str, Dict[str, Spec]] = field(default_factory=dict)
    field_specs: Dict[str, Spec] = field(default_factory=dict)
    masked: Dict[str, str] = field(default_factory=dict)
    # cross-file prover facts (first definition wins — best-effort like
    # every prover rule; a wrong merge can only HIDE a finding, never
    # invent one): module-level int constants and function ASTs, so
    # imported round-up helpers (lane_round_up, packed_words) and
    # packing constants (PACK_BITS) discharge SC004 wherever they are
    # USED, not just where they are defined
    int_consts: Dict[str, int] = field(default_factory=dict)
    functions: Dict[str, "ast.FunctionDef"] = field(default_factory=dict)

    def absorb(self, scan: ModuleScan) -> None:
        for cls, fields in scan.class_contracts.items():
            self.class_contracts.setdefault(cls, fields)
            for name, sp in fields.items():
                self.field_specs.setdefault(name, sp)
                if sp.mask:
                    self.masked.setdefault(name, sp.mask)
        for fn, specs in scan.func_contracts.items():
            self.func_contracts.setdefault(fn, specs)
            for name, sp in specs.items():
                if sp.mask:
                    self.masked.setdefault(name, sp.mask)
        for name, val in scan.int_consts.items():
            self.int_consts.setdefault(name, val)
        for name, fn_ast in scan.functions.items():
            self.functions.setdefault(name, fn_ast)


CTOR_FULL = {"full"}
CTOR_FILLED = {"zeros": 0, "ones": 1, "empty": None}
CTOR_ARRAY = {"array", "asarray", "ascontiguousarray"}


def _unify_si(infos: Sequence[Optional[SI]]) -> Optional[SI]:
    """Merge return-path inferences: keep an attribute only when no two
    KNOWN values disagree (unknown agrees with everything)."""
    known = [i for i in infos if i is not None]
    if not known:
        return None
    out = SI()
    ranks = {i.rank for i in known if i.rank is not None}
    if len(ranks) == 1:
        out.rank = ranks.pop()
    dtypes = {i.dtype for i in known if i.dtype is not None}
    if len(dtypes) == 1:
        out.dtype = dtypes.pop()
    return out


class Inferencer:
    """Symbolic shape/dtype propagation over one module, with one level
    of same-run call-site return inference."""

    def __init__(self, scan: ModuleScan, registry: Registry):
        self.scan = scan
        self.registry = registry
        self._ret_cache: Dict[str, object] = {}
        self._inferring: Set[str] = set()

    # -- helpers -----------------------------------------------------------

    def _shape_dims(self, node: ast.AST) -> Optional[Tuple[object, ...]]:
        if isinstance(node, (ast.Tuple, ast.List)):
            return tuple(_dim_of(e) for e in node.elts)
        c = _const_int(node)
        if c is not None:
            return (c,)
        if isinstance(node, ast.Name):
            return (node.id,)
        return None

    def _literal_rank(self, node: ast.AST) -> Optional[int]:
        if isinstance(node, (ast.List, ast.Tuple)):
            if any(isinstance(e, (ast.List, ast.Tuple)) for e in node.elts):
                return 2
            return 1
        if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
            return 1
        return None

    def _dtype_kw(self, call: ast.Call, pos: Optional[int]) -> Optional[str]:
        for kw in call.keywords:
            if kw.arg == "dtype":
                return resolve_dtype(kw.value)
        if pos is not None and len(call.args) > pos:
            return resolve_dtype(call.args[pos])
        return None

    # -- call-site return inference (one level) ----------------------------

    def _returns_of(self, name: str) -> object:
        """Unified SI (or tuple of SIs) of a same-module function's
        return expressions, inferred with the callee's own env."""
        if name in self._ret_cache:
            return self._ret_cache[name]
        fn = self.scan.functions.get(name)
        if fn is None or name in self._inferring:
            return None
        self._inferring.add(name)
        try:
            env: Dict[str, object] = {}
            for p, sp in self.scan.func_contracts.get(name, {}).items():
                env[p] = _spec_si(sp)
            rets: List[ast.AST] = []

            def walk(stmts: List[ast.stmt]) -> None:
                for s in stmts:
                    if isinstance(s, ast.Return) and s.value is not None:
                        rets.append(s.value)
                    elif isinstance(s, ast.Assign):
                        self.bind(s.targets, self.infer(s.value, env), env)
                    elif isinstance(
                        s, (ast.If, ast.For, ast.While, ast.With, ast.Try)
                    ):
                        for attr in ("body", "orelse", "finalbody"):
                            walk(getattr(s, attr, []) or [])
                        for h in getattr(s, "handlers", []):
                            walk(h.body)

            walk(fn.body)
            vals = [self.infer(r, env) for r in rets]
            if vals and all(isinstance(v, tuple) for v in vals):
                width = {len(v) for v in vals}
                if len(width) == 1:
                    w = width.pop()
                    out: object = tuple(
                        _unify_si([v[i] for v in vals]) for i in range(w)
                    )
                else:
                    out = None
            else:
                out = _unify_si(
                    [v if isinstance(v, SI) else None for v in vals]
                )
            self._ret_cache[name] = out
            return out
        finally:
            self._inferring.discard(name)

    # -- binding -----------------------------------------------------------

    def bind(
        self, targets: List[ast.AST], value: object, env: Dict[str, object]
    ) -> None:
        for t in targets:
            if isinstance(t, ast.Name):
                env[t.id] = value
            elif isinstance(t, (ast.Tuple, ast.List)):
                if isinstance(value, tuple) and len(value) == len(t.elts):
                    for el, v in zip(t.elts, value):
                        self.bind([el], v, env)
                else:
                    for el in t.elts:
                        self.bind([el], None, env)

    # -- inference ---------------------------------------------------------

    def infer(self, e: ast.AST, env: Dict[str, object]) -> object:
        if isinstance(e, ast.Name):
            return env.get(e.id)
        if isinstance(e, ast.IfExp):
            return _unify_si(
                [
                    v if isinstance(v, SI) else None
                    for v in (self.infer(e.body, env), self.infer(e.orelse, env))
                ]
            )
        if isinstance(e, ast.Tuple):
            return tuple(self.infer(el, env) for el in e.elts)
        if isinstance(e, ast.Subscript):
            # dict-style access to a declared field: d["ip_mask"]
            if isinstance(e.slice, ast.Constant) and isinstance(
                e.slice.value, str
            ):
                sp = self.registry.field_specs.get(e.slice.value)
                if sp is not None:
                    return _spec_si(sp)
                return None
            base = self.infer(e.value, env)
            if isinstance(base, SI) and base.dtype:
                return SI(dtype=base.dtype)  # indexing keeps the dtype
            return None
        if isinstance(e, ast.Attribute):
            sp = self.registry.field_specs.get(e.attr)
            if sp is None:
                return None
            if isinstance(e.value, ast.Name) and e.value.id in ARRAY_MODULES:
                return None  # np.int32 etc., not a field access
            return _spec_si(sp)
        if isinstance(e, ast.BinOp):
            left = self.infer(e.left, env)
            right = self.infer(e.right, env)
            lt = left.dtype if isinstance(left, SI) else None
            rt = right.dtype if isinstance(right, SI) else None
            if lt and rt and lt == rt:
                return SI(dtype=lt)
            return None
        if isinstance(e, ast.UnaryOp):
            return self.infer(e.operand, env)
        if isinstance(e, ast.Call):
            return self._infer_call(e, env)
        return None

    def _infer_call(self, e: ast.Call, env: Dict[str, object]) -> object:
        f = e.func
        # method calls ------------------------------------------------------
        if isinstance(f, ast.Attribute):
            root = _attr_root(f)
            if root in ARRAY_MODULES:
                return self._infer_np(f.attr, e, env)
            base = self.infer(f.value, env)
            if f.attr == "astype":
                dt = resolve_dtype(e.args[0]) if e.args else None
                out = SI(dtype=dt)
                if isinstance(base, SI):
                    out.rank, out.dims = base.rank, base.dims
                return out
            if f.attr == "reshape":
                shape_args = e.args
                if len(shape_args) == 1 and isinstance(
                    shape_args[0], (ast.Tuple, ast.List)
                ):
                    shape_args = shape_args[0].elts
                dims = tuple(_dim_of(a) for a in shape_args)
                dt = base.dtype if isinstance(base, SI) else None
                if len(dims) == 1 and dims[0] == -1:
                    return SI(rank=1, dtype=dt)
                return SI(rank=len(dims), dims=dims, dtype=dt)
            if f.attr in ("copy", "T"):
                return base
            return None
        return None

    def _infer_np(self, name: str, e: ast.Call, env: Dict[str, object]) -> object:
        if name in CTOR_FULL and e.args:
            dims = self._shape_dims(e.args[0])
            fill = _const_int(e.args[1]) if len(e.args) > 1 else None
            return SI(
                rank=len(dims) if dims else None,
                dims=dims,
                dtype=self._dtype_kw(e, 2),
                fill=fill if fill is not None else _NOFILL,
            )
        if name in CTOR_FILLED and e.args:
            dims = self._shape_dims(e.args[0])
            fill = CTOR_FILLED[name]
            return SI(
                rank=len(dims) if dims else None,
                dims=dims,
                dtype=self._dtype_kw(e, 1),
                fill=fill if fill is not None else _NOFILL,
            )
        if name in CTOR_ARRAY and e.args:
            return SI(
                rank=self._literal_rank(e.args[0]),
                dtype=self._dtype_kw(e, 1),
            )
        if name == "arange":
            return SI(rank=1, dtype=self._dtype_kw(e, None))
        if name in ("concatenate", "pad"):
            if e.args:
                inner = e.args[0]
                if name == "pad":
                    base = self.infer(inner, env)
                    if isinstance(base, SI):
                        return SI(rank=base.rank, dims=base.dims, dtype=base.dtype)
                    return None
                if isinstance(inner, (ast.List, ast.Tuple)):
                    return _unify_si(
                        [
                            v if isinstance(v, SI) else None
                            for v in (self.infer(el, env) for el in inner.elts)
                        ]
                    )
            return None
        if name == "stack" and e.args:
            inner = e.args[0]
            if isinstance(inner, (ast.List, ast.Tuple)) and inner.elts:
                base = self.infer(inner.elts[0], env)
                if isinstance(base, SI) and base.rank is not None:
                    return SI(rank=base.rank + 1, dtype=base.dtype)
            return None
        if name == "broadcast_to" and len(e.args) > 1:
            dims = self._shape_dims(e.args[1])
            base = self.infer(e.args[0], env)
            return SI(
                rank=len(dims) if dims else None,
                dims=dims,
                dtype=base.dtype if isinstance(base, SI) else None,
            )
        return None

    def infer_with_calls(self, e: ast.AST, env: Dict[str, object]) -> object:
        """infer() plus one level of same-module call-return inference."""
        if (
            isinstance(e, ast.Call)
            and isinstance(e.func, ast.Name)
            and e.func.id in self.scan.functions
        ):
            return self._returns_of(e.func.id)
        return self.infer(e, env)


# --- the SC004 multiple-of-k prover ---------------------------------------


class Prover:
    """Best-effort 'is this expression a multiple of k' discharge over
    the function's visible assignments plus module constants and one
    level of same-module call returns — plus, through the cross-file
    registry, IMPORTED integer constants and round-up helpers (the
    packed-lane arithmetic: encoding.PACK_BITS / packed_words and
    pallas_kernel.lane_round_up prove in every module importing them)."""

    def __init__(self, scan: ModuleScan, registry: Optional[Registry] = None):
        self.scan = scan
        self.registry = registry
        self._defs_cache: Dict[int, Dict[str, List[object]]] = {}
        self._module_defs = self._collect(scan.tree.body)

    def _collect(self, stmts: List[ast.stmt]) -> Dict[str, List[object]]:
        defs: Dict[str, List[object]] = {}

        def walk(body: List[ast.stmt]) -> None:
            for s in body:
                if isinstance(s, ast.Assign):
                    for t in s.targets:
                        if isinstance(t, ast.Name):
                            defs.setdefault(t.id, []).append(s.value)
                        elif isinstance(t, (ast.Tuple, ast.List)):
                            paired = (
                                isinstance(s.value, (ast.Tuple, ast.List))
                                and len(s.value.elts) == len(t.elts)
                            )
                            for i, el in enumerate(t.elts):
                                if not isinstance(el, ast.Name):
                                    continue
                                if paired:
                                    defs.setdefault(el.id, []).append(
                                        s.value.elts[i]
                                    )
                                elif isinstance(s.value, ast.Call):
                                    defs.setdefault(el.id, []).append(
                                        ("elt", s.value, i)
                                    )
                                # non-call unpack (e.g. `a, b, c = x.shape`):
                                # runtime facts, out of the prover's reach —
                                # leave the name undefined so it is trusted
                elif isinstance(s, ast.AugAssign) and isinstance(
                    s.target, ast.Name
                ):
                    defs.setdefault(s.target.id, []).append(
                        ("aug", s.op, s.value)
                    )
                elif isinstance(s, (ast.If, ast.For, ast.While, ast.With, ast.Try)):
                    for attr in ("body", "orelse", "finalbody"):
                        walk(getattr(s, attr, []) or [])
                    for h in getattr(s, "handlers", []):
                        walk(h.body)

        walk(stmts)
        return defs

    def _defs_for(self, fn: Optional[ast.FunctionDef]) -> Dict[str, List[object]]:
        if fn is None:
            return self._module_defs
        key = id(fn)
        if key not in self._defs_cache:
            self._defs_cache[key] = self._collect(fn.body)
        return self._defs_cache[key]

    def prove(
        self,
        e: ast.AST,
        k: int,
        fn: Optional[ast.FunctionDef],
        visited: Optional[Set[str]] = None,
        depth: int = 0,
    ) -> bool:
        if depth > 12:
            return False
        visited = visited or set()
        c = _const_int(e)
        if c is not None:
            return c % k == 0
        if isinstance(e, ast.UnaryOp) and isinstance(e.op, (ast.USub, ast.UAdd)):
            return self.prove(e.operand, k, fn, visited, depth + 1)
        if isinstance(e, ast.BinOp):
            if isinstance(e.op, ast.Mult):
                return self.prove(e.left, k, fn, visited, depth + 1) or self.prove(
                    e.right, k, fn, visited, depth + 1
                )
            if isinstance(e.op, (ast.Add, ast.Sub)):
                return self.prove(e.left, k, fn, visited, depth + 1) and self.prove(
                    e.right, k, fn, visited, depth + 1
                )
            if isinstance(e.op, ast.LShift):
                sh = _const_int(e.right)
                if sh is not None and (1 << sh) % k == 0:
                    return True
            return False
        if isinstance(e, ast.Call):
            fname = e.func.id if isinstance(e.func, ast.Name) else None
            if fname in ("max", "min"):
                return all(
                    self.prove(a, k, fn, visited, depth + 1) for a in e.args
                )
            if (
                fname is not None
                and fname not in visited
                and self._resolve_fn(fname) is not None
            ):
                return self._prove_call(fname, None, k, visited, depth)
            return False
        if isinstance(e, ast.Name):
            # scope the cycle guard per function: a caller's `bs` must
            # not shadow a callee's `bs`
            key = f"{id(fn)}:{e.id}"
            if key in visited:
                return False
            defs = self._defs_for(fn)
            cand = defs.get(e.id)
            if cand is None and fn is not None:
                cand = self._module_defs.get(e.id)
            if not cand:
                c = self._foreign_const(e.id)
                if c is not None:
                    return c % k == 0
                return False
            visited = visited | {key}
            plain_ok = True
            saw_plain = False
            for d in cand:
                if isinstance(d, tuple) and d[0] == "aug":
                    _, op, val = d
                    if isinstance(op, ast.Mult):
                        continue  # multiplying preserves multiples
                    if isinstance(op, (ast.Add, ast.Sub)) and self.prove(
                        val, k, fn, visited, depth + 1
                    ):
                        continue
                    return False
                elif isinstance(d, tuple) and d[0] == "elt":
                    _, call, idx = d
                    if not (
                        isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Name)
                        and self._prove_call(
                            call.func.id, idx, k, visited, depth
                        )
                    ):
                        return False
                    saw_plain = True
                else:
                    saw_plain = True
                    if not self.prove(d, k, fn, visited, depth + 1):
                        plain_ok = False
            return saw_plain and plain_ok
        return False

    def _foreign_const(self, name: str) -> Optional[int]:
        """Integer constant behind a name with no local definition: an
        explicit `from X import NAME` resolved through the registry, or
        — for ALL_CAPS names only (constants by convention; anything
        looser would invite cross-module collisions) — any scanned
        module's constant.  The latter is what lets a FOREIGN function
        body (e.g. encoding.packed_words proved from a module that
        imports it) reference its own module's PACK_BITS."""
        if self.registry is None:
            return None
        orig = self.scan.imports.get(name)
        if orig is not None:
            c = self.registry.int_consts.get(orig)
            if c is not None:
                return c
        if name.isupper():
            return self.registry.int_consts.get(name)
        return None

    def _resolve_fn(self, fname: str) -> Optional[ast.FunctionDef]:
        """Same-module function, or an explicitly imported one resolved
        through the cross-file registry (lane_round_up / packed_words
        prove where they are used)."""
        fn = self.scan.functions.get(fname)
        if fn is not None:
            return fn
        if self.registry is not None:
            orig = self.scan.imports.get(fname)
            if orig is not None:
                return self.registry.functions.get(orig)
        return None

    def _prove_call(
        self, fname: str, idx: Optional[int], k: int, visited: Set[str], depth: int
    ) -> bool:
        fn = self._resolve_fn(fname)
        if fn is None or fname in visited or depth > 12:
            return False
        visited = visited | {fname}
        rets: List[ast.AST] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                rets.append(node.value)
        if not rets:
            return False
        for r in rets:
            target: Optional[ast.AST] = r
            if idx is not None:
                if isinstance(r, ast.Tuple) and idx < len(r.elts):
                    target = r.elts[idx]
                else:
                    return False
            if not self.prove(target, k, fn, visited, depth + 1):
                return False
        return True


def _has_round_math(e: ast.AST) -> bool:
    for node in ast.walk(e):
        if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.FloorDiv, ast.Mod, ast.Mult, ast.LShift)
        ):
            return True
    return False


# --- per-module checker ---------------------------------------------------


class Checker:
    def __init__(self, scan: ModuleScan, registry: Registry):
        self.scan = scan
        self.registry = registry
        self.inf = Inferencer(scan, registry)
        self.prover = Prover(scan, registry)
        self.findings: List[Finding] = []

    def _add(self, node: ast.AST, code: str, message: str) -> None:
        self.findings.append(
            Finding(
                self.scan.path,
                getattr(node, "lineno", 0),
                getattr(node, "col_offset", 0),
                code,
                message,
            )
        )

    def run(self) -> List[Finding]:
        env: Dict[str, object] = {}
        self._exec(self.scan.tree.body, env, None)
        for fn in self._all_functions(self.scan.tree):
            fenv: Dict[str, object] = {}
            for p, sp in self.scan.func_contracts.get(fn.name, {}).items():
                fenv[p] = _spec_si(sp)
            self._exec(fn.body, fenv, fn)
        for cls, keys in self.scan.wire_contracts.items():
            self._check_wire(cls, keys)
        return self.findings

    def _all_functions(self, tree: ast.Module) -> List[ast.FunctionDef]:
        return [n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)]

    # -- statement walk ----------------------------------------------------

    def _exec(
        self,
        stmts: List[ast.stmt],
        env: Dict[str, object],
        fn: Optional[ast.FunctionDef],
    ) -> None:
        for s in stmts:
            if isinstance(s, ast.FunctionDef):
                continue  # checked at top level with its own env
            if isinstance(s, ast.Assign):
                val = self.inf.infer_with_calls(s.value, env)
                self.inf.bind(s.targets, val, env)
                self._check_assign_comment(s, val, env, fn)
            elif isinstance(s, ast.AnnAssign) and s.value is not None:
                val = self.inf.infer_with_calls(s.value, env)
                self.inf.bind([s.target], val, env)
            elif isinstance(s, ast.AugAssign):
                pass
            self._check_stmt(s, env, fn)
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(s, attr, None)
                if sub and not isinstance(s, (ast.FunctionDef, ast.ClassDef)):
                    self._exec(sub, env, fn)
            for h in getattr(s, "handlers", []):
                self._exec(h.body, env, fn)

    def _check_assign_comment(
        self,
        s: ast.Assign,
        val: object,
        env: Dict[str, object],
        fn: Optional[ast.FunctionDef],
    ) -> None:
        """Canonical `# shape:` / `# tile:` trailing comments on an
        assignment assert (and, for shape, re-declare) the target."""
        if not (0 < s.lineno <= len(self.scan.lines)):
            return
        line = self.scan.lines[s.lineno - 1]
        end = getattr(s, "end_lineno", s.lineno) or s.lineno
        if "# shape:" not in line and "# tile:" not in line \
                and 0 < end <= len(self.scan.lines):
            line = self.scan.lines[end - 1]  # comment on the closing line
        tm = _TILE_RE.search(line)
        if tm:
            k = int(tm.group(1))
            if not self.prover.prove(s.value, k, fn):
                self._add(
                    s,
                    "SC004",
                    f"asserted `# tile: {k}` but the value is not provably "
                    f"a multiple of {k} (hand-rolled round math the prover "
                    f"can't discharge)",
                )
        if "# shape:" not in line:
            return
        sp = parse_comment_spec(line)
        if sp is None or len(s.targets) != 1:
            return
        target = s.targets[0]
        if isinstance(target, ast.Name):
            name = target.id
        elif isinstance(target, ast.Subscript) and isinstance(
            target.slice, ast.Constant
        ) and isinstance(target.slice.value, str):
            name = target.slice.value  # t["pod_ip"] = ... style
        else:
            return
        self.scan.n_annotations += 1
        si = val if isinstance(val, SI) else None
        if si is not None:
            if si.rank is not None and si.rank != len(sp.dims):
                self._add(
                    s,
                    "SC001",
                    f"{name} declared {sp.render()} (rank {len(sp.dims)}) "
                    f"but built with rank {si.rank}",
                )
            if si.dtype is not None and sp.dtype and si.dtype != sp.dtype:
                self._add(
                    s,
                    "SC001",
                    f"{name} declared dtype {sp.dtype} but built as {si.dtype}",
                )
            if (
                sp.sentinel
                and si.fill is not _NOFILL
                and isinstance(si.fill, int)
                and si.fill < 0
                and si.fill not in sp.sentinel
            ):
                self._add(
                    s,
                    "SC003",
                    f"{name} declared sentinel {list(sp.sentinel)} but "
                    f"filled with {si.fill}",
                )
        env[name] = _spec_si(sp)

    # -- expression checks -------------------------------------------------

    def _check_stmt(
        self,
        s: ast.stmt,
        env: Dict[str, object],
        fn: Optional[ast.FunctionDef],
    ) -> None:
        names_in_stmt = {
            n.id for n in ast.walk(s) if isinstance(n, ast.Name)
        } | {
            n.attr for n in ast.walk(s) if isinstance(n, ast.Attribute)
        } | {
            n.slice.value
            for n in ast.walk(s)
            if isinstance(n, ast.Subscript)
            and isinstance(n.slice, ast.Constant)
            and isinstance(n.slice.value, str)
        }
        own_exprs = self._own_exprs(s)
        for node in own_exprs:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    self._check_call(sub, env, fn)
                elif isinstance(sub, ast.Compare):
                    self._check_compare(sub, env, names_in_stmt)
                elif isinstance(sub, ast.BinOp):
                    self._check_binop(sub, env, names_in_stmt)

    def _own_exprs(self, s: ast.stmt) -> List[ast.AST]:
        """Expressions belonging to THIS statement (not its nested
        blocks, which _exec visits separately)."""
        out: List[ast.AST] = []
        for name, value in ast.iter_fields(s):
            if name in ("body", "orelse", "finalbody", "handlers"):
                continue
            if isinstance(value, ast.AST):
                out.append(value)
            elif isinstance(value, list):
                out.extend(v for v in value if isinstance(v, ast.AST))
        return out

    def _masked_ref(self, e: ast.AST) -> Optional[Tuple[str, str]]:
        """(field, mask) when `e` references a mask-declared field."""
        name = None
        if isinstance(e, ast.Name):
            name = e.id
        elif isinstance(e, ast.Attribute):
            name = e.attr
        elif isinstance(e, ast.Subscript) and isinstance(
            e.slice, ast.Constant
        ) and isinstance(e.slice.value, str):
            name = e.slice.value
        if name is not None and name in self.registry.masked:
            return name, self.registry.masked[name]
        return None

    def _check_compare(
        self, node: ast.Compare, env: Dict[str, object], stmt_names: Set[str]
    ) -> None:
        operands = [node.left, *node.comparators]
        self._cross_sign(node, operands, env)
        self._rank_broadcast(node, operands, env)
        # SC003: a masked field compared without its validity mask in
        # the same statement
        for sub in ast.walk(node):
            ref = self._masked_ref(sub)
            if ref is not None and ref[1] not in stmt_names:
                self._add(
                    node,
                    "SC003",
                    f"{ref[0]} is only meaningful where {ref[1]} is True "
                    f"(declared mask), but this comparison does not "
                    f"consult {ref[1]} in the same statement",
                )
                break

    def _check_binop(
        self, node: ast.BinOp, env: Dict[str, object], stmt_names: Set[str]
    ) -> None:
        if isinstance(node.op, (ast.BitAnd, ast.BitOr, ast.BitXor)):
            self._cross_sign(node, [node.left, node.right], env)
        if isinstance(node.op, (ast.Add, ast.Sub, ast.Mult, ast.MatMult)):
            lt = self.inf.infer(node.left, env)
            rt = self.inf.infer(node.right, env)
            if (
                isinstance(lt, SI)
                and isinstance(rt, SI)
                and lt.dtype == "bool"
                and rt.dtype == "bool"
            ):
                msg = (
                    "matmul on two bool arrays stays bool (every nonzero "
                    "sum collapses to True — counts are lost; astype an "
                    "integer dtype first)"
                    if isinstance(node.op, ast.MatMult)
                    else "arithmetic on two bool arrays upcasts to int "
                    "(use logical &/| or an explicit astype)"
                )
                self._add(node, "SC002", msg)
        self._rank_broadcast(node, [node.left, node.right], env)

    def _cross_sign(
        self, node: ast.AST, operands: List[ast.AST], env: Dict[str, object]
    ) -> None:
        dtypes = []
        for op in operands:
            si = self.inf.infer(op, env)
            dtypes.append(si.dtype if isinstance(si, SI) else None)
        signed = [d for d in dtypes if d in SIGNED]
        unsigned = [d for d in dtypes if d in UNSIGNED]
        if signed and unsigned:
            self._add(
                node,
                "SC002",
                f"cross-signedness operation ({unsigned[0]} vs {signed[0]}) "
                f"silently promotes to int64 — cast one side explicitly",
            )

    def _rank_broadcast(
        self, node: ast.AST, operands: List[ast.AST], env: Dict[str, object]
    ) -> None:
        """SC001: two bare declared names of different rank broadcast
        implicitly (a reshape/[None]-index marks intent and skips)."""
        ranks = []
        for op in operands:
            if not isinstance(op, ast.Name):
                return
            si = self.inf.infer(op, env)
            if not isinstance(si, SI) or si.rank is None:
                return
            ranks.append(si.rank)
        if len(set(ranks)) > 1:
            self._add(
                node,
                "SC001",
                f"implicit rank-changing broadcast between declared arrays "
                f"of rank {ranks[0]} and rank {ranks[1]} (insert an "
                f"explicit [None]-index or reshape)",
            )

    def _check_call(
        self,
        node: ast.Call,
        env: Dict[str, object],
        fn: Optional[ast.FunctionDef],
    ) -> None:
        f = node.func
        cname = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if cname == "BlockSpec":
            self._check_blockspec(node, fn)
            return
        # SC002: bare float literals in an array ctor without dtype
        root = _attr_root(f)
        if (
            root in ARRAY_MODULES
            and isinstance(f, ast.Attribute)
            and f.attr in CTOR_ARRAY | {"full"}
            and node.args
        ):
            has_dtype = any(kw.arg == "dtype" for kw in node.keywords) or (
                f.attr in CTOR_ARRAY and len(node.args) > 1
            )
            lit = node.args[1] if f.attr == "full" and len(node.args) > 1 \
                else node.args[0]
            if not has_dtype and not (f.attr == "full" and len(node.args) > 2):
                for sub in ast.walk(lit):
                    if isinstance(sub, ast.Constant) and isinstance(
                        sub.value, float
                    ):
                        self._add(
                            node,
                            "SC002",
                            "bare float literal in an array constructor "
                            "without dtype= (poisons to float64 under "
                            "x64; pin the dtype)",
                        )
                        break
        # contract-class constructor / contract-function call
        if cname in self.registry.class_contracts:
            self._check_ctor(node, cname, env)
        elif cname in self.registry.func_contracts and not isinstance(
            f, ast.Attribute
        ):
            self._check_func_call(node, cname, env)

    def _check_value_against(
        self,
        node: ast.AST,
        what: str,
        sp: Spec,
        si: object,
    ) -> None:
        if not isinstance(si, SI):
            return
        if si.rank is not None and si.rank != len(sp.dims):
            self._add(
                node,
                "SC001",
                f"{what} declared {sp.render()} (rank {len(sp.dims)}) but "
                f"built/passed with rank {si.rank}",
            )
            return
        if si.dtype is not None and sp.dtype and si.dtype != sp.dtype:
            self._add(
                node,
                "SC001",
                f"{what} declared dtype {sp.dtype} but built/passed as "
                f"{si.dtype}",
            )
        if si.dims is not None:
            for want, got in zip(sp.dims, si.dims):
                if (
                    isinstance(want, int)
                    and isinstance(got, int)
                    and want != got
                ):
                    self._add(
                        node,
                        "SC001",
                        f"{what} declared dim {want} but built with {got}",
                    )
        if (
            sp.sentinel
            and si.fill is not _NOFILL
            and isinstance(si.fill, int)
            and si.fill < 0
            and si.fill not in sp.sentinel
        ):
            self._add(
                node,
                "SC003",
                f"{what} declared sentinel {list(sp.sentinel)} but filled "
                f"with {si.fill}",
            )

    def _check_ctor(
        self, node: ast.Call, cname: str, env: Dict[str, object]
    ) -> None:
        fields = self.registry.class_contracts[cname]
        for kw in node.keywords:
            if kw.arg in fields:
                si = self.inf.infer_with_calls(kw.value, env)
                self._check_value_against(
                    kw.value, f"{cname}.{kw.arg}", fields[kw.arg], si
                )

    def _check_func_call(
        self, node: ast.Call, fname: str, env: Dict[str, object]
    ) -> None:
        specs = self.registry.func_contracts[fname]
        fn = self.scan.functions.get(fname)
        pos: List[str] = []
        if fn is not None:
            pos = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        for i, a in enumerate(node.args):
            if i < len(pos) and pos[i] in specs:
                si = self.inf.infer_with_calls(a, env)
                self._check_value_against(
                    a, f"{fname}({pos[i]})", specs[pos[i]], si
                )
        for kw in node.keywords:
            if kw.arg in specs:
                si = self.inf.infer_with_calls(kw.value, env)
                self._check_value_against(
                    kw.value, f"{fname}({kw.arg})", specs[kw.arg], si
                )

    def _check_blockspec(
        self, node: ast.Call, fn: Optional[ast.FunctionDef]
    ) -> None:
        """SC004: the LANE (last) dim of a pallas block shape must be a
        provable multiple of 128.  Full-axis symbolic dims with no
        visible round math are trusted (Mosaic pads whole axes)."""
        if not node.args or not isinstance(node.args[0], (ast.Tuple, ast.List)):
            return
        dims = node.args[0].elts
        if not dims:
            return
        last = dims[-1]
        c = _const_int(last)
        if c is not None:
            if c % LANE != 0 and c != 1:
                self._add(
                    last,
                    "SC004",
                    f"BlockSpec lane dim {c} is not a multiple of the "
                    f"{LANE}-lane tile",
                )
            return
        if isinstance(last, ast.Name):
            defs = self.prover._defs_for(fn)
            cand = defs.get(last.id) or self.prover._module_defs.get(last.id)
            if not cand:
                return  # parameter / unknown: out of the prover's reach
            interesting = [
                d
                for d in cand
                if isinstance(d, tuple)
                or _has_round_math(d)
                or isinstance(d, ast.Call)
            ]
            if not interesting:
                return  # opaque definition with no round math: trusted
        elif not (_has_round_math(last) or isinstance(last, ast.Call)):
            return
        if not self.prover.prove(last, LANE, fn):
            self._add(
                last,
                "SC004",
                f"BlockSpec lane dim `{ast.unparse(last)}` cannot be "
                f"proven a multiple of the {LANE}-lane tile (hand-rolled "
                f"round math the prover can't discharge)",
            )

    # -- wire contracts ----------------------------------------------------

    def _check_wire(self, cls: str, keys: Dict[str, bool]) -> None:
        """SC001: the emit side of a WIRE-declared class must match the
        contract — required keys emitted unconditionally, optional keys
        only behind a condition, no undeclared keys."""
        node = self.scan.classes.get(cls)
        if node is None:
            return
        for meth in node.body:
            if not isinstance(meth, ast.FunctionDef) or meth.name not in (
                "to_dict",
                "to_json",
            ):
                continue
            base: Set[str] = set()
            for sub in ast.walk(meth):
                if isinstance(sub, ast.Dict):
                    ks = {
                        k.value
                        for k in sub.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                    }
                    if len(ks) > len(base):
                        base = ks
            conditional: Set[str] = set()
            unconditional: Set[str] = set(base)

            def scan_assigns(stmts: List[ast.stmt], in_if: bool) -> None:
                for s in stmts:
                    if isinstance(s, ast.Assign):
                        for t in s.targets:
                            if (
                                isinstance(t, ast.Subscript)
                                and isinstance(t.slice, ast.Constant)
                                and isinstance(t.slice.value, str)
                            ):
                                (conditional if in_if else unconditional).add(
                                    t.slice.value
                                )
                    for attr in ("body", "orelse"):
                        sub = getattr(s, attr, None)
                        if sub:
                            scan_assigns(
                                sub, in_if or isinstance(s, (ast.If, ast.While))
                            )

            scan_assigns(meth.body, False)
            for key in sorted(unconditional | conditional):
                if key not in keys:
                    self._add(
                        meth,
                        "SC001",
                        f"{cls}.{meth.name} emits wire key {key!r} with no "
                        f"WIRE contract entry",
                    )
            for key, optional in keys.items():
                if not optional and key not in unconditional:
                    self._add(
                        meth,
                        "SC001",
                        f"{cls}.{meth.name}: required wire key {key!r} is "
                        f"not emitted unconditionally (compat rule: the "
                        f"reference shape is frozen)",
                    )
                elif optional and key in unconditional:
                    self._add(
                        meth,
                        "SC001",
                        f"{cls}.{meth.name}: optional wire key {key!r} is "
                        f"emitted unconditionally (compat rule: extensions "
                        f"must be omitted when unset)",
                    )


# --- driver ---------------------------------------------------------------


def lint_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, int]]:
    files = iter_py_files(paths)
    scans: List[ModuleScan] = []
    findings: List[Finding] = []
    registry = Registry()
    for path in files:
        with open(path, "r") as f:
            source = f.read()
        scan = scan_module(path, source)
        if scan is None:
            findings.append(Finding(path, 0, 0, "SC000", "syntax error"))
            continue
        scans.append(scan)
        registry.absorb(scan)
    for scan in scans:
        findings.extend(
            suppress(Checker(scan, registry).run(), scan.lines, _IGNORE_RE)
        )
    stats = {
        "contracts": sum(s.n_annotations for s in scans),
        "files": len(files),
    }
    return (
        sorted(findings, key=lambda f: (f.path, f.line, f.col, f.code)),
        stats,
    )


def main(argv: Optional[List[str]] = None) -> int:
    return run_cli(
        "shapelint",
        __doc__,
        lint_paths,
        ["cyclonus_tpu/engine"],
        lambda findings, stats: (
            f"shapelint: {len(findings)} finding(s), {stats['contracts']} "
            f"contract annotation(s) in {stats['files']} file(s)"
        ),
        argv,
    )


if __name__ == "__main__":
    sys.exit(main())
