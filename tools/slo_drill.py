#!/usr/bin/env python
"""`make slo`: the SLO enforcement drill — shed under REAL overload,
non-shed parity, and budget recovery, in one process.

Shrinks the objective knobs (tiny query_p99 target so every real query
is a bad event; 2s/4s burn windows; 1s exit hold) so the full
ok -> exhausted -> ok arc runs in seconds, then:

  1. drives query batches through a VerdictService with enforcement
     armed, scraping the registry between batches (the scrape IS the
     accounting cadence in production — the drill uses the same path),
     until the query_p99 budget exhausts and queries come back SHED;
  2. asserts the shed answers are typed refusals (shed=True + error,
     HTTP-mapped 429 elsewhere) and — the differential gate extended to
     the shed path — that every NON-shed answer stayed bit-identical to
     an unloaded twin service with enforcement off;
  3. stops the load, keeps scraping, and asserts the budget RECOVERS:
     the bad events age out of the slow window, the hysteresis hold
     expires, the route returns to live, budget_remaining returns to
     1.0, and a fresh query answers (twin-identical) again.

Wired into `make check` via the `slo` target next to the unit legs in
tests/test_slo.py."""

from __future__ import annotations

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# objective knobs BEFORE any cyclonus_tpu import: declared_objectives()
# resolves them when a controller is constructed
os.environ["CYCLONUS_SLO_QUERY_P99_S"] = "0.000001"  # every query is bad
os.environ["CYCLONUS_SLO_FAST_S"] = "2"
os.environ["CYCLONUS_SLO_SLOW_S"] = "4"
os.environ["CYCLONUS_SLO_HOLD_S"] = "1"
os.environ["CYCLONUS_SLO_ENFORCE"] = "1"
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from cyclonus_tpu.cli.serve_cmd import synthetic_cluster  # noqa: E402
from cyclonus_tpu.slo.engine import SloController  # noqa: E402
from cyclonus_tpu.serve.service import VerdictService  # noqa: E402
from cyclonus_tpu.telemetry import instruments as ti  # noqa: E402
from cyclonus_tpu.worker.model import FlowQuery  # noqa: E402

N_PODS, N_NS, SEED = 16, 2, 11


def bits(v):
    """The answer bits parity compares (latency/epoch excluded: timing
    and apply history may differ between the twins by construction)."""
    return (v.ingress, v.egress, v.combined, v.error)


def scrape() -> None:
    """One registry scrape: runs every registered collector, which is
    what advances the SLO accounting in production."""
    ti.REGISTRY.snapshot()


def main() -> int:
    import random

    pods, namespaces = synthetic_cluster(N_PODS, N_NS, SEED)
    keys = [f"{p[0]}/{p[1]}" for p in pods]
    rng = random.Random(SEED)
    queries = [
        FlowQuery(src=rng.choice(keys), dst=rng.choice(keys), port=80,
                  protocol="TCP", port_name="serve-80-tcp")
        for _ in range(8)
    ]

    svc = VerdictService(pods, namespaces, [])
    twin = VerdictService(
        pods, namespaces, [], slo=SloController(enforce=False)
    )
    assert svc.slo.enforce, "drill requires CYCLONUS_SLO_ENFORCE armed"
    baseline = [bits(v) for v in twin.query(queries)]

    # phase 1: overload until shed.  Every query is a bad event under
    # the shrunk target, so the budget exhausts within a few scrapes.
    shed_seen = 0
    non_shed_checked = 0
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        out = svc.query(queries)
        if all(v.shed for v in out):
            shed_seen += len(out)
            break
        for v, want in zip(out, baseline):
            assert not v.shed, "partial shed inside one batch"
            assert bits(v) == want, (
                f"PARITY under load: {v.query.src}->{v.query.dst}: "
                f"{bits(v)} != {want}"
            )
            non_shed_checked += 1
        scrape()
        time.sleep(0.05)
    assert shed_seen, "overload never shed (budget did not exhaust)"
    snap = svc.slo_snapshot()
    q = snap["objectives"]["query_p99"]
    assert q["state"] == "exhausted", snap
    assert q["budget_remaining"] == 0.0, snap
    assert snap["shed_queries"] >= shed_seen, snap
    shed_verdict = svc.query(queries[:1])[0]
    assert shed_verdict.shed and shed_verdict.error, shed_verdict
    shed_seen += 1

    # phase 2: load stops; bad events age out of the 4s slow window and
    # the 1s hold expires — the budget must RECOVER, not latch.
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        scrape()
        if svc.slo.query_route() == "live":
            break
        time.sleep(0.2)
    snap = svc.slo_snapshot()
    q = snap["objectives"]["query_p99"]
    assert q["state"] == "ok", f"budget never recovered: {snap}"
    assert q["budget_remaining"] == 1.0, snap

    out = [bits(v) for v in svc.query(queries)]
    assert out == baseline, "post-recovery answers drifted from the twin"
    print(
        f"slo-drill: OK — {non_shed_checked} parity-checked answers "
        f"under load, {shed_seen} shed refusals at exhaustion, budget "
        f"recovered to 1.0 and answers twin-identical again"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
