#!/usr/bin/env python
"""`make audit`: the audit-plane drill against a REAL serve subprocess.

Boots `cyclonus-tpu serve` with the audit plane armed at rate 1.0 and a
metrics port, drives deltas + queries over the stdio wire, and asserts
the whole observable surface from the OUTSIDE — the way a fleet
operator would:

  1. /audit answers 200 with checked > 0, diverged == 0, and a state
     digest for every committed epoch;
  2. /state carries the same audit block, and /metrics exports the
     cyclonus_tpu_audit_* family (checked counter > 0, diverged == 0);
  3. a second replica booted from the SAME synthetic cluster at the
     same churn point reports the SAME epoch digest — the replica-vs-
     replica string equality the digests exist for;
  4. an armed `verdict_corrupt` on a third replica produces a nonzero
     diverged count on /audit within the check budget (detection is
     observable from the outside, not just in the flight recorder).

Wired into `make check` via the `audit` target next to the unit legs in
tests/test_audit.py."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_PODS, N_NS, SEED = 12, 2, 19
CHECK_BUDGET = 24


class Serve:
    """A serve subprocess with the audit plane armed and a metrics
    port; stderr to a file so a chatty child can never deadlock."""

    def __init__(self, tag: str, workdir: str, extra_env=None):
        self.stderr_path = os.path.join(workdir, f"serve-{tag}.stderr")
        self._stderr = open(self.stderr_path, "w")
        env = dict(os.environ)
        env.update({
            "CYCLONUS_AUDIT": "1",
            "CYCLONUS_AUDIT_RATE": "1.0",
            "CYCLONUS_AUDIT_SEED": "5",
            "CYCLONUS_FLIGHT_RECORDER_PATH": os.path.join(
                workdir, f"dump-{tag}.json"
            ),
        })
        env.update(extra_env or {})
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "cyclonus_tpu", "serve",
             "--synthetic-pods", str(N_PODS),
             "--synthetic-namespaces", str(N_NS),
             "--seed", str(SEED),
             "--metrics-port", "0"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=self._stderr, text=True, bufsize=1, env=env, cwd=REPO,
        )
        self.url = self._discover_url()

    def _discover_url(self) -> str:
        """The banner prints the ephemeral port; poll stderr for it."""
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            with open(self.stderr_path) as f:
                for line in f:
                    if "metrics on " in line:
                        return line.split("metrics on ", 1)[1].split(
                            "/metrics", 1
                        )[0]
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"serve died before banner (rc={self.proc.poll()}): "
                    f"{open(self.stderr_path).read()[-500:]}"
                )
            time.sleep(0.05)
        raise RuntimeError("serve never printed its metrics banner")

    def round_trip(self, line: str) -> dict:
        self.proc.stdin.write(line + "\n")
        self.proc.stdin.flush()
        reply = self.proc.stdout.readline()
        if not reply:
            raise RuntimeError(
                f"serve died mid-reply (rc={self.proc.poll()}); stderr: "
                f"{open(self.stderr_path).read()[-500:]}"
            )
        return json.loads(reply)

    def get(self, path: str):
        with urllib.request.urlopen(self.url + path, timeout=10) as r:
            return r.status, json.loads(r.read().decode())

    def get_text(self, path: str) -> str:
        with urllib.request.urlopen(self.url + path, timeout=10) as r:
            return r.read().decode()

    def close(self) -> int:
        try:
            self.proc.stdin.close()
        except OSError:
            pass
        rc = self.proc.wait(timeout=60)
        self._stderr.close()
        return rc

    def kill(self) -> None:
        self.proc.kill()
        self.proc.wait(timeout=30)
        self._stderr.close()


def churn_lines(keys, steps: int, seed: int):
    import random

    from cyclonus_tpu.worker.model import Batch, Delta, FlowQuery

    rng = random.Random(seed)
    for step in range(steps):
        key = keys[rng.randrange(len(keys))]
        ns, name = key.split("/", 1)
        yield Batch(
            namespace="", pod="", container="",
            deltas=[Delta(
                kind="pod_labels", namespace=ns, name=name,
                labels={"pod": f"p{step}", "app": f"a{step % 5}"},
            )],
            queries=[FlowQuery(
                src=keys[rng.randrange(len(keys))],
                dst=keys[rng.randrange(len(keys))],
                port=80, protocol="TCP", port_name="serve-80-tcp",
            )],
        ).to_json()


def wait_audit(srv: Serve, pred, timeout: float = 20.0):
    """Poll /audit until pred(payload) (the worker is async)."""
    deadline = time.monotonic() + timeout
    payload = None
    while time.monotonic() < deadline:
        status, payload = srv.get("/audit")
        assert status == 200, payload
        if pred(payload):
            return payload
        time.sleep(0.1)
    raise AssertionError(f"/audit never satisfied predicate: {payload}")


def main() -> int:
    import tempfile

    from cyclonus_tpu.cli.serve_cmd import synthetic_cluster

    workdir = tempfile.mkdtemp(prefix="cyclonus-audit-drill-")
    pods, _ns = synthetic_cluster(N_PODS, N_NS, SEED)
    keys = [f"{p[0]}/{p[1]}" for p in pods]
    steps = 6

    # 1+2: a clean replica under churn — /audit, /state, /metrics agree
    a = Serve("a", workdir)
    for line in churn_lines(keys, steps, 1):
        reply = a.round_trip(line)
        assert not reply.get("Error"), reply
    snap = wait_audit(a, lambda p: (
        p["checked"] > 0
        and p["queue_depth"] == 0
        and p["pending_digests"] == 0
        and str(steps) in p["digests"]
    ))
    assert snap["enabled"] is True and snap["diverged"] == 0, snap
    assert set(snap["digests"]) == {str(e) for e in range(steps + 1)}, (
        snap["digests"]
    )
    status, st = a.get("/state")
    assert status == 200 and st["audit"]["enabled"] is True, st
    assert st["audit"]["diverged"] == 0, st
    prom = a.get_text("/metrics")
    assert "cyclonus_tpu_audit_checked_total" in prom
    assert "cyclonus_tpu_audit_diverged_total 0" in prom

    # 3: a second replica, same cluster + same churn -> equal digest
    b = Serve("b", workdir)
    for line in churn_lines(keys, steps, 1):
        reply = b.round_trip(line)
        assert not reply.get("Error"), reply
    snap_b = wait_audit(b, lambda p: str(steps) in p["digests"])
    assert snap_b["digests"][str(steps)] == snap["digests"][str(steps)], (
        "replica digests diverged at the same epoch:\n"
        f"  a: {snap['digests'][str(steps)]}\n"
        f"  b: {snap_b['digests'][str(steps)]}"
    )
    rc_a, rc_b = a.close(), b.close()
    assert rc_a == 0 and rc_b == 0, (rc_a, rc_b)

    # 4: armed corruption is detected, observable on /audit
    c = Serve("c", workdir, extra_env={"CYCLONUS_CHAOS": "verdict_corrupt:1"})
    detected = None
    for i, line in enumerate(churn_lines(keys, CHECK_BUDGET, 2)):
        reply = c.round_trip(line)
        assert not reply.get("Error"), reply
        status, payload = c.get("/audit")
        if payload.get("diverged", 0) > 0:
            detected = i + 1
            break
        time.sleep(0.05)
    if detected is None:
        payload = wait_audit(c, lambda p: p["diverged"] > 0, timeout=10.0)
        detected = CHECK_BUDGET
    last = c.get("/audit")[1]["last_divergence"]
    assert last and last["route"].startswith("serve.query."), last
    assert os.path.exists(os.path.join(workdir, "dump-c.json")), (
        "no audit-divergence dump on disk"
    )
    c.kill()

    print(
        f"audit-drill: OK — {int(snap['checked'])} shadow checks clean "
        f"across {steps + 1} epochs, replica digests equal at epoch "
        f"{steps}, injected corruption detected within {detected} "
        f"churn steps (budget {CHECK_BUDGET})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
