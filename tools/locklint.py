#!/usr/bin/env python
"""Lock-discipline static lint: AST checks for the concurrency defect
classes the threaded telemetry/worker/engine paths keep meeting (the
_slab_lock TOCTOU of PR 1 was found by hand; this pass finds its family
mechanically, in the spirit of Clang's Thread Safety Analysis and of the
reference project's `go test -race` gate).

  LK001  guarded-by: an attribute declared `# guarded-by: <lock-expr>`
         (trailing comment on its initializing assignment), via a
         class-level `GUARDED_BY = {"attr": "self.<lock>"}` map, or via
         a `guards.Guarded("<lock>")` descriptor, is read or written
         outside a `with <lock-expr>:` block.  Constructors are exempt
         (construction happens-before publication).  A function whose
         docstring contains `holds-lock: <lock-expr>` or that is
         decorated `@guards.holds("<lock-expr>")` is analyzed with the
         lock held; a private helper whose visible call sites ALL hold
         the lock inherits it one level, like jaxlint's nested-def
         taint.

  LK002  lock-order cycle: the whole-run acquisition graph (nested
         `with` statements, plus calls one level deep into same-module
         functions that acquire) contains a cycle — the classic
         deadlock precondition.  The finding message carries the cycle
         path.  A self-edge (re-acquiring a non-reentrant Lock) is a
         one-node cycle.

  LK003  leaked guard: `<lock>.acquire()` with no matching `release()`
         inside a `finally` block in the same function; or a blocking
         call (time.sleep / subprocess / socket / requests / urlopen /
         kubectl exec / Thread.join / Event.wait) made while a declared
         lock is held — the whole process stalls behind one slow
         syscall.

Lock discovery: module-level `NAME = threading.Lock()` / `RLock()`, and
`self.NAME = threading.Lock()` inside a class, plus anything named by a
guarded-by declaration.  Lock identity for the cycle graph is
`<module>.<Class>.<attr>` / `<module>.<name>`, so two classes' private
`_lock`s never alias.

Suppress a finding with `# locklint: ignore` or
`# locklint: ignore[LK001,...]` on the offending line (same convention
as tools/jaxlint.py).

Usage: python tools/locklint.py [paths...]   (default: cyclonus_tpu)
Exit status 1 iff findings remain.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from lintcore import Finding, ignore_regex, iter_py_files, run_cli
from lintcore import suppress as _core_suppress

_IGNORE_RE = ignore_regex("locklint")
_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")
_HOLDS_DOC_RE = re.compile(r"holds-lock:\s*([A-Za-z_][A-Za-z0-9_.]*)")
# `with m._lock:  # locklint: lock-class Metric` — declares the class
# owning a NON-self lock expression, so the acquisition enters the
# LK002 graph under that class's lock identity (static receiver typing
# is out of scope; the declaration is the Clang-TSA-style answer)
_LOCK_CLASS_RE = re.compile(r"#\s*locklint:\s*lock-class\s+([A-Za-z_][A-Za-z0-9_]*)")

# Call roots / attribute names that block the calling thread.  Holding a
# declared lock across any of these serializes every hot-path thread
# behind one syscall (and, for Event.wait/Thread.join, risks deadlock
# when the waited-on thread needs the same lock).
BLOCKING_ROOTS = {"subprocess", "socket", "requests", "urllib"}
BLOCKING_ATTRS = {
    "sleep",                    # time.sleep
    "execute_remote_command",   # kubectl exec (kube/ikubernetes.py)
    "check_output", "check_call", "communicate", "urlopen",
    "wait", "join",             # Event.wait / Thread.join
}
CONSTRUCTOR_EXEMPT = {"__init__", "__new__", "__set_name__", "__init_subclass__"}


def _expr_str(node: ast.AST) -> str:
    """Normalized source text of a lock expression ('self._lock')."""
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse handles all exprs we meet
        return ""


def _attr_root(node: ast.AST) -> Optional[str]:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_lock_ctor(node: ast.AST) -> bool:
    """threading.Lock() / threading.RLock() / Lock() / RLock() /
    guards.lock() (the ownership-checkable ctor of utils/guards.py)."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in ("Lock", "RLock"):
        return _attr_root(f) == "threading"
    if isinstance(f, ast.Attribute) and f.attr == "lock":
        return _attr_root(f) == "guards"
    if isinstance(f, ast.Name) and f.id in ("Lock", "RLock"):
        return True
    return False


def _is_guarded_ctor(node: ast.AST) -> Optional[str]:
    """`guards.Guarded("_lock")` / `Guarded("_lock")` -> 'self._lock'."""
    if not (isinstance(node, ast.Call) and node.args):
        return None
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None
    )
    if name != "Guarded":
        return None
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return f"self.{arg.value}"
    return None


@dataclass
class ClassModel:
    name: str
    # attr name -> guarding lock expression ("self._lock")
    guarded: Dict[str, str] = field(default_factory=dict)
    locks: Set[str] = field(default_factory=set)  # {"self._lock", ...}
    methods: Dict[str, ast.AST] = field(default_factory=dict)
    bases: List[str] = field(default_factory=list)  # same-module names


@dataclass
class ModuleModel:
    path: str
    modname: str
    lines: List[str]
    classes: Dict[str, ClassModel] = field(default_factory=dict)
    # module-level guarded name -> lock expression ("_lock")
    guarded_globals: Dict[str, str] = field(default_factory=dict)
    module_locks: Set[str] = field(default_factory=set)
    functions: Dict[str, ast.AST] = field(default_factory=dict)


# one acquisition-order edge: lock A held while lock B is acquired
@dataclass(frozen=True)
class Edge:
    src: str  # global lock id
    dst: str
    path: str
    line: int
    col: int


def effective_class_view(
    model: "ModuleModel", cls: Optional["ClassModel"]
) -> Tuple[Dict[str, str], Set[str]]:
    """(guarded map, lock set) merged through same-module base classes,
    subclass declarations winning — Counter.inc mutates Metric's guarded
    `_series`, and the contract must follow the inheritance, not the
    syntactic class."""
    guarded: Dict[str, str] = {}
    locks: Set[str] = set()
    seen: Set[str] = set()

    def visit(c: Optional["ClassModel"]) -> None:
        if c is None or c.name in seen:
            return
        seen.add(c.name)
        for b in c.bases:
            visit(model.classes.get(b))
        guarded.update(c.guarded)
        locks.update(c.locks)

    visit(cls)
    return guarded, locks


def declaring_class(
    model: "ModuleModel", cls: Optional["ClassModel"], expr: str
) -> Optional[str]:
    """Base-most same-module class whose own body declares lock `expr`
    ('self._lock') — lock IDENTITY follows the declaration, so a
    subclass's `with self._lock:` aliases its base's lock in the LK002
    graph (it IS the same object at runtime)."""
    best: List[str] = []
    seen: Set[str] = set()

    def visit(c: Optional["ClassModel"]) -> None:
        if c is None or c.name in seen:
            return
        seen.add(c.name)
        for b in c.bases:
            visit(model.classes.get(b))
        if not best and expr in c.locks:
            best.append(c.name)

    visit(cls)
    if best:
        return best[0]
    return cls.name if cls is not None else None


def _module_name(path: str) -> str:
    rel = os.path.relpath(path).replace(os.sep, "/")
    if rel.endswith(".py"):
        rel = rel[:-3]
    return rel.replace("/", ".")


def _trailing_guard(lines: List[str], lineno: int) -> Optional[str]:
    if 0 < lineno <= len(lines):
        m = _GUARDED_BY_RE.search(lines[lineno - 1])
        if m:
            return m.group(1)
    return None


def build_model(path: str, tree: ast.Module, lines: List[str]) -> ModuleModel:
    model = ModuleModel(path=path, modname=_module_name(path), lines=lines)
    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            model.functions[stmt.name] = stmt
        elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            )
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                if stmt.value is not None and _is_lock_ctor(stmt.value):
                    model.module_locks.add(t.id)
                guard = _trailing_guard(lines, stmt.lineno)
                if guard:
                    model.guarded_globals[t.id] = guard
                    model.module_locks.add(guard.split(".")[-1])
        elif isinstance(stmt, ast.ClassDef):
            cm = ClassModel(name=stmt.name)
            cm.bases = [
                b.id if isinstance(b, ast.Name) else b.attr
                for b in stmt.bases
                if isinstance(b, (ast.Name, ast.Attribute))
            ]
            model.classes[stmt.name] = cm
            for sub in stmt.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    cm.methods[sub.name] = sub
                elif isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if not isinstance(t, ast.Name):
                            continue
                        lock = _is_guarded_ctor(sub.value)
                        if lock:
                            cm.guarded[t.id] = lock
                            cm.locks.add(lock)
                        elif t.id == "GUARDED_BY" and isinstance(
                            sub.value, ast.Dict
                        ):
                            for k, v in zip(sub.value.keys, sub.value.values):
                                if (
                                    isinstance(k, ast.Constant)
                                    and isinstance(k.value, str)
                                    and isinstance(v, ast.Constant)
                                    and isinstance(v.value, str)
                                ):
                                    cm.guarded[k.value] = v.value
                                    cm.locks.add(v.value)
            # self.X = threading.Lock() / guarded-by trailing comments,
            # anywhere inside the class's methods
            for meth in cm.methods.values():
                for node in ast.walk(meth):
                    if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                        continue
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        if not (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            continue
                        if node.value is not None and _is_lock_ctor(node.value):
                            cm.locks.add(f"self.{t.attr}")
                        guard = _trailing_guard(lines, node.lineno)
                        if guard:
                            cm.guarded[t.attr] = guard
                            cm.locks.add(guard)
    return model


def _declared_holds(func: ast.AST) -> Set[str]:
    """Locks a function declares held: docstring `holds-lock: expr`
    lines and `@guards.holds("expr")` decorators."""
    out: Set[str] = set()
    doc = ast.get_docstring(func, clean=False) or ""
    out.update(_HOLDS_DOC_RE.findall(doc))
    for dec in getattr(func, "decorator_list", []):
        if isinstance(dec, ast.Call):
            name = (
                dec.func.attr
                if isinstance(dec.func, ast.Attribute)
                else dec.func.id if isinstance(dec.func, ast.Name) else None
            )
            if name == "holds":
                for a in dec.args:
                    if isinstance(a, ast.Constant) and isinstance(a.value, str):
                        out.add(a.value)
    return out


def _with_locks(stmt: ast.With, known: Set[str]) -> List[str]:
    """Lock expressions acquired by this with-statement (only exprs
    recognized as locks in this module/class)."""
    out = []
    for item in stmt.items:
        expr = _expr_str(item.context_expr)
        if expr in known:
            out.append(expr)
    return out


class FunctionChecker:
    """LK001 + LK003 over ONE function, and acquisition-edge collection
    for the global LK002 graph."""

    def __init__(
        self,
        model: ModuleModel,
        cls: Optional[ClassModel],
        func: ast.AST,
        entry_locks: Set[str],
    ):
        self.model = model
        self.cls = cls
        self.func = func
        self.entry = set(entry_locks) | _declared_holds(func)
        self.findings: List[Finding] = []
        self.edges: List[Edge] = []
        # guarded contract + lock set, merged through base classes
        self.guarded_map, cls_locks = effective_class_view(model, cls)
        # every lock expr this function might name
        self.known: Set[str] = set(model.module_locks) | cls_locks
        self.known |= set(model.guarded_globals.values())
        # non-self lock exprs declared via `# locklint: lock-class C`,
        # mapped to their owning class's lock id for the LK002 graph
        self.foreign: Dict[str, str] = {}
        # released-in-finally set for LK003a
        self._finally_released: Set[str] = set()
        for node in ast.walk(func):
            if isinstance(node, ast.Try):
                for s in node.finalbody:
                    for call in ast.walk(s):
                        if (
                            isinstance(call, ast.Call)
                            and isinstance(call.func, ast.Attribute)
                            and call.func.attr == "release"
                        ):
                            self._finally_released.add(
                                _expr_str(call.func.value)
                            )

    # -- lock identity -----------------------------------------------------

    def lock_id(self, expr: str) -> str:
        if expr in self.foreign:
            return self.foreign[expr]
        if expr.startswith("self.") and self.cls is not None:
            owner = declaring_class(self.model, self.cls, expr)
            return f"{self.model.modname}.{owner}.{expr[5:]}"
        return f"{self.model.modname}.{expr}"

    def _with_locks_here(self, stmt: ast.With) -> List[str]:
        """Lock exprs this with-statement acquires: recognized self./
        module locks, plus non-self `<obj>.<attr>` exprs the line
        declares via `# locklint: lock-class <Class>` (registered under
        that class's lock identity)."""
        out = _with_locks(stmt, self.known)
        line = (
            self.model.lines[stmt.lineno - 1]
            if 0 < stmt.lineno <= len(self.model.lines)
            else ""
        )
        m = _LOCK_CLASS_RE.search(line)
        if m:
            for item in stmt.items:
                expr = _expr_str(item.context_expr)
                if expr in out or not isinstance(
                    item.context_expr, ast.Attribute
                ):
                    continue
                self.foreign[expr] = (
                    f"{self.model.modname}.{m.group(1)}."
                    f"{item.context_expr.attr}"
                )
                self.known.add(expr)
                out.append(expr)
        return out

    # -- traversal ---------------------------------------------------------

    def run(self) -> None:
        held = set(self.entry)
        for stmt in self.func.body:
            self._visit(stmt, held)

    def _visit(self, stmt: ast.AST, held: Set[str]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs get their own checker via the module pass;
            # their bodies run at call time, not under these locks
            return
        if isinstance(stmt, ast.With):
            self._check_exprs(stmt, held)
            # `with A, B:` acquires in order: A is held when B is taken
            inner = set(held)
            for lock in self._with_locks_here(stmt):
                for heldlock in inner:
                    self.edges.append(
                        Edge(
                            self.lock_id(heldlock),
                            self.lock_id(lock),
                            self.model.path,
                            stmt.lineno,
                            stmt.col_offset,
                        )
                    )
                inner.add(lock)
            for s in stmt.body:
                self._visit(s, inner)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._check_node(stmt.test, held)
            # `if not lock.acquire(blocking=False): return` — the TEST
            # runs on every path, so its acquire is held from here to
            # function exit (conservative).  Acquires INSIDE a branch
            # stay scoped to that branch: a shared set would leak an
            # if-body acquire into the else arm and the statements
            # after, silently suppressing LK001 there.
            held |= self._acquired_locks(stmt.test)
            body_held = set(held)
            for s in stmt.body:
                self._visit(s, body_held)
            else_held = set(held)
            for s in stmt.orelse:
                self._visit(s, else_held)
            return
        if isinstance(stmt, ast.For):
            self._check_node(stmt.iter, held)
            self._check_node(stmt.target, held)
            body_held = set(held)
            for s in stmt.body:
                self._visit(s, body_held)
            else_held = set(held)
            for s in stmt.orelse:
                self._visit(s, else_held)
            return
        if isinstance(stmt, ast.Try):
            for s in stmt.body + stmt.orelse + stmt.finalbody:
                self._visit(s, held)
            for h in stmt.handlers:
                for s in h.body:
                    self._visit(s, held)
            return
        # acquire() as (part of) a statement: manual guard — LK003a and
        # held-tracking for the rest of the function body
        self._check_node(stmt, held)
        acq = self._acquired_locks(stmt)
        if acq:
            held |= acq  # held until function exit (conservative)

    def _acquired_locks(self, stmt: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(stmt):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"
            ):
                expr = _expr_str(node.func.value)
                if expr in self.known:
                    out.add(expr)
                    if expr not in self._finally_released:
                        self._add(
                            node,
                            "LK003",
                            f"{expr}.acquire() without a matching "
                            f"release() in a finally block (a raise "
                            f"between them leaks the lock forever)",
                        )
        return out

    # -- node-level checks -------------------------------------------------

    def _check_exprs(self, stmt: ast.With, held: Set[str]) -> None:
        for item in stmt.items:
            self._check_node(item.context_expr, held)

    def _check_node(self, node: ast.AST, held: Set[str]) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute):
                self._check_attr(sub, held)
            elif isinstance(sub, ast.Name):
                self._check_global(sub, held)
            if isinstance(sub, ast.Call):
                self._check_blocking(sub, held)
                self._collect_call_edges(sub, held)

    def _check_attr(self, node: ast.Attribute, held: Set[str]) -> None:
        if self.cls is None:
            return
        if not (isinstance(node.value, ast.Name) and node.value.id == "self"):
            return
        lock = self.guarded_map.get(node.attr)
        if lock is None or lock in held:
            return
        fname = getattr(self.func, "name", "<lambda>")
        if fname in CONSTRUCTOR_EXEMPT:
            return
        verb = "written" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read"
        self._add(
            node,
            "LK001",
            f"self.{node.attr} {verb} without declared guard "
            f"`with {lock}:` ({self.cls.name} guarded-by contract)",
        )

    def _check_global(self, node: ast.Name, held: Set[str]) -> None:
        lock = self.model.guarded_globals.get(node.id)
        if lock is None or lock in held:
            return
        verb = "written" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read"
        self._add(
            node,
            "LK001",
            f"module global {node.id} {verb} without declared guard "
            f"`with {lock}:`",
        )

    def _check_blocking(self, node: ast.Call, held: Set[str]) -> None:
        if not held:
            return
        f = node.func
        blocking = None
        if isinstance(f, ast.Attribute):
            root = _attr_root(f)
            if f.attr in BLOCKING_ATTRS:
                blocking = f.attr
            elif root in BLOCKING_ROOTS:
                blocking = f"{root}.{f.attr}"
        elif isinstance(f, ast.Name) and f.id in BLOCKING_ATTRS:
            blocking = f.id
        if blocking:
            locks = ", ".join(sorted(held))
            self._add(
                node,
                "LK003",
                f"blocking call {blocking}() while holding {locks} "
                f"(every thread contending on the lock stalls behind it)",
            )

    def _collect_call_edges(self, node: ast.Call, held: Set[str]) -> None:
        """One-level interprocedural edges: while holding L, a call to a
        same-module/class function whose body acquires K adds L->K."""
        if not held:
            return
        f = node.func
        callee: Optional[ast.AST] = None
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "self"
            and self.cls is not None
        ):
            callee = self.cls.methods.get(f.attr)
        elif isinstance(f, ast.Name):
            callee = self.model.functions.get(f.id)
        if callee is None:
            return
        for sub in ast.walk(callee):
            if isinstance(sub, ast.With):
                for lock in _with_locks(sub, self.known):
                    for heldlock in held:
                        self.edges.append(
                            Edge(
                                self.lock_id(heldlock),
                                self.lock_id(lock),
                                self.model.path,
                                node.lineno,
                                node.col_offset,
                            )
                        )

    def _add(self, node: ast.AST, code: str, message: str) -> None:
        self.findings.append(
            Finding(
                self.model.path, node.lineno, node.col_offset, code, message
            )
        )


def _call_site_locks(
    model: ModuleModel, cls: Optional[ClassModel], fname: str
) -> Optional[Set[str]]:
    """Locks held at EVERY visible call site of `fname` (one level of
    the jaxlint-style call-site inference: a private helper only ever
    called under the lock is analyzed as lock-held).  None when the
    function has no visible call sites."""
    sites: List[Set[str]] = []
    funcs = (
        list(cls.methods.values()) if cls is not None else []
    ) + list(model.functions.values())
    known: Set[str] = set(model.module_locks) | set(
        model.guarded_globals.values()
    )
    _guarded, cls_locks = effective_class_view(model, cls)
    known |= cls_locks

    def find_calls(node: ast.AST, held: Set[str]) -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            called = (
                f.attr
                if isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "self"
                else f.id if isinstance(f, ast.Name) else None
            )
            if called == fname:
                sites.append(set(held))

    def scan(stmt: ast.AST, held: Set[str]) -> None:
        if isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                find_calls(item.context_expr, held)
            inner = held | set(_with_locks(stmt, known))
            for s in stmt.body:
                scan(s, inner)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            find_calls(stmt.test, held)
            for s in stmt.body + stmt.orelse:
                scan(s, held)
            return
        if isinstance(stmt, ast.For):
            find_calls(stmt.iter, held)
            for s in stmt.body + stmt.orelse:
                scan(s, held)
            return
        if isinstance(stmt, ast.Try):
            for s in stmt.body + stmt.orelse + stmt.finalbody:
                scan(s, held)
            for h in stmt.handlers:
                for s in h.body:
                    scan(s, held)
            return
        find_calls(stmt, held)

    for fn in funcs:
        if getattr(fn, "name", None) == fname:
            continue
        for stmt in fn.body:
            scan(stmt, set())
    if not sites:
        return None
    common = sites[0]
    for s in sites[1:]:
        common &= s
    return common


def _detect_cycles(edges: List[Edge]) -> List[Finding]:
    """DFS over the global acquisition digraph; one finding per distinct
    cycle (canonicalized by rotation)."""
    graph: Dict[str, List[Edge]] = {}
    for e in edges:
        graph.setdefault(e.src, []).append(e)
    findings: List[Finding] = []
    seen_cycles: Set[Tuple[str, ...]] = set()

    for start in sorted(graph):
        path: List[str] = []
        path_edges: List[Edge] = []

        def dfs(node: str) -> None:
            if node in path:
                i = path.index(node)
                cycle = path[i:] + [node]
                canon = tuple(sorted(cycle[:-1]))
                if canon in seen_cycles:
                    return
                seen_cycles.add(canon)
                site = path_edges[-1]
                findings.append(
                    Finding(
                        site.path,
                        site.line,
                        site.col,
                        "LK002",
                        "lock-order cycle (deadlock precondition): "
                        + " -> ".join(cycle),
                    )
                )
                return
            if len(path) > 16:  # graphs here are tiny; belt and braces
                return
            path.append(node)
            for e in graph.get(node, []):
                path_edges.append(e)
                dfs(e.dst)
                path_edges.pop()
            path.pop()

        dfs(start)
    return findings


def analyze_file(path: str) -> Tuple[List[Finding], List[Edge], int]:
    """Per-file pass: (LK001+LK003 findings, acquisition edges, number
    of live guarded-by declarations)."""
    with open(path, "r") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return (
            [Finding(path, e.lineno or 0, 0, "LK000", f"syntax error: {e.msg}")],
            [],
            0,
        )
    lines = source.splitlines()
    model = build_model(path, tree, lines)
    findings: List[Finding] = []
    edges: List[Edge] = []

    def check(func: ast.AST, cls: Optional[ClassModel]) -> None:
        entry: Set[str] = set()
        name = getattr(func, "name", "")
        if name.startswith("_") and name not in CONSTRUCTOR_EXEMPT:
            inherited = _call_site_locks(model, cls, name)
            if inherited:
                entry |= inherited
        checker = FunctionChecker(model, cls, func, entry)
        checker.run()
        findings.extend(checker.findings)
        edges.extend(checker.edges)

    def check_tree(func: ast.AST, cls: Optional[ClassModel]) -> None:
        check(func, cls)
        # nested defs (any depth) each get their own pass in the same
        # class context; their bodies run at call time, not under the
        # parent's lexical locks
        for sub in ast.walk(func):
            if (
                isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                and sub is not func
            ):
                check(sub, cls)

    for fn in model.functions.values():
        check_tree(fn, None)
    for cm in model.classes.values():
        for meth in cm.methods.values():
            check_tree(meth, cm)

    n_guarded = sum(len(c.guarded) for c in model.classes.values()) + len(
        model.guarded_globals
    )
    return _suppress(findings, lines), edges, n_guarded


def _suppress(findings: List[Finding], lines: List[str]) -> List[Finding]:
    return _core_suppress(findings, lines, _IGNORE_RE)


def lint_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, int]]:
    """All three analyses over a file set.  LK002 runs on the UNION of
    every file's acquisition edges: cross-module nesting (telemetry
    calling into utils) is exactly where the interesting cycles live."""
    findings: List[Finding] = []
    edges: List[Edge] = []
    n_guarded = 0
    files = iter_py_files(paths)
    sources: Dict[str, List[str]] = {}
    for path in files:
        f, e, g = analyze_file(path)
        findings.extend(f)
        edges.extend(e)
        n_guarded += g
    cycle_findings = _detect_cycles(edges)
    for cf in cycle_findings:
        if cf.path not in sources:
            try:
                with open(cf.path) as fh:
                    sources[cf.path] = fh.read().splitlines()
            except OSError:
                sources[cf.path] = []
        findings.extend(_suppress([cf], sources[cf.path]))
    stats = {
        "files": len(files),
        "guarded": n_guarded,
        "edges": len(edges),
        "findings": len(findings),
    }
    return findings, stats


def main(argv: Optional[List[str]] = None) -> int:
    return run_cli(
        "locklint",
        __doc__,
        lint_paths,
        ["cyclonus_tpu"],
        lambda findings, stats: (
            f"locklint: {stats['findings']} finding(s), {stats['guarded']} "
            f"guarded attribute(s), {stats['edges']} acquisition edge(s) in "
            f"{stats['files']} file(s)"
        ),
        argv,
    )


if __name__ == "__main__":
    sys.exit(main())
