"""Shared scaffolding of the five linter legs (jaxlint / locklint /
shapelint / cachelint / planlint): the Finding record, the
`# <tool>: ignore[CODE,...]` suppression convention, dedup, the
filesystem walk, and the argparse CLI driver.

Every leg previously carried its own copy of this file's contents; the
behavior here is pinned by the existing test_*lint suites running
unchanged against the importing legs.  Conventions:

  * a Finding renders as `path:line:col: CODE message` (clickable);
  * `# tool: ignore` on the offending line suppresses every code,
    `# tool: ignore[AB001,AB002]` the listed codes only;
  * findings are deduplicated on (path, line, col, code[, message]) and
    reported sorted by position;
  * the CLI lints files/directories (recursive *.py walk, sorted for
    deterministic output), prints findings to stdout, a one-line
    summary to stderr, and exits 1 iff findings remain.

The tools directory is not a package: legs do `import lintcore`, which
resolves because both `python tools/<leg>.py` and the test suites put
this directory on sys.path.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    code: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"


def ignore_regex(tool: str) -> "re.Pattern":
    """The per-tool suppression-comment pattern:
    `# <tool>: ignore` / `# <tool>: ignore[CODE,...]`."""
    return re.compile(rf"#\s*{re.escape(tool)}:\s*ignore(?:\[([A-Z0-9,\s]+)\])?")


def is_suppressed(finding: Finding, line_src: str, ignore_re) -> bool:
    """Does the source line carry an ignore comment covering this code?"""
    m = ignore_re.search(line_src)
    if not m:
        return False
    codes = m.group(1)
    return codes is None or finding.code in {c.strip() for c in codes.split(",")}


def suppress(
    findings: List[Finding],
    lines: List[str],
    ignore_re,
    *,
    key_includes_message: bool = True,
) -> List[Finding]:
    """Dedup + ignore-comment filter over one file's findings, sorted by
    position.  `lines` is the file's source split into lines (used to
    look up each finding's line for the ignore comment).  The dedup key
    includes the message by default (two different defects on one line
    both report); jaxlint passes False to keep its one-per-position
    convention."""
    out = []
    seen = set()
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.code)):
        key = (
            (f.path, f.line, f.col, f.code, f.message)
            if key_includes_message
            else (f.path, f.line, f.col, f.code)
        )
        if key in seen:
            continue
        seen.add(key)
        line_src = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        if is_suppressed(f, line_src, ignore_re):
            continue
        out.append(f)
    return out


def iter_py_files(paths: List[str]) -> List[str]:
    """Recursive, sorted *.py walk over files/directories (deterministic
    lint output is part of the CLI contract)."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                out.extend(
                    os.path.join(root, f)
                    for f in sorted(files)
                    if f.endswith(".py")
                )
        elif p.endswith(".py"):
            out.append(p)
    return out


def run_cli(
    tool: str,
    doc: Optional[str],
    lint_paths: Callable[[List[str]], Tuple[List[Finding], Dict[str, int]]],
    default_paths: List[str],
    summary: Callable[[List[Finding], Dict[str, int]], str],
    argv: Optional[List[str]] = None,
    extra_args: Optional[Callable[[argparse.ArgumentParser], None]] = None,
    post: Optional[Callable[[argparse.Namespace, List[Finding], Dict], None]] = None,
) -> int:
    """The shared argparse driver: positional paths (defaulting per
    leg), findings to stdout sorted by position, `summary(findings,
    stats)` to stderr, exit 1 iff findings.  `extra_args` lets a leg add
    flags (planlint's --manifest); `post` runs after linting with the
    parsed namespace (artifact emission)."""
    ap = argparse.ArgumentParser(description=(doc or "").splitlines()[0])
    ap.add_argument(
        "paths",
        nargs="*",
        default=default_paths,
        help=f"files/directories to lint (default: {' '.join(default_paths)})",
    )
    if extra_args is not None:
        extra_args(ap)
    args = ap.parse_args(argv)
    findings, stats = lint_paths(args.paths)
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col)):
        print(f.render())
    print(summary(findings, stats), file=sys.stderr)
    if post is not None:
        post(args, findings, stats)
    return 1 if findings else 0
