#!/usr/bin/env python
"""`make serve-smoke`: end-to-end smoke of the verdict service.

Starts a REAL `cyclonus-tpu serve` subprocess on a seeded synthetic
cluster, then over its stdin/stdout wire:

  1. applies a policy_upsert delta batch (rule-slab path),
  2. applies a single-pod label flip and asserts the INCREMENTAL path
     took it (reply Mode),
  3. queries a seeded set of flows and asserts every verdict against
     the scalar oracle evaluated over the same post-delta state
     (the driver mirrors the delta stream onto its own copy),
  4. closes stdin and asserts a clean rc=0 shutdown.

Wired into `make check` so the serve wire loop, the incremental encode
path, and the oracle stay pinned together in CI."""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from cyclonus_tpu.analysis.oracle import (  # noqa: E402
    oracle_verdicts,
    traffic_for_cell,
)
from cyclonus_tpu.cli.serve_cmd import synthetic_cluster  # noqa: E402
from cyclonus_tpu.kube.yaml_io import parse_policy_dict  # noqa: E402
from cyclonus_tpu.matcher.builder import build_network_policies  # noqa: E402
from cyclonus_tpu.worker.model import Batch, Delta, FlowQuery  # noqa: E402

N_PODS, N_NS, SEED = 24, 2, 7

POLICY = {
    "apiVersion": "networking.k8s.io/v1",
    "kind": "NetworkPolicy",
    "metadata": {"name": "smoke-allow-app1", "namespace": "ns0"},
    "spec": {
        "podSelector": {"matchLabels": {"app": "app0"}},
        "policyTypes": ["Ingress"],
        "ingress": [
            {
                "from": [{"podSelector": {"matchLabels": {"app": "app1"}}}],
                "ports": [{"protocol": "TCP", "port": 80}],
            }
        ],
    },
}


def main() -> int:
    import random

    pods, namespaces = synthetic_cluster(N_PODS, N_NS, SEED)
    state = {f"{p[0]}/{p[1]}": p for p in pods}
    flip_key = next(iter(state))
    flip_ns, flip_name = flip_key.split("/", 1)
    new_labels = {"pod": "p0", "app": "app1", "tier": "tier0"}

    line1 = Batch(
        namespace="", pod="", container="",
        deltas=[Delta(kind="policy_upsert", namespace="ns0",
                      name="smoke-allow-app1", policy=POLICY)],
    ).to_json()
    line2 = Batch(
        namespace="", pod="", container="",
        deltas=[Delta(kind="pod_labels", namespace=flip_ns,
                      name=flip_name, labels=dict(new_labels))],
    ).to_json()
    rng = random.Random(99)
    keys = list(state)
    queries = [
        FlowQuery(src=rng.choice(keys), dst=rng.choice(keys), port=80,
                  protocol="TCP", port_name="serve-80-tcp")
        for _ in range(12)
    ]
    line3 = Batch(
        namespace="", pod="", container="", queries=queries
    ).to_json()

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "cyclonus_tpu", "serve",
         "--synthetic-pods", str(N_PODS),
         "--synthetic-namespaces", str(N_NS),
         "--seed", str(SEED), "--max-lines", "3"],
        input="\n".join([line1, line2, line3]) + "\n",
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    if proc.returncode != 0:
        print(proc.stderr[-3000:], file=sys.stderr)
        print(f"serve-smoke: FAIL (rc={proc.returncode})")
        return 1
    replies = [json.loads(x) for x in proc.stdout.strip().splitlines()]
    assert len(replies) == 3, replies
    assert replies[0]["Applied"] == 1 and replies[0]["Epoch"] == 1, replies[0]
    assert replies[1]["Applied"] == 1 and replies[1]["Epoch"] == 2, replies[1]
    assert replies[1]["Mode"] == "incremental", (
        f"single-pod delta must take the incremental path: {replies[1]}"
    )

    # mirror the deltas onto the driver's copy and oracle-check verdicts
    p = state[flip_key]
    state[flip_key] = (p[0], p[1], new_labels, p[3])
    policy = build_network_policies(True, [parse_policy_dict(POLICY)])
    plist = list(state.values())
    idx = {f"{p[0]}/{p[1]}": i for i, p in enumerate(plist)}
    verdicts = replies[2]["Verdicts"]
    assert len(verdicts) == len(queries)
    from cyclonus_tpu.engine.api import PortCase

    checked = 0
    for q, v in zip(queries, verdicts):
        assert not v.get("Error"), v
        case = PortCase(q.port, q.port_name, q.protocol)
        want = oracle_verdicts(
            policy,
            traffic_for_cell(
                plist, namespaces, case, idx[q.src], idx[q.dst]
            ),
        )
        got = (v["Ingress"], v["Egress"], v["Combined"])
        assert got == want, (
            f"PARITY: {q.src}->{q.dst}: service={got} oracle={want}"
        )
        assert v["Epoch"] == 2
        checked += 1
    print(
        f"serve-smoke: OK — policy upsert + incremental pod patch + "
        f"{checked} oracle-checked verdicts, clean shutdown"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
