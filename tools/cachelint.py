#!/usr/bin/env python
"""Cache-coherence static lint: AST checks for the two contracts every
compiled/persisted cache in this repo leans on (docs/DESIGN.md "Cache
discipline").  PRs 11-12 made per-shape autotune winners, the module
program caches (_SHARDED_PROGRAMS, _RING_PIPELINES), and the persisted
AOT executable cache the backbone of both perf and restart survival —
and a single value baked into a compiled program but missing from its
cache key silently serves STALE VERDICTS after a delta or a restart,
the wrong-answer failure mode that is strictly worse than a crash.
This pass makes key completeness and never-raise degradation
lint-enforced, the way locks (tools/locklint.py) and tensor shapes
(tools/shapelint.py) already are.

  CC001  trace-baked value not covered by the declared cache key: at an
         `AotProgram(...)` construction, a fill of a module-level
         program-cache dict (`_X_PROGRAMS[key] = fn`), or a
         module-global jit assignment, every closure-captured value and
         `self._*` attribute read baked into the wrapped body must be
         covered by the key — the key/`plan=`/`schedule=` expressions,
         a trailing `# cache-key: a, b, ...` comment, or a
         `cachekeys.program("a", "b")` descriptor
         (cyclonus_tpu/utils/cachekeys.py, the runtime twin).  One
         level of jaxlint-style inference applies both ways: a baked
         name ASSIGNED FROM covered values is covered (n_dev =
         mesh.devices.size), and a value the key DERIVES FROM is
         covered (leaves, treedef = tree_flatten(in_specs) covers
         in_specs when treedef/leaves are in the key).  A module
         program-cache dict with no `# cache-key:` declaration on its
         definition line flags.

  CC002  value-derived cache not registered for invalidation: in a
         class that defines `invalidate_after_patch`, an attribute
         declared `# derived-from: <tokens>` (trailing comment on its
         initializing assignment) with a VALUE token must be reset by
         `invalidate_after_patch`; the special tokens `shapes`
         (program/shape-derived — survives an in-place value patch)
         and `patched` (maintained in place by the patch path itself)
         are exempt.  A cache-patterned attribute (`*cache*`, `*_jit`,
         `*_aot`, `*_buf`, `*_dev`, `*device_tensors`, `*_programs`,
         `*_plan_state`) initialized in `__init__` WITHOUT any
         declaration flags — a new cache cannot silently skip the
         invalidation audit.

  CC003  env/config read on a cached path: os.environ / os.getenv
         reachable from a jit-traced or AotProgram-wrapped body (one
         level of same-module call-site inference) — the value is
         baked at trace time and a later env change silently serves
         the stale program.  The repo pattern is eager resolution
         (CYCLONUS_PACK -> engine._pack at construction).

  CC004  persisted-cache write discipline: in a module that defines
         CACHE_VERSION, a writer (a function calling os.replace) must
         stage through tempfile.mkstemp (atomic tmp + replace), must
         reference CACHE_VERSION and its cache `key` in the entry it
         writes, and a direct `open(path, "w"/"wb")` outside the
         tmp+replace idiom flags; a module with a persisted writer but
         no `# never-raises`-annotated load/read twin flags (a cache
         you can write but not safely read back is a crash on the next
         restart).

  CC005  never-raise contract: a function whose `def` line carries
         `# never-raises` is verified statement by statement — every
         risky statement (a call outside the safe set, a plain-index
         subscript, a raise) must sit under a `try` with a BROAD
         handler (bare / Exception / BaseException), or call only
         other `# never-raises` functions / whitelisted stdlib
         accessors; a broad handler that swallows without incrementing
         a counter (.inc / *count*) or logging flags — degradation
         must leave evidence.

Suppress a finding with `# cachelint: ignore` or
`# cachelint: ignore[CC001,...]` on the offending line (same convention
as tools/jaxlint.py / locklint.py / shapelint.py).

Usage: python tools/cachelint.py [paths...]
       (default: cyclonus_tpu/engine cyclonus_tpu/serve
        cyclonus_tpu/perfobs cyclonus_tpu/chaos)
Exit status 1 iff findings remain.
"""

from __future__ import annotations

import ast
import builtins
import re
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from lintcore import Finding, ignore_regex, iter_py_files, run_cli
from lintcore import suppress as _core_suppress

_IGNORE_RE = ignore_regex("cachelint")
_CACHE_KEY_RE = re.compile(r"#\s*cache-key:\s*(.+)")
_DERIVED_RE = re.compile(r"#\s*derived-from:\s*(.+)")
_NEVER_RAISES_RE = re.compile(r"#\s*never-raises")
_TOKEN_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_.\-]*")

#: derived-from tokens that do NOT demand an invalidate_after_patch
#: reset: `shapes` = program/shape-derived (an in-place value patch
#: keeps it valid), `patched` = the patch path maintains it in place
DERIVED_EXEMPT_TOKENS = {"shapes", "patched"}

#: attribute-name pattern that marks a per-engine cache (CC002's "new
#: cache attribute" heuristic)
_CACHE_ATTR_RE = re.compile(
    r"cache|_jit$|_aot$|_buf$|_dev$|device_tensors$|_programs$"
    r"|_pipelines$|_plan_state$"
)

#: callables whose construction arguments become part of a compiled
#: program (their argument names are trace-baked surface for CC001)
_PROGRAM_CTOR_NAMES = {"jit", "pjit", "shard_map", "shard_map_no_check"}

# -- CC005 whitelists -------------------------------------------------------

#: dotted-call prefixes that cannot realistically raise in these
#: degradation paths (attribute chains joined with '.')
SAFE_CALL_PREFIXES = (
    "os.path.",
    "os.environ.get",
    "os.getpid",
    "time.",
    "math.",
    "hashlib.",
    "logging.getLogger",
    # the central env-flag registry accessors are never-raise by
    # construction (unparseable values degrade to the registered
    # default; tests/test_envflags.py pins it) — both import spellings
    "envflags.get_",
    "utils.envflags.get_",
)
#: bare builtins safe to call with any argument
SAFE_BARE_CALLS = {
    "len", "isinstance", "issubclass", "getattr", "hasattr", "repr",
    "str", "dict", "list", "tuple", "set", "sorted", "min", "max",
    "type", "callable", "id", "bool", "print", "format", "zip",
    "enumerate", "range",
}
#: method names safe on any receiver (string/dict/metric accessors the
#: degradation paths use; .inc/.set/.observe are this repo's own
#: metric ops, which are never-raise by construction)
SAFE_METHOD_ATTRS = {
    "get", "strip", "lower", "upper", "split", "rsplit", "join",
    "startswith", "endswith", "items", "keys", "values", "encode",
    "decode", "hexdigest", "append", "setdefault", "copy", "format",
    "expanduser", "inc", "set", "observe", "warning", "info", "error",
    "exception", "debug", "bit_length",
}
#: handler-body calls that count as swallow EVIDENCE (counter or log)
EVIDENCE_ATTRS = {
    "inc", "observe", "warning", "info", "error", "exception", "debug",
}


def _attr_root(node: ast.AST) -> Optional[str]:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _attr_chain(node: ast.AST) -> Optional[str]:
    """'os.environ.get' for a nested Attribute, None when not rooted at
    a Name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _self_attr(node: ast.AST) -> Optional[str]:
    """'self.x' for Attribute(value=Name('self'))."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return f"self.{node.attr}"
    return None


def _components(text: str) -> List[str]:
    """Parse a `# cache-key:` / `# derived-from:` component list: split
    on commas, keep each item's leading identifier token (a trailing
    parenthetical note is welcome but cannot contain commas)."""
    out = []
    for part in text.split(","):
        m = _TOKEN_RE.search(part)
        if m:
            out.append(m.group(0))
    return out


def _trailing(lines: List[str], lineno: int, regex: re.Pattern) -> Optional[str]:
    if 0 < lineno <= len(lines):
        m = regex.search(lines[lineno - 1])
        if m:
            return m.group(1) if m.groups() else m.group(0)
    return None


def _names_and_self_attrs(expr: ast.AST) -> Set[str]:
    """Every Name load and 'self.x' chain referenced in an expression,
    excluding names the expression binds itself (comprehension targets,
    lambda parameters) — those are expression-local, not references to
    the enclosing scope."""
    bound: Set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.comprehension):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    bound.add(sub.id)
        elif isinstance(node, ast.Lambda):
            a = node.args
            bound |= {x.arg for x in a.posonlyargs + a.args + a.kwonlyargs}
        elif isinstance(node, ast.NamedExpr) and isinstance(
            node.target, ast.Name
        ):
            bound.add(node.target.id)
    out: Set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id not in bound:
            out.add(node.id)
        sa = _self_attr(node)
        if sa:
            out.add(sa)
    out.discard("self")
    return out


def _bound_names(fn: ast.AST) -> Set[str]:
    """Names bound inside a function: params, assignments, imports,
    nested defs, comprehension/loop/with targets."""
    a = fn.args
    bound = {x.arg for x in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        bound.add(a.vararg.arg)
    if a.kwarg:
        bound.add(a.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        bound.add(sub.id)
        elif isinstance(node, (ast.For, ast.comprehension)):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    bound.add(sub.id)
        elif isinstance(node, ast.With):
            for item in node.items:
                if item.optional_vars is not None:
                    for sub in ast.walk(item.optional_vars):
                        if isinstance(sub, ast.Name):
                            bound.add(sub.id)
        elif isinstance(node, ast.Import):
            for al in node.names:
                bound.add(al.asname or al.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for al in node.names:
                bound.add(al.asname or al.name)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            bound.add(node.name)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            bound.add(node.name)
        elif isinstance(node, ast.Lambda):
            la = node.args
            bound |= {x.arg for x in la.posonlyargs + la.args + la.kwonlyargs}
    return bound


def _free_loads(fn: ast.AST) -> Set[str]:
    """Free variables of a def/lambda: Name loads not bound within, plus
    'self.x' attribute reads (the closure-captured surface CC001
    audits).  `self` alone is not free — only its attributes are."""
    bound = _bound_names(fn)
    out: Set[str] = set()
    body = fn.body if isinstance(fn.body, list) else [ast.Expr(fn.body)]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if node.id not in bound:
                    out.add(node.id)
            sa = _self_attr(node)
            if sa:
                out.add(sa)
    out.discard("self")
    return out


class ModuleModel:
    """Per-module facts shared by every check."""

    def __init__(self, path: str, tree: ast.Module, lines: List[str]):
        self.path = path
        self.tree = tree
        self.lines = lines
        self.aliases: Dict[str, str] = {}
        self.module_names: Set[str] = set()
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        #: module-level dict caches: name -> (declared components or
        #: None, definition line)
        self.cache_dicts: Dict[str, Tuple[Optional[List[str]], int]] = {}
        #: module-level plain globals (for the module-global jit check)
        self.global_lines: Dict[str, int] = {}
        self.has_cache_version = False
        self.never_raise_funcs: Set[str] = set()
        self.never_raise_methods: Dict[str, Set[str]] = {}
        # annotation census (the acceptance gate counts live lines)
        self.n_cache_keys = sum(
            1 for ln in lines if _CACHE_KEY_RE.search(ln)
        )
        self.n_derived = sum(1 for ln in lines if _DERIVED_RE.search(ln))
        self.n_never_raises = sum(
            1 for ln in lines if _NEVER_RAISES_RE.search(ln)
        )

        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for al in node.names:
                    self.aliases[al.asname or al.name.split(".")[0]] = al.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for al in node.names:
                    self.aliases[al.asname or al.name] = (
                        f"{node.module}.{al.name}"
                    )

        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[stmt.name] = stmt
                self.module_names.add(stmt.name)
            elif isinstance(stmt, ast.ClassDef):
                self.classes[stmt.name] = stmt
                self.module_names.add(stmt.name)
            elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
                for al in stmt.names:
                    self.module_names.add(
                        al.asname or al.name.split(".")[0]
                    )
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                targets = (
                    stmt.targets
                    if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                for t in targets:
                    if not isinstance(t, ast.Name):
                        continue
                    self.module_names.add(t.id)
                    self.global_lines[t.id] = stmt.lineno
                    if t.id == "CACHE_VERSION":
                        self.has_cache_version = True
                    if isinstance(stmt.value, (ast.Dict, ast.DictComp)) or (
                        isinstance(stmt.value, ast.Call)
                        and isinstance(stmt.value.func, ast.Name)
                        and stmt.value.func.id == "dict"
                    ):
                        decl = _trailing(lines, stmt.lineno, _CACHE_KEY_RE)
                        comps = _components(decl) if decl else None
                        self.cache_dicts[t.id] = (comps, stmt.lineno)

        # never-raises annotations on def lines (functions and methods)
        def scan_defs(body, owner: Optional[str]) -> None:
            for node in body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if _trailing(lines, node.lineno, _NEVER_RAISES_RE):
                        if owner is None:
                            self.never_raise_funcs.add(node.name)
                        else:
                            self.never_raise_methods.setdefault(
                                owner, set()
                            ).add(node.name)
                elif isinstance(node, ast.ClassDef):
                    scan_defs(node.body, node.name)

        scan_defs(tree.body, None)

    def is_exempt_name(self, name: str, local_imports: Set[str]) -> bool:
        """A name that cannot be a trace-baked VALUE: module-level
        bindings (functions, classes, imports, ALL_CAPS constants are
        module-owned, the jaxlint JX004 domain), builtins, and
        function-level imports."""
        if name in local_imports:
            return True
        if name in self.module_names or name in self.aliases:
            return True
        if hasattr(builtins, name):
            return True
        return name.isupper() or (name.startswith("_") and name[1:].isupper())


# -- CC001 -----------------------------------------------------------------


class FunctionSites:
    """CC001 over one function (or the module body pseudo-function):
    find AotProgram / cache-dict-fill / module-global-jit sites, compute
    the baked and covered sets, emit findings."""

    def __init__(
        self,
        model: ModuleModel,
        cls: Optional[ast.ClassDef],
        func: ast.AST,
        body: List[ast.stmt],
    ):
        self.model = model
        self.cls = cls
        self.func = func
        self.body = body
        self.findings: List[Finding] = []
        # local structure
        self.assigns: Dict[str, List[ast.expr]] = {}
        self.local_defs: Dict[str, ast.AST] = {}
        self.local_imports: Set[str] = set()
        self.params: Set[str] = set()
        if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = func.args
            self.params = {
                x.arg for x in a.posonlyargs + a.args + a.kwonlyargs
            }
        def bind(t: ast.AST, value: ast.expr) -> None:
            # only NAME bindings map to the value; a subscript/attribute
            # store does not bind its index/receiver names (treating
            # `CACHE[key] = fn` as an assignment of `key` would leak
            # the program's refs into the covered set backwards)
            if isinstance(t, ast.Name):
                self.assigns.setdefault(t.id, []).append(value)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for el in t.elts:
                    bind(el, value)
            elif isinstance(t, ast.Starred):
                bind(t.value, value)

        for node in [n for s in body for n in ast.walk(s)]:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    bind(t, node.value)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                bind(node.target, node.value)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node is not func:
                    self.local_defs[node.name] = node
            elif isinstance(node, ast.Import):
                for al in node.names:
                    self.local_imports.add(al.asname or al.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                for al in node.names:
                    self.local_imports.add(al.asname or al.name)

    # -- baked-set collection ------------------------------------------

    def _classify(self, name: str, out: Set[str], seen: Set[int]) -> None:
        if name.startswith("self."):
            out.add(name)
            return
        if name in self.local_defs:
            self._add_def_frees(self.local_defs[name], out, seen)
            return
        if self.model.is_exempt_name(name, self.local_imports):
            return
        if name in self.params or name in self.assigns:
            out.add(name)

    def _classify_expr(self, expr: ast.AST, out: Set[str], seen: Set[int]) -> None:
        for n in _names_and_self_attrs(expr):
            self._classify(n, out, seen)

    def _add_def_frees(self, fn: ast.AST, out: Set[str], seen: Set) -> None:
        # namespaced guard: `visit` tracks raw node ids in the same set
        if ("def", id(fn)) in seen:
            return
        seen.add(("def", id(fn)))
        for name in _free_loads(fn):
            self._classify(name, out, seen)
        # default expressions evaluate in the enclosing scope at def
        # time: `def body(t, _n=n_dev)` bakes n_dev
        args = getattr(fn, "args", None)
        if args is not None:
            for d in list(args.defaults) + [d for d in args.kw_defaults if d]:
                self._classify_expr(d, out, seen)

    def _is_program_ctor(self, call: ast.Call) -> bool:
        f = call.func
        name = (
            f.attr
            if isinstance(f, ast.Attribute)
            else f.id if isinstance(f, ast.Name) else None
        )
        return name in _PROGRAM_CTOR_NAMES

    def _is_aot_ctor(self, call: ast.Call) -> bool:
        f = call.func
        name = (
            f.attr
            if isinstance(f, ast.Attribute)
            else f.id if isinstance(f, ast.Name) else None
        )
        return name == "AotProgram"

    def collect_baked(self, expr: ast.AST, seen: Optional[Set[int]] = None) -> Set[str]:
        """The trace-baked surface of a program-constructing expression:
        free variables (and argument-expression names) of every lambda,
        local def, jit/shard_map call, and AotProgram call reachable
        from `expr`.  Plain calls (dict lookups etc.) are ignored — they
        run at fill time, not inside the compiled body."""
        out: Set[str] = set()
        seen = set() if seen is None else seen

        def visit(e: ast.AST) -> None:
            if e is None or id(e) in seen:
                return
            seen.add(id(e))
            if isinstance(e, ast.Lambda):
                self._add_def_frees(e, out, seen)
                return
            if isinstance(e, ast.Name):
                if e.id in self.local_defs:
                    self._add_def_frees(self.local_defs[e.id], out, seen)
                elif e.id in self.assigns:
                    for rhs in self.assigns[e.id]:
                        visit(rhs)
                return
            sa = _self_attr(e)
            if sa is not None and not isinstance(e.ctx, ast.Store):
                # a bound method / closure stored on self, wrapped whole
                out.add(sa)
                return
            if isinstance(e, ast.Call):
                if self._is_program_ctor(e):
                    if e.args:
                        visit(e.args[0])
                    for a in e.args[1:]:
                        self._classify_expr(a, out, seen)
                    for kw in e.keywords:
                        self._classify_expr(kw.value, out, seen)
                    return
                if self._is_aot_ctor(e):
                    if len(e.args) > 1:
                        visit(e.args[1])
                    for kw in e.keywords:
                        self._classify_expr(kw.value, out, seen)
                    return
                # plain call: not program construction — ignore
                return
            if isinstance(e, (ast.Tuple, ast.List)):
                for el in e.elts:
                    visit(el)
                return
            for child in ast.iter_child_nodes(e):
                visit(child)

        visit(expr)
        return out

    # -- covered-set construction --------------------------------------

    def _expand_method(self, call: ast.Call, covered: Set[str]) -> None:
        """plan=self._aot_plan(...) — one level into the same-class
        method: the self attributes its body reads are covered key
        components, and so are the call's own argument names."""
        f = call.func
        if not (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "self"
            and self.cls is not None
        ):
            return
        for sub in self.cls.body:
            if (
                isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                and sub.name == f.attr
            ):
                for node in ast.walk(sub):
                    sa = _self_attr(node)
                    if sa:
                        covered.add(sa)
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            covered.update(_names_and_self_attrs(a))

    def _method_self_attrs(self, ref: str) -> Set[str]:
        """'self.M' -> the self attributes method M of the enclosing
        class reads (empty for non-methods)."""
        if not ref.startswith("self.") or self.cls is None:
            return set()
        meth = ref[5:]
        out: Set[str] = set()
        for sub in self.cls.body:
            if (
                isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                and sub.name == meth
            ):
                for node in ast.walk(sub):
                    sa = _self_attr(node)
                    if sa:
                        out.add(sa)
        return out

    def _comment_components(self, lo: int, hi: int) -> Set[str]:
        out: Set[str] = set()
        for ln in range(lo, hi + 1):
            decl = _trailing(self.model.lines, ln, _CACHE_KEY_RE)
            if decl:
                out.update(_components(decl))
        return out

    def _descriptor_components(self) -> Set[str]:
        """cachekeys.program("a", "b") descriptor calls anywhere in the
        function declare covered components."""
        out: Set[str] = set()
        for stmt in self.body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                chain = _attr_chain(node.func)
                if chain is None or not chain.endswith("cachekeys.program"):
                    if not (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr == "program"
                        and _attr_root(node.func) == "cachekeys"
                    ):
                        continue
                for a in node.args:
                    if isinstance(a, ast.Constant) and isinstance(a.value, str):
                        out.add(a.value)
                for kw in node.keywords:
                    for sub in ast.walk(kw.value):
                        if isinstance(sub, ast.Constant) and isinstance(
                            sub.value, str
                        ):
                            out.add(sub.value)
        return out

    def _close_over_derivations(
        self, baked: Set[str], covered: Set[str]
    ) -> Set[str]:
        """One-level-each-way derivation closure (module docstring):
        forward — a baked local assigned only from covered/exempt names
        is covered; backward — names a covered local's assignment
        references are covered (the key embeds a digest of them)."""
        covered = set(covered)
        for _ in range(6):
            before = len(covered)
            # backward
            for c in list(covered):
                for rhs in self.assigns.get(c, []):
                    for r in _names_and_self_attrs(rhs):
                        if not self.model.is_exempt_name(
                            r, self.local_imports
                        ) or r.startswith("self."):
                            covered.add(r)
                            # self.M where M is a same-class method:
                            # the key derives from its return value, so
                            # the self attributes ITS body reads are
                            # key components too (one level)
                            covered |= self._method_self_attrs(r)
            # forward
            for b in list(baked - covered):
                if b.startswith("self."):
                    continue
                for rhs in self.assigns.get(b, []):
                    refs = {
                        r
                        for r in _names_and_self_attrs(rhs)
                        if r.startswith("self.")
                        or not self.model.is_exempt_name(
                            r, self.local_imports
                        )
                    }
                    if all(r in covered for r in refs):
                        covered.add(b)
                        break
                # a baked self attribute assigned in __init__ cannot be
                # chased here; it must be covered explicitly
            if len(covered) == before:
                break
        return covered

    # -- site checks ----------------------------------------------------

    def _scope_walk(self, stmts: List[ast.stmt]):
        """Walk statements WITHOUT descending into nested function
        defs: each site belongs to exactly one (innermost) scope, whose
        assignment map is the one that resolves its names."""
        stack = list(stmts)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested scope: its own FunctionSites pass owns it
            yield node
            for child in ast.iter_child_nodes(node):
                stack.append(child)

    def check(self) -> None:
        for node in self._scope_walk(self.body):
            if isinstance(node, ast.Call) and self._is_aot_ctor(node):
                self._check_aot_site(node)
            elif isinstance(node, ast.Assign):
                self._check_fill_site(node)

    def _check_aot_site(self, call: ast.Call) -> None:
        name = (
            call.args[0].value
            if call.args
            and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, str)
            else "<?>"
        )
        baked: Set[str] = set()
        if len(call.args) > 1:
            baked = self.collect_baked(call.args[1])
        covered: Set[str] = set()
        for kw in call.keywords:
            if kw.arg in ("plan", "schedule"):
                covered.update(_names_and_self_attrs(kw.value))
                for sub in ast.walk(kw.value):
                    if isinstance(sub, ast.Call):
                        self._expand_method(sub, covered)
            if kw.arg == "static_argnames":
                for sub in ast.walk(kw.value):
                    if isinstance(sub, ast.Constant) and isinstance(
                        sub.value, str
                    ):
                        covered.add(sub.value)
        covered |= self._comment_components(
            call.lineno, call.end_lineno or call.lineno
        )
        covered |= self._descriptor_components()
        covered = self._close_over_derivations(baked, covered)
        for miss in sorted(baked - covered):
            self._add(
                call,
                "CC001",
                f"trace-baked value '{miss}' is not covered by the cache "
                f"key of AotProgram '{name}' (a stale program outlives a "
                f"change to it; put it in plan=/schedule=, list it in a "
                f"trailing `# cache-key:` comment, or pass it as an "
                f"argument)",
            )

    def _check_fill_site(self, stmt: ast.Assign) -> None:
        """`_PROGRAMS[key] = value` fills of module cache dicts, plus
        module-global jit rebinds (`global _JIT; _JIT = jax.jit(...)`)."""
        for t in stmt.targets:
            if (
                isinstance(t, ast.Subscript)
                and isinstance(t.value, ast.Name)
                and t.value.id in self.model.cache_dicts
            ):
                self._check_dict_fill(t.value.id, t.slice, stmt)
            elif (
                isinstance(t, ast.Name)
                and t.id in self.model.global_lines
                and any(
                    isinstance(n, ast.Call) and self._is_program_ctor(n)
                    for n in ast.walk(stmt.value)
                )
            ):
                self._check_global_jit(t.id, stmt)

    def _check_dict_fill(
        self, dict_name: str, key_expr: ast.AST, stmt: ast.Assign
    ) -> None:
        baked = self.collect_baked(stmt.value)
        stores_program = baked or any(
            (isinstance(n, ast.Call) and (self._is_program_ctor(n) or self._is_aot_ctor(n)))
            or isinstance(n, ast.Lambda)
            or (isinstance(n, ast.Name) and n.id in self.local_defs)
            for n in ast.walk(stmt.value)
        )
        comps, decl_line = self.model.cache_dicts[dict_name]
        if comps is None:
            if stores_program:
                self._add(
                    stmt,
                    "CC001",
                    f"module program cache '{dict_name}' (line {decl_line}) "
                    f"has no `# cache-key:` declaration on its definition "
                    f"line",
                )
            if not baked:
                return
            comps = []
        covered: Set[str] = set(comps)
        covered.update(_names_and_self_attrs(key_expr))
        if isinstance(key_expr, ast.Name):
            covered.add(key_expr.id)
        covered |= self._comment_components(
            stmt.lineno, stmt.end_lineno or stmt.lineno
        )
        covered |= self._descriptor_components()
        covered = self._close_over_derivations(baked, covered)
        for miss in sorted(baked - covered):
            self._add(
                stmt,
                "CC001",
                f"trace-baked value '{miss}' is not covered by the key "
                f"stored into module program cache '{dict_name}' (a "
                f"same-key lookup would serve a program compiled for a "
                f"different '{miss}')",
            )

    def _check_global_jit(self, gname: str, stmt: ast.Assign) -> None:
        baked = self.collect_baked(stmt.value)
        covered = self._comment_components(
            stmt.lineno, stmt.end_lineno or stmt.lineno
        )
        decl_line = self.model.global_lines.get(gname)
        if decl_line:
            covered |= {
                c
                for c in _components(
                    _trailing(self.model.lines, decl_line, _CACHE_KEY_RE) or ""
                )
            }
        covered |= self._descriptor_components()
        covered = self._close_over_derivations(baked, covered)
        for miss in sorted(baked - covered):
            self._add(
                stmt,
                "CC001",
                f"module-global program '{gname}' bakes '{miss}' with no "
                f"cache key at all (process-lifetime staleness; declare "
                f"`# cache-key:` or key the program per value)",
            )

    def _add(self, node: ast.AST, code: str, message: str) -> None:
        self.findings.append(
            Finding(
                self.model.path, node.lineno, node.col_offset, code, message
            )
        )


# -- CC002 -----------------------------------------------------------------


def derived_model(
    model: ModuleModel, cls: ast.ClassDef
) -> Tuple[Dict[str, Tuple[List[str], int]], Optional[ast.AST], Set[str]]:
    """(declarations, invalidate_after_patch node, attrs it resets) for
    one class.  Declarations map attr -> (tokens, line) from
    `# derived-from:` trailing comments on `self.X = ...` lines in any
    method."""
    decls: Dict[str, Tuple[List[str], int]] = {}
    invalidate: Optional[ast.AST] = None
    for sub in cls.body:
        if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if sub.name == "invalidate_after_patch":
            invalidate = sub
        for node in ast.walk(sub):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                sa = _self_attr(t)
                if sa is None:
                    continue
                decl = _trailing(model.lines, node.lineno, _DERIVED_RE)
                if decl:
                    attr = sa[5:]
                    if attr not in decls:
                        decls[attr] = (_components(decl), node.lineno)
    reset: Set[str] = set()
    if invalidate is not None:
        for node in ast.walk(invalidate):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    sa = _self_attr(t)
                    if sa:
                        reset.add(sa[5:])
    return decls, invalidate, reset


def check_cc002(model: ModuleModel) -> List[Finding]:
    findings: List[Finding] = []
    for cls in model.classes.values():
        decls, invalidate, reset = derived_model(model, cls)
        if invalidate is None:
            continue
        for attr, (tokens, line) in decls.items():
            value_tokens = [
                t for t in tokens if t not in DERIVED_EXEMPT_TOKENS
            ]
            if value_tokens and attr not in reset:
                findings.append(
                    Finding(
                        model.path,
                        line,
                        0,
                        "CC002",
                        f"{cls.name}.{attr} is declared value-derived "
                        f"(`# derived-from: {', '.join(tokens)}`) but "
                        f"invalidate_after_patch never resets it — a "
                        f"patched buffer would serve its stale contents",
                    )
                )
        # new cache attributes must declare themselves
        init = next(
            (
                s
                for s in cls.body
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
                and s.name == "__init__"
            ),
            None,
        )
        if init is None:
            continue
        for node in ast.walk(init):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                sa = _self_attr(t)
                if sa is None:
                    continue
                attr = sa[5:]
                if _CACHE_ATTR_RE.search(attr) and attr not in decls:
                    findings.append(
                        Finding(
                            model.path,
                            node.lineno,
                            node.col_offset,
                            "CC002",
                            f"cache attribute {cls.name}.{attr} has no "
                            f"`# derived-from:` declaration (new caches "
                            f"must name what they derive from so the "
                            f"invalidation audit sees them; use 'shapes' "
                            f"for program caches, 'patched' for state "
                            f"the patch path maintains in place)",
                        )
                    )
    return findings


# -- CC003 -----------------------------------------------------------------


def _is_env_read(node: ast.Call, aliases: Dict[str, str]) -> Optional[str]:
    chain = _attr_chain(node.func)
    if chain is None:
        return None
    root = chain.split(".")[0]
    resolved = aliases.get(root, root)
    full = ".".join([resolved] + chain.split(".")[1:])
    if full in ("os.environ.get", "os.getenv"):
        return full
    if full.startswith("os.environ"):
        return full
    return None


def _env_subscript(node: ast.Subscript, aliases: Dict[str, str]) -> bool:
    chain = _attr_chain(node.value)
    if chain is None:
        return False
    root = chain.split(".")[0]
    resolved = aliases.get(root, root)
    full = ".".join([resolved] + chain.split(".")[1:])
    return full == "os.environ"


def collect_traced_functions(model: ModuleModel) -> List[ast.AST]:
    """Functions whose bodies trace into a compiled program: jit
    decorated/wrapped defs and lambdas, AotProgram-wrapped local defs,
    shard_map bodies."""
    out: List[ast.AST] = []
    seen: Set[int] = set()
    all_defs: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(model.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            all_defs.setdefault(node.name, []).append(node)

    def add(fn: ast.AST) -> None:
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append(fn)

    def is_jit_expr(e: ast.AST) -> bool:
        if isinstance(e, ast.Attribute) and e.attr in _PROGRAM_CTOR_NAMES:
            return True
        if isinstance(e, ast.Name):
            if e.id in _PROGRAM_CTOR_NAMES:
                return True
            return model.aliases.get(e.id, "").endswith(".jit")
        return False

    for node in ast.walk(model.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if is_jit_expr(dec):
                    add(node)
                elif isinstance(dec, ast.Call) and is_jit_expr(dec.func):
                    add(node)
        elif isinstance(node, ast.Call):
            target = None
            if is_jit_expr(node.func) and node.args:
                target = node.args[0]
            elif (
                isinstance(node.func, (ast.Attribute, ast.Name))
                and (
                    getattr(node.func, "attr", None) == "AotProgram"
                    or getattr(node.func, "id", None) == "AotProgram"
                )
                and len(node.args) > 1
            ):
                target = node.args[1]
            if target is None:
                continue
            if isinstance(target, ast.Lambda):
                add(target)
            elif isinstance(target, ast.Name):
                for fn in all_defs.get(target.id, []):
                    add(fn)
    return out


def check_cc003(model: ModuleModel) -> List[Finding]:
    findings: List[Finding] = []
    traced = collect_traced_functions(model)
    traced_ids = {id(f) for f in traced}

    def env_findings(fn: ast.AST, via: str) -> None:
        body = fn.body if isinstance(fn.body, list) else [ast.Expr(fn.body)]
        for stmt in body:
            for node in ast.walk(stmt):
                hit = None
                if isinstance(node, ast.Call):
                    hit = _is_env_read(node, model.aliases)
                elif isinstance(node, ast.Subscript):
                    if _env_subscript(node, model.aliases):
                        hit = "os.environ[...]"
                if hit:
                    findings.append(
                        Finding(
                            model.path,
                            node.lineno,
                            node.col_offset,
                            "CC003",
                            f"{hit} read on a cached/compiled path{via} — "
                            f"the value bakes in at trace time and a "
                            f"later env change serves the stale program; "
                            f"resolve it eagerly (the CYCLONUS_PACK "
                            f"pattern) and key the program on it",
                        )
                    )

    for fn in traced:
        env_findings(fn, "")
        # one level of same-module call-site inference
        name = getattr(fn, "name", "<lambda>")
        body = fn.body if isinstance(fn.body, list) else [ast.Expr(fn.body)]
        for stmt in body:
            for node in ast.walk(stmt):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in model.functions
                ):
                    callee = model.functions[node.func.id]
                    if id(callee) not in traced_ids:
                        env_findings(
                            callee,
                            f" (helper '{node.func.id}' reached from "
                            f"jit-traced '{name}')",
                        )
    # dedupe (a helper reached from several jit bodies)
    uniq: Dict[Tuple, Finding] = {}
    for f in findings:
        uniq.setdefault((f.path, f.line, f.col, f.code), f)
    return list(uniq.values())


# -- CC004 -----------------------------------------------------------------


def check_cc004(model: ModuleModel) -> List[Finding]:
    if not model.has_cache_version:
        return []
    findings: List[Finding] = []
    writers: List[ast.AST] = []

    def all_funcs():
        for node in ast.walk(model.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    for fn in all_funcs():
        calls = [n for n in ast.walk(fn) if isinstance(n, ast.Call)]
        has_replace = any(
            _attr_chain(c.func) in ("os.replace", "os.rename") for c in calls
        )
        has_mkstemp = any(
            (_attr_chain(c.func) or "").endswith("mkstemp")
            or (_attr_chain(c.func) or "").endswith("NamedTemporaryFile")
            for c in calls
        )
        names = {
            n.id for n in ast.walk(fn) if isinstance(n, ast.Name)
        }
        if has_replace:
            writers.append(fn)
            if not has_mkstemp:
                findings.append(
                    Finding(
                        model.path, fn.lineno, fn.col_offset, "CC004",
                        f"persisted-cache writer '{fn.name}' replaces "
                        f"without a tempfile.mkstemp stage (a reader can "
                        f"observe a half-written entry)",
                    )
                )
            if "CACHE_VERSION" not in names:
                findings.append(
                    Finding(
                        model.path, fn.lineno, fn.col_offset, "CC004",
                        f"persisted-cache writer '{fn.name}' does not "
                        f"embed CACHE_VERSION in the entry (a layout "
                        f"change would load as garbage instead of "
                        f"invalidating)",
                    )
                )
            if not any("key" in n for n in names):
                findings.append(
                    Finding(
                        model.path, fn.lineno, fn.col_offset, "CC004",
                        f"persisted-cache writer '{fn.name}' does not "
                        f"embed its cache key in the entry (a digest "
                        f"collision or stale stamp would load silently)",
                    )
                )
        else:
            for c in calls:
                fname = (
                    c.func.id
                    if isinstance(c.func, ast.Name)
                    else getattr(c.func, "attr", None)
                )
                if fname != "open" or len(c.args) < 2:
                    continue
                mode = c.args[1]
                if isinstance(mode, ast.Constant) and isinstance(
                    mode.value, str
                ) and "w" in mode.value:
                    findings.append(
                        Finding(
                            model.path, c.lineno, c.col_offset, "CC004",
                            f"direct open(..., {mode.value!r}) in a "
                            f"CACHE_VERSION module outside the atomic "
                            f"tmp+os.replace idiom (torn cache entry on "
                            f"a crash mid-write)",
                        )
                    )
    if writers:
        read_twin = any(
            re.match(r"^_?(load|read)", name)
            for name in model.never_raise_funcs
        ) or any(
            re.match(r"^_?(load|read)", m)
            for ms in model.never_raise_methods.values()
            for m in ms
        )
        if not read_twin:
            fn = writers[0]
            findings.append(
                Finding(
                    model.path, fn.lineno, fn.col_offset, "CC004",
                    "persisted write path without a `# never-raises` "
                    "annotated load/read twin (corrupt entries must "
                    "degrade to a fresh build, never crash the restart)",
                )
            )
    return findings


# -- CC005 -----------------------------------------------------------------


class NeverRaiseChecker:
    """Statement-by-statement verification of one `# never-raises`
    function."""

    def __init__(
        self,
        model: ModuleModel,
        cls_name: Optional[str],
        fn: ast.AST,
    ):
        self.model = model
        self.cls_name = cls_name
        self.fn = fn
        self.findings: List[Finding] = []

    def _safe_call(self, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in SAFE_BARE_CALLS:
                return True
            if f.id in self.model.never_raise_funcs:
                return True
            if "count" in f.id:
                return True
            return False
        chain = _attr_chain(f)
        if chain is not None:
            root = chain.split(".")[0]
            resolved = self.model.aliases.get(root, root)
            full = ".".join([resolved] + chain.split(".")[1:])
            for prefix in SAFE_CALL_PREFIXES:
                if full == prefix.rstrip(".") or full.startswith(prefix):
                    return True
            if chain.startswith("self.") and self.cls_name:
                meth = chain.split(".")[1]
                if meth in self.model.never_raise_methods.get(
                    self.cls_name, set()
                ):
                    return True
        if isinstance(f, ast.Attribute) and f.attr in SAFE_METHOD_ATTRS:
            return True
        return False

    def _risky(self, stmt: ast.AST) -> Optional[Tuple[ast.AST, str]]:
        # bounded walk: a nested def/lambda body runs at CALL time, not
        # here — its contents are not this statement's risk
        stack = [stmt]
        nodes = []
        while stack:
            n = stack.pop()
            if isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            nodes.append(n)
            stack.extend(ast.iter_child_nodes(n))
        for node in nodes:
            if isinstance(node, ast.Raise):
                return node, "raise statement"
            if isinstance(node, ast.Call) and not self._safe_call(node):
                name = _attr_chain(node.func) or getattr(
                    node.func, "id", "<call>"
                )
                return node, f"call to {name}()"
            if (
                isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and not isinstance(node.slice, ast.Slice)
            ):
                return node, "plain-index subscript"
        return None

    @staticmethod
    def _broad_handler(h: ast.ExceptHandler) -> bool:
        t = h.type
        if t is None:
            return True
        names = []
        if isinstance(t, ast.Tuple):
            names = [getattr(e, "id", None) for e in t.elts]
        else:
            names = [getattr(t, "id", None)]
        return any(n in ("Exception", "BaseException") for n in names)

    def _handler_has_evidence(self, h: ast.ExceptHandler) -> bool:
        for node in ast.walk(h):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr in EVIDENCE_ATTRS:
                    return True
                if isinstance(f, ast.Name) and "count" in f.id:
                    return True
        return False

    def run(self) -> List[Finding]:
        for stmt in self.fn.body:
            self._visit(stmt)
        return self.findings

    def _visit(self, stmt: ast.AST) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(stmt, ast.Try):
            shielded = any(self._broad_handler(h) for h in stmt.handlers)
            for h in stmt.handlers:
                if self._broad_handler(h) and not self._handler_has_evidence(h):
                    self.findings.append(
                        Finding(
                            self.model.path,
                            h.lineno,
                            h.col_offset,
                            "CC005",
                            f"never-raises '{self._name()}' swallows "
                            f"exceptions without evidence — the handler "
                            f"must increment a counter, log, or re-raise "
                            f"(silent degradation is undebuggable)",
                        )
                    )
            if not shielded:
                for s in stmt.body:
                    self._visit(s)
            for s in stmt.orelse + stmt.finalbody:
                self._visit(s)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            risk = self._risky_expr_only(stmt)
            if risk:
                self._flag(*risk)
            for s in stmt.body + stmt.orelse:
                self._visit(s)
            return
        if isinstance(stmt, ast.For):
            risk = self._risky_expr_only(stmt)
            if risk:
                self._flag(*risk)
            for s in stmt.body + stmt.orelse:
                self._visit(s)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                risk = self._risky(item.context_expr)
                if risk:
                    self._flag(*risk)
            for s in stmt.body:
                self._visit(s)
            return
        risk = self._risky(stmt)
        if risk:
            self._flag(*risk)

    def _risky_expr_only(self, stmt) -> Optional[Tuple[ast.AST, str]]:
        """Risk of a compound statement's OWN expressions (test/iter),
        not its body (visited separately)."""
        expr = stmt.test if isinstance(stmt, (ast.If, ast.While)) else stmt.iter
        return self._risky(expr)

    def _name(self) -> str:
        return getattr(self.fn, "name", "<lambda>")

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(
            Finding(
                self.model.path,
                node.lineno,
                node.col_offset,
                "CC005",
                f"never-raises '{self._name()}' has an unshielded "
                f"{what} — wrap it in a try with a broad handler or "
                f"call only `# never-raises` / whitelisted functions",
            )
        )


def check_cc005(model: ModuleModel) -> List[Finding]:
    findings: List[Finding] = []

    def scan(body, owner: Optional[str]) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                annotated = (
                    node.name in model.never_raise_funcs
                    if owner is None
                    else node.name
                    in model.never_raise_methods.get(owner, set())
                )
                if annotated:
                    findings.extend(
                        NeverRaiseChecker(model, owner, node).run()
                    )
            elif isinstance(node, ast.ClassDef):
                scan(node.body, node.name)

    scan(model.tree.body, None)
    return findings


# -- driver -----------------------------------------------------------------


def analyze_file(path: str) -> Tuple[List[Finding], Dict[str, int]]:
    with open(path, "r") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return (
            [Finding(path, e.lineno or 0, 0, "CC000", f"syntax error: {e.msg}")],
            {"cache_keys": 0, "derived": 0, "never_raises": 0},
        )
    lines = source.splitlines()
    model = ModuleModel(path, tree, lines)
    findings: List[Finding] = []

    # CC001 over every function scope (and the module body); a site is
    # analyzed exactly once, in its innermost enclosing function, whose
    # assignment map is what resolves the baked/covered names
    def run_sites(func, cls, body):
        fs = FunctionSites(model, cls, func, body)
        fs.check()
        findings.extend(fs.findings)

    owning_class: Dict[int, ast.ClassDef] = {}
    for c in model.classes.values():
        for node in ast.walk(c):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                owning_class.setdefault(id(node), c)

    run_sites(tree, None, tree.body)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            run_sites(node, owning_class.get(id(node)), node.body)

    findings.extend(check_cc002(model))
    findings.extend(check_cc003(model))
    findings.extend(check_cc004(model))
    findings.extend(check_cc005(model))

    stats = {
        "cache_keys": model.n_cache_keys,
        "derived": model.n_derived,
        "never_raises": model.n_never_raises,
    }
    return _suppress(findings, lines), stats


def _suppress(findings: List[Finding], lines: List[str]) -> List[Finding]:
    return _core_suppress(findings, lines, _IGNORE_RE)


def lint_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, int]]:
    findings: List[Finding] = []
    totals = {"cache_keys": 0, "derived": 0, "never_raises": 0}
    files = iter_py_files(paths)
    for path in files:
        f, stats = analyze_file(path)
        findings.extend(f)
        for k in totals:
            totals[k] += stats[k]
    totals["files"] = len(files)
    totals["findings"] = len(findings)
    totals["annotations"] = (
        totals["cache_keys"] + totals["derived"] + totals["never_raises"]
    )
    return findings, totals


DEFAULT_PATHS = [
    "cyclonus_tpu/engine",
    "cyclonus_tpu/serve",
    "cyclonus_tpu/perfobs",
    "cyclonus_tpu/chaos",
]


def main(argv: Optional[List[str]] = None) -> int:
    return run_cli(
        "cachelint",
        __doc__,
        lint_paths,
        DEFAULT_PATHS,
        lambda findings, stats: (
            f"cachelint: {stats['findings']} finding(s), "
            f"{stats['cache_keys']} cache-key / {stats['derived']} "
            f"derived-from / {stats['never_raises']} never-raises "
            f"annotation(s) in {stats['files']} file(s)"
        ),
        argv,
    )


if __name__ == "__main__":
    sys.exit(main())
