"""Git-diff-scoped lint driver (`make lint-changed`): run only the
linter legs whose scanned paths intersect the files changed against
HEAD (working tree + index; falls back to the last commit's diff when
the tree is clean, so it is useful right after a commit too).

Leg selection, not path narrowing: a leg whose scope is touched runs
over its FULL path set, because every leg's findings can be cross-file
(a cache-key declared in one module and baked in another, a PathSpec
recorded three files away).  planlint additionally runs whenever the
registry, the Makefile, or a tests/ gate file changes — its PL002/PL005
checks read those directly.  Changing a tools/ file reruns every leg.

Exit 1 if any selected leg fails; prints the legs it skipped.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: (leg name, argv after the script, scanned path prefixes)
LEGS: Tuple[Tuple[str, List[str], List[str]], ...] = (
    (
        "jaxlint",
        ["cyclonus_tpu/engine", "cyclonus_tpu/telemetry",
         "cyclonus_tpu/worker", "cyclonus_tpu/analysis",
         "cyclonus_tpu/probe", "cyclonus_tpu/perfobs",
         "cyclonus_tpu/serve", "cyclonus_tpu/tiers", "cyclonus_tpu/chaos",
         "cyclonus_tpu/linter", "cyclonus_tpu/recipes", "cyclonus_tpu/slo",
         "cyclonus_tpu/audit"],
        ["cyclonus_tpu/"],
    ),
    ("locklint", ["cyclonus_tpu"], ["cyclonus_tpu/"]),
    (
        "shapelint",
        ["cyclonus_tpu/engine", "cyclonus_tpu/analysis",
         "cyclonus_tpu/worker/model.py", "cyclonus_tpu/perfobs",
         "cyclonus_tpu/serve", "cyclonus_tpu/tiers", "cyclonus_tpu/chaos",
         "cyclonus_tpu/linter", "cyclonus_tpu/recipes", "cyclonus_tpu/slo",
         "cyclonus_tpu/audit"],
        ["cyclonus_tpu/engine", "cyclonus_tpu/analysis",
         "cyclonus_tpu/worker/model.py", "cyclonus_tpu/perfobs",
         "cyclonus_tpu/serve", "cyclonus_tpu/tiers", "cyclonus_tpu/chaos",
         "cyclonus_tpu/linter", "cyclonus_tpu/recipes", "cyclonus_tpu/slo",
         "cyclonus_tpu/audit"],
    ),
    (
        "cachelint",
        ["cyclonus_tpu/engine", "cyclonus_tpu/serve",
         "cyclonus_tpu/perfobs", "cyclonus_tpu/chaos",
         "cyclonus_tpu/audit"],
        ["cyclonus_tpu/engine", "cyclonus_tpu/serve",
         "cyclonus_tpu/perfobs", "cyclonus_tpu/chaos",
         "cyclonus_tpu/audit"],
    ),
    (
        "planlint",
        ["--manifest", "artifacts/plan_manifest.json",
         "cyclonus_tpu/engine", "cyclonus_tpu/serve", "cyclonus_tpu/tiers",
         "cyclonus_tpu/slo", "cyclonus_tpu/audit"],
        ["cyclonus_tpu/engine", "cyclonus_tpu/serve", "cyclonus_tpu/tiers",
         "cyclonus_tpu/slo", "cyclonus_tpu/audit", "Makefile", "tests/"],
    ),
    (
        # registry-level leg like planlint: the ST003/ST005 checks read
        # the wire model, the Makefile, and tests/ gate files directly
        "statelint",
        ["cyclonus_tpu/serve", "cyclonus_tpu/audit"],
        ["cyclonus_tpu/serve", "cyclonus_tpu/audit",
         "cyclonus_tpu/worker/model.py", "Makefile", "tests/"],
    ),
    (
        # registry-level leg: WR003 reads the frozen wire_schema.json
        # golden, and the harness gate files live under tests/
        "wirelint",
        ["cyclonus_tpu/worker", "cyclonus_tpu/serve"],
        ["cyclonus_tpu/worker", "cyclonus_tpu/serve", "Makefile",
         "tests/"],
    ),
)


def changed_files() -> List[str]:
    def _git(*args: str) -> List[str]:
        out = subprocess.run(
            ["git", *args], capture_output=True, text=True, cwd=REPO,
        )
        if out.returncode != 0:
            return []
        return [ln.strip() for ln in out.stdout.splitlines() if ln.strip()]

    files = _git("diff", "--name-only", "HEAD")
    files += _git("ls-files", "--others", "--exclude-standard")
    if not files:
        files = _git("diff", "--name-only", "HEAD~1", "HEAD")
    return sorted(set(files))


def legs_for(files: List[str]) -> List[str]:
    if any(f.startswith("tools/") for f in files):
        return [name for name, _a, _p in LEGS]
    selected = []
    for name, _argv, prefixes in LEGS:
        if any(f.startswith(p) for f in files for p in prefixes):
            selected.append(name)
    return selected


def main(argv=None) -> int:
    files = changed_files()
    if not files:
        print("lint-changed: no changed files, nothing to lint",
              file=sys.stderr)
        return 0
    selected = legs_for(files)
    skipped = [n for n, _a, _p in LEGS if n not in selected]
    print(
        f"lint-changed: {len(files)} changed file(s) -> "
        f"leg(s) {selected or ['-']}"
        + (f", skipping {skipped}" if skipped else ""),
        file=sys.stderr,
    )
    rc = 0
    for name, leg_argv, _prefixes in LEGS:
        if name not in selected:
            continue
        proc = subprocess.run(
            [sys.executable, os.path.join("tools", f"{name}.py"), *leg_argv],
            cwd=REPO,
        )
        rc = rc or proc.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
