#!/usr/bin/env python
"""JAX hot-path static lint: AST checks for the device-throughput defect
classes that have repeatedly cost rounds 1-5 their kernel time.

Scans jit-traced functions (decorated with jax.jit / partial(jax.jit),
or passed to a jax.jit(...) call as a named function or lambda) and
flags, via a per-function taint pass seeded from the traced parameters:

  JX001  implicit device->host sync on a traced value: `.item()` /
         `.tolist()`, `float()`/`int()`/`bool()` coercion, or any
         `np.*` call fed a traced argument (np.asarray on a tracer is
         the classic silent round trip)
  JX002  Python control flow on a traced value: `if` / `while` /
         ternary / `assert` whose condition depends on a tracer
         (TracerBoolConversionError at best, silent concretization and
         per-value recompilation at worst)
  JX003  recompilation hazard: jit function with a mutable default
         argument (dict/list/set) — a fresh object per call site makes
         the static-argument cache key unstable
  JX004  recompilation/staleness hazard: jit function closing over a
         module-level array — the array is baked into the compiled
         program as a constant; rebinding the global silently keeps the
         stale weights
  JX005  host callback inside a jit-traced function: jax.debug.print /
         jax.debug.callback / pure_callback / io_callback /
         host_callback — each staged call round-trips device->host
         EVERY step, serializing the dispatch pipeline (fine for a
         debug session, never for a hot path)
  JX006  host-numpy seam one level out: a module-level helper that is
         NOT itself jit-traced, called from a jit body with traced
         arguments, whose body feeds those parameters to `np.*` — the
         call silently falls back to host numpy (np dispatches via
         __array__, concretizing the tracer) even though the helper
         looks like innocent host code in isolation.  This is the
         host/device seam the tensor-contract lint (tools/shapelint.py)
         propagates shapes across.

`static_argnames` / `static_argnums` parameters are exempt from taint
(branching on a static is the whole point of statics), as are shape /
dtype attribute reads (`.shape`, `.ndim`, `.dtype`, `.size`,
`.nbytes`), `is` / `in` tests, and isinstance/len/hasattr conditions.
Nested defs inherit taint through their call sites when visible (a
helper called only with static arguments stays static).

Suppress a finding with `# jaxlint: ignore` or
`# jaxlint: ignore[JX001,...]` on the offending line.

Usage: python tools/jaxlint.py [paths...]   (default: cyclonus_tpu/engine)
Exit status 1 iff findings remain.
"""

from __future__ import annotations

import ast
import sys
from typing import Dict, List, Optional, Set, Tuple

from lintcore import Finding, ignore_regex, iter_py_files, run_cli, suppress

STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes", "itemsize"}
COERCIONS = {"float", "int", "bool", "complex"}
SYNC_METHODS = {"item", "tolist"}
# names that stage a host callback into the compiled program (JX005);
# matched as jax.* attribute chains and as from-imported aliases
HOST_CALLBACKS = {"pure_callback", "io_callback"}
HOST_CALLBACK_MODULES = ("jax.experimental.host_callback",)
EXEMPT_CALLS = {"isinstance", "len", "hasattr", "callable", "getattr", "type"}
MUTABLE_DEFAULTS = (ast.Dict, ast.List, ast.Set, ast.DictComp, ast.ListComp, ast.SetComp)
_IGNORE_RE = ignore_regex("jaxlint")


def _attr_root(node: ast.AST) -> Optional[str]:
    """Base Name id of an attribute chain (jnp.foo.bar -> 'jnp')."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class ModuleInfo:
    """Import aliases, module-level array globals, function defs by name."""

    def __init__(self, tree: ast.Module):
        self.aliases: Dict[str, str] = {}  # local name -> module path
        self.array_globals: Set[str] = set()
        self.funcs: Dict[str, List[ast.FunctionDef]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.funcs.setdefault(node.name, []).append(node)
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
                root = _attr_root(stmt.value.func)
                if root and self.module_kind(root) in ("numpy", "jax"):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            self.array_globals.add(t.id)

    def module_kind(self, name: str) -> Optional[str]:
        """'jax' / 'numpy' for names aliasing those module trees."""
        path = self.aliases.get(name, "")
        if path == "numpy" or path.startswith("numpy."):
            return "numpy"
        if path == "jax" or path.startswith("jax."):
            return "jax"
        return None


def _is_jit_func_expr(info: ModuleInfo, node: ast.AST) -> bool:
    """Does this expression denote jax.jit (or an alias of it)?"""
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        root = _attr_root(node)
        return root is not None and info.module_kind(root) == "jax"
    if isinstance(node, ast.Name):
        return info.aliases.get(node.id, "") in ("jax.jit", "jit")
    return False


def _static_names(call: Optional[ast.Call], func: ast.AST) -> Set[str]:
    """Parameter names marked static via static_argnames/static_argnums."""
    out: Set[str] = set()
    if call is None:
        return out
    params: List[str] = []
    if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        a = func.args
        params = [x.arg for x in a.posonlyargs + a.args]
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    out.add(el.value)
        elif kw.arg == "static_argnums":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, int):
                    if 0 <= el.value < len(params):
                        out.add(params[el.value])
    return out


def collect_jit_functions(
    info: ModuleInfo, tree: ast.Module
) -> List[Tuple[ast.AST, Set[str]]]:
    """(function node, static param names) for every jit-traced function
    discoverable in the module: decorated defs, jax.jit(named_func),
    jax.jit(lambda ...)."""
    out: List[Tuple[ast.AST, Set[str]]] = []
    seen: Set[int] = set()

    def add(node: ast.AST, statics: Set[str]) -> None:
        if id(node) not in seen:
            seen.add(id(node))
            out.append((node, statics))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_func_expr(info, dec):
                    add(node, set())
                elif isinstance(dec, ast.Call):
                    if _is_jit_func_expr(info, dec.func):
                        add(node, _static_names(dec, node))
                    elif (
                        _attr_root(dec.func) is not None
                        and (
                            info.aliases.get(_attr_root(dec.func), "")
                            in ("functools.partial", "partial")
                            or (
                                isinstance(dec.func, ast.Attribute)
                                and dec.func.attr == "partial"
                            )
                        )
                        and dec.args
                        and _is_jit_func_expr(info, dec.args[0])
                    ):
                        add(node, _static_names(dec, node))
        elif isinstance(node, ast.Call) and _is_jit_func_expr(info, node.func):
            if not node.args:
                continue
            target = node.args[0]
            if isinstance(target, ast.Lambda):
                add(target, _static_names(node, target))
            elif isinstance(target, ast.Name):
                for fn in info.funcs.get(target.id, []):
                    add(fn, _static_names(node, fn))
    return out


class TaintChecker:
    """Intra-function taint propagation + finding detection for ONE
    jit-traced function.  Conservative by construction: unknown calls
    with a tainted argument return taint; shape/dtype reads drop it."""

    def __init__(self, info: ModuleInfo, path: str, func: ast.AST, statics: Set[str]):
        self.info = info
        self.path = path
        self.func = func
        self.statics = statics
        self.tainted: Set[str] = set()
        self.locals: Set[str] = set()
        self.findings: List[Finding] = []

    # -- taint seeding ----------------------------------------------------

    def _params(self, func: ast.AST) -> List[str]:
        a = func.args
        names = [x.arg for x in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names

    def run(self) -> List[Finding]:
        for p in self._params(self.func):
            self.locals.add(p)
            if p not in self.statics and p not in ("self", "cls"):
                self.tainted.add(p)
        body = (
            self.func.body
            if isinstance(self.func.body, list)
            else [ast.Expr(self.func.body)]  # lambda
        )
        for _ in range(3):  # fixpoint-ish: late defs feeding earlier loops
            before = set(self.tainted)
            for stmt in body:
                self._propagate(stmt)
            if self.tainted == before:
                break
        for stmt in body:
            self._detect(stmt)
        return self.findings

    # -- expression taint -------------------------------------------------

    def taints(self, e: ast.AST) -> bool:
        if e is None or isinstance(e, (ast.Constant, ast.Lambda)):
            return False
        if isinstance(e, ast.Name):
            return e.id in self.tainted
        if isinstance(e, ast.Attribute):
            if e.attr in STATIC_ATTRS:
                return False
            return self.taints(e.value)
        if isinstance(e, ast.Call):
            root = _attr_root(e.func)
            if root and self.info.module_kind(root) == "jax":
                return True  # jnp./jax./lax. calls produce tracers in jit
            if (
                isinstance(e.func, ast.Name)
                and e.func.id in EXEMPT_CALLS | COERCIONS
            ):
                return False  # host scalars (the coercion itself is JX001)
            return any(self.taints(a) for a in e.args) or any(
                self.taints(k.value) for k in e.keywords
            ) or self.taints(e.func)
        if isinstance(e, ast.Subscript):
            return self.taints(e.value) or self.taints(e.slice)
        if isinstance(e, (ast.BinOp,)):
            return self.taints(e.left) or self.taints(e.right)
        if isinstance(e, ast.UnaryOp):
            return self.taints(e.operand)
        if isinstance(e, ast.BoolOp):
            return any(self.taints(v) for v in e.values)
        if isinstance(e, ast.Compare):
            return self.taints(e.left) or any(self.taints(c) for c in e.comparators)
        if isinstance(e, ast.IfExp):
            return self.taints(e.body) or self.taints(e.orelse)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any(self.taints(el) for el in e.elts)
        if isinstance(e, ast.Dict):
            return any(self.taints(v) for v in e.values if v is not None)
        if isinstance(e, ast.Starred):
            return self.taints(e.value)
        if isinstance(e, (ast.GeneratorExp, ast.ListComp, ast.SetComp, ast.DictComp)):
            return any(self.taints(g.iter) for g in e.generators)
        if isinstance(e, ast.Slice):
            return any(
                self.taints(x) for x in (e.lower, e.upper, e.step) if x is not None
            )
        return False

    def branch_taint(self, e: ast.AST) -> bool:
        """Taint of a CONDITION, after the host-safe exemptions."""
        if isinstance(e, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn)) for op in e.ops
        ):
            return False
        if (
            isinstance(e, ast.Call)
            and isinstance(e.func, ast.Name)
            and e.func.id in EXEMPT_CALLS
        ):
            return False
        if isinstance(e, ast.UnaryOp) and isinstance(e.op, ast.Not):
            return self.branch_taint(e.operand)
        if isinstance(e, ast.BoolOp):
            return any(self.branch_taint(v) for v in e.values)
        return self.taints(e)

    # -- statement-level propagation --------------------------------------

    def _assign_target(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            self.locals.add(target.id)
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._assign_target(el, tainted)
        elif isinstance(target, ast.Starred):
            self._assign_target(target.value, tainted)
        # Subscript/Attribute stores don't rebind a name: skip

    def _seed_nested(self, fn: ast.AST) -> None:
        """Taint a nested def's params from its visible call sites; a
        helper never called in view defaults to all-tainted."""
        params = self._params(fn)
        pos = [x.arg for x in fn.args.posonlyargs + fn.args.args]
        calls = [
            c
            for c in ast.walk(self.func)
            if isinstance(c, ast.Call)
            and isinstance(c.func, ast.Name)
            and isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
            and c.func.id == fn.name
        ]
        if not calls:
            for p in params:
                self.locals.add(p)
                self.tainted.add(p)
            return
        taint_by_name: Dict[str, bool] = {p: False for p in params}
        for c in calls:
            for i, a in enumerate(c.args):
                if i < len(pos):
                    taint_by_name[pos[i]] = taint_by_name[pos[i]] or self.taints(a)
            for kw in c.keywords:
                if kw.arg in taint_by_name:
                    taint_by_name[kw.arg] = taint_by_name[kw.arg] or self.taints(
                        kw.value
                    )
        for p, t in taint_by_name.items():
            self.locals.add(p)
            if t:
                self.tainted.add(p)

    def _propagate(self, stmt: ast.AST) -> None:
        if isinstance(stmt, ast.Assign):
            t = self.taints(stmt.value)
            for target in stmt.targets:
                self._assign_target(target, t)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._assign_target(stmt.target, self.taints(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.target, ast.Name):
                self.locals.add(stmt.target.id)
                if self.taints(stmt.value) or stmt.target.id in self.tainted:
                    self.tainted.add(stmt.target.id)
        elif isinstance(stmt, ast.For):
            self._assign_target(stmt.target, self.taints(stmt.iter))
            for s in stmt.body + stmt.orelse:
                self._propagate(s)
        elif isinstance(stmt, (ast.If, ast.While)):
            for s in stmt.body + stmt.orelse:
                self._propagate(s)
        elif isinstance(stmt, ast.With):
            for s in stmt.body:
                self._propagate(s)
        elif isinstance(stmt, ast.Try):
            for s in stmt.body + stmt.orelse + stmt.finalbody:
                self._propagate(s)
            for h in stmt.handlers:
                for s in h.body:
                    self._propagate(s)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.locals.add(stmt.name)
            self._seed_nested(stmt)
            for s in stmt.body:
                self._propagate(s)

    # -- detection --------------------------------------------------------

    def _add(self, node: ast.AST, code: str, message: str) -> None:
        self.findings.append(
            Finding(self.path, node.lineno, node.col_offset, code, message)
        )

    def _detect(self, stmt: ast.AST) -> None:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                self._check_call(node)
            elif isinstance(node, (ast.If, ast.While)):
                if self.branch_taint(node.test):
                    self._add(
                        node,
                        "JX002",
                        "Python branch on a traced value inside a "
                        "jit-traced function (use jnp.where / lax.cond)",
                    )
            elif isinstance(node, ast.IfExp):
                if self.branch_taint(node.test):
                    self._add(
                        node,
                        "JX002",
                        "ternary on a traced value inside a jit-traced "
                        "function (use jnp.where)",
                    )
            elif isinstance(node, ast.Assert):
                if self.branch_taint(node.test):
                    self._add(
                        node,
                        "JX002",
                        "assert on a traced value inside a jit-traced "
                        "function (use checkify or a host-side check)",
                    )
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if (
                    node.id in self.info.array_globals
                    and node.id not in self.locals
                ):
                    self._add(
                        node,
                        "JX004",
                        f"jit-traced function closes over module-level "
                        f"array '{node.id}' (baked in as a constant; "
                        f"pass it as an argument)",
                    )
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            self._check_defaults(stmt)

    def _host_callback_name(self, f: ast.AST) -> Optional[str]:
        """Dotted name when `f` denotes a host-callback staging function
        (jax.debug.print / jax.debug.callback / pure_callback /
        io_callback / host_callback.*), else None."""
        if isinstance(f, ast.Attribute):
            chain: List[str] = []
            node: ast.AST = f
            while isinstance(node, ast.Attribute):
                chain.append(node.attr)
                node = node.value
            if not isinstance(node, ast.Name):
                return None
            chain.reverse()
            path = self.info.aliases.get(node.id, node.id)
            if not (path == "jax" or path.startswith("jax.")):
                return None
            full = ".".join([path] + chain)
            if full.startswith(HOST_CALLBACK_MODULES):
                return full
            if chain[-1] in HOST_CALLBACKS:
                return full
            if "debug" in full.split(".") and chain[-1] in ("print", "callback"):
                return full
            return None
        if isinstance(f, ast.Name):
            path = self.info.aliases.get(f.id, "")
            if path.startswith("jax") and path.split(".")[-1] in HOST_CALLBACKS:
                return path
            if path.startswith(HOST_CALLBACK_MODULES):
                return path
            if path in ("jax.debug.print", "jax.debug.callback"):
                return path
        return None

    def _check_call(self, node: ast.Call) -> None:
        f = node.func
        cb = self._host_callback_name(f)
        if cb is not None:
            self._add(
                node,
                "JX005",
                f"host callback {cb}() staged into a jit-traced function "
                f"(device->host round trip on every execution; gate it "
                f"behind a debug flag or move it to host code)",
            )
            return
        if (
            isinstance(f, ast.Attribute)
            and f.attr in SYNC_METHODS
            and self.taints(f.value)
        ):
            self._add(
                node,
                "JX001",
                f".{f.attr}() on a traced value forces a device->host "
                f"sync inside a jit-traced function",
            )
            return
        if (
            isinstance(f, ast.Name)
            and f.id in COERCIONS
            and node.args
            and self.taints(node.args[0])
        ):
            self._add(
                node,
                "JX001",
                f"{f.id}() coercion of a traced value forces a "
                f"device->host sync inside a jit-traced function",
            )
            return
        root = _attr_root(f)
        if root and self.info.module_kind(root) == "numpy":
            if any(self.taints(a) for a in node.args) or any(
                self.taints(k.value) for k in node.keywords
            ):
                self._add(
                    node,
                    "JX001",
                    "numpy call on a traced value inside a jit-traced "
                    "function (np.* concretizes: device->host sync; "
                    "use jnp)",
                )

    def _check_defaults(self, fn: ast.AST) -> None:
        a = fn.args
        for default in list(a.defaults) + [d for d in a.kw_defaults if d]:
            bad = isinstance(default, MUTABLE_DEFAULTS) or (
                isinstance(default, ast.Call)
                and isinstance(default.func, ast.Name)
                and default.func.id in ("dict", "list", "set")
            )
            if bad:
                self._add(
                    default,
                    "JX003",
                    "mutable default argument on a jit-traced function "
                    "(unstable cache key: every call risks a retrace)",
                )


def _helper_seam_findings(
    info: ModuleInfo,
    path: str,
    checkers: List[TaintChecker],
    jit_ids: Set[int],
) -> List[Finding]:
    """JX006: one level of call-site inference into non-jit module
    helpers.  For each helper called from a jit body with traced
    arguments, re-run the taint pass over the helper with ONLY those
    parameters traced, and surface its np-on-tracer hits."""
    # defs lexically nested inside a jit body are already covered by the
    # nested-def taint pass (JX001 at the same line) — never re-code them
    nested_ids: Set[int] = set()
    for checker in checkers:
        for node in ast.walk(checker.func):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node is not checker.func:
                    nested_ids.add(id(node))
    # helper name -> (tainted param names, one caller name for the message)
    reached: Dict[str, Tuple[Set[str], str]] = {}
    for checker in checkers:
        caller = getattr(checker.func, "name", "<lambda>")
        for node in ast.walk(checker.func):
            if not (
                isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            ):
                continue
            for callee in info.funcs.get(node.func.id, []):
                if id(callee) in jit_ids or id(callee) in nested_ids:
                    continue  # already linted as/inside a jit function
                pos = [
                    a.arg
                    for a in callee.args.posonlyargs + callee.args.args
                ]
                tainted: Set[str] = set()
                for i, a in enumerate(node.args):
                    if i < len(pos) and checker.taints(a):
                        tainted.add(pos[i])
                for kw in node.keywords:
                    if kw.arg in pos and checker.taints(kw.value):
                        tainted.add(kw.arg)
                if tainted:
                    entry = reached.setdefault(
                        node.func.id, (set(), caller)
                    )
                    entry[0].update(tainted)
    out: List[Finding] = []
    for fname, (tainted, caller) in reached.items():
        for callee in info.funcs.get(fname, []):
            if id(callee) in jit_ids or id(callee) in nested_ids:
                continue
            a = callee.args
            all_params = {
                x.arg for x in a.posonlyargs + a.args + a.kwonlyargs
            }
            sub = TaintChecker(info, path, callee, all_params - tainted)
            for f in sub.run():
                if f.code == "JX001" and "numpy call" in f.message:
                    out.append(
                        Finding(
                            f.path,
                            f.line,
                            f.col,
                            "JX006",
                            f"numpy call on a traced value inside host "
                            f"helper '{fname}' reached from jit-traced "
                            f"'{caller}' (silent host-numpy fallback "
                            f"concretizes the tracer; use jnp or keep "
                            f"np.* out of the traced path)",
                        )
                    )
    return out


def lint_file(path: str) -> List[Finding]:
    with open(path, "r") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, 0, "JX000", f"syntax error: {e.msg}")]
    info = ModuleInfo(tree)
    findings: List[Finding] = []
    jit_funcs = collect_jit_functions(info, tree)
    jit_ids = {id(f) for f, _ in jit_funcs}
    checkers: List[TaintChecker] = []
    for func, statics in jit_funcs:
        # JX003 applies to the jit function's own signature even before
        # the taint pass
        checker = TaintChecker(info, path, func, statics)
        checker._check_defaults(func)
        findings.extend(checker.run())
        checkers.append(checker)
    findings.extend(_helper_seam_findings(info, path, checkers, jit_ids))
    return suppress(
        findings, source.splitlines(), _IGNORE_RE, key_includes_message=False
    )


def lint_paths(paths: List[str]):
    findings: List[Finding] = []
    files = iter_py_files(paths)
    for path in files:
        findings.extend(lint_file(path))
    return findings, {"files": len(files), "findings": len(findings)}


def main(argv: Optional[List[str]] = None) -> int:
    return run_cli(
        "jaxlint",
        __doc__,
        lint_paths,
        ["cyclonus_tpu/engine"],
        lambda findings, stats: (
            f"jaxlint: {len(findings)} finding(s) in {stats['files']} file(s)"
        ),
        argv,
    )


if __name__ == "__main__":
    sys.exit(main())
