#!/usr/bin/env python
"""Attribute the fused counts kernel's time: matmul depth vs grid-step
overhead (VERDICT r3 item 4 groundwork).

The eval floor at the 100k x 10k bench config is ~0.14-0.15 s against a
~0.13 s dense-MXU model (2*q*N^2*(kt_e+kt_i) int8 MACs at 394.7 TOPS).
Two competing explanations for where the next 2x lives:

  A. depth-bound: the contraction (kt_e + kt_i = ~640) dominates; then
     per-src-tile target slabs (depth -> ~256) are worth ~2x.  (An r3
     windowed-slab attempt measured only 10-15%, evidence against.)
  B. step-bound: ~9.6k grid steps x fixed per-step cost (DMA setup,
     epilogue flush) dominate; then depth cuts buy nothing and the acc
     VMEM wall (16 MiB -> >= ~5k steps) is the real ceiling.

This probe separates them on hardware: it runs the SAME pod axis and
grid with the real target depth and with the depth truncated to one
128-lane chunk per direction.  If B, both times are close; if A, the
truncated run is ~(128+128)/(kt_e+kt_i) of the full one.

Usage (needs the TPU; CPU interpret mode would measure nothing real):
    python tools/kernel_probe.py [pods] [policies]
Prints one JSON line per case.
"""

import json
import random
import sys
import time

import numpy as np


def main() -> int:
    n_pods = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    n_pols = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000

    sys.path.insert(0, ".")
    from bench import build_synthetic

    from cyclonus_tpu.engine import PortCase, TpuPolicyEngine
    from cyclonus_tpu.engine.pallas_kernel import (
        sum_partials,
        verdict_counts_pallas_rect,
    )
    from cyclonus_tpu.engine.tiled import _precompute_jit
    from cyclonus_tpu.matcher import build_network_policies

    import os

    import jax

    if jax.default_backend() != "tpu" and os.environ.get("PROBE_ALLOW_CPU") != "1":
        print(json.dumps({"error": "needs TPU (interpret mode measures nothing)"}))
        return 1

    rng = random.Random(20260729)
    pods, namespaces, policies = build_synthetic(n_pods, n_pols, rng)
    policy = build_network_policies(True, policies)
    engine = TpuPolicyEngine(policy, pods, namespaces)
    cases = [PortCase(80, "serve-80-tcp", "TCP"), PortCase(81, "serve-81-udp", "UDP")]
    q = len(cases)

    # the REAL precompute the fast path runs on (compacted, ns-sorted)
    pre = _precompute_jit(engine._tensors_with_cases(cases))
    e, ig = pre["egress"], pre["ingress"]
    args_full = (
        e["tmatch"], e["has_target"], e["tallow_bf"],
        ig["tmatch"], ig["has_target"], ig["tallow_bf"],
    )
    # depth-truncated twin: one 128-lane chunk per direction, same pod
    # axis, same tile grid -> same step count, ~1/5 the MACs
    args_thin = (
        e["tmatch"][:127], e["has_target"], e["tallow_bf"][:127],
        ig["tmatch"][:127], ig["has_target"], ig["tallow_bf"][:127],
    )

    interpret = jax.default_backend() != "tpu"  # CPU smoke only

    def run(args, label):
        out = verdict_counts_pallas_rect(*args, interpret=interpret)
        np.asarray(out)  # readback barrier (block_until_ready lies over the tunnel)
        times = []
        for _ in range(5):
            t0 = time.time()
            out = verdict_counts_pallas_rect(*args, interpret=interpret)
            np.asarray(out)
            times.append(time.time() - t0)
        counts = sum_partials(out, q, 0)
        print(
            json.dumps(
                {
                    "case": label,
                    "t_e": int(args[0].shape[0]),
                    "t_i": int(args[3].shape[0]),
                    "eval_s": round(min(times), 4),
                    "reps": [round(t, 4) for t in times],
                    "combined": counts["combined"],
                }
            ),
            flush=True,
        )
        return min(times)

    full = run(args_full, "full-depth")
    thin = run(args_thin, "thin-depth-128")
    depth_full = int(args_full[0].shape[0]) + int(args_full[3].shape[0])
    print(
        json.dumps(
            {
                "case": "attribution",
                "thin_over_full": round(thin / full, 3),
                "depth_ratio": round(256 / max(depth_full, 1), 3),
                "verdict": "depth-bound (slabs worth it)"
                if thin / full < 0.6
                else "step-bound (cut grid steps, not depth)",
            }
        ),
        flush=True,
    )

    # the actual candidate: per-tile slab kernel, measured via the full
    # engine path (CYCLONUS_PALLAS_SLAB=1) so gather overhead is included.
    # The parity reference MUST be pinned before the env flips — the
    # first engine's slab plan is still unset, and a later counts call
    # would engage the slab path there too, making the check slab-vs-slab.
    os.environ["CYCLONUS_PALLAS_SLAB"] = "0"
    want = engine.evaluate_grid_counts(cases, backend="pallas")
    # apples-to-apples baseline: the DEFAULT kernel through the same
    # engine path (dispatch + pre-cache + host sum included), so the
    # flip decision isn't skewed by engine overhead absent from `full`
    base_times = []
    for _ in range(5):
        t0 = time.time()
        want = engine.evaluate_grid_counts(cases, backend="pallas")
        base_times.append(time.time() - t0)
    base = min(base_times)
    print(
        json.dumps(
            {
                "case": "default-engine-path",
                "eval_s": round(base, 4),
                "reps": [round(t, 4) for t in base_times],
            }
        ),
        flush=True,
    )
    os.environ["CYCLONUS_PALLAS_SLAB"] = "1"
    slab_engine = TpuPolicyEngine(policy, pods, namespaces)
    counts = slab_engine.evaluate_grid_counts(cases, backend="pallas")
    if slab_engine._slab_plan_state is None:
        print(json.dumps({"case": "slab", "skipped": "plan ineligible"}))
        return 0
    times = []
    for _ in range(5):
        t0 = time.time()
        counts = slab_engine.evaluate_grid_counts(cases, backend="pallas")
        times.append(time.time() - t0)
    print(
        json.dumps(
            {
                "case": "slab-engine-path",
                "eval_s": round(min(times), 4),
                "reps": [round(t, 4) for t in times],
                "speedup_vs_default_path": round(base / min(times), 2),
                "counts_match_default": counts == want,
            }
        )
    )
    if counts != want:
        print(json.dumps({"error": "SLAB COUNTS MISMATCH", "slab": counts, "want": want}))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
