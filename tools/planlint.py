"""planlint: static model of the evaluator dispatch surface.

The fifth linter leg (jaxlint / locklint / shapelint / cachelint /
planlint — shared scaffolding in tools/lintcore.py).  The runtime twin
is cyclonus_tpu/engine/planspec.py: a declarative registry of evaluator
paths (PathSpec) and pairwise feature-compatibility cells (Interaction)
that engine/api.py's dispatch actually reads.  planlint extracts BOTH
sides statically — the declarations from planspec.py's AST, the
dispatch graph from the scanned engine/serve modules — and
cross-checks them:

  PL001  route-recorder literal (planspec.record("...")) that names no
         declared PathSpec, or a record() call whose argument is not a
         string literal (statically unverifiable route).
  PL002  declared path with no differential gate: gate empty, or the
         referenced tests/ file / make target does not exist.
  PL003  feature interaction reachable in dispatch (two governing
         features combined in one boolean test, or a matrix-backed
         resolver call) with no declared Interaction cell.
  PL004  determinism hazard on a verdict-affecting path (a function
         that constructs tensors): set-display/set()/set-comprehension
         iteration order feeding the function, module-level unseeded
         rng reads (random.*, np.random.*), wall-clock time.time()
         reads, or unordered (set-sourced) float accumulation.  Seeded
         generator INSTANCES (random.Random(k)) and monotonic clocks
         (perf_counter) are not hazards.
  PL005  declared PathSpec no scanned record() literal ever routes to
         (dead declaration).

Suppress a finding with `# planlint: ignore[PL00X]` on the offending
line.  `--manifest PATH` additionally emits the extracted registry as
JSON (the plan manifest tests/test_planlint.py schema-checks and `make
planlint` writes to artifacts/plan_manifest.json).

Run: python tools/planlint.py [--manifest artifacts/plan_manifest.json] [paths...]
"""

from __future__ import annotations

import ast
import json
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from lintcore import Finding, ignore_regex, iter_py_files, run_cli, suppress

_IGNORE_RE = ignore_regex("planlint")

DEFAULT_PATHS = [
    "cyclonus_tpu/engine",
    "cyclonus_tpu/serve",
    "cyclonus_tpu/tiers",
]

PLANSPEC_BASENAME = "planspec.py"


# --------------------------------------------------------------------------
# Registry extraction: planspec.py's PATHS / INTERACTIONS tuples are
# literal PathSpec(...) / Interaction(...) calls — read them off the
# AST so the lint needs no runtime import (and a syntax error in the
# package cannot take the linter down with it).
# --------------------------------------------------------------------------

@dataclass
class SpecDecl:
    name: str
    entry: str
    gate: str
    coverage: str
    line: int
    fields: Dict[str, object] = field(default_factory=dict)


@dataclass
class InterDecl:
    a: str
    b: str
    verdict: str
    line: int
    fields: Dict[str, object] = field(default_factory=dict)


@dataclass
class Registry:
    path: str = ""
    stages: Tuple[str, ...] = ()
    specs: List[SpecDecl] = field(default_factory=list)
    inters: List[InterDecl] = field(default_factory=list)

    def spec_names(self) -> Set[str]:
        return {s.name for s in self.specs}

    def has_cell(self, a: str, b: str) -> bool:
        for i in self.inters:
            if (i.a, i.b) == (a, b) or (i.a, i.b) == (b, a):
                return True
        return False


def _literal(node: ast.AST):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def _call_kwargs(call: ast.Call, positional: List[str]) -> Dict[str, object]:
    out: Dict[str, object] = {}
    for i, arg in enumerate(call.args):
        if i < len(positional):
            out[positional[i]] = _literal(arg)
    for kw in call.keywords:
        if kw.arg:
            out[kw.arg] = _literal(kw.value)
    return out


def load_registry(planspec_path: str) -> Optional[Registry]:
    try:
        with open(planspec_path, "r") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return None
    reg = Registry(path=planspec_path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "STAGES":
                    val = _literal(node.value)
                    if isinstance(val, tuple):
                        reg.stages = val
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else getattr(fn, "attr", "")
        if name == "PathSpec":
            kw = _call_kwargs(node, ["name", "entry"])
            reg.specs.append(
                SpecDecl(
                    name=str(kw.get("name") or ""),
                    entry=str(kw.get("entry") or ""),
                    gate=str(kw.get("gate") or ""),
                    coverage=str(kw.get("coverage") or "tier1"),
                    line=node.lineno,
                    fields=kw,
                )
            )
        elif name == "Interaction":
            kw = _call_kwargs(node, ["a", "b", "verdict"])
            reg.inters.append(
                InterDecl(
                    a=str(kw.get("a") or ""),
                    b=str(kw.get("b") or ""),
                    verdict=str(kw.get("verdict") or ""),
                    line=node.lineno,
                    fields=kw,
                )
            )
    return reg


def find_planspec(paths: List[str]) -> Optional[str]:
    """Locate planspec.py: inside a scanned directory, else relative to
    the repo root the scanned paths live under."""
    for p in paths:
        if os.path.isdir(p):
            cand = os.path.join(p, PLANSPEC_BASENAME)
            if os.path.exists(cand):
                return cand
        elif os.path.basename(p) == PLANSPEC_BASENAME:
            return p
    # walk up from the first path to a dir holding cyclonus_tpu/engine
    anchor = os.path.abspath(paths[0]) if paths else os.getcwd()
    cur = anchor if os.path.isdir(anchor) else os.path.dirname(anchor)
    for _ in range(6):
        cand = os.path.join(cur, "cyclonus_tpu", "engine", PLANSPEC_BASENAME)
        if os.path.exists(cand):
            return cand
        cur = os.path.dirname(cur)
    return None


# --------------------------------------------------------------------------
# Dispatch-graph extraction from the scanned modules.
# --------------------------------------------------------------------------

def _func_name(call: ast.Call) -> str:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def _attr_chain(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


# A resolver call is a matrix read: the cell it consults must exist.
RESOLVER_CELLS = {
    "resolve_counts_backend": ("tiers", "backend=pallas"),
    "resolve_sharded_counts_kernel": ("tiers", "kernel=pallas"),
}

# Governing-feature signals recognized inside one boolean test.
_ATTR_FEATURES = {
    "tiers": "tiers",
    "_class_state": "classes",
    "_pack": "pack",
    "_slab_plan_state": "slab",
}
_CALL_FEATURES = {
    "_class_counts_eligible": "over_budget",
    "_packed_tier_ok": "packed_tier_ok",
    "_pre_cache_enabled": "pre_cache=0",
}
_NAME_FEATURES = {
    "slab_ok": "slab",
}


def _features_in(node: ast.AST) -> Set[str]:
    feats: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _ATTR_FEATURES:
            feats.add(_ATTR_FEATURES[sub.attr])
        elif isinstance(sub, ast.Call):
            fn = _func_name(sub)
            if fn in _CALL_FEATURES:
                feats.add(_CALL_FEATURES[fn])
            elif fn == "is_set" and _attr_chain(sub.func).startswith(
                "self._ready"
            ):
                feats.add("warming")
        elif isinstance(sub, ast.Name) and sub.id in _NAME_FEATURES:
            feats.add(_NAME_FEATURES[sub.id])
        elif isinstance(sub, ast.Compare) and isinstance(sub.left, ast.Name):
            if sub.left.id in ("backend", "kernel"):
                for cmp in sub.comparators:
                    val = _literal(cmp)
                    if val == "pallas":
                        feats.add(f"{sub.left.id}=pallas")
                    elif val == "xla" and isinstance(
                        sub.ops[0], (ast.NotEq, ast.IsNot)
                    ):
                        feats.add(f"{sub.left.id}=pallas")
    return feats


_TENSOR_CTORS = {
    "array", "asarray", "stack", "concatenate", "zeros", "ones", "full",
    "arange", "frombuffer", "device_put",
}


def _is_tensor_ctor(call: ast.Call) -> bool:
    fn = call.func
    if not isinstance(fn, ast.Attribute) or fn.attr not in _TENSOR_CTORS:
        return False
    root = _attr_chain(fn).split(".", 1)[0]
    return root in ("np", "numpy", "jnp", "jax")


_RNG_MODULES = ("random", "np.random", "numpy.random", "_random")


def _is_unseeded_rng(call: ast.Call) -> bool:
    """Module-level rng read (random.sample(...), np.random.rand(...)).
    Constructing a seeded generator (Random(k), default_rng(k),
    RandomState(k)) is NOT a hazard — the hazard is drawing from global
    unseeded state on a verdict path."""
    fn = call.func
    if not isinstance(fn, ast.Attribute):
        return False
    chain = _attr_chain(fn)
    mod, _, leaf = chain.rpartition(".")
    if mod not in _RNG_MODULES:
        return False
    return leaf not in ("Random", "SystemRandom", "default_rng", "RandomState", "seed")


def _contains_set_source(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Set, ast.SetComp)):
            return True
        if isinstance(sub, ast.Call) and _func_name(sub) in ("set", "frozenset"):
            return True
    return False


@dataclass
class ModuleScan:
    path: str
    record_literals: List[Tuple[str, int, int]] = field(default_factory=list)
    record_dynamic: List[Tuple[int, int]] = field(default_factory=list)
    resolver_calls: List[Tuple[str, int, int]] = field(default_factory=list)
    feature_pairs: List[Tuple[str, str, int, int]] = field(default_factory=list)
    hazards: List[Finding] = field(default_factory=list)
    lines: List[str] = field(default_factory=list)


def scan_module(path: str, source: str) -> Optional[ModuleScan]:
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    scan = ModuleScan(path=path, lines=source.splitlines())

    # record() literals + resolver calls + interaction tests
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            chain = _attr_chain(fn) if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else ""
            )
            leaf = chain.rsplit(".", 1)[-1]
            if leaf == "record" and chain.endswith(("planspec.record",)):
                if node.args and isinstance(node.args[0], ast.Constant) and (
                    isinstance(node.args[0].value, str)
                ):
                    scan.record_literals.append(
                        (node.args[0].value, node.lineno, node.col_offset)
                    )
                else:
                    scan.record_dynamic.append((node.lineno, node.col_offset))
            elif leaf in RESOLVER_CELLS:
                scan.resolver_calls.append(
                    (leaf, node.lineno, node.col_offset)
                )
        test = None
        if isinstance(node, (ast.If, ast.While)):
            test = node.test
        elif isinstance(node, ast.IfExp):
            test = node.test
        elif isinstance(node, ast.BoolOp):
            test = node
        if test is not None:
            feats = sorted(_features_in(test))
            for i in range(len(feats)):
                for j in range(i + 1, len(feats)):
                    scan.feature_pairs.append(
                        (feats[i], feats[j], test.lineno, test.col_offset)
                    )

    # PL004: determinism hazards, scoped to tensor-constructing functions
    for fnode in ast.walk(tree):
        if not isinstance(fnode, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        builds_tensors = any(
            isinstance(sub, ast.Call) and _is_tensor_ctor(sub)
            for sub in ast.walk(fnode)
        )
        if not builds_tensors:
            continue
        for sub in ast.walk(fnode):
            if isinstance(sub, ast.For) and _contains_set_source(sub.iter):
                scan.hazards.append(Finding(
                    path, sub.lineno, sub.col_offset, "PL004",
                    f"set-iteration order feeds tensor-constructing "
                    f"function {fnode.name!r} (wrap in sorted())",
                ))
            elif isinstance(sub, ast.Call):
                fn_name = _func_name(sub)
                chain = _attr_chain(sub.func) if isinstance(
                    sub.func, ast.Attribute
                ) else fn_name
                if _is_unseeded_rng(sub):
                    scan.hazards.append(Finding(
                        path, sub.lineno, sub.col_offset, "PL004",
                        f"unseeded rng read {chain!r} on a verdict-"
                        f"affecting path ({fnode.name!r}); draw from a "
                        f"seeded generator instance",
                    ))
                elif chain in ("time.time", "_time.time", "datetime.now"):
                    scan.hazards.append(Finding(
                        path, sub.lineno, sub.col_offset, "PL004",
                        f"wall-clock read {chain!r} on a verdict-"
                        f"affecting path ({fnode.name!r})",
                    ))
                elif fn_name == "sum" and sub.args and _contains_set_source(
                    sub.args[0]
                ):
                    scan.hazards.append(Finding(
                        path, sub.lineno, sub.col_offset, "PL004",
                        f"unordered accumulation over a set in "
                        f"{fnode.name!r} (float sum order is "
                        f"iteration order)",
                    ))
    return scan


# --------------------------------------------------------------------------
# Cross-checks.
# --------------------------------------------------------------------------

def _repo_root_for(planspec_path: str) -> str:
    # .../cyclonus_tpu/engine/planspec.py -> repo root
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(planspec_path)
    )))


def _gate_exists(gate: str, root: str) -> bool:
    if gate.startswith("tests/"):
        return os.path.exists(os.path.join(root, gate))
    if gate.startswith("make "):
        target = gate.split(None, 1)[1]
        mk = os.path.join(root, "Makefile")
        if not os.path.exists(mk):
            return False
        with open(mk) as f:
            return re.search(
                rf"^{re.escape(target)}:", f.read(), re.MULTILINE
            ) is not None
    return False


def lint_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, object]]:
    files = iter_py_files(paths)
    planspec_path = find_planspec(paths)
    findings: List[Finding] = []
    if planspec_path is None:
        findings.append(Finding(
            paths[0] if paths else ".", 0, 0, "PL001",
            "cyclonus_tpu/engine/planspec.py not found: the dispatch "
            "surface has no declared registry to lint against",
        ))
        return findings, {"files": len(files), "paths": 0, "interactions": 0,
                          "records": 0, "findings": len(findings)}
    reg = load_registry(planspec_path)
    if reg is None or not reg.specs:
        findings.append(Finding(
            planspec_path, 0, 0, "PL001",
            "planspec registry unparseable or empty",
        ))
        return findings, {"files": len(files), "paths": 0, "interactions": 0,
                          "records": 0, "findings": len(findings)}

    root = _repo_root_for(planspec_path)
    declared = reg.spec_names()
    recorded: Set[str] = set()
    per_file: List[Tuple[ModuleScan, List[Finding]]] = []

    for path in files:
        if os.path.basename(path) == PLANSPEC_BASENAME:
            continue  # the registry itself is not a dispatch site
        with open(path, "r") as f:
            source = f.read()
        scan = scan_module(path, source)
        if scan is None:
            findings.append(Finding(path, 0, 0, "PL000", "syntax error"))
            continue
        file_findings: List[Finding] = []
        for name, line, col in scan.record_literals:
            recorded.add(name)
            if name not in declared:
                file_findings.append(Finding(
                    path, line, col, "PL001",
                    f"route target {name!r} is not a declared PathSpec",
                ))
        for line, col in scan.record_dynamic:
            file_findings.append(Finding(
                path, line, col, "PL001",
                "planspec.record() argument is not a string literal: "
                "the route cannot be statically verified",
            ))
        for resolver, line, col in scan.resolver_calls:
            a, b = RESOLVER_CELLS[resolver]
            if not reg.has_cell(a, b):
                file_findings.append(Finding(
                    path, line, col, "PL003",
                    f"dispatch resolves the ({a!r}, {b!r}) interaction "
                    f"but the compatibility matrix declares no such cell",
                ))
        seen_pairs: Set[Tuple[str, str, int]] = set()
        for a, b, line, col in scan.feature_pairs:
            key = (a, b, line)
            if key in seen_pairs:
                continue
            seen_pairs.add(key)
            if not reg.has_cell(a, b):
                file_findings.append(Finding(
                    path, line, col, "PL003",
                    f"dispatch combines features {a!r} x {b!r} but the "
                    f"compatibility matrix declares no such cell",
                ))
        file_findings.extend(scan.hazards)
        per_file.append((scan, file_findings))
        findings.extend(
            suppress(file_findings, scan.lines, _IGNORE_RE)
        )

    # registry-side checks (anchored at the declaration lines; the
    # registry file's own ignore comments apply)
    reg_findings: List[Finding] = []
    for spec in reg.specs:
        if not spec.gate:
            reg_findings.append(Finding(
                planspec_path, spec.line, 0, "PL002",
                f"path {spec.name!r} declares no differential gate",
            ))
        elif not _gate_exists(spec.gate, root):
            reg_findings.append(Finding(
                planspec_path, spec.line, 0, "PL002",
                f"path {spec.name!r} gate {spec.gate!r} does not exist "
                f"(want an existing tests/ file or make target)",
            ))
        if spec.name not in recorded:
            reg_findings.append(Finding(
                planspec_path, spec.line, 0, "PL005",
                f"declared path {spec.name!r} is unreachable: no "
                f"scanned dispatch site records it",
            ))
    with open(planspec_path, "r") as f:
        reg_lines = f.read().splitlines()
    findings.extend(suppress(reg_findings, reg_lines, _IGNORE_RE))

    n_records = sum(len(s.record_literals) for s, _ in per_file)
    stats = {
        "files": len(files),
        "paths": len(reg.specs),
        "interactions": len(reg.inters),
        "records": n_records,
        "findings": len(findings),
        "registry": reg,
        "planspec_path": planspec_path,
    }
    return (
        sorted(findings, key=lambda f: (f.path, f.line, f.col, f.code)),
        stats,
    )


# --------------------------------------------------------------------------
# Manifest emission.
# --------------------------------------------------------------------------

def build_manifest(reg: Registry) -> Dict:
    return {
        "version": 1,
        "entries": sorted({s.entry for s in reg.specs}),
        "stages": list(reg.stages),
        "paths": [
            {
                "name": s.name,
                "entry": s.entry,
                "stages": list(s.fields.get("stages") or ()),
                "flags": list(s.fields.get("flags") or ()),
                "ctor_args": list(s.fields.get("ctor_args") or ()),
                "cache_key_family": s.fields.get("cache_key_family") or "",
                "gate": s.gate,
                "backends": list(s.fields.get("backends") or ("cpu", "tpu")),
                "coverage": s.coverage,
                "when": dict(s.fields.get("when") or {}),
            }
            for s in reg.specs
        ],
        "interactions": [
            {
                "a": i.a,
                "b": i.b,
                "verdict": i.verdict,
                "on_explicit": i.fields.get("on_explicit") or "",
                "unless": list(i.fields.get("unless") or ()),
                "resolves_to": i.fields.get("resolves_to") or "",
                "message": i.fields.get("message") or "",
                "note": i.fields.get("note") or "",
            }
            for i in reg.inters
        ],
    }


def write_manifest(path: str, reg: Registry) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(build_manifest(reg), f, indent=2, sort_keys=True)
        f.write("\n")


def _extra_args(ap) -> None:
    ap.add_argument(
        "--manifest",
        default=None,
        help="also write the extracted plan manifest JSON here",
    )


def _post(args, findings, stats) -> None:
    reg = stats.pop("registry", None)
    stats.pop("planspec_path", None)
    if getattr(args, "manifest", None) and reg is not None:
        write_manifest(args.manifest, reg)
        print(f"planlint: wrote {args.manifest}", file=sys.stderr)


def main(argv: Optional[List[str]] = None) -> int:
    return run_cli(
        "planlint",
        __doc__,
        lint_paths,
        DEFAULT_PATHS,
        lambda findings, stats: (
            f"planlint: {len(findings)} finding(s), "
            f"{stats['paths']} path / {stats['interactions']} interaction "
            f"declaration(s), {stats['records']} route record(s) in "
            f"{stats['files']} file(s)"
        ),
        argv,
        extra_args=_extra_args,
        post=_post,
    )


if __name__ == "__main__":
    sys.exit(main())
