#!/usr/bin/env python
"""Tunnel watchdog: probe the TPU backend all round, fire the bench the
moment a device answers (VERDICT r4 next-round item 1).

Rounds 3 and 4 lost their perf scoreboard to a dead remote-TPU tunnel:
the driver's single end-of-round bench attempt found no device and
recorded 0 cells/s, while nothing retried in between.  This tool is the
retry: run it first thing (in the background) and it probes the backend
with a BOUNDED subprocess every PROBE_INTERVAL_S; whenever the tunnel is
alive and the last bench artifact is stale, it runs the full bench and
writes the JSON to --out (default artifacts/bench_watchdog_latest.json,
plus a timestamped copy).  Every attempt is appended to the log so the
round's tunnel-availability history is itself evidence.

The probe is a SUBPROCESS because a wedged `jax.devices()` blocks its
process forever (utils/bounded.py docstring); a fresh interpreter per
probe is the only reliable bound.

Usage:
    python tools/tunnel_wait.py [--interval 300] [--max-hours 11]
        [--once] [--out artifacts/bench_watchdog_latest.json]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # standalone script: make cyclonus_tpu importable
    sys.path.insert(0, REPO)

PROBE_CODE = (
    "import jax; ds = jax.devices(); "
    "import sys; sys.exit(0 if any('tpu' in str(d).lower() or "
    "'TPU' in str(d) for d in ds) else 3)"
)

try:
    # the one shared backoff envelope (bench.py's init thread uses the
    # same helper)
    from cyclonus_tpu.utils.retry import full_jitter_pause
except Exception:  # package unimportable: the watchdog must still run

    def full_jitter_pause(base_s, attempt, rng):
        return base_s * (2 ** (attempt - 1)) * (0.5 + rng.random())


def _count_probe(outcome: str) -> None:
    """Feed cyclonus_tpu_tunnel_probe_attempts_total; the watchdog must
    keep running even if the package is unimportable (e.g. moved), so a
    failed import costs the metric, never the probe."""
    try:
        from cyclonus_tpu.telemetry import instruments

        instruments.TUNNEL_PROBE_ATTEMPTS.inc(outcome=outcome)
    except Exception:
        pass


def probe_tunnel(
    bound_s: float = 90.0,
    attempts: int = 1,
    backoff_s: float = 2.0,
    rng: random.Random = None,
    state: dict = None,
) -> bool:
    """True iff a fresh interpreter can enumerate a TPU device within
    bound_s.  Timeout/crash/non-TPU all count as dead.  With attempts
    > 1, dead probes retry after a full-jitter exponential backoff
    (base * 2^(n-1) * U[0.5, 1.5) — desynced from other clients racing
    for the same chip); every attempt lands in the
    cyclonus_tpu_tunnel_probe_attempts_total counter by outcome.

    `state` (optional dict) is filled with STRUCTURED forensics —
    {"attempts": n, "last_error": {"type", "message"} | None} — so the
    round artifact can say WHAT killed the probe (a SIGILL-class host
    fault prints a signature the attempt count alone can't carry),
    distinguishing it from plain tunnel death without scraping the
    stderr tail."""
    rng = rng or random.Random()
    if state is None:
        state = {}
    state.setdefault("last_error", None)
    for attempt in range(1, max(1, attempts) + 1):
        state["attempts"] = attempt
        try:
            proc = subprocess.run(
                [sys.executable, "-c", PROBE_CODE],
                capture_output=True,
                timeout=bound_s,
                cwd=REPO,
            )
            outcome = "alive" if proc.returncode == 0 else "dead"
            if outcome == "dead":
                stderr = (proc.stderr or b"")
                if isinstance(stderr, bytes):
                    stderr = stderr.decode(errors="replace")
                state["last_error"] = {
                    "type": f"ProbeExit{proc.returncode}",
                    "message": stderr.strip()[-200:],
                }
        except (subprocess.TimeoutExpired, OSError) as e:
            outcome = "timeout"
            state["last_error"] = {
                "type": type(e).__name__,
                "message": str(e)[:200],
            }
        _count_probe(outcome)
        if outcome == "alive":
            state["last_error"] = None
            return True
        if attempt <= max(1, attempts) - 1:
            time.sleep(full_jitter_pause(backoff_s, attempt, rng))
    return False


def run_bench(
    out_path: str, bound_s: float = None, probe_forensics: dict = None
) -> dict:
    """One full bench attempt; returns the parsed JSON line (or an error
    dict).  The bench's own watchdogs are the real bounds — they print
    the diagnostic JSON with phase history that this tool exists to
    capture — so the subprocess backstop must fire strictly AFTER them
    (inner deadline + margin), never first.

    --out only ever holds the LATEST SUCCESS (value > 0); failed
    attempts go to a .failed.json sibling, so a mid-round tunnel death
    cannot clobber a same-round success.  Every attempt also gets a
    timestamped copy — the round's availability history."""
    if bound_s is None:
        bound_s = float(os.environ.get("BENCH_DEADLINE_S", "1500")) + 300.0
    from bench import last_json_line

    rc = None
    tail = ""
    try:
        proc = subprocess.run(
            [sys.executable, "bench.py"],
            capture_output=True,
            text=True,
            timeout=bound_s,
            cwd=REPO,
        )
        rc = proc.returncode
        # keep the stdout+stderr tail as classification EVIDENCE: a
        # bench that died printing only the backend warning (r03) has
        # its signature here, not in any JSON
        tail = (proc.stdout or "")[-2000:] + (proc.stderr or "")[-2000:]
        result = last_json_line(proc.stdout) or {
            "error": f"bench produced no JSON (rc={rc})",
            # structured last-error: the no-JSON signature (r03's
            # backend warning, a SIGILL banner) lives in the tail —
            # class + truncated message, machine-readable
            "last_error": {
                "type": f"BenchExit{rc}",
                "message": tail.strip()[-200:],
            },
        }
    except subprocess.TimeoutExpired as e:
        result = {
            "error": f"bench exceeded the {bound_s:g}s subprocess bound",
            "last_error": {
                "type": type(e).__name__,
                "message": str(e)[:200],
            },
        }
        for out in (e.stdout, e.stderr):  # same evidence as the normal path
            if not out:
                continue
            if isinstance(out, bytes):
                out = out.decode(errors="replace")
            tail += out[-2000:]
    except json.JSONDecodeError as e:
        # a killed/crashed bench can leave a TRUNCATED final JSON line on
        # stdout; that's an error result, not a watchdog-loop killer
        result = {
            "error": f"bench stdout ended in unparseable JSON: {e}",
            "last_error": {
                "type": type(e).__name__,
                "message": str(e)[:200],
            },
        }
    if probe_forensics:
        # the round's probe history rides the same JSON line: attempt
        # count + the structured last probe error (None when the final
        # probe answered alive)
        result["probe"] = dict(probe_forensics)
    result["bench_rc"] = rc
    result["at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    if "failure_class" not in result:
        # older benches (and the no-JSON/timeout paths above) don't
        # say; classify from the evidence so the round artifact is
        # ledger-ready without re-deriving (perfobs is the one place
        # the classification rules live)
        try:
            from cyclonus_tpu.perfobs import classify

            result["failure_class"] = classify(result, rc, tail)
        except Exception:
            pass
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    ok = "error" not in result and result.get("value", 0) > 0
    target = out_path if ok else out_path.replace(".json", ".failed.json")
    with open(target, "w") as f:
        json.dump(result, f)
        f.write("\n")
    stamped = out_path.replace(
        ".json", time.strftime("-%Y%m%d-%H%M%S.json")
    )
    with open(stamped, "w") as f:
        json.dump(result, f)
        f.write("\n")
    return result


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--interval", type=float, default=300.0,
                    help="seconds between tunnel probes (default 300)")
    ap.add_argument("--max-hours", type=float, default=11.0,
                    help="give up after this many hours (default 11)")
    ap.add_argument("--once", action="store_true",
                    help="probe once; bench if alive; exit")
    ap.add_argument("--out", default="artifacts/bench_watchdog_latest.json")
    ap.add_argument("--probe-bound", type=float, default=90.0)
    ap.add_argument(
        "--probe-retries", type=int, default=3,
        help="probe attempts per cycle before calling the tunnel dead "
        "(jittered exponential backoff between them; default 3)",
    )
    ap.add_argument(
        "--probe-backoff", type=float, default=2.0,
        help="backoff base seconds between probe attempts (default 2)",
    )
    ap.add_argument(
        "--rebench-every", type=float, default=3600.0,
        help="re-run the bench if the last success is older than this "
        "(a fresh artifact beats a stale one; default 1h)",
    )
    args = ap.parse_args()

    deadline = time.time() + args.max_hours * 3600
    last_success = 0.0
    benched_ok = None  # tri-state for --once: None = bench never ran
    while True:
        probe_state: dict = {}
        alive = probe_tunnel(
            args.probe_bound,
            attempts=args.probe_retries,
            backoff_s=args.probe_backoff,
            state=probe_state,
        )
        now = time.strftime("%H:%M:%S")
        if alive and (time.time() - last_success) >= args.rebench_every:
            print(f"[{now}] tunnel ALIVE -> running bench", flush=True)
            result = run_bench(args.out, probe_forensics=probe_state)
            benched_ok = "error" not in result and result.get("value", 0) > 0
            print(
                f"[{time.strftime('%H:%M:%S')}] bench "
                f"{'OK value=' + str(result.get('value')) if benched_ok else 'FAILED: ' + str(result.get('error'))[:120]}",
                flush=True,
            )
            if benched_ok:
                last_success = time.time()
        else:
            state = "alive (artifact fresh)" if alive else "DEAD"
            err = probe_state.get("last_error")
            suffix = (
                f" (last error {err['type']}: {err['message'][:80]})"
                if err
                else ""
            )
            print(f"[{now}] tunnel {state}{suffix}", flush=True)
        if args.once:
            # rc reflects the OUTCOME, not just the probe: a caller
            # gating on --once must not mistake "tunnel answered but
            # the bench failed" for a produced artifact
            if not alive:
                return 3
            return 0 if benched_ok in (True, None) else 4
        if time.time() >= deadline:
            print("max duration reached; exiting", flush=True)
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
