"""statelint: authoritative-state & epoch-discipline verifier.

The sixth linter leg (jaxlint / locklint / shapelint / cachelint /
planlint / statelint — shared scaffolding in tools/lintcore.py).  The
runtime twin is cyclonus_tpu/serve/stateregistry.py: a declarative
registry of authoritative-state fields (StateField), delta-kind
lifecycle rows (KindSpec), and the guarded commit-path contract
(COMMIT) that VerdictService's commit path actually reads.  statelint
extracts the registry from the AST (no import — a package syntax error
cannot take the linter down) and cross-checks it against the scanned
serve/ + audit/ modules, worker/model.py's wire Delta.KINDS, and
audit/digest.py's canonicalization:

  ST001  registered state field mutated outside the guarded commit
         path (not under the declared lock, not lock-covered by
         one-level call inference, not construction), or the commit
         path applies deltas before their validator runs.
  ST002  registered field missing from the apply_pending rollback
         snapshot or its restore (an apply failure would commit
         poison); the registry-driven snapshot/restore helpers are
         fully covered by construction.
  ST003  field absent from audit/digest.py's canonical_state, from the
         note_epoch snapshot, or from the state() payload (replica
         digest equality silently loses coverage).
  ST004  epoch-bump discipline: the commit path increments the epoch
         exactly once, under the lock, after all mutations; no other
         function bumps it; no epoch read pairs with state reads
         outside a consistent (locked) snapshot.
  ST005  delta Kind without full lifecycle coverage — wire (a
         Delta.KINDS member), validate (the validator vets kind
         membership), apply (the applier names the kind), rollback
         (the owning field snapshots), and a named existing test gate
         — or a wire kind with no declared lifecycle row at all.

Suppress a finding with `# statelint: ignore[ST00X]` on the offending
line.

Run: python tools/statelint.py [paths...]
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from lintcore import Finding, ignore_regex, iter_py_files, run_cli, suppress

_IGNORE_RE = ignore_regex("statelint")

DEFAULT_PATHS = [
    "cyclonus_tpu/serve",
    "cyclonus_tpu/audit",
]

REGISTRY_BASENAME = "stateregistry.py"

#: dict-mutating method calls on a registered field attribute
_MUTATING_METHODS = {
    "update", "clear", "pop", "popitem", "setdefault", "extend", "append",
}


# --------------------------------------------------------------------------
# Registry extraction (planlint's discipline: literal StateField(...) /
# KindSpec(...) calls and the COMMIT literal dict, read off the AST).
# --------------------------------------------------------------------------

@dataclass
class FieldDecl:
    name: str
    attr: str
    container: str
    kinds: Tuple[str, ...]
    digest_key: str
    state_key: str
    rollback: bool
    line: int
    fields: Dict[str, object] = field(default_factory=dict)


@dataclass
class KindDecl:
    kind: str
    field: str
    gate: str
    payload: str
    line: int
    fields: Dict[str, object] = field(default_factory=dict)


@dataclass
class Registry:
    path: str = ""
    fields: List[FieldDecl] = field(default_factory=list)
    kinds: List[KindDecl] = field(default_factory=list)
    commit: Dict[str, str] = field(default_factory=dict)

    def field_by_name(self, name: str) -> Optional[FieldDecl]:
        for f in self.fields:
            if f.name == name:
                return f
        return None

    def attrs(self) -> Dict[str, FieldDecl]:
        return {f.attr: f for f in self.fields}


def _literal(node: ast.AST):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def _call_kwargs(call: ast.Call, positional: List[str]) -> Dict[str, object]:
    out: Dict[str, object] = {}
    for i, arg in enumerate(call.args):
        if i < len(positional):
            out[positional[i]] = _literal(arg)
    for kw in call.keywords:
        if kw.arg:
            out[kw.arg] = _literal(kw.value)
    return out


def load_registry(registry_path: str) -> Optional[Registry]:
    try:
        with open(registry_path, "r") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return None
    reg = Registry(path=registry_path)
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            tgts = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for tgt in tgts:
                if isinstance(tgt, ast.Name) and tgt.id == "COMMIT":
                    val = _literal(node.value) if node.value else None
                    if isinstance(val, dict):
                        reg.commit = {str(k): str(v) for k, v in val.items()}
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else getattr(fn, "attr", "")
        if name == "StateField":
            kw = _call_kwargs(node, ["name"])
            reg.fields.append(FieldDecl(
                name=str(kw.get("name") or ""),
                attr=str(kw.get("attr") or kw.get("name") or ""),
                container=str(kw.get("container") or "dict"),
                kinds=tuple(kw.get("kinds") or ()),
                digest_key=str(kw.get("digest_key") or ""),
                state_key=str(kw.get("state_key") or ""),
                rollback=bool(kw.get("rollback", True)),
                line=node.lineno,
                fields=kw,
            ))
        elif name == "KindSpec":
            kw = _call_kwargs(node, ["kind"])
            reg.kinds.append(KindDecl(
                kind=str(kw.get("kind") or ""),
                field=str(kw.get("field") or ""),
                gate=str(kw.get("gate") or ""),
                payload=str(kw.get("payload") or ""),
                line=node.lineno,
                fields=kw,
            ))
    return reg


def find_registry(paths: List[str]) -> Optional[str]:
    """Locate stateregistry.py: inside a scanned directory, else
    relative to the repo root the scanned paths live under."""
    for p in paths:
        if os.path.isdir(p):
            cand = os.path.join(p, REGISTRY_BASENAME)
            if os.path.exists(cand):
                return cand
        elif os.path.basename(p) == REGISTRY_BASENAME:
            return p
    anchor = os.path.abspath(paths[0]) if paths else os.getcwd()
    cur = anchor if os.path.isdir(anchor) else os.path.dirname(anchor)
    for _ in range(6):
        cand = os.path.join(
            cur, "cyclonus_tpu", "serve", REGISTRY_BASENAME
        )
        if os.path.exists(cand):
            return cand
        cur = os.path.dirname(cur)
    return None


def _repo_root_for(registry_path: str) -> str:
    # .../cyclonus_tpu/serve/stateregistry.py -> repo root
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(registry_path)
    )))


def _gate_exists(gate: str, root: str) -> bool:
    if gate.startswith("tests/"):
        return os.path.exists(os.path.join(root, gate))
    if gate.startswith("make "):
        target = gate.split(None, 1)[1]
        mk = os.path.join(root, "Makefile")
        if not os.path.exists(mk):
            return False
        with open(mk) as f:
            return re.search(
                rf"^{re.escape(target)}:", f.read(), re.MULTILINE
            ) is not None
    return False


# --------------------------------------------------------------------------
# Wire + digest side extraction.
# --------------------------------------------------------------------------

def load_wire_kinds(root: str) -> Optional[Tuple[Set[str], str, int]]:
    """Delta.KINDS from worker/model.py's AST: (kinds, path, lineno), or
    None when the model module is absent (scratch fixture trees)."""
    path = os.path.join(root, "cyclonus_tpu", "worker", "model.py")
    try:
        with open(path, "r") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef) or node.name != "Delta":
            continue
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                tgts = (
                    sub.targets if isinstance(sub, ast.Assign)
                    else [sub.target]
                )
                for tgt in tgts:
                    if isinstance(tgt, ast.Name) and tgt.id == "KINDS":
                        val = _literal(sub.value)
                        if isinstance(val, tuple):
                            return set(val), path, sub.lineno
    return None


def load_digest_keys(root: str) -> Optional[Tuple[Set[str], str, int]]:
    """canonical_state's literal return-dict keys from audit/digest.py:
    (keys, path, lineno), or None when the digest module is absent."""
    path = os.path.join(root, "cyclonus_tpu", "audit", "digest.py")
    try:
        with open(path, "r") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        if node.name != "canonical_state":
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Return) and isinstance(
                sub.value, ast.Dict
            ):
                keys = {
                    k.value for k in sub.value.keys
                    if isinstance(k, ast.Constant) and isinstance(
                        k.value, str
                    )
                }
                return keys, path, node.lineno
    return None


# --------------------------------------------------------------------------
# Service-class analysis: mutations, reads, call edges, lock context.
# --------------------------------------------------------------------------

def _attr_chain(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


_HOLDS_DOC_RE = re.compile(r"holds-lock:\s*([A-Za-z_][A-Za-z0-9_.]*)")


def _declared_holds(func: ast.AST) -> Set[str]:
    """Locks a function declares held: docstring `holds-lock: expr` and
    `@guards.holds("expr")` decorators (the locklint convention)."""
    out: Set[str] = set()
    doc = ast.get_docstring(func, clean=False) or ""
    out.update(_HOLDS_DOC_RE.findall(doc))
    for dec in getattr(func, "decorator_list", []):
        if isinstance(dec, ast.Call):
            name = (
                dec.func.attr if isinstance(dec.func, ast.Attribute)
                else dec.func.id if isinstance(dec.func, ast.Name) else None
            )
            if name == "holds":
                for a in dec.args:
                    if isinstance(a, ast.Constant) and isinstance(
                        a.value, str
                    ):
                        out.add(a.value)
    return out


@dataclass
class Site:
    """One mutation / read / call / bump site with its lock context."""
    attr: str
    line: int
    col: int
    in_lock: bool
    func: str


@dataclass
class ServiceModel:
    """Everything statelint needs about one service class."""
    path: str = ""
    cls: str = ""
    mutations: List[Site] = field(default_factory=list)
    epoch_bumps: List[Site] = field(default_factory=list)
    epoch_reads: List[Site] = field(default_factory=list)
    field_reads: List[Site] = field(default_factory=list)
    call_edges: List[Site] = field(default_factory=list)  # attr=callee
    entry_holds: Dict[str, bool] = field(default_factory=dict)
    funcs: Dict[str, ast.AST] = field(default_factory=dict)
    registry_calls: List[Tuple[str, int, str]] = field(default_factory=list)


class _FuncWalker:
    """One function's lexical lock-context walk.  `held` tracks whether
    the declared lock is held at each statement (entry holds + nested
    `with self._lock:` blocks)."""

    def __init__(self, model: ServiceModel, func: ast.AST, lock_expr: str,
                 field_attrs: Set[str], epoch_attr: str):
        self.model = model
        self.func = func
        self.lock = lock_expr
        self.field_attrs = field_attrs
        self.epoch = epoch_attr
        self.entry = lock_expr in _declared_holds(func)
        model.entry_holds[func.name] = self.entry

    def run(self) -> None:
        for stmt in self.func.body:
            self._visit(stmt, self.entry)

    # -- helpers -----------------------------------------------------------

    def _site(self, attr: str, node: ast.AST, held: bool) -> Site:
        return Site(attr, node.lineno, node.col_offset, held,
                    self.func.name)

    def _self_attr(self, node: ast.AST) -> Optional[str]:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr
        return None

    def _target_attrs(self, tgt: ast.AST) -> List[str]:
        """Registered/epoch attrs a statement target mutates: plain
        `self.x`, `self.x[...]`, and tuple targets."""
        out: List[str] = []
        if isinstance(tgt, ast.Tuple):
            for el in tgt.elts:
                out.extend(self._target_attrs(el))
            return out
        if isinstance(tgt, ast.Subscript):
            tgt = tgt.value
        attr = self._self_attr(tgt)
        if attr is not None:
            out.append(attr)
        return out

    def _scan_expr(self, node: ast.AST, held: bool) -> None:
        """Reads + mutating method calls + self-call edges + registry
        helper calls inside one expression/statement subtree."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                fn = sub.func
                if isinstance(fn, ast.Attribute):
                    chain = _attr_chain(fn)
                    # self._method(...) edge for one-level inference
                    owner = self._self_attr(fn.value)
                    if (
                        isinstance(fn.value, ast.Name)
                        and fn.value.id == "self"
                    ):
                        self.model.call_edges.append(
                            self._site(fn.attr, sub, held)
                        )
                    # mutating dict-method call on a registered field
                    if (
                        owner in self.field_attrs
                        and fn.attr in _MUTATING_METHODS
                    ):
                        self.model.mutations.append(
                            self._site(owner, sub, held)
                        )
                    # registry helper call (stateregistry.snapshot etc.)
                    root, _, leaf = chain.rpartition(".")
                    if root.endswith("stateregistry") or root == "":
                        if leaf in ("snapshot", "restore", "audit_state",
                                    "state_counts"):
                            self.model.registry_calls.append(
                                (leaf, sub.lineno, self.func.name)
                            )
            if isinstance(sub, ast.Attribute) and isinstance(
                sub.ctx, ast.Load
            ):
                attr = self._self_attr(sub)
                if attr in self.field_attrs:
                    self.model.field_reads.append(
                        self._site(attr, sub, held)
                    )
                elif attr == self.epoch:
                    self.model.epoch_reads.append(
                        self._site(attr, sub, held)
                    )

    # -- traversal ---------------------------------------------------------

    def _visit(self, stmt: ast.AST, held: bool) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs run at call time, not under this lock
        if isinstance(stmt, ast.With):
            inner = held
            for item in stmt.items:
                if _attr_chain(item.context_expr) == self.lock:
                    inner = True
                self._scan_expr(item.context_expr, held)
            for s in stmt.body:
                self._visit(s, inner)
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            tgts = (
                stmt.targets if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            for tgt in tgts:
                for attr in self._target_attrs(tgt):
                    if attr in self.field_attrs:
                        self.model.mutations.append(
                            self._site(attr, stmt, held)
                        )
                    elif attr == self.epoch:
                        self.model.epoch_bumps.append(
                            self._site(attr, stmt, held)
                        )
            if stmt.value is not None:
                self._scan_expr(stmt.value, held)
            return
        if isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                for attr in self._target_attrs(tgt):
                    if attr in self.field_attrs:
                        self.model.mutations.append(
                            self._site(attr, stmt, held)
                        )
            return
        # compound statements: recurse into bodies with the same held
        # flag, scan the tests/expressions for reads
        for fld in ("test", "iter", "value", "exc"):
            sub = getattr(stmt, fld, None)
            if isinstance(sub, ast.AST):
                self._scan_expr(sub, held)
        for fld in ("body", "orelse", "finalbody"):
            for s in getattr(stmt, fld, []) or []:
                self._visit(s, held)
        for handler in getattr(stmt, "handlers", []) or []:
            for s in handler.body:
                self._visit(s, held)


def scan_service_class(path: str, cls: ast.ClassDef, commit: Dict[str, str],
                       field_attrs: Set[str]) -> ServiceModel:
    model = ServiceModel(path=path, cls=cls.name)
    lock = commit.get("lock", "self._lock")
    epoch = commit.get("epoch_attr", "_epoch")
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            model.funcs[node.name] = node
            _FuncWalker(model, node, lock, field_attrs, epoch).run()
    return model


def _lock_covered(model: ServiceModel, func: str) -> bool:
    """One-level call inference: a function is lock-covered when it
    declares holds, or when every scanned call site of it sits in lock
    context (and at least one exists)."""
    if model.entry_holds.get(func):
        return True
    sites = [e for e in model.call_edges if e.attr == func]
    return bool(sites) and all(e.in_lock for e in sites)


# --------------------------------------------------------------------------
# The lint proper.
# --------------------------------------------------------------------------

def _calls_of(func: ast.AST, name: str) -> List[ast.Call]:
    out = []
    for sub in ast.walk(func):
        if isinstance(sub, ast.Call):
            fn = sub.func
            leaf = (
                fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else ""
            )
            if leaf == name:
                out.append(sub)
    return out


def _names_in(node: ast.AST) -> Set[str]:
    """`self.<attr>` attribute names referenced anywhere in a subtree."""
    out: Set[str] = set()
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Attribute)
            and isinstance(sub.value, ast.Name)
            and sub.value.id == "self"
        ):
            out.add(sub.attr)
    return out


def _string_constants(node: ast.AST) -> Set[str]:
    return {
        sub.value for sub in ast.walk(node)
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str)
    }


def _has_kinds_membership(func: ast.AST) -> bool:
    """Does the validator vet kind membership against the wire KINDS
    tuple (`d.kind not in Delta.KINDS` / `... in KINDS`)?"""
    for sub in ast.walk(func):
        if not isinstance(sub, ast.Compare):
            continue
        if not any(isinstance(op, (ast.In, ast.NotIn)) for op in sub.ops):
            continue
        for cmp in sub.comparators:
            if _attr_chain(cmp).endswith("KINDS"):
                return True
    return False


def _double_star_covered(call: ast.Call, leaf: str) -> bool:
    """Does the call carry `**<...>.<leaf>(...)` (the registry-driven
    kwarg form)?"""
    for kw in call.keywords:
        if kw.arg is not None:
            continue
        for sub in ast.walk(kw.value):
            if isinstance(sub, ast.Call):
                fn = sub.func
                name = (
                    fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else ""
                )
                if name == leaf:
                    return True
    return False


def _commit_checks(model: ServiceModel, reg: Registry,
                   findings: List[Finding]) -> None:
    """ST001 (validator ordering), ST002 (snapshot/restore), ST004
    (epoch bump discipline) over the declared commit function."""
    commit_name = reg.commit.get("commit", "apply_pending")
    validator = reg.commit.get("validator", "_validate_delta")
    applier = reg.commit.get("applier", "_apply_to_state")
    func = model.funcs.get(commit_name)
    if func is None:
        return
    path = model.path
    rollback_fields = [f for f in reg.fields if f.rollback]

    applier_calls = _calls_of(func, applier)
    validator_calls = _calls_of(func, validator)
    if applier_calls:
        first_apply = min(c.lineno for c in applier_calls)
        if not validator_calls:
            findings.append(Finding(
                path, first_apply, applier_calls[0].col_offset, "ST001",
                f"commit path {commit_name!r} applies deltas without "
                f"calling the declared validator {validator!r}",
            ))
        elif min(c.lineno for c in validator_calls) > first_apply:
            findings.append(Finding(
                path, first_apply, applier_calls[0].col_offset, "ST001",
                f"commit path {commit_name!r} mutates state (via "
                f"{applier!r}) before its validator {validator!r} runs",
            ))

    # -- ST002: the rollback snapshot + restore ---------------------------
    reg_snapshot = [
        (line, fn) for op, line, fn in model.registry_calls
        if op == "snapshot" and fn == commit_name
    ]
    reg_restore = [
        (line, fn) for op, line, fn in model.registry_calls
        if op == "restore" and fn == commit_name
    ]
    if reg_snapshot:
        # registry-driven snapshot: covered by construction; the restore
        # must be registry-driven too
        if not reg_restore:
            findings.append(Finding(
                path, reg_snapshot[0][0], 0, "ST002",
                f"commit path {commit_name!r} takes the registry "
                f"snapshot but never calls stateregistry.restore on "
                f"failure",
            ))
    else:
        # literal snapshot: the assignment referencing the most
        # registered attrs is the rollback point; every rollback field
        # must appear in it (and in the restore target)
        snap_assign = None
        snap_cover: Set[str] = set()
        for sub in ast.walk(func):
            if isinstance(sub, ast.Assign) and sub.value is not None:
                names = _names_in(sub.value) & {
                    f.attr for f in rollback_fields
                }
                if len(names) > len(snap_cover):
                    snap_assign, snap_cover = sub, names
        if snap_assign is None:
            if applier_calls:
                findings.append(Finding(
                    path, func.lineno, func.col_offset, "ST002",
                    f"commit path {commit_name!r} takes no rollback "
                    f"snapshot before applying deltas",
                ))
        else:
            for f in rollback_fields:
                if f.attr not in snap_cover:
                    findings.append(Finding(
                        path, snap_assign.lineno, snap_assign.col_offset,
                        "ST002",
                        f"registered state field {f.name!r} "
                        f"(self.{f.attr}) is missing from the rollback "
                        f"snapshot",
                    ))
            restore_cover: Set[str] = set()
            for sub in ast.walk(func):
                if isinstance(sub, ast.Assign):
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Tuple):
                            names = _names_in(tgt) & {
                                f.attr for f in rollback_fields
                            }
                            if len(names) > len(restore_cover):
                                restore_cover = names
            for f in rollback_fields:
                if f.attr in snap_cover and f.attr not in restore_cover:
                    findings.append(Finding(
                        path, snap_assign.lineno, snap_assign.col_offset,
                        "ST002",
                        f"registered state field {f.name!r} "
                        f"(self.{f.attr}) is snapshotted but never "
                        f"restored on apply failure",
                    ))

    # -- ST004: the epoch bump --------------------------------------------
    epoch = reg.commit.get("epoch_attr", "_epoch")
    commit_bumps = [
        b for b in model.epoch_bumps
        if b.func == commit_name
    ]
    if applier_calls and not commit_bumps:
        findings.append(Finding(
            path, func.lineno, func.col_offset, "ST004",
            f"commit path {commit_name!r} never increments the epoch "
            f"(self.{epoch})",
        ))
    elif len(commit_bumps) > 1:
        for b in commit_bumps[1:]:
            findings.append(Finding(
                path, b.line, b.col, "ST004",
                f"commit path {commit_name!r} increments the epoch "
                f"{len(commit_bumps)} times (want exactly once)",
            ))
    if commit_bumps:
        b = commit_bumps[0]
        if not b.in_lock:
            findings.append(Finding(
                path, b.line, b.col, "ST004",
                f"epoch bump in {commit_name!r} is outside the "
                f"declared lock ({reg.commit.get('lock')})",
            ))
        mut_lines = [
            m.line for m in model.mutations if m.func == commit_name
        ] + [c.lineno for c in applier_calls]
        late = [ln for ln in mut_lines if ln > b.line]
        if late:
            findings.append(Finding(
                path, b.line, b.col, "ST004",
                f"epoch bump in {commit_name!r} runs before state "
                f"mutations complete (mutation at line {min(late)})",
            ))


def lint_paths(paths: List[str]) -> Tuple[List[Finding], Dict[str, object]]:
    files = iter_py_files(paths)
    registry_path = find_registry(paths)
    findings: List[Finding] = []
    empty_stats = {
        "files": len(files), "fields": 0, "kinds": 0, "annotations": 0,
        "findings": 1,
    }
    if registry_path is None:
        findings.append(Finding(
            paths[0] if paths else ".", 0, 0, "ST001",
            "cyclonus_tpu/serve/stateregistry.py not found: the "
            "authoritative-state surface has no declared registry to "
            "lint against",
        ))
        return findings, empty_stats
    reg = load_registry(registry_path)
    if reg is None or not reg.fields:
        findings.append(Finding(
            registry_path, 0, 0, "ST001",
            "state registry unparseable or empty",
        ))
        return findings, empty_stats

    root = _repo_root_for(registry_path)
    wire = load_wire_kinds(root)
    digest = load_digest_keys(root)
    field_attrs = set(reg.attrs())
    commit_cls = reg.commit.get("class", "VerdictService")
    commit_name = reg.commit.get("commit", "apply_pending")
    validator_name = reg.commit.get("validator", "_validate_delta")
    applier_name = reg.commit.get("applier", "_apply_to_state")
    note_name = reg.commit.get("audit_note", "note_epoch")

    models: List[ServiceModel] = []
    annotations = len(reg.fields) + len(reg.kinds)
    note_sites: List[Tuple[str, ast.Call, List[str]]] = []
    state_funcs: List[Tuple[str, ast.AST, List[str]]] = []

    for path in files:
        if os.path.basename(path) == REGISTRY_BASENAME:
            continue  # the registry itself is not a mutation site
        try:
            with open(path, "r") as f:
                source = f.read()
            tree = ast.parse(source)
        except (OSError, SyntaxError):
            findings.append(Finding(path, 0, 0, "ST000", "syntax error"))
            continue
        lines = source.splitlines()
        file_findings: List[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            defines_commit = any(
                isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n.name == commit_name
                for n in node.body
            )
            if node.name != commit_cls and not defines_commit:
                continue
            model = scan_service_class(path, node, reg.commit, field_attrs)
            models.append(model)
            annotations += len(model.registry_calls)

            # ST001: mutations outside the guarded commit path
            for m in model.mutations:
                if m.func == "__init__":
                    continue  # construction precedes concurrency
                if m.in_lock or _lock_covered(model, m.func):
                    continue
                fdecl = reg.attrs()[m.attr]
                file_findings.append(Finding(
                    path, m.line, m.col, "ST001",
                    f"state field {fdecl.name!r} (self.{m.attr}) mutated "
                    f"outside the guarded commit path in {m.func!r} "
                    f"(not under {reg.commit.get('lock')}, not "
                    f"lock-covered by its call sites)",
                ))

            # ST004: epoch bumps outside the commit function
            for b in model.epoch_bumps:
                if b.func in (commit_name, "__init__"):
                    continue
                file_findings.append(Finding(
                    path, b.line, b.col, "ST004",
                    f"epoch (self.{reg.commit.get('epoch_attr')}) "
                    f"mutated outside the commit path, in {b.func!r}",
                ))

            # ST004: epoch read paired with state outside the lock
            flagged: Set[str] = set()
            for er in model.epoch_reads:
                if er.in_lock or er.func in flagged:
                    continue
                if _lock_covered(model, er.func):
                    continue
                paired = [
                    fr for fr in model.field_reads
                    if fr.func == er.func and not fr.in_lock
                ]
                if paired:
                    flagged.add(er.func)
                    file_findings.append(Finding(
                        path, er.line, er.col, "ST004",
                        f"epoch read paired with state field "
                        f"{paired[0].attr!r} in {er.func!r} outside a "
                        f"consistent locked snapshot",
                    ))

            # ST001/ST002/ST004 over the commit function itself
            _commit_checks(model, reg, file_findings)

        # note_epoch call sites + state() payloads (ST003, checked after
        # the scan so registry-call coverage is known)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Attribute) and fn.attr == note_name:
                    kwargs = [
                        kw.arg for kw in node.keywords if kw.arg
                    ]
                    note_sites.append((path, node, kwargs))
            elif isinstance(node, ast.FunctionDef) and node.name == "state":
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Return) and isinstance(
                        sub.value, ast.Dict
                    ):
                        keys = [
                            k.value for k in sub.value.keys
                            if isinstance(k, ast.Constant)
                        ]
                        covered = any(
                            k is None and any(
                                isinstance(c, ast.Call) and (
                                    getattr(c.func, "attr", "")
                                    or getattr(c.func, "id", "")
                                ) == "state_counts"
                                for c in ast.walk(v)
                            )
                            for k, v in zip(
                                sub.value.keys, sub.value.values
                            )
                        )
                        state_funcs.append((
                            path, sub,
                            ["*"] if covered else keys,
                        ))
        findings.extend(suppress(file_findings, lines, _IGNORE_RE))

    # ST003 over audit call sites: every field must ride note_epoch
    st3: Dict[str, List[Finding]] = {}
    for path, call, kwargs in note_sites:
        if _double_star_covered(call, "audit_state"):
            continue  # registry-driven; counted via registry_calls
        missing = [
            f.name for f in reg.fields if f.name not in kwargs
        ]
        for name in missing:
            st3.setdefault(path, []).append(Finding(
                path, call.lineno, call.col_offset, "ST003",
                f"registered state field {name!r} is missing from the "
                f"{note_name} snapshot",
            ))
    for path, ret, keys in state_funcs:
        if keys == ["*"]:
            continue
        for f in reg.fields:
            if f.state_key and f.state_key not in keys:
                st3.setdefault(path, []).append(Finding(
                    path, ret.value.lineno, ret.value.col_offset, "ST003",
                    f"registered state field {f.name!r} (key "
                    f"{f.state_key!r}) is missing from the state() "
                    f"payload",
                ))
    for path, fl in st3.items():
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            lines = []
        findings.extend(suppress(fl, lines, _IGNORE_RE))

    # ST003 digest coverage + registry-side ST005, anchored at the
    # declaration lines (the registry/digest files' own ignore comments
    # apply)
    reg_findings: List[Finding] = []
    digest_findings: List[Finding] = []
    if digest is not None:
        dkeys, dpath, dline = digest
        for f in reg.fields:
            if f.digest_key and f.digest_key not in dkeys:
                digest_findings.append(Finding(
                    dpath, dline, 0, "ST003",
                    f"registered state field {f.name!r} (key "
                    f"{f.digest_key!r}) is missing from "
                    f"canonical_state: replica digest equality would "
                    f"silently lose coverage",
                ))
            elif f.digest_key:
                annotations += 1  # live digest-surface participation
        try:
            with open(dpath) as fh:
                dlines = fh.read().splitlines()
        except OSError:
            dlines = []
        findings.extend(suppress(digest_findings, dlines, _IGNORE_RE))

    declared_kinds = {k.kind for k in reg.kinds}
    validator_func = None
    applier_func = None
    for model in models:
        validator_func = validator_func or model.funcs.get(validator_name)
        applier_func = applier_func or model.funcs.get(applier_name)
    applier_kinds = (
        _string_constants(applier_func) if applier_func is not None
        else None
    )
    validator_vets = (
        validator_func is None or _has_kinds_membership(validator_func)
    )
    for k in reg.kinds:
        owner = reg.field_by_name(k.field)
        if owner is None:
            reg_findings.append(Finding(
                reg.path, k.line, 0, "ST005",
                f"delta kind {k.kind!r} declares unknown owning field "
                f"{k.field!r}",
            ))
            continue
        if k.kind not in owner.kinds:
            reg_findings.append(Finding(
                reg.path, k.line, 0, "ST005",
                f"delta kind {k.kind!r} is not listed in field "
                f"{owner.name!r}'s kinds tuple",
            ))
        if wire is not None and k.kind not in wire[0]:
            reg_findings.append(Finding(
                reg.path, k.line, 0, "ST005",
                f"delta kind {k.kind!r} has no wire Delta kind "
                f"(worker/model.py Delta.KINDS): it cannot round-trip",
            ))
        if validator_func is not None and not validator_vets:
            reg_findings.append(Finding(
                reg.path, k.line, 0, "ST005",
                f"delta kind {k.kind!r}: the validator "
                f"{validator_name!r} never vets kind membership "
                f"against Delta.KINDS",
            ))
        if applier_kinds is not None and k.kind not in applier_kinds:
            reg_findings.append(Finding(
                reg.path, k.line, 0, "ST005",
                f"delta kind {k.kind!r} is never applied: the applier "
                f"{applier_name!r} does not name it",
            ))
        if not owner.rollback:
            reg_findings.append(Finding(
                reg.path, k.line, 0, "ST005",
                f"delta kind {k.kind!r} mutates field {owner.name!r} "
                f"which opts out of the rollback snapshot",
            ))
        if not k.gate:
            reg_findings.append(Finding(
                reg.path, k.line, 0, "ST005",
                f"delta kind {k.kind!r} declares no lifecycle gate",
            ))
        elif not _gate_exists(k.gate, root):
            reg_findings.append(Finding(
                reg.path, k.line, 0, "ST005",
                f"delta kind {k.kind!r} gate {k.gate!r} does not exist "
                f"(want an existing tests/ file or make target)",
            ))
    for f in reg.fields:
        for kind in f.kinds:
            if kind not in declared_kinds:
                reg_findings.append(Finding(
                    reg.path, f.line, 0, "ST005",
                    f"field {f.name!r} kind {kind!r} has no declared "
                    f"KindSpec lifecycle row",
                ))
    try:
        with open(reg.path) as f:
            reg_lines = f.read().splitlines()
    except OSError:
        reg_lines = []
    findings.extend(suppress(reg_findings, reg_lines, _IGNORE_RE))

    # the reverse wire check: a Delta.KINDS member with no lifecycle row
    if wire is not None:
        wkinds, wpath, wline = wire
        wire_findings = [
            Finding(
                wpath, wline, 0, "ST005",
                f"wire delta kind {kind!r} has no KindSpec lifecycle "
                f"row in the state registry",
            )
            for kind in sorted(wkinds - declared_kinds)
        ]
        if wire_findings:
            try:
                with open(wpath) as f:
                    wlines = f.read().splitlines()
            except OSError:
                wlines = []
            findings.extend(suppress(wire_findings, wlines, _IGNORE_RE))

    stats = {
        "files": len(files),
        "fields": len(reg.fields),
        "kinds": len(reg.kinds),
        "annotations": annotations,
        "findings": len(findings),
        "registry": reg,
        "registry_path": registry_path,
    }
    stats["findings"] = len(findings)
    return (
        sorted(findings, key=lambda f: (f.path, f.line, f.col, f.code)),
        stats,
    )


# --------------------------------------------------------------------------
# Manifest (pinned byte-identical to stateregistry.manifest()).
# --------------------------------------------------------------------------

def build_manifest(reg: Registry) -> Dict:
    return {
        "version": 1,
        "fields": [
            {
                "name": f.name,
                "attr": f.attr,
                "container": f.container,
                "kinds": list(f.kinds),
                "digest_key": f.digest_key,
                "state_key": f.state_key,
                "rollback": f.rollback,
                "note": str(f.fields.get("note") or ""),
            }
            for f in reg.fields
        ],
        "kinds": [
            {
                "kind": k.kind,
                "field": k.field,
                "gate": k.gate,
                "payload": k.payload,
                "note": str(k.fields.get("note") or ""),
            }
            for k in reg.kinds
        ],
        "commit": dict(reg.commit),
    }


def _post(args, findings, stats) -> None:
    stats.pop("registry", None)
    stats.pop("registry_path", None)


def main(argv: Optional[List[str]] = None) -> int:
    return run_cli(
        "statelint",
        __doc__,
        lint_paths,
        DEFAULT_PATHS,
        lambda findings, stats: (
            f"statelint: {len(findings)} finding(s), "
            f"{stats['fields']} field / {stats['kinds']} kind "
            f"declaration(s), {stats['annotations']} live annotation(s) "
            f"in {stats['files']} file(s)"
        ),
        argv,
        post=_post,
    )


if __name__ == "__main__":
    import sys

    sys.exit(main())
