"""L2 tests: matcher canonicalization algebra
(in the spirit of the reference's matcher/simplifier_tests.go)."""

from cyclonus_tpu.kube.netpol import IntOrString, LabelSelector
from cyclonus_tpu.matcher import (
    ALL_PEERS_PORTS,
    AllNamespaceMatcher,
    AllPodMatcher,
    AllPortMatcher,
    ExactNamespaceMatcher,
    IPPeerMatcher,
    PodPeerMatcher,
    PortProtocolMatcher,
    PortsForAllPeersMatcher,
    SpecificPortMatcher,
    combine_port_matchers,
    simplify,
    subtract_port_matchers,
)
from cyclonus_tpu.kube.netpol import IPBlock


def specific(*port_protos):
    return SpecificPortMatcher(
        ports=[
            PortProtocolMatcher(
                port=IntOrString(p) if p is not None else None, protocol=proto
            )
            for p, proto in port_protos
        ]
    )


class TestCombinePortMatchers:
    def test_all_wins(self):
        assert isinstance(
            combine_port_matchers(AllPortMatcher(), specific((80, "TCP"))),
            AllPortMatcher,
        )
        assert isinstance(
            combine_port_matchers(specific((80, "TCP")), AllPortMatcher()),
            AllPortMatcher,
        )

    def test_specific_union_replicates_reference_dedup_bug(self):
        # portmatcher.go:102-111's dedup loop appends the incoming port at
        # every non-equal element until an equal one breaks — so 80 (equal at
        # index 0) is dropped, while 82 is appended twice (once per non-equal
        # element of [80, 81]).  Wart replicated for oracle parity; duplicates
        # are harmless for evaluation (OR semantics).
        a = specific((80, "TCP"), (81, "TCP"))
        b = specific((80, "TCP"), (82, "TCP"))
        combined = combine_port_matchers(a, b)
        vals = [(p.port.value, p.protocol) for p in combined.ports]
        assert vals == [(80, "TCP"), (81, "TCP"), (82, "TCP"), (82, "TCP")]

    def test_combine_into_empty_drops_other_ports(self):
        # The drop half of the same reference wart: when self.ports is empty
        # the inner loop never runs, so other's ports vanish
        # (portmatcher.go:104-111).
        a = SpecificPortMatcher()
        b = specific((80, "TCP"))
        combined = combine_port_matchers(a, b)
        assert combined.ports == []

    def test_sort_order_nil_string_int(self):
        a = SpecificPortMatcher(
            ports=[
                PortProtocolMatcher(port=IntOrString(99), protocol="TCP"),
                PortProtocolMatcher(port=None, protocol="UDP"),
                PortProtocolMatcher(port=IntOrString("zzz"), protocol="TCP"),
            ]
        )
        combined = a.combine(SpecificPortMatcher())
        kinds = [
            (p.port is None, None if p.port is None else p.port.value)
            for p in combined.ports
        ]
        assert kinds == [(True, None), (False, "zzz"), (False, 99)]


class TestSubtractPortMatchers:
    def test_all_minus_all_is_empty(self):
        empty, rest = subtract_port_matchers(AllPortMatcher(), AllPortMatcher())
        assert empty and rest is None

    def test_all_minus_specific_is_all(self):
        # the reference wart: all-but is not handled (simplifier.go:151-153)
        empty, rest = subtract_port_matchers(AllPortMatcher(), specific((80, "TCP")))
        assert not empty
        assert isinstance(rest, AllPortMatcher)

    def test_specific_minus_all_is_empty(self):
        empty, rest = subtract_port_matchers(specific((80, "TCP")), AllPortMatcher())
        assert empty and rest is None

    def test_specific_minus_specific(self):
        a = specific((80, "TCP"), (81, "TCP"))
        b = specific((80, "TCP"))
        empty, rest = subtract_port_matchers(a, b)
        assert not empty
        assert [(p.port.value, p.protocol) for p in rest.ports] == [(81, "TCP")]


class TestSimplify:
    def test_all_peers_collapses_everything(self):
        pod = PodPeerMatcher(
            namespace=AllNamespaceMatcher(),
            pod=AllPodMatcher(),
            port=AllPortMatcher(),
        )
        result = simplify([ALL_PEERS_PORTS, pod])
        assert result == [ALL_PEERS_PORTS]

    def test_merge_same_pod_matchers_unions_ports(self):
        ns = ExactNamespaceMatcher(namespace="x")
        a = PodPeerMatcher(namespace=ns, pod=AllPodMatcher(), port=specific((80, "TCP")))
        b = PodPeerMatcher(namespace=ns, pod=AllPodMatcher(), port=specific((81, "TCP")))
        result = simplify([a, b])
        assert len(result) == 1
        ports = [(p.port.value, p.protocol) for p in result[0].port.ports]
        assert ports == [(80, "TCP"), (81, "TCP")]

    def test_different_pod_matchers_not_merged(self):
        a = PodPeerMatcher(
            namespace=ExactNamespaceMatcher(namespace="x"),
            pod=AllPodMatcher(),
            port=AllPortMatcher(),
        )
        b = PodPeerMatcher(
            namespace=ExactNamespaceMatcher(namespace="y"),
            pod=AllPodMatcher(),
            port=AllPortMatcher(),
        )
        assert len(simplify([a, b])) == 2

    def test_ip_matchers_merge_by_primary_key(self):
        blk = IPBlock.make(cidr="10.0.0.0/24")
        a = IPPeerMatcher(ip_block=blk, port=specific((80, "TCP")))
        b = IPPeerMatcher(ip_block=blk, port=specific((81, "TCP")))
        result = simplify([a, b])
        assert len(result) == 1
        assert len(result[0].port.ports) == 2

    def test_ports_for_all_subtracts_from_pods(self):
        # simplifier.go:87-114: pod matcher covered by all-peers-port drops out
        all_80 = PortsForAllPeersMatcher(port=specific((80, "TCP")))
        pod_80 = PodPeerMatcher(
            namespace=ExactNamespaceMatcher(namespace="x"),
            pod=AllPodMatcher(),
            port=specific((80, "TCP")),
        )
        result = simplify([all_80, pod_80])
        assert len(result) == 1
        assert isinstance(result[0], PortsForAllPeersMatcher)

    def test_ports_for_all_merge(self):
        a = PortsForAllPeersMatcher(port=specific((80, "TCP")))
        b = PortsForAllPeersMatcher(port=specific((81, "TCP")))
        result = simplify([a, b])
        assert len(result) == 1
        assert len(result[0].port.ports) == 2
