"""Recipe scenarios: semantic golden checks + oracle/tpu engine agreement
(reference: pkg/recipes — untested there; tested here)."""

import pytest

from cyclonus_tpu.probe.connectivity import (
    CONNECTIVITY_ALLOWED,
    CONNECTIVITY_BLOCKED,
)
from cyclonus_tpu.recipes import ALL_RECIPES


def recipe(name):
    for r in ALL_RECIPES:
        if r.name == name:
            return r
    raise KeyError(name)


def combined(table, fr, to):
    (jr,) = table.get(fr, to).job_results.values()
    return jr.combined


def test_recipe_count():
    assert len(ALL_RECIPES) == 15


def test_all_recipes_parse_policies():
    for r in ALL_RECIPES:
        policies = r.policies()
        assert policies, r.name
        for p in policies:
            assert p.name


def test_01_deny_all_to_web():
    table = recipe("01-deny-all-to-app").run_probe(engine="oracle")
    # web pod unreachable from anyone (incl. itself); everything else open
    for fr in ("x/a", "default/a", "y/c", "default/b"):
        assert combined(table, fr, "default/b") == CONNECTIVITY_BLOCKED
    assert combined(table, "x/a", "y/c") == CONNECTIVITY_ALLOWED
    assert combined(table, "default/b", "x/a") == CONNECTIVITY_ALLOWED


def test_02a_allow_all_overrides_deny_all():
    table = recipe("02a-allow-all-to-app").run_probe(engine="oracle")
    for fr in ("x/a", "default/a", "y/c"):
        assert combined(table, fr, "default/b") == CONNECTIVITY_ALLOWED


def test_04_deny_from_other_namespaces():
    table = recipe("04-deny-other-namespaces").run_probe(engine="oracle")
    assert combined(table, "secondary/a", "secondary/b") == CONNECTIVITY_ALLOWED
    assert combined(table, "x/a", "secondary/b") == CONNECTIVITY_BLOCKED
    assert combined(table, "default/a", "secondary/b") == CONNECTIVITY_BLOCKED
    assert combined(table, "secondary/a", "x/a") == CONNECTIVITY_ALLOWED


def test_06_allow_prod_namespace_only():
    table = recipe("06-allow-prod-namespace").run_probe(engine="oracle")
    # x is labelled purpose=production
    assert combined(table, "x/a", "default/b") == CONNECTIVITY_ALLOWED
    assert combined(table, "y/a", "default/b") == CONNECTIVITY_BLOCKED
    assert combined(table, "default/a", "default/b") == CONNECTIVITY_BLOCKED


def test_07_ns_and_pod_selector():
    table = recipe("07-allow-monitoring-pods").run_probe(engine="oracle")
    # only type=monitoring pods in team=operations namespaces
    assert combined(table, "x/a", "default/b") == CONNECTIVITY_ALLOWED
    assert combined(table, "y/a", "default/b") == CONNECTIVITY_ALLOWED
    assert combined(table, "x/b", "default/b") == CONNECTIVITY_BLOCKED
    # default/a is type=monitoring but default ns has no team=operations
    assert combined(table, "default/a", "default/b") == CONNECTIVITY_BLOCKED


def test_09_port_gate():
    table = recipe("09-allow-port-5000").run_probe(engine="oracle")
    # bare podSelector peer matches only the policy's own namespace
    assert combined(table, "default/a", "default/b") == CONNECTIVITY_ALLOWED
    assert combined(table, "x/a", "default/b") == CONNECTIVITY_BLOCKED
    assert combined(table, "default/c", "default/b") == CONNECTIVITY_BLOCKED


def test_11_deny_egress():
    table = recipe("11-deny-egress").run_probe(engine="oracle")
    assert combined(table, "default/b", "x/a") == CONNECTIVITY_BLOCKED
    assert combined(table, "x/a", "default/b") == CONNECTIVITY_ALLOWED


def test_11a_unserved_port_buckets_as_invalid():
    # the probe targets TCP 53 but every container serves only port 80:
    # jobs land in the bad-port-protocol bucket (resources.go:284-334
    # semantics), same as the reference running recipe 11_2
    from cyclonus_tpu.probe.connectivity import (
        CONNECTIVITY_INVALID_PORT_PROTOCOL,
    )

    table = recipe("11a-deny-egress-allow-dns").run_probe(engine="oracle")
    assert (
        combined(table, "default/b", "x/a") == CONNECTIVITY_INVALID_PORT_PROTOCOL
    )


def test_14_cluster_internal_egress_allowed():
    table = recipe("14-deny-external-egress").run_probe(engine="oracle")
    # namespaceSelector {} allows all in-cluster egress on any port
    assert combined(table, "default/b", "x/a") == CONNECTIVITY_ALLOWED


@pytest.mark.parametrize("r", ALL_RECIPES, ids=lambda r: r.name)
def test_oracle_tpu_engine_agreement(r):
    oracle = r.run_probe(engine="oracle")
    tpu = r.run_probe(engine="tpu")
    assert oracle.render_table() == tpu.render_table()
    assert oracle.render_ingress() == tpu.render_ingress()
    assert oracle.render_egress() == tpu.render_egress()
