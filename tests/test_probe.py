"""L3 tests: resources immutability (ported from resources_test.go), job
fan-out, bad-port buckets, truth tables, and simulated runner engine parity
(oracle vs tpu) at the probe-table level."""

import pytest

from cyclonus_tpu.kube import MockKubernetes
from cyclonus_tpu.kube.netpol import IntOrString
from cyclonus_tpu.kube.yaml_io import load_policies_from_yaml
from cyclonus_tpu.matcher import build_network_policies
from cyclonus_tpu.probe import (
    CONNECTIVITY_INVALID_NAMED_PORT,
    CONNECTIVITY_INVALID_PORT_PROTOCOL,
    Pod,
    ProbeConfig,
    Resources,
    new_simulated_runner,
)
from cyclonus_tpu.probe.probeconfig import PROBE_MODE_SERVICE_NAME


def make_resources() -> Resources:
    kube = MockKubernetes(1.0)
    return Resources.new_default(
        kube,
        ["x", "y", "z"],
        ["a", "b", "c"],
        [80, 81],
        ["TCP", "UDP", "SCTP"],
        pod_creation_timeout_seconds=1,
    )


class TestResources:
    def test_default_creation(self):
        r = make_resources()
        assert len(r.pods) == 9
        assert len(r.namespaces) == 3
        assert all(p.ip.startswith("192.168.") for p in r.pods)
        # 2 ports x 3 protocols = 6 containers per pod
        assert all(len(p.containers) == 6 for p in r.pods)
        # the mock allocates ClusterIPs like a real apiserver so the
        # service-ip probe destination mode works clusterless
        assert r.pods[0].service_ip.startswith("10.96.")

    def test_immutable_updates(self):
        # resources_test.go:immutability specs
        r = make_resources()
        r2 = r.create_namespace("w", {"ns": "w"})
        assert "w" not in r.namespaces and "w" in r2.namespaces

        r3 = r.update_namespace_labels("x", {"ns": "x", "extra": "1"})
        assert r.namespaces["x"] == {"ns": "x"}
        assert r3.namespaces["x"]["extra"] == "1"

        r4 = r.delete_namespace("x")
        assert len(r4.pods) == 6 and len(r.pods) == 9

        r5 = r.set_pod_labels("x", "a", {"pod": "a", "new": "1"})
        assert r.get_pod("x", "a").labels == {"pod": "a"}
        assert r5.get_pod("x", "a").labels["new"] == "1"

        r6 = r.delete_pod("x", "a")
        assert len(r6.pods) == 8
        with pytest.raises(Exception):
            r6.get_pod("x", "a")

        r7 = r.create_pod("x", "d", {"pod": "d"})
        assert len(r7.pods) == 10
        # new pods copy the first pod's containers (reference TODO preserved)
        assert r7.get_pod("x", "d").containers == r.pods[0].containers

    def test_error_cases(self):
        r = make_resources()
        with pytest.raises(Exception):
            r.create_namespace("x", {})
        with pytest.raises(Exception):
            r.delete_namespace("nope")
        with pytest.raises(Exception):
            r.set_pod_labels("x", "nope", {})
        with pytest.raises(Exception):
            r.create_pod("nope", "d", {})


class TestJobFanOut:
    def test_all_available(self):
        r = make_resources()
        jobs = r.get_jobs_all_available_servers(PROBE_MODE_SERVICE_NAME)
        # 9 x 9 pairs x 6 containers
        assert len(jobs.valid) == 9 * 9 * 6
        assert not jobs.bad_named_port and not jobs.bad_port_protocol
        j = jobs.valid[0]
        assert j.to_host.endswith(".svc.cluster.local")
        assert j.resolved_port in (80, 81)
        assert j.resolved_port_name.startswith("serve-")

    def test_numbered_port(self):
        r = make_resources()
        jobs = r.get_jobs_for_named_port_protocol(
            IntOrString(80), "TCP", PROBE_MODE_SERVICE_NAME
        )
        assert len(jobs.valid) == 81
        assert jobs.valid[0].resolved_port_name == "serve-80-tcp"

    def test_unserved_numbered_port(self):
        r = make_resources()
        jobs = r.get_jobs_for_named_port_protocol(
            IntOrString(7777), "TCP", PROBE_MODE_SERVICE_NAME
        )
        assert len(jobs.valid) == 0
        assert len(jobs.bad_port_protocol) == 81

    def test_named_port(self):
        r = make_resources()
        jobs = r.get_jobs_for_named_port_protocol(
            IntOrString("serve-81-udp"), "UDP", PROBE_MODE_SERVICE_NAME
        )
        assert len(jobs.valid) == 81
        assert jobs.valid[0].resolved_port == 81

    def test_bad_named_port(self):
        r = make_resources()
        jobs = r.get_jobs_for_named_port_protocol(
            IntOrString("no-such-port"), "TCP", PROBE_MODE_SERVICE_NAME
        )
        assert len(jobs.bad_named_port) == 81


DENY_ALL_Y = """
apiVersion: networking.k8s.io/v1
kind: NetworkPolicy
metadata:
  name: deny-all
  namespace: y
spec:
  podSelector: {}
  policyTypes:
  - Ingress
"""


class TestSimulatedRunner:
    @pytest.mark.parametrize("engine", ["oracle", "tpu"])
    def test_deny_all_y_table(self, engine):
        r = make_resources()
        policy = build_network_policies(True, load_policies_from_yaml(DENY_ALL_Y))
        runner = new_simulated_runner(policy, engine=engine)
        table = runner.run_probe_for_config(
            ProbeConfig.port_protocol_config(IntOrString(80), "TCP"), r
        )
        for fr, to in table.wrapped.keys():
            item = table.get(fr, to)
            result = list(item.job_results.values())[0]
            expected = "blocked" if to.startswith("y/") else "allowed"
            assert result.combined == expected, (fr, to)

    def test_engines_agree_all_available(self):
        r = make_resources()
        policy = build_network_policies(True, load_policies_from_yaml(DENY_ALL_Y))
        t_oracle = new_simulated_runner(policy, engine="oracle").run_probe_for_config(
            ProbeConfig.all_available_config(), r
        )
        t_tpu = new_simulated_runner(policy, engine="tpu").run_probe_for_config(
            ProbeConfig.all_available_config(), r
        )
        for fr, to in t_oracle.wrapped.keys():
            a = t_oracle.get(fr, to).job_results
            b = t_tpu.get(fr, to).job_results
            assert set(a) == set(b)
            for k in a:
                assert (a[k].ingress, a[k].egress, a[k].combined) == (
                    b[k].ingress,
                    b[k].egress,
                    b[k].combined,
                ), (fr, to, k)

    def test_tpu_engine_demotes_on_dead_backend(self, monkeypatch):
        """A dead/wedged accelerator backend must DEMOTE the tpu engine
        to the host path with identical verdicts — not hang the probe
        (round-3 failure: `generate --mock` blocked 300s+ on a dead
        tunnel because the simulated runner initialized the backend
        unbounded)."""
        import cyclonus_tpu.probe.runner as runner_mod

        r = make_resources()
        policy = build_network_policies(True, load_policies_from_yaml(DENY_ALL_Y))
        monkeypatch.setattr(runner_mod, "accelerator_available", lambda: False)
        runner = new_simulated_runner(policy, engine="tpu")
        table = runner.run_probe_for_config(
            ProbeConfig.port_protocol_config(IntOrString(80), "TCP"), r
        )
        assert runner.job_runner.engine in ("native", "oracle")  # demoted
        want = new_simulated_runner(policy, engine="oracle").run_probe_for_config(
            ProbeConfig.port_protocol_config(IntOrString(80), "TCP"), r
        )
        for fr, to in want.wrapped.keys():
            a = want.get(fr, to).job_results
            b = table.get(fr, to).job_results
            assert set(a) == set(b)
            for k in a:
                assert a[k].combined == b[k].combined, (fr, to, k)

    def test_accelerator_available_probe(self, monkeypatch):
        """The bounded probe: available on this (CPU) backend, cached
        after the first call, and trust-without-probe when the timeout
        knob is <= 0."""
        import cyclonus_tpu.probe.runner as runner_mod

        monkeypatch.setattr(
            runner_mod, "_BACKEND_STATE", {"checked": False, "available": False}
        )
        assert runner_mod.accelerator_available(timeout_s=60) is True
        assert runner_mod._BACKEND_STATE["checked"] is True
        # cached: a poisoned cache is returned as-is, no re-probe
        runner_mod._BACKEND_STATE["available"] = False
        assert runner_mod.accelerator_available(timeout_s=60) is False
        monkeypatch.setattr(
            runner_mod, "_BACKEND_STATE", {"checked": False, "available": False}
        )
        assert runner_mod.accelerator_available(timeout_s=0) is True

    def test_bad_buckets_in_table(self):
        r = make_resources()
        policy = build_network_policies(True, [])
        runner = new_simulated_runner(policy, engine="tpu")
        table = runner.run_probe_for_config(
            ProbeConfig.port_protocol_config(IntOrString("no-such"), "TCP"), r
        )
        result = list(table.get("x/a", "x/b").job_results.values())[0]
        assert result.combined == CONNECTIVITY_INVALID_NAMED_PORT

        table2 = runner.run_probe_for_config(
            ProbeConfig.port_protocol_config(IntOrString(9999), "TCP"), r
        )
        result2 = list(table2.get("x/a", "x/b").job_results.values())[0]
        assert result2.combined == CONNECTIVITY_INVALID_PORT_PROTOCOL

    def test_table_rendering(self):
        r = make_resources()
        policy = build_network_policies(True, load_policies_from_yaml(DENY_ALL_Y))
        runner = new_simulated_runner(policy, engine="tpu")
        table = runner.run_probe_for_config(
            ProbeConfig.port_protocol_config(IntOrString(80), "TCP"), r
        )
        rendered = table.render_table()
        assert "x/a" in rendered and "z/c" in rendered
        assert "X" in rendered and "." in rendered
        # multi-port render path
        table_multi = runner.run_probe_for_config(
            ProbeConfig.all_available_config(), r
        )
        rendered_multi = table_multi.render_table()
        assert "TCP/80" in rendered_multi


class TestKubeRunner:
    def test_mock_exec_all_pass(self):
        from cyclonus_tpu.probe import new_kube_runner

        kube = MockKubernetes(1.0)
        r = Resources.new_default(
            kube, ["x"], ["a", "b"], [80], ["TCP"], pod_creation_timeout_seconds=1
        )
        runner = new_kube_runner(kube)
        table = runner.run_probe_for_config(
            ProbeConfig.port_protocol_config(IntOrString(80), "TCP"), r
        )
        for fr, to in table.wrapped.keys():
            result = list(table.get(fr, to).job_results.values())[0]
            assert result.combined == "allowed"
            assert result.ingress is None  # kube probes only see combined

    def test_mock_exec_policy_aware(self):
        # exec_verdict_fn lets the mock emulate a CNI
        from cyclonus_tpu.probe import new_kube_runner

        kube = MockKubernetes(1.0)
        r = Resources.new_default(
            kube, ["x"], ["a", "b"], [80], ["TCP"], pod_creation_timeout_seconds=1
        )
        kube.exec_verdict_fn = lambda ns, pod, cont, cmd: pod != "a"
        runner = new_kube_runner(kube)
        table = runner.run_probe_for_config(
            ProbeConfig.port_protocol_config(IntOrString(80), "TCP"), r
        )
        assert (
            list(table.get("x/a", "x/b").job_results.values())[0].combined
            == "blocked"
        )
        assert (
            list(table.get("x/b", "x/a").job_results.values())[0].combined
            == "allowed"
        )
