"""Unit tests for tools/tunnel_wait.py — the round-long bench watchdog.
The subprocess boundary is stubbed; what's under test is the artifact
routing (success vs .failed.json), the backstop arithmetic, and the
JSON parsing contract shared with bench.last_json_line."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
sys.path.insert(0, REPO)

import tunnel_wait


class _Proc:
    def __init__(self, stdout, rc=0, stderr=""):
        self.stdout = stdout
        self.stderr = stderr
        self.returncode = rc


class TestRunBench:
    def _run(self, monkeypatch, tmp_path, stdout, rc=0, raise_timeout=False):
        def fake_run(*a, **kw):
            if raise_timeout:
                raise subprocess.TimeoutExpired(cmd="bench", timeout=1)
            return _Proc(stdout, rc)

        monkeypatch.setattr(tunnel_wait.subprocess, "run", fake_run)
        out = str(tmp_path / "latest.json")
        result = tunnel_wait.run_bench(out, bound_s=5)
        return result, out

    def test_success_written_to_latest(self, monkeypatch, tmp_path):
        line = json.dumps({"metric": "m", "value": 123, "unit": "cells/sec"})
        result, out = self._run(monkeypatch, tmp_path, f"noise\n{line}\n")
        assert result["value"] == 123
        assert result["bench_rc"] == 0
        assert json.load(open(out))["value"] == 123
        assert not os.path.exists(out.replace(".json", ".failed.json"))

    def test_failure_does_not_clobber_success(self, monkeypatch, tmp_path):
        good = json.dumps({"metric": "m", "value": 99, "unit": "cells/sec"})
        self._run(monkeypatch, tmp_path, f"{good}\n")
        bad = json.dumps({"metric": "m", "value": 0, "error": "tunnel dead"})
        result, out = self._run(monkeypatch, tmp_path, f"{bad}\n", rc=3)
        assert result["error"] == "tunnel dead"
        # the latest-success artifact survives; the failure lands aside
        assert json.load(open(out))["value"] == 99
        failed = out.replace(".json", ".failed.json")
        assert json.load(open(failed))["error"] == "tunnel dead"

    def test_truncated_json_is_error_result(self, monkeypatch, tmp_path):
        """A bench killed mid-write leaves a truncated final JSON line:
        recorded as an error result, never a watchdog-killing raise."""
        result, out = self._run(
            monkeypatch, tmp_path, '{"metric": "m", "val', rc=2
        )
        assert "unparseable JSON" in result["error"]
        assert result["bench_rc"] == 2
        failed = out.replace(".json", ".failed.json")
        assert json.load(open(failed))["bench_rc"] == 2
        assert not os.path.exists(out)

    def test_no_json_output(self, monkeypatch, tmp_path):
        result, out = self._run(monkeypatch, tmp_path, "garbage only\n", rc=7)
        assert "no JSON" in result["error"]
        assert result["bench_rc"] == 7

    def test_subprocess_timeout(self, monkeypatch, tmp_path):
        result, out = self._run(
            monkeypatch, tmp_path, "", raise_timeout=True
        )
        assert "subprocess bound" in result["error"]
        assert result["bench_rc"] is None

    def test_backstop_exceeds_inner_deadline(self, monkeypatch):
        """The subprocess bound must fire AFTER bench.py's own watchdog
        (which prints the diagnostic JSON this tool exists to capture)."""
        captured = {}

        def fake_run(*a, timeout=None, **kw):
            captured["timeout"] = timeout
            return _Proc(json.dumps({"value": 1, "metric": "m"}) + "\n")

        monkeypatch.setattr(tunnel_wait.subprocess, "run", fake_run)
        monkeypatch.setenv("BENCH_DEADLINE_S", "700")
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            tunnel_wait.run_bench(os.path.join(d, "o.json"))
        assert captured["timeout"] > 700


class TestProbe:
    def test_probe_timeout_counts_dead(self, monkeypatch):
        def fake_run(*a, **kw):
            raise subprocess.TimeoutExpired(cmd="p", timeout=1)

        monkeypatch.setattr(tunnel_wait.subprocess, "run", fake_run)
        assert tunnel_wait.probe_tunnel(0.1) is False

    def test_probe_rc_maps(self, monkeypatch):
        for rc, want in ((0, True), (3, False), (1, False)):
            monkeypatch.setattr(
                tunnel_wait.subprocess,
                "run",
                lambda *a, _rc=rc, **kw: _Proc("", _rc),
            )
            assert tunnel_wait.probe_tunnel(0.1) is want

    def test_retry_backoff_until_alive(self, monkeypatch):
        """Two dead probes, then alive: three attempts, two jittered
        backoff sleeps in the expected exponential envelope, every
        attempt counted into the telemetry layer by outcome."""
        import random

        from cyclonus_tpu.telemetry.instruments import TUNNEL_PROBE_ATTEMPTS

        rcs = iter([3, 3, 0])
        monkeypatch.setattr(
            tunnel_wait.subprocess,
            "run",
            lambda *a, **kw: _Proc("", next(rcs)),
        )
        sleeps = []
        monkeypatch.setattr(tunnel_wait.time, "sleep", sleeps.append)
        dead0 = TUNNEL_PROBE_ATTEMPTS.value(outcome="dead")
        alive0 = TUNNEL_PROBE_ATTEMPTS.value(outcome="alive")
        assert (
            tunnel_wait.probe_tunnel(
                0.1, attempts=4, backoff_s=2.0, rng=random.Random(7)
            )
            is True
        )
        assert len(sleeps) == 2
        # full jitter: base * 2^(n-1) * [0.5, 1.5)
        assert 1.0 <= sleeps[0] < 3.0
        assert 2.0 <= sleeps[1] < 6.0
        assert TUNNEL_PROBE_ATTEMPTS.value(outcome="dead") == dead0 + 2
        assert TUNNEL_PROBE_ATTEMPTS.value(outcome="alive") == alive0 + 1

    def test_retry_exhaustion_is_dead(self, monkeypatch):
        monkeypatch.setattr(
            tunnel_wait.subprocess, "run", lambda *a, **kw: _Proc("", 3)
        )
        sleeps = []
        monkeypatch.setattr(tunnel_wait.time, "sleep", sleeps.append)
        assert tunnel_wait.probe_tunnel(0.1, attempts=3) is False
        assert len(sleeps) == 2  # no sleep after the final attempt


class TestStructuredLastError:
    """Satellite: the probe/bench retry loops report a structured
    last-error (exception class + truncated message) into the JSON
    line, so perfobs forensics can split SIGILL-class host faults from
    tunnel death without scraping the stderr tail."""

    def test_probe_dead_records_exit_and_stderr(self, monkeypatch):
        monkeypatch.setattr(
            tunnel_wait.subprocess,
            "run",
            lambda *a, **kw: _Proc("", 3, stderr="Illegal instruction\n"),
        )
        state = {}
        assert tunnel_wait.probe_tunnel(0.1, state=state) is False
        assert state["attempts"] == 1
        assert state["last_error"]["type"] == "ProbeExit3"
        assert "Illegal instruction" in state["last_error"]["message"]

    def test_probe_timeout_records_exception_class(self, monkeypatch):
        def fake_run(*a, **kw):
            raise subprocess.TimeoutExpired(cmd="p", timeout=1)

        monkeypatch.setattr(tunnel_wait.subprocess, "run", fake_run)
        state = {}
        assert tunnel_wait.probe_tunnel(0.1, state=state) is False
        assert state["last_error"]["type"] == "TimeoutExpired"

    def test_probe_alive_clears_last_error(self, monkeypatch):
        rcs = iter([3, 0])
        monkeypatch.setattr(
            tunnel_wait.subprocess,
            "run",
            lambda *a, **kw: _Proc("", next(rcs)),
        )
        monkeypatch.setattr(tunnel_wait.time, "sleep", lambda s: None)
        state = {}
        assert tunnel_wait.probe_tunnel(0.1, attempts=2, state=state)
        assert state["attempts"] == 2
        assert state["last_error"] is None

    def test_run_bench_no_json_carries_structured_error(
        self, monkeypatch, tmp_path
    ):
        monkeypatch.setattr(
            tunnel_wait.subprocess,
            "run",
            lambda *a, **kw: _Proc("no json here", 1, stderr="SIGILL\n"),
        )
        out = str(tmp_path / "o.json")
        result = tunnel_wait.run_bench(out, bound_s=5)
        assert result["last_error"]["type"] == "BenchExit1"
        assert "SIGILL" in result["last_error"]["message"]

    def test_run_bench_timeout_carries_structured_error(
        self, monkeypatch, tmp_path
    ):
        def fake_run(*a, **kw):
            raise subprocess.TimeoutExpired(cmd="bench", timeout=5)

        monkeypatch.setattr(tunnel_wait.subprocess, "run", fake_run)
        result = tunnel_wait.run_bench(str(tmp_path / "o.json"), bound_s=5)
        assert result["last_error"]["type"] == "TimeoutExpired"

    def test_run_bench_attaches_probe_forensics(self, monkeypatch, tmp_path):
        line = json.dumps({"value": 1, "unit": "cells/sec",
                           "failure_class": "ok"})
        monkeypatch.setattr(
            tunnel_wait.subprocess,
            "run",
            lambda *a, **kw: _Proc(line + "\n", 0),
        )
        probe_state = {
            "attempts": 3,
            "last_error": {"type": "ProbeExit3", "message": "no tpu"},
        }
        result = tunnel_wait.run_bench(
            str(tmp_path / "o.json"), bound_s=5,
            probe_forensics=probe_state,
        )
        assert result["probe"]["attempts"] == 3
        assert result["probe"]["last_error"]["type"] == "ProbeExit3"


class TestFailureClass:
    def test_success_result_carries_ok(self, monkeypatch, tmp_path):
        line = json.dumps(
            {"metric": "m", "value": 123, "unit": "cells/sec"}
        )
        monkeypatch.setattr(
            tunnel_wait.subprocess, "run", lambda *a, **kw: _Proc(line + "\n")
        )
        result = tunnel_wait.run_bench(str(tmp_path / "o.json"), bound_s=5)
        assert result["failure_class"] == "ok"

    def test_explicit_class_preserved(self, monkeypatch, tmp_path):
        line = json.dumps(
            {"metric": "m (FAILED)", "value": 0,
             "error": "backend init failed after 3 attempt(s): boom",
             "failure_class": "backend_init"}
        )
        monkeypatch.setattr(
            tunnel_wait.subprocess,
            "run",
            lambda *a, **kw: _Proc(line + "\n", rc=4),
        )
        result = tunnel_wait.run_bench(str(tmp_path / "o.json"), bound_s=5)
        assert result["failure_class"] == "backend_init"

    def test_subprocess_bound_classifies_tunnel(self, monkeypatch, tmp_path):
        """The outer backstop firing means bench's own watchdogs never
        printed — the pre-import-hang signature of a dead tunnel."""

        def fake_run(*a, **kw):
            raise subprocess.TimeoutExpired(cmd="bench", timeout=1)

        monkeypatch.setattr(tunnel_wait.subprocess, "run", fake_run)
        result = tunnel_wait.run_bench(str(tmp_path / "o.json"), bound_s=5)
        assert result["failure_class"] == "tunnel"

    def test_no_json_classifies_from_stdout_tail(self, monkeypatch, tmp_path):
        """A bench that died printing only the backend warning (the r03
        signature) leaves its evidence on STDOUT, not in any JSON — the
        round artifact must classify backend_init, not engine."""
        tail = (
            "WARNING: Platform 'axon' is experimental\n"
            "UserWarning: Error reading cache entry: JaxRuntimeError: "
            "UNAVAILABLE: TPU backend setup/compile error (Unavailable).\n"
        )
        monkeypatch.setattr(
            tunnel_wait.subprocess,
            "run",
            lambda *a, **kw: _Proc(tail, rc=1),
        )
        result = tunnel_wait.run_bench(str(tmp_path / "o.json"), bound_s=5)
        assert "no JSON" in result["error"]
        assert result["failure_class"] == "backend_init"

    def test_silent_rc124_no_json_is_tunnel(self, monkeypatch, tmp_path):
        monkeypatch.setattr(
            tunnel_wait.subprocess,
            "run",
            lambda *a, **kw: _Proc("WARNING: axon\n", rc=124),
        )
        result = tunnel_wait.run_bench(str(tmp_path / "o.json"), bound_s=5)
        assert result["failure_class"] == "tunnel"
