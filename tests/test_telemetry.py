"""Telemetry subsystem tests (docs/DESIGN.md "Telemetry"):

  * span registry hammered from 8 threads (counts conserved, nesting
    isolated per thread);
  * golden Prometheus text exposition (stable names/labels/ordering);
  * flight-recorder dump-on-crash via a subprocess;
  * a probe run with --metrics-port exposes the engine metrics over a
    real (curl-able) HTTP scrape;
  * hot-path overhead with telemetry enabled <2% vs the disabled path on
    the steady-state bench eval loop;
  * instrumentation is JX001-clean: tools/jaxlint.py over engine/ AND
    telemetry/ finds nothing (no device syncs smuggled into jit paths).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from cyclonus_tpu import telemetry
from cyclonus_tpu.telemetry import instruments as ti
from cyclonus_tpu.telemetry.metrics import MetricRegistry
from cyclonus_tpu.telemetry.spans import span
from cyclonus_tpu.utils import tracing
from cyclonus_tpu.utils.bounded import BoundedRing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestSpans:
    def test_nesting_attributes_and_flat_backcompat(self):
        telemetry.SPANS.reset()
        with tracing.phase("t.outer"):
            with span("t.inner", pods=4) as s:
                s.set(targets=7)
        flat = tracing.stats()
        assert flat["t.outer"]["count"] == 1
        assert flat["t.inner"]["count"] == 1
        tree = telemetry.SPANS.tree()
        assert "t.outer" in tree
        assert tree["t.outer/t.inner"]["attrs"] == {"pods": 4, "targets": 7}
        rendered = telemetry.SPANS.render_tree()
        assert "t.inner" in rendered and "pods=4" in rendered

    def test_registry_concurrency_8_threads(self):
        """8 threads hammer the registry with nested spans; every count
        must be conserved and nesting must stay thread-local."""
        telemetry.SPANS.reset()
        n_threads, n_iter = 8, 400
        errors = []

        def hammer(tid):
            try:
                for i in range(n_iter):
                    with span("conc.outer", thread=tid):
                        with span("conc.inner", i=i):
                            pass
                        # a sibling at the same level
                        with span(f"conc.leaf{tid % 2}"):
                            pass
            except Exception as e:  # pragma: no cover - failure detail
                errors.append(e)

        threads = [
            threading.Thread(target=hammer, args=(t,)) for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        flat = telemetry.SPANS.stats()
        total = n_threads * n_iter
        assert flat["conc.outer"]["count"] == total
        assert flat["conc.inner"]["count"] == total
        assert (
            flat["conc.leaf0"]["count"] + flat["conc.leaf1"]["count"] == total
        )
        tree = telemetry.SPANS.tree()
        # nesting held under concurrency: children recorded under outer
        assert tree["conc.outer/conc.inner"]["count"] == total
        # no stray top-level inner spans (a cross-thread parent leak
        # would materialize inner at the root or under a foreign path)
        assert "conc.inner" not in tree

    def test_disabled_spans_cost_nothing_and_record_nothing(self):
        telemetry.SPANS.reset()
        telemetry.set_enabled(False)
        try:
            with span("off.a") as s:
                s.set(x=1)
        finally:
            telemetry.set_enabled(True)
        assert "off.a" not in telemetry.SPANS.stats()


class TestMetrics:
    def test_prometheus_golden(self):
        """Byte-stable exposition: names, labels, ordering, histogram
        cumulative buckets + sum/count."""
        reg = MetricRegistry()
        c = reg.counter("cyclonus_tpu_test_events_total", "Test events.")
        g = reg.gauge(
            "cyclonus_tpu_test_bytes", "Test bytes.", labelnames=("kind",)
        )
        h = reg.histogram(
            "cyclonus_tpu_test_latency_seconds",
            "Test latency.",
            buckets=(0.01, 0.1, 1.0),
        )
        c.inc()
        c.inc(2)
        g.set(1024, kind="slab")
        g.set(5.5, kind="pre")
        h.observe(0.05)
        h.observe(0.05)
        h.observe(10.0)
        golden = (
            "# HELP cyclonus_tpu_test_bytes Test bytes.\n"
            "# TYPE cyclonus_tpu_test_bytes gauge\n"
            'cyclonus_tpu_test_bytes{kind="pre"} 5.5\n'
            'cyclonus_tpu_test_bytes{kind="slab"} 1024\n'
            "# HELP cyclonus_tpu_test_events_total Test events.\n"
            "# TYPE cyclonus_tpu_test_events_total counter\n"
            "cyclonus_tpu_test_events_total 3\n"
            "# HELP cyclonus_tpu_test_latency_seconds Test latency.\n"
            "# TYPE cyclonus_tpu_test_latency_seconds histogram\n"
            'cyclonus_tpu_test_latency_seconds_bucket{le="0.01"} 0\n'
            'cyclonus_tpu_test_latency_seconds_bucket{le="0.1"} 2\n'
            'cyclonus_tpu_test_latency_seconds_bucket{le="1"} 2\n'
            'cyclonus_tpu_test_latency_seconds_bucket{le="+Inf"} 3\n'
            "cyclonus_tpu_test_latency_seconds_sum 10.1\n"
            "cyclonus_tpu_test_latency_seconds_count 3\n"
        )
        assert reg.render_prometheus() == golden

    def test_snapshot_json_roundtrip_and_idempotent_registration(self):
        reg = MetricRegistry()
        c1 = reg.counter("a_total", "A.")
        c2 = reg.counter("a_total", "A.")
        assert c1 is c2
        with pytest.raises(ValueError):
            reg.gauge("a_total", "not a counter")
        c1.inc(4)
        snap = json.loads(json.dumps(reg.snapshot()))
        assert snap["a_total"]["samples"][0]["value"] == 4

    def test_label_validation_and_counter_monotonicity(self):
        reg = MetricRegistry()
        c = reg.counter("b_total", "B.", labelnames=("x",))
        with pytest.raises(ValueError):
            c.inc(1, wrong="label")
        with pytest.raises(ValueError):
            c.inc(-1, x="v")
        c.inc(x="v")
        assert c.value(x="v") == 1

    def test_disabled_metrics_do_not_move(self):
        reg = MetricRegistry()
        c = reg.counter("c_total", "C.")
        telemetry.set_enabled(False)
        try:
            c.inc(100)
        finally:
            telemetry.set_enabled(True)
        assert c.value() == 0


class TestBoundedRing:
    def test_window_and_lifetime_count(self):
        ring = BoundedRing(3)
        for i in range(7):
            ring.append(i)
        assert ring.snapshot() == [4, 5, 6]
        assert len(ring) == 3
        assert ring.appended == 7
        ring.clear()
        assert ring.snapshot() == [] and ring.appended == 0
        with pytest.raises(ValueError):
            BoundedRing(0)


class TestFlightRecorder:
    def test_eval_flight_records_ok_and_error(self):
        telemetry.recorder.reset()
        with ti.eval_flight("test.path", 16, 2) as fl:
            fl.set(cells=512)
        with pytest.raises(RuntimeError):
            with ti.eval_flight("test.path", 16, 2):
                raise RuntimeError("boom")
        ents = telemetry.recorder.entries()
        assert ents[-2]["outcome"] == "ok" and ents[-2]["cells"] == 512
        assert ents[-1]["outcome"].startswith("RuntimeError")
        assert ents[-1]["seq"] > ents[-2]["seq"]

    def test_dump_on_demand(self, tmp_path):
        telemetry.recorder.reset()
        telemetry.recorder.record(path="x", n_pods=1, q=1, outcome="ok")
        p = telemetry.recorder.dump(str(tmp_path / "fr.json"))
        data = json.loads(open(p).read())
        assert data["reason"] == "on-demand"
        assert data["entries"][0]["path"] == "x"

    def test_dump_on_crash_subprocess(self, tmp_path):
        """An unhandled crash must leave a flight-recorder JSON dump via
        the chained excepthook, without masking the crash itself."""
        dump_path = str(tmp_path / "crash.json")
        code = (
            "from cyclonus_tpu.telemetry import instruments as ti\n"
            "with ti.eval_flight('counts.pallas', 64, 2) as fl:\n"
            "    fl.set(cells=8192)\n"
            "raise RuntimeError('engine exploded')\n"
        )
        env = dict(os.environ, CYCLONUS_FLIGHT_RECORDER_PATH=dump_path)
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=120,
            cwd=REPO,
            env=env,
        )
        assert proc.returncode != 0
        assert "engine exploded" in proc.stderr  # crash not masked
        data = json.loads(open(dump_path).read())
        assert data["reason"].startswith("crash: RuntimeError")
        assert data["entries"][0]["path"] == "counts.pallas"
        assert data["entries"][0]["cells"] == 8192

    def test_crash_hook_skips_benign_terminations(self, tmp_path):
        """sys.exit / Ctrl-C / a closed stdout pipe are not crashes: the
        _NO_DUMP exemptions must leave no dump file behind even with a
        populated ring (the hook is installed by the record())."""
        for snippet, rc in (
            ("raise SystemExit(3)", 3),
            ("raise KeyboardInterrupt()", None),  # interpreter picks rc
            ("raise BrokenPipeError('stdout gone')", 1),
        ):
            dump_path = str(tmp_path / "no-dump.json")
            code = (
                "from cyclonus_tpu.telemetry import recorder\n"
                "recorder.record(path='x', outcome='ok')\n"
                f"{snippet}\n"
            )
            env = dict(os.environ, CYCLONUS_FLIGHT_RECORDER_PATH=dump_path)
            proc = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=120,
                cwd=REPO,
                env=env,
            )
            assert proc.returncode != 0, snippet
            if rc is not None:
                assert proc.returncode == rc, snippet
            assert not os.path.exists(dump_path), (
                f"{snippet} must not leave a crash dump"
            )

    def test_telemetry_cli_renders_flight_file(self, tmp_path, capsys):
        from cyclonus_tpu.cli.root import main

        telemetry.recorder.reset()
        telemetry.recorder.record(
            path="counts.pallas", n_pods=9, q=2, seconds=0.5, outcome="ok"
        )
        p = telemetry.recorder.dump(str(tmp_path / "fr.json"))
        assert main(["telemetry", "--flight-file", p]) == 0
        out = capsys.readouterr().out
        assert "counts.pallas" in out and "n_pods=9" in out

    def test_telemetry_cli_prometheus_and_json(self, capsys):
        from cyclonus_tpu.cli.root import main

        assert main(["telemetry", "--format", "prometheus"]) == 0
        out = capsys.readouterr().out
        assert "# TYPE cyclonus_tpu_eval_cells_per_sec gauge" in out
        assert main(["telemetry", "--format", "json"]) == 0
        snap = json.loads(capsys.readouterr().out)
        assert "metrics" in snap and "flight_recorder" in snap


def _scrape(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.read().decode()


def _scrape_status(url):
    """(status, body) — 503s must be readable, not raised."""
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class TestReadiness:
    """Liveness vs readiness split (docs/DESIGN.md "Cold start &
    chaos"): /healthz answers liveness unconditionally; /readyz
    consults the optional registered callback so warming != ready."""

    def _server(self):
        from cyclonus_tpu.telemetry.server import start_metrics_server

        return start_metrics_server(0)

    def test_healthz_stays_liveness_and_readyz_defaults_ready(self):
        from cyclonus_tpu.telemetry.server import (
            register_readiness,
            stop_metrics_server,
        )

        register_readiness(None)
        srv = self._server()
        try:
            assert _scrape(srv.url + "/healthz").strip() == "ok"
            status, body = _scrape_status(srv.url + "/readyz")
            assert status == 200 and body.startswith("ready")
        finally:
            stop_metrics_server()

    def test_readyz_follows_callback_healthz_does_not(self):
        """The regression the satellite fix exists for: one mounted
        server, one readiness answer per STATE — a warming callback
        turns /readyz 503 while /healthz keeps answering 200."""
        from cyclonus_tpu.telemetry.server import (
            register_readiness,
            stop_metrics_server,
        )

        state = {"ready": False}
        register_readiness(lambda: (state["ready"], "warming test"))
        srv = self._server()
        try:
            status, body = _scrape_status(srv.url + "/readyz")
            assert status == 503 and "warming" in body
            assert _scrape(srv.url + "/healthz").strip() == "ok"
            state["ready"] = True
            status, body = _scrape_status(srv.url + "/readyz")
            assert status == 200 and "warming test" in body
        finally:
            register_readiness(None)
            stop_metrics_server()

    def test_broken_callback_reads_not_ready(self):
        from cyclonus_tpu.telemetry.server import (
            register_readiness,
            stop_metrics_server,
        )

        def boom():
            raise RuntimeError("probe exploded")

        register_readiness(boom)
        srv = self._server()
        try:
            status, body = _scrape_status(srv.url + "/readyz")
            assert status == 503 and "probe exploded" in body
        finally:
            register_readiness(None)
            stop_metrics_server()


class TestMetricsEndpoint:
    def test_probe_run_with_metrics_port_exposes_engine_metrics(self):
        """Acceptance: a probe run with --metrics-port serves the engine
        metrics over HTTP — cells/sec gauge, HBM watermarks, cache
        hit/miss counters — scraped with a real GET."""
        from cyclonus_tpu.cli.root import main
        from cyclonus_tpu.telemetry.server import (
            active_server,
            stop_metrics_server,
        )

        telemetry.reset()
        try:
            rc = main(
                [
                    "probe",
                    "--mock",
                    "--perfect-cni",
                    "--ignore-loopback",
                    "--metrics-port",
                    "0",
                ]
            )
            assert rc == 0
            srv = active_server()
            assert srv is not None
            body = _scrape(srv.url + "/metrics")
            for name in (
                "cyclonus_tpu_eval_cells_per_sec",
                "cyclonus_tpu_slab_hbm_bytes",
                "cyclonus_tpu_slab_hbm_budget_bytes",
                "cyclonus_tpu_pre_cache_hits_total",
                "cyclonus_tpu_pre_cache_misses_total",
                "cyclonus_tpu_slab_ops_cache_hits_total",
                "cyclonus_tpu_slab_ops_cache_misses_total",
            ):
                assert name in body, f"{name} missing from exposition"
            # the probe's simulated grid evaluation went through the
            # instrumented engine: dispatches and verdicts moved
            assert 'cyclonus_tpu_eval_dispatches_total{path="grid"}' in body
            snap = json.loads(_scrape(srv.url + "/telemetry.json"))
            assert snap["metrics"]["cyclonus_tpu_verdicts_total"]["samples"]
            assert any(
                e["path"] == "grid" for e in snap["flight_recorder"]
            )
            assert _scrape(srv.url + "/healthz").strip() == "ok"
        finally:
            stop_metrics_server()


@pytest.fixture(scope="module")
def steady_engine():
    """A small engine at the pinned-precompute steady state (the bench
    eval loop's regime), shared by the overhead test.  Pinned to the
    CYCLONUS_PACK=0 dtype plan: the 2% telemetry budget is calibrated
    against the dense steady-state floor, and the packed kernel roughly
    halved the CPU floor — failing the telemetry layer because the
    ENGINE got faster would invert the test's meaning (on hardware the
    eval floor is orders of magnitude above the fixed ~tens-of-us
    telemetry cost either way)."""
    import os
    import random

    sys.path.insert(0, REPO)
    from bench import build_synthetic

    from cyclonus_tpu.engine import PortCase, TpuPolicyEngine
    from cyclonus_tpu.matcher import build_network_policies

    pods, namespaces, policies = build_synthetic(512, 48, random.Random(7))
    policy = build_network_policies(True, policies)
    saved = os.environ.get("CYCLONUS_PACK")
    os.environ["CYCLONUS_PACK"] = "0"
    try:
        engine = TpuPolicyEngine(policy, pods, namespaces)
    finally:
        if saved is None:
            os.environ.pop("CYCLONUS_PACK", None)
        else:
            os.environ["CYCLONUS_PACK"] = saved
    cases = [PortCase(80, "serve-80-tcp", "TCP")]
    for _ in range(3):  # reach the split/pinned steady state
        engine.evaluate_grid_counts(cases, backend="pallas")
    return engine, cases


class TestOverhead:
    @staticmethod
    def _per_eval_telemetry_ops():
        """Exactly the telemetry call sequence one steady-state counts
        eval executes (api._counts_pallas_dispatch): the flight wrapper,
        the branch attrs, the cache counter, the two phase spans, and
        the dispatch/execute split gauges."""
        with ti.eval_flight("counts.pallas", 512, 1) as fl:
            fl.set(mode="steady", slab=False)
            ti.PRE_CACHE_HITS.inc()
            with span("engine.dispatch"):
                pass
            ti.EVAL_DISPATCH_SECONDS.set(0.001)
            with span("engine.execute"):
                pass
            ti.EVAL_EXECUTE_SECONDS.set(0.002)
            fl.set(cells=262144)

    def test_hot_path_overhead_under_2_percent(self, steady_engine):
        """Acceptance: telemetry ON costs <2% of the steady-state bench
        eval loop, asserted against the disabled path.  The per-eval
        instrument cost is measured DIFFERENTIALLY (enabled minus
        disabled over a tight loop of the exact per-eval call sequence —
        deterministic, unlike end-to-end wall-clock on a loaded CI box
        where a single eval drifts +-5%) and compared to the measured
        per-eval floor of the real loop."""
        engine, cases = steady_engine
        # the real eval loop's per-eval floor, telemetry enabled
        floor = float("inf")
        for _ in range(20):
            t0 = time.perf_counter()
            engine.evaluate_grid_counts(cases, backend="pallas")
            floor = min(floor, time.perf_counter() - t0)
        # differential instrument cost per eval
        reps = 3000

        def ops_loop():
            t0 = time.perf_counter()
            for _ in range(reps):
                self._per_eval_telemetry_ops()
            return (time.perf_counter() - t0) / reps

        ops_loop()  # warm
        t_enabled = ops_loop()
        telemetry.set_enabled(False)
        try:
            ops_loop()
            t_disabled = ops_loop()
        finally:
            telemetry.set_enabled(True)
        overhead = max(t_enabled - t_disabled, 0.0)
        assert overhead < 0.02 * floor, (
            f"telemetry costs {overhead * 1e6:.1f} us/eval = "
            f"{100 * overhead / floor:.2f}% of the {floor * 1e3:.2f} ms "
            f"steady-state eval (budget 2%)"
        )

    def test_no_gross_regression_end_to_end(self, steady_engine):
        """Tripwire against instrumentation smuggling real work (a
        device sync costs ~ms, far above this bound) — deliberately
        loose because end-to-end timing on a shared box drifts +-5%."""
        engine, cases = steady_engine
        samples = {True: [], False: []}
        try:
            for i in range(60):
                enabled = i % 2 == 0
                telemetry.set_enabled(enabled)
                t0 = time.perf_counter()
                engine.evaluate_grid_counts(cases, backend="pallas")
                samples[enabled].append(time.perf_counter() - t0)
        finally:
            telemetry.set_enabled(True)
        t_on, t_off = min(samples[True]), min(samples[False])
        assert t_on <= 1.25 * t_off, (
            f"enabled path {100 * (t_on / t_off - 1):.1f}% slower — "
            f"instrumentation is doing real work on the hot path"
        )


class TestEventsOverhead:
    def test_events_enabled_hot_path_under_2_percent(self, steady_engine):
        """The trace-event recorder's bar is the SAME 2% budget as the
        aggregate path: with event capture ON (every span now also
        appends B/E dicts to the ring), the per-eval telemetry call
        sequence must still cost <2% of the steady-state eval floor —
        measured differentially against the fully-disabled path, like
        TestOverhead (end-to-end wall-clock drifts ±5% on a loaded box)."""
        from cyclonus_tpu.telemetry import events

        engine, cases = steady_engine
        floor = float("inf")
        for _ in range(20):
            t0 = time.perf_counter()
            engine.evaluate_grid_counts(cases, backend="pallas")
            floor = min(floor, time.perf_counter() - t0)
        reps = 3000

        def ops_loop():
            # min-of-5: a single scheduler blip on a loaded CI box can
            # inflate one loop by more than the entire budget
            best = float("inf")
            for _ in range(5):
                t0 = time.perf_counter()
                for _ in range(reps):
                    TestOverhead._per_eval_telemetry_ops()
                best = min(best, (time.perf_counter() - t0) / reps)
            return best

        events.enable()
        try:
            t_events = ops_loop()
        finally:
            events.disable()
            events.reset()
        telemetry.set_enabled(False)
        try:
            t_disabled = ops_loop()
        finally:
            telemetry.set_enabled(True)
        overhead = max(t_events - t_disabled, 0.0)
        assert overhead < 0.02 * floor, (
            f"events-enabled telemetry costs {overhead * 1e6:.1f} us/eval "
            f"= {100 * overhead / floor:.2f}% of the {floor * 1e3:.2f} ms "
            f"steady-state eval (budget 2%)"
        )


class TestInstrumentationIsClean:
    def test_engine_and_telemetry_are_jx001_clean(self, capsys):
        """The instrumentation must add no .item()-style device syncs or
        other JAX hot-path hazards: the static lint over engine/ AND
        telemetry/ must stay at zero findings."""
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import jaxlint

        rc = jaxlint.main(
            [
                os.path.join(REPO, "cyclonus_tpu", "engine"),
                os.path.join(REPO, "cyclonus_tpu", "telemetry"),
            ]
        )
        captured = capsys.readouterr()
        assert rc == 0, f"jaxlint findings:\n{captured.out}"


class TestEngineInstrumentation:
    def test_counts_path_feeds_cache_counters_and_flight(self):
        import random

        sys.path.insert(0, REPO)
        from bench import build_synthetic

        from cyclonus_tpu.engine import PortCase, TpuPolicyEngine
        from cyclonus_tpu.matcher import build_network_policies

        telemetry.reset()
        pods, namespaces, policies = build_synthetic(
            256, 24, random.Random(11)
        )
        policy = build_network_policies(True, policies)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        cases = [PortCase(80, "serve-80-tcp", "TCP")]
        for _ in range(3):
            counts = engine.evaluate_grid_counts(cases, backend="pallas")
        # eval 1 = fused (miss), eval 2 = split build (miss), eval 3 =
        # pinned steady state (hit)
        assert ti.PRE_CACHE_MISSES.value() == 2
        assert ti.PRE_CACHE_HITS.value() == 1
        assert ti.PRE_CACHE_BYTES.value() > 0
        assert ti.EVAL_CELLS_PER_SEC.value() > 0
        ents = telemetry.recorder.entries()
        modes = [e.get("mode") for e in ents if e["path"] == "counts.pallas"]
        assert modes == ["fused", "split", "steady"]
        assert all(e["outcome"] == "ok" for e in ents)
        assert ents[-1]["cells"] == counts["cells"]
        # dispatch/execute split gauges moved
        assert ti.EVAL_DISPATCH_SECONDS.value() > 0
        assert ti.EVAL_EXECUTE_SECONDS.value() > 0


class TestWorkerLatency:
    def test_issue_one_stamps_latency_and_json_roundtrip(self):
        from cyclonus_tpu.worker.model import Batch, Request, Result
        from cyclonus_tpu.worker.worker import run_worker

        batch = Batch(
            namespace="x",
            pod="a",
            container="c",
            requests=[
                Request(key="k1", protocol="tcp", host="127.0.0.1", port=1)
            ],
        )
        out = json.loads(run_worker(batch.to_json()))
        assert out[0]["LatencyMs"] > 0
        parsed = Result.from_dict(out[0])
        assert parsed.latency_ms == out[0]["LatencyMs"]
        # backward compatible: pre-latency JSON still parses
        legacy = Result.from_dict(
            {
                "Request": {
                    "Key": "k",
                    "Protocol": "tcp",
                    "Host": "h",
                    "Port": 1,
                },
                "Output": "",
                "Error": "",
            }
        )
        assert legacy.latency_ms is None
        assert "LatencyMs" not in legacy.to_dict()

    def test_batch_runner_observes_driver_side_histogram(self):
        from cyclonus_tpu.probe.runner import KubeBatchJobRunner
        from cyclonus_tpu.worker.model import Batch, Request, Result

        telemetry.METRICS.reset()

        class _FakeClient:
            def batch(self, batch):
                return [
                    Result(
                        request=Request(
                            key="k", protocol="tcp", host="h", port=1
                        ),
                        output="connected",
                        latency_ms=12.5,
                    )
                ]

        runner = KubeBatchJobRunner.__new__(KubeBatchJobRunner)
        runner.client = _FakeClient()
        runner.workers = 1
        out = runner._run_batch(Batch(namespace="x", pod="a", container="c"))
        assert out[0][1] == "allowed"
        snap = telemetry.METRICS.snapshot()
        samples = snap["cyclonus_tpu_probe_latency_seconds"]["samples"]
        batch_sample = [
            s for s in samples if s["labels"].get("source") == "batch"
        ]
        assert batch_sample and batch_sample[0]["count"] == 1
        assert abs(batch_sample[0]["sum"] - 0.0125) < 1e-9


class TestTraceVerdicts:
    def test_verdicts_logged_only_when_enabled(self, caplog):
        """CYCLONUS_TRACE_VERDICTS=1 logs each simulated verdict
        (reference jobrunner.go:80 logrus trace parity); off by default
        so the hot loop pays one env check per probe."""
        from cyclonus_tpu.kube import MockKubernetes
        from cyclonus_tpu.matcher import build_network_policies
        from cyclonus_tpu.probe import Resources, new_simulated_runner
        from cyclonus_tpu.probe.probeconfig import ProbeConfig

        kube = MockKubernetes(1.0)
        resources = Resources.new_default(
            kube,
            ["x"],
            ["a", "b"],
            [80],
            ["TCP"],
            pod_creation_timeout_seconds=1,
        )
        policy = build_network_policies(True, [])
        runner = new_simulated_runner(policy, engine="oracle")
        config = ProbeConfig.all_available_config()
        with caplog.at_level("DEBUG", logger="cyclonus.trace.verdicts"):
            os.environ.pop("CYCLONUS_TRACE_VERDICTS", None)
            runner.run_probe_for_config(config, resources)
            assert not [
                r for r in caplog.records if "verdict" in r.getMessage()
            ]
            os.environ["CYCLONUS_TRACE_VERDICTS"] = "1"
            try:
                runner.run_probe_for_config(config, resources)
            finally:
                os.environ.pop("CYCLONUS_TRACE_VERDICTS", None)
        verdicts = [r for r in caplog.records if "verdict" in r.getMessage()]
        assert verdicts, "no verdicts logged with CYCLONUS_TRACE_VERDICTS=1"
        assert "ingress=" in verdicts[0].getMessage()
