"""Peer version-skew harness: the dynamic proof behind
tools/wirelint.py (docs/DESIGN.md "Wire discipline"), mirroring
tests/stateharness.py's role for the state lint.

The static pass proves the scanned emit/read sites agree with the
declared wire registry (cyclonus_tpu/worker/wireregistry.py) and that
the registry agrees with the frozen golden wire_schema.json.  This
harness proves the declarations PREDICT live interop: it arms the
skew-view recorder (CYCLONUS_SKEWHARNESS=1, read once at import — the
strip contract) plus the reader-side wire checks
(CYCLONUS_SHAPE_CHECK=1), and for every registered message drives both
peer-skew directions through the REAL codecs and the REAL serve wire
loop:

  * older emitter -> newer reader: every version view synthesized by
    ``wireregistry.legacy_view`` (keys newer than the peer dropped,
    recursively) round-trips the real parse/emit pair unchanged, and a
    pre-verdict-service Batch answers a bare epoch reply with ZERO
    state change (wirelint WR002/WR003's dynamic twin),
  * newer emitter -> older reader: ``inject_unknown`` views (undeclared
    keys at every nesting level) parse IDENTICALLY to clean ones, and
    two live services fed clean vs unknown-injected lines answer
    equal replies under the registry's portable projection
    (the frozen tolerate-unknown-keys rule, live),
  * reply-epoch discipline: every verdict in a reply carries the
    reply's own Epoch stamp (WR004's dynamic twin),
  * a malformed peer line (non-object payload, drifted key type) is
    rejected with the offending key NAMED (check_wire_read, the
    reader-side half of satellite 2),

plus a coverage census that fails if any registered optional key was
never exercised under skew in both directions (present in a parsed
view AND absent from one).

The quick slice runs in tier-1 (via tests/test_wirelint.py, the
planlint/statelint subprocess pattern); ``--full``
(``make skewharness``) adds the scaled mixed-version stream leg.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Callable, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the recorder is armed at wireregistry IMPORT (strip contract) and the
# reader-side checks at contracts import — set both flags before any
# cyclonus_tpu import, plus the standalone-run env the pytest path gets
# from tests/conftest.py
os.environ["CYCLONUS_SKEWHARNESS"] = "1"
os.environ["CYCLONUS_SHAPE_CHECK"] = "1"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("CYCLONUS_AUTOTUNE_CACHE", "0")
os.environ.setdefault("CYCLONUS_AOT_CACHE", "0")


class HarnessFailure(AssertionError):
    """A live wire exchange diverged from the registry's declaration;
    the message names the scenario and the divergence."""


def _check(cond: bool, scenario: str, detail: str) -> None:
    if not cond:
        raise HarnessFailure(f"{scenario}: {detail}")


class Ctx:
    """Shared scenario context: small live services (8 pods across 2
    namespaces) built on demand — twin-parity legs need FRESH peers, so
    services are constructed per call from the same seed."""

    def __init__(self, seed: int):
        self.seed = seed
        self.sweep: Optional[Dict] = None
        self.loop_messages: set = set()
        self._pods = None
        self._namespaces = None

    def cluster(self):
        if self._pods is None:
            from cyclonus_tpu.cli.serve_cmd import synthetic_cluster

            self._pods, self._namespaces = synthetic_cluster(
                8, 2, self.seed
            )
        return self._pods, self._namespaces

    def fresh_service(self):
        from cyclonus_tpu.serve import VerdictService

        pods, namespaces = self.cluster()
        return VerdictService(pods, namespaces, [])

    def full_batch_payload(self) -> dict:
        """A current-version Batch exercising every optional envelope
        key: trace context, a committing delta, and an answerable
        query between two real pods."""
        from cyclonus_tpu.worker.model import Batch, Delta, FlowQuery

        pods, _ = self.cluster()
        src = f"{pods[0][0]}/{pods[0][1]}"
        dst = f"{pods[1][0]}/{pods[1][1]}"
        batch = Batch(
            namespace="", pod="", container="",
            trace_id="t-skew", parent_span="0.1",
            deltas=[Delta(
                kind="pod_add", namespace="ns0", name="skew-pod",
                labels={"pod": "p99", "app": "app1", "tier": "tier1"},
                ip="10.99.0.1",
            )],
            queries=[FlowQuery(src=src, dst=dst, port=80,
                               protocol="TCP")],
        )
        return json.loads(batch.to_json())


# --- scenarios --------------------------------------------------------------


def scenario_registry_sweep(ctx: Ctx) -> Dict:
    """Both skew directions for every registered message through the
    REAL model codecs, synthesized from the registry — plus the proof
    (via the armed recorder) that the views came from the registry
    helpers, not a hand-rolled copy that could drift."""
    from cyclonus_tpu.worker import model, wireregistry

    wireregistry.drain()
    sweep = wireregistry.skew_sweep(model.CODECS)
    _check(
        not sweep["problems"], "sweep",
        f"skew round-trips diverged: {sweep['problems']}",
    )
    gaps = wireregistry.census_gaps(sweep)
    _check(not gaps, "sweep", f"census gaps: {gaps}")
    _check(
        sweep["keys"] == wireregistry.key_count(),
        "sweep",
        f"sweep saw {sweep['keys']} keys, registry declares "
        f"{wireregistry.key_count()}",
    )
    _check(
        sweep["skew_pairs_checked"] >= 40, "sweep",
        f"only {sweep['skew_pairs_checked']} skew pairs checked "
        f"(want >= 40: both directions x every message x versions)",
    )
    calls = set(wireregistry.drain())
    for op in ("legacy_view", "inject", "drop"):
        _check(
            op in calls, "sweep",
            f"registry helper {op!r} never recorded: the skew views "
            f"did not come from the registry",
        )
    ctx.sweep = sweep
    return {
        "pairs": sweep["skew_pairs_checked"],
        "keys": sweep["keys"],
        "messages": sweep["messages"],
    }


def scenario_manifest_pinned(ctx: Ctx) -> Dict:
    """The static extraction (tools/wirelint.py, AST-only) is
    byte-identical to the runtime manifest — the linter provably lints
    the real declarations."""
    from cyclonus_tpu.worker import wireregistry

    tools_dir = os.path.join(REPO, "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import wirelint

    reg = wirelint.load_registry(
        os.path.join(REPO, "cyclonus_tpu", "worker", "wireregistry.py")
    )
    _check(reg is not None, "manifest", "static registry load failed")
    static = json.dumps(wirelint.build_manifest(reg), sort_keys=True)
    runtime = json.dumps(wireregistry.manifest(), sort_keys=True)
    _check(
        static == runtime, "manifest",
        "static manifest != wireregistry.manifest() (the linter is "
        "checking a drifted view of the protocol)",
    )
    return {"bytes": len(static)}


def scenario_reply_discipline(ctx: Ctx) -> Dict:
    """WR004 live: a verdict-bearing reply from the real loop stamps
    exactly one Epoch, equal to every verdict's own stamp, and the
    whole reply validates against the Reply declaration."""
    from cyclonus_tpu.serve import loop as serve_loop
    from cyclonus_tpu.worker import wireregistry

    svc = ctx.fresh_service()
    reply = serve_loop.handle_line(
        svc, json.dumps(ctx.full_batch_payload())
    )
    wireregistry.check_read("Reply", reply)
    declared = {k.name for k in wireregistry.message("Reply").keys}
    _check(
        set(reply) <= declared, "reply",
        f"loop reply carries undeclared keys: "
        f"{sorted(set(reply) - declared)}",
    )
    _check("Epoch" in reply, "reply", f"no Epoch stamp: {reply}")
    verdicts = reply.get("Verdicts") or []
    _check(bool(verdicts), "reply", "query line answered no verdicts")
    for v in verdicts:
        _check(
            v.get("Epoch") == reply["Epoch"], "reply",
            f"verdict epoch {v.get('Epoch')} != reply epoch "
            f"{reply['Epoch']} (mixed-epoch reply)",
        )
    _check(
        reply["Epoch"] == svc.epoch, "reply",
        f"reply epoch {reply['Epoch']} != service epoch {svc.epoch}",
    )
    ctx.loop_messages.update(
        {"Batch", "Reply", "Verdict", "Delta", "FlowQuery"}
    )
    return {"verdicts": len(verdicts), "epoch": reply["Epoch"]}


def scenario_older_emitter(ctx: Ctx) -> Dict:
    """Older emitter -> newer reader through the real loop: a peer at
    v1..v3 predates the verdict service, so its view of the same line
    (registry-synthesized) must answer a bare epoch reply and change
    NOTHING; after the real line commits, the skewed peer's service
    and a clean twin agree exactly."""
    from cyclonus_tpu.serve import loop as serve_loop
    from cyclonus_tpu.worker import wireregistry

    svc_skew = ctx.fresh_service()
    svc_twin = ctx.fresh_service()
    full = ctx.full_batch_payload()
    epoch0 = svc_skew.epoch
    for v in (1, 2, 3):
        view = wireregistry.legacy_view("Batch", full, v)
        reply = serve_loop.handle_line(svc_skew, json.dumps(view))
        _check(
            set(reply) == {"Epoch"} and reply["Epoch"] == epoch0,
            f"older.v{v}",
            f"pre-service view was not a no-op: {reply}",
        )
        _check(
            svc_skew.epoch == epoch0, f"older.v{v}",
            f"legacy view mutated state (epoch {svc_skew.epoch})",
        )
    reply_a = serve_loop.handle_line(svc_skew, json.dumps(full))
    reply_b = serve_loop.handle_line(svc_twin, json.dumps(full))
    strip = wireregistry.strip_nonportable
    _check(
        strip("Reply", reply_a) == strip("Reply", reply_b), "older",
        "a service that saw legacy no-op lines diverged from a clean "
        "twin on the same committed line",
    )
    return {"versions": 3, "epoch": svc_skew.epoch}


def scenario_newer_emitter(ctx: Ctx) -> Dict:
    """Newer emitter -> older reader through the real loop: unknown
    keys injected at every nesting level of the line must be ignored —
    twin services fed clean vs injected lines answer equal replies
    under the registry's portable projection."""
    from cyclonus_tpu.serve import loop as serve_loop
    from cyclonus_tpu.worker import wireregistry

    svc_a = ctx.fresh_service()
    svc_b = ctx.fresh_service()
    full = ctx.full_batch_payload()
    injected = wireregistry.inject_unknown("Batch", full)
    _check(
        injected != full, "newer",
        "inject_unknown produced no unknown keys",
    )
    reply_a = serve_loop.handle_line(svc_a, json.dumps(full))
    reply_b = serve_loop.handle_line(svc_b, json.dumps(injected))
    strip = wireregistry.strip_nonportable
    _check(
        strip("Reply", reply_a) == strip("Reply", reply_b), "newer",
        f"unknown keys changed the reply: "
        f"{strip('Reply', reply_a)} != {strip('Reply', reply_b)}",
    )
    _check(
        svc_a.epoch == svc_b.epoch, "newer",
        "unknown keys changed the commit",
    )
    return {"epoch": svc_a.epoch}


def scenario_malformed_rejected(ctx: Ctx) -> Dict:
    """check_wire_read live (CYCLONUS_SHAPE_CHECK=1): a non-object
    payload and a drifted-type key are rejected with the payload /
    offending key NAMED, not surfaced as a downstream KeyError."""
    from cyclonus_tpu.utils import contracts
    from cyclonus_tpu.worker.model import Batch, Result

    _check(contracts.CHECK, "malformed", "shape checks are not armed")
    try:
        Batch.from_json("[1, 2]")
    except contracts.ContractViolation as e:
        _check(
            "Batch" in str(e), "malformed",
            f"rejection does not name the payload: {e}",
        )
    else:
        raise HarnessFailure(
            "malformed: non-object Batch payload was accepted"
        )
    bad = {
        "Request": {"Key": "k", "Protocol": "TCP", "Host": "h",
                    "Port": 80},
        "Output": "", "Error": "", "LatencyMs": "fast",
    }
    try:
        Result.from_dict(bad)
    except contracts.ContractViolation as e:
        _check(
            "LatencyMs" in str(e), "malformed",
            f"rejection does not name the offending key: {e}",
        )
    else:
        raise HarnessFailure(
            "malformed: drifted-type LatencyMs was accepted"
        )
    return {"rejections": 2}


def scenario_delta_kinds_skew(ctx: Ctx) -> Dict:
    """Every wire Delta kind survives a newer peer's unknown keys: the
    injected envelope parses to the same emitted dict as the clean
    one (the kind lifecycle stays wire-stable under skew)."""
    from cyclonus_tpu.worker import wireregistry
    from cyclonus_tpu.worker.model import Delta

    for kind in Delta.KINDS:
        d = Delta(kind=kind, namespace="ns0", name="skew-n").to_dict()
        injected = wireregistry.inject_unknown("Delta", d)
        back = Delta.from_dict(injected).to_dict()
        _check(
            back == d, f"kinds.{kind}",
            f"unknown keys leaked through the Delta envelope: "
            f"{back} != {d}",
        )
    return {"kinds": len(Delta.KINDS)}


def scenario_scaled_stream(ctx: Ctx) -> Dict:
    """The slow leg (`make skewharness`): a mixed-version stdio stream
    (clean, legacy-view, and unknown-injected lines interleaved)
    through the real run_stdio loop; every reply validates against the
    Reply declaration, and a clean twin fed only the effective lines
    lands on the same epoch and the same final verdicts."""
    import io

    from cyclonus_tpu.serve import loop as serve_loop
    from cyclonus_tpu.worker import wireregistry
    from cyclonus_tpu.worker.model import Batch, Delta, FlowQuery

    pods, _ = ctx.cluster()
    src = f"{pods[0][0]}/{pods[0][1]}"
    dst = f"{pods[1][0]}/{pods[1][1]}"
    svc = ctx.fresh_service()
    svc_twin = ctx.fresh_service()
    lines: List[str] = []
    effective: List[str] = []
    for i in range(24):
        batch = Batch(
            namespace="", pod="", container="",
            deltas=[Delta(
                kind="pod_add", namespace="ns0", name=f"skew-{i}",
                labels={"pod": f"p{50 + i}", "app": "app1",
                        "tier": "tier1"},
                ip=f"10.99.1.{i}",
            )],
            queries=[FlowQuery(src=src, dst=dst, port=80,
                               protocol="TCP")],
        )
        payload = json.loads(batch.to_json())
        mode = i % 3
        if mode == 0:
            lines.append(json.dumps(payload))
            effective.append(json.dumps(payload))
        elif mode == 1:
            # a v1 peer's view: pre-service, must be a no-op
            lines.append(json.dumps(
                wireregistry.legacy_view("Batch", payload, 1)
            ))
        else:
            injected = wireregistry.inject_unknown("Batch", payload)
            lines.append(json.dumps(injected))
            effective.append(json.dumps(payload))
    out = io.StringIO()
    handled = serve_loop.run_stdio(
        svc, io.StringIO("\n".join(lines) + "\n"), out
    )
    _check(handled == len(lines), "stream", f"handled {handled}")
    replies = [json.loads(l) for l in out.getvalue().splitlines()]
    for reply in replies:
        wireregistry.check_read("Reply", reply)
        _check(
            "Error" not in reply, "stream",
            f"stream line answered an error: {reply}",
        )
    for line in effective:
        serve_loop.handle_line(svc_twin, line)
    _check(
        svc.epoch == svc_twin.epoch, "stream",
        f"mixed-version stream epoch {svc.epoch} != clean twin "
        f"{svc_twin.epoch}",
    )
    strip = wireregistry.strip_nonportable
    final_a = [strip("Verdict", v.to_dict()) for v in svc.query(
        [FlowQuery(src=src, dst=dst, port=80, protocol="TCP")]
    )]
    final_b = [strip("Verdict", v.to_dict()) for v in svc_twin.query(
        [FlowQuery(src=src, dst=dst, port=80, protocol="TCP")]
    )]
    _check(
        final_a == final_b, "stream",
        f"final verdicts diverged: {final_a} != {final_b}",
    )
    return {"lines": len(lines), "epoch": svc.epoch}


#: (name, fn, in_quick_slice)
SCENARIOS: List[Tuple[str, Callable[[Ctx], Dict], bool]] = [
    ("registry_sweep", scenario_registry_sweep, True),
    ("manifest_pinned", scenario_manifest_pinned, True),
    ("reply_discipline", scenario_reply_discipline, True),
    ("older_emitter", scenario_older_emitter, True),
    ("newer_emitter", scenario_newer_emitter, True),
    ("malformed_rejected", scenario_malformed_rejected, True),
    ("delta_kinds_skew", scenario_delta_kinds_skew, True),
    ("scaled_stream", scenario_scaled_stream, False),
]


def coverage_census(ctx: Ctx) -> Dict:
    """Every registered optional key must have been exercised under
    skew in BOTH directions, and the loop-visible messages must all
    have crossed the real wire loop — the acceptance gate ISSUE 20
    names."""
    from cyclonus_tpu.worker import wireregistry

    _check(ctx.sweep is not None, "coverage", "sweep never ran")
    gaps = wireregistry.census_gaps(ctx.sweep)
    _check(
        not gaps, "coverage",
        f"registered keys never exercised under skew: {gaps}",
    )
    loop_expected = {"Batch", "Reply", "Verdict", "Delta", "FlowQuery"}
    missing = sorted(loop_expected - ctx.loop_messages)
    _check(
        not missing, "coverage",
        f"messages never driven through the live loop: {missing}",
    )
    return {
        "keys": ctx.sweep["keys"],
        "pairs": ctx.sweep["skew_pairs_checked"],
        "loop_messages": len(ctx.loop_messages),
    }


def run(
    *,
    quick: bool = True,
    only: Optional[List[str]] = None,
    seed: int = 0,
    log: Optional[Callable[[str], None]] = None,
) -> Dict[str, Dict]:
    """Run the scenario set; raises HarnessFailure on the first
    divergence.  Returns per-scenario stats."""
    ctx = Ctx(seed)
    results: Dict[str, Dict] = {}
    for name, fn, in_quick in SCENARIOS:
        if only is not None:
            if name not in only:
                continue
        elif quick and not in_quick:
            continue
        stats = fn(ctx)
        results[name] = stats
        if log is not None:
            log(f"skewharness {name}: OK {stats}")
    if only is None:
        results["coverage_census"] = coverage_census(ctx)
        if log is not None:
            log(
                f"skewharness coverage_census: OK "
                f"{results['coverage_census']}"
            )
    return results


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--full", action="store_true", help="all scenarios")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--scenarios", nargs="*", default=None,
        help=f"subset (choices: {[n for n, _f, _q in SCENARIOS]})",
    )
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    results = run(
        quick=not args.full,
        only=args.scenarios,
        seed=args.seed,
        log=print if args.verbose else None,
    )
    print(
        f"skewharness: {len(results)} scenario(s) passed "
        f"({', '.join(sorted(results))})",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
