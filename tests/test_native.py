"""Native C++ grid evaluator: build, parity vs the Python oracle and the
TPU kernel, graceful fallback."""

import random

import numpy as np
import pytest

from cyclonus_tpu.engine import PortCase, TpuPolicyEngine
from cyclonus_tpu.matcher import (
    InternalPeer,
    Traffic,
    TrafficPeer,
    build_network_policies,
)
from cyclonus_tpu.native import (
    NativeUnsupported,
    evaluate_grid_native,
    native_available,
)

pytestmark = pytest.mark.skipif(
    not native_available(), reason="g++ toolchain unavailable"
)


def synthetic(n_pods, n_policies, seed):
    import sys, os

    sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
    from bench import build_synthetic

    return build_synthetic(n_pods, n_policies, random.Random(seed))


CASES = [
    PortCase(80, "serve-80-tcp", "TCP"),
    PortCase(81, "serve-81-udp", "UDP"),
    PortCase(9999, "", "SCTP"),
]


def oracle_verdict(policy, pods, namespaces, case, si, di):
    sns, _, sl, sip = pods[si]
    dns, _, dl, dip = pods[di]
    t = Traffic(
        source=TrafficPeer(internal=InternalPeer(sl, namespaces[sns], sns), ip=sip),
        destination=TrafficPeer(
            internal=InternalPeer(dl, namespaces[dns], dns), ip=dip
        ),
        resolved_port=case.port,
        resolved_port_name=case.port_name,
        protocol=case.protocol,
    )
    r = policy.is_traffic_allowed(t)
    return (r.ingress.is_allowed, r.egress.is_allowed, r.is_allowed)


def test_native_matches_oracle_sampled():
    pods, namespaces, policies = synthetic(80, 60, seed=3)
    policy = build_network_policies(True, policies)
    grid = evaluate_grid_native(policy, pods, namespaces, CASES)
    rng = random.Random(5)
    for _ in range(400):
        qi = rng.randrange(len(CASES))
        si, di = rng.randrange(80), rng.randrange(80)
        assert grid.job_verdict(qi, si, di) == oracle_verdict(
            policy, pods, namespaces, CASES[qi], si, di
        )


def test_native_matches_tpu_full_grid():
    pods, namespaces, policies = synthetic(50, 40, seed=9)
    policy = build_network_policies(True, policies)
    native = evaluate_grid_native(policy, pods, namespaces, CASES)
    tpu = TpuPolicyEngine(policy, pods, namespaces).evaluate_grid(CASES)
    assert np.array_equal(native.ingress, tpu.ingress)
    assert np.array_equal(native.egress, tpu.egress)
    assert np.array_equal(native.combined, tpu.combined)


def test_native_match_expressions():
    from cyclonus_tpu.kube.netpol import (
        LabelSelector,
        LabelSelectorRequirement,
        NetworkPolicy,
        NetworkPolicyIngressRule,
        NetworkPolicyPeer,
        NetworkPolicySpec,
    )

    sel = LabelSelector.make(
        match_expressions=[
            LabelSelectorRequirement(key="tier", operator="NotIn", values=["web"]),
            LabelSelectorRequirement(key="app", operator="Exists"),
        ]
    )
    pol = NetworkPolicy(
        name="exp",
        namespace="n1",
        spec=NetworkPolicySpec(
            pod_selector=LabelSelector.make(match_labels={"role": "db"}),
            policy_types=["Ingress"],
            ingress=[NetworkPolicyIngressRule(
                ports=[], from_=[NetworkPolicyPeer(pod_selector=sel)]
            )],
        ),
    )
    namespaces = {"n1": {"ns": "n1"}}
    pods = [
        ("n1", "db", {"role": "db"}, "10.0.0.1"),
        ("n1", "api", {"app": "x", "tier": "api"}, "10.0.0.2"),
        ("n1", "web", {"app": "x", "tier": "web"}, "10.0.0.3"),
        ("n1", "bare", {"tier": "api"}, "10.0.0.4"),  # NotIn ok, Exists fails
        ("n1", "nokey", {"app": "y"}, "10.0.0.5"),  # NotIn absent-key => match? NO
    ]
    policy = build_network_policies(True, [pol])
    cases = [PortCase(80, "", "TCP")]
    grid = evaluate_grid_native(policy, pods, namespaces, cases)
    for si in range(len(pods)):
        for di in range(len(pods)):
            assert grid.job_verdict(0, si, di) == oracle_verdict(
                policy, pods, namespaces, cases[0], si, di
            ), (si, di)


def test_native_rejects_ipv6():
    pods, namespaces, policies = synthetic(10, 5, seed=1)
    pods[0] = (pods[0][0], pods[0][1], pods[0][2], "fd00::1")
    policy = build_network_policies(True, policies)
    with pytest.raises(NativeUnsupported):
        evaluate_grid_native(policy, pods, namespaces, CASES[:1])


def test_runner_native_engine_matches_oracle():
    from cyclonus_tpu.recipes import ALL_RECIPES

    for r in ALL_RECIPES[:4]:
        oracle = r.run_probe(engine="oracle")
        native = r.run_probe(engine="native")
        assert oracle.render_table() == native.render_table(), r.name
