"""Aux subsystems: tracing phase timers and the generate journal
(greenfield for the rebuild — SURVEY.md section 5)."""

import json

from cyclonus_tpu.connectivity.journal import Journal
from cyclonus_tpu.utils import tracing


def test_phase_timer_accumulates():
    tracing.reset()
    with tracing.phase("unit.a"):
        pass
    with tracing.phase("unit.a"):
        pass
    with tracing.phase("unit.b"):
        pass
    s = tracing.stats()
    assert s["unit.a"]["count"] == 2
    assert s["unit.b"]["count"] == 1
    assert s["unit.a"]["total_s"] >= s["unit.a"]["max_s"]
    assert "unit.a" in tracing.render_stats()
    tracing.reset()
    assert tracing.stats() == {}


def test_jax_profile_noop_without_dir():
    with tracing.jax_profile(""):
        pass
    with tracing.jax_profile(None):
        pass


def test_journal_roundtrip(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = Journal(path)
    assert j.completed() == set()
    j.record("case one", passed=True, step_count=1, tags=["t1"])
    j.record("case two", passed=False, step_count=2, error="boom")

    j2 = Journal(path)
    assert j2.completed() == {"case one", "case two"}
    assert j2.is_completed("case one")
    assert not j2.is_completed("case three")
    by_desc = {e["description"]: e for e in j2.entries()}
    assert by_desc["case one"]["passed"] is True
    assert by_desc["case two"]["error"] == "boom"


def test_journal_resume_reruns_errored_cases(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = Journal(path)
    j.record("clean", passed=True, step_count=1, key="0:clean")
    j.record("flaked", passed=False, step_count=1, error="kube timeout", key="1:flaked")
    j2 = Journal(path)
    assert j2.should_skip("0:clean")
    assert not j2.should_skip("1:flaked")  # errored => re-run on resume
    assert not j2.should_skip("2:never-ran")


def test_journal_tolerates_torn_write(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = Journal(path)
    j.record("good case", passed=True, step_count=1)
    with open(path, "a") as f:
        f.write('{"description": "torn ca')  # crash mid-line
    j2 = Journal(path)
    assert j2.completed() == {"good case"}
    # appending after a torn line still yields parseable entries
    j2.record("after torn", passed=True, step_count=1)
    lines = open(path).read().splitlines()
    assert json.loads(lines[-1])["description"] == "after torn"


def test_generate_resume_skips_journaled(tmp_path, capsys):
    from cyclonus_tpu.cli.root import main

    journal = str(tmp_path / "j.jsonl")
    args = [
        "generate",
        "--mock",
        # perfect CNI so the cases PASS: generate now exits nonzero on
        # failing cases, and the plain mock's always-succeed exec makes
        # deny-case comparisons fail by design (mockcni docstring)
        "--perfect-cni",
        "--engine",
        "oracle",
        "--max-cases",
        "2",
        "--journal",
        journal,
    ]
    assert main(args) == 0
    entries = [json.loads(l) for l in open(journal) if l.strip()]
    assert len(entries) == 2

    # resume: both cases skipped, journal unchanged
    assert main(args + ["--resume"]) == 0
    out = capsys.readouterr().out
    assert "skipping journaled test case" in out
    entries2 = [json.loads(l) for l in open(journal) if l.strip()]
    assert len(entries2) == 2


def test_run_bounded_three_outcomes():
    """utils.bounded.run_bounded: the contract every bounded backend
    touchpoint (CLI --devices, runner probe, autotune candidate) rests
    on — ok with the value, error with the exception, timeout with
    None, and a timeout must not block the caller."""
    import time

    from cyclonus_tpu.utils.bounded import run_bounded

    assert run_bounded(lambda: 42, 5) == ("ok", 42)

    status, exc = run_bounded(lambda: 1 / 0, 5)
    assert status == "error"
    assert isinstance(exc, ZeroDivisionError)

    t0 = time.time()
    status, value = run_bounded(lambda: time.sleep(10), 0.2)
    assert status == "timeout"
    assert value is None
    assert time.time() - t0 < 5
