"""The bit-packed kernel's differential gate (docs/DESIGN.md "Bit-packed
kernel").

Everything the packed dtype plan touches must stay BIT-IDENTICAL to the
scalar oracle and to the CYCLONUS_PACK=0 legacy plan: the packing
primitives (numpy/jnp twins), the XLA tile bodies, the packed Pallas
kernel with its fused tier and class-gather epilogues, every route
(dense / compressed / tiered / sharded ring), and the persisted tile
autotuner's adopt-on-restart contract.
"""

import json
import os

import numpy as np
import pytest

from cyclonus_tpu.engine import PortCase, TpuPolicyEngine
from cyclonus_tpu.engine.encoding import (
    PACK_BITS,
    pack_bool_words,
    pack_enabled,
    packed_words,
)
from cyclonus_tpu.matcher import build_network_policies

from test_engine_tiled import CASES, fuzz_problem, full_grids

#: the fuzz seeds every route must hold bit-identity on (the same
#: generator `make fuzz` drives: dense + tiered + CIDR-heavy cases)
FUZZ_SEEDS = range(8)


def _engines_packed_unpacked(monkeypatch, policy, pods, namespaces, **kw):
    """(packed, unpacked) engines over one problem — the kill-switch
    pair every parity test diffs."""
    monkeypatch.setenv("CYCLONUS_PACK", "1")
    packed = TpuPolicyEngine(policy, pods, namespaces, **kw)
    monkeypatch.setenv("CYCLONUS_PACK", "0")
    unpacked = TpuPolicyEngine(policy, pods, namespaces, **kw)
    monkeypatch.setenv("CYCLONUS_PACK", "1")
    return packed, unpacked


class TestPackPrimitives:
    @pytest.mark.parametrize("t", [1, 5, 31, 32, 33, 64, 70, 257])
    def test_numpy_jnp_twins_bit_identical(self, t):
        import jax.numpy as jnp

        from cyclonus_tpu.engine.kernel import pack_bool_words_jnp

        rng = np.random.default_rng(t)
        a = rng.random((t, 6, 3)) > 0.5
        for axis in (0, 1, 2):
            want = pack_bool_words(a, axis=axis)
            got = np.asarray(pack_bool_words_jnp(jnp.asarray(a), axis=axis))
            assert want.dtype == np.int32
            assert np.array_equal(want, got)

    def test_pack_round_trips_every_bit(self):
        rng = np.random.default_rng(7)
        a = rng.random((70, 9)) > 0.3
        words = pack_bool_words(a)  # [W, 9]
        assert words.shape == (packed_words(70), 9)
        # unpack by hand: bit b of word w is element w * 32 + b
        back = np.zeros_like(a)
        uw = words.view(np.uint32)
        for i in range(70):
            back[i] = (uw[i // PACK_BITS] >> np.uint32(i % PACK_BITS)) & 1
        assert np.array_equal(back, a)

    def test_packed_any_equals_bool_contraction(self):
        import jax.numpy as jnp

        from cyclonus_tpu.engine.kernel import packed_any, pack_bool_words_jnp

        rng = np.random.default_rng(3)
        a = rng.random((67, 12)) > 0.8  # [T, A]
        b = rng.random((67, 20)) > 0.6  # [T, B]
        want = (a.astype(np.int64).T @ b.astype(np.int64)) > 0
        got = np.asarray(
            packed_any(
                pack_bool_words_jnp(jnp.asarray(a)),
                pack_bool_words_jnp(jnp.asarray(b)),
            )
        )
        assert np.array_equal(want, got)

    def test_pack_enabled_resolution(self, monkeypatch):
        monkeypatch.delenv("CYCLONUS_PACK", raising=False)
        assert pack_enabled() is True  # auto default: on
        monkeypatch.setenv("CYCLONUS_PACK", "0")
        assert pack_enabled() is False
        monkeypatch.setenv("CYCLONUS_PACK", "1")
        assert pack_enabled() is True
        monkeypatch.setenv("CYCLONUS_PACK", "bogus")
        with pytest.raises(ValueError, match="CYCLONUS_PACK"):
            pack_enabled()


class TestPackedFuzzParity:
    """packed == unpacked == scalar oracle, across the same seeded
    generator `make fuzz` gates — dense, class-compressed, tiered, and
    the 8-virtual-device overlapped mesh route."""

    @pytest.mark.parametrize("seed", FUZZ_SEEDS)
    def test_grid_and_counts_routes(self, seed, monkeypatch):
        from cyclonus_tpu.tiers.fuzz import (
            _engine_table,
            _oracle_table,
            _table_from_grid,
            build_fuzz_case,
        )

        fc = build_fuzz_case(seed)
        policy = build_network_policies(fc.simplify, fc.netpols)
        want = _oracle_table(policy, fc.tiers, fc.pods, fc.namespaces, fc.cases)
        packed, unpacked = _engines_packed_unpacked(
            monkeypatch, policy, fc.pods, fc.namespaces, tiers=fc.tiers
        )
        got = _engine_table(packed, fc.cases)
        assert np.array_equal(got, want), f"seed {seed}: packed grid != oracle"
        assert np.array_equal(
            _engine_table(unpacked, fc.cases), got
        ), f"seed {seed}: packed != unpacked grid"

        # counts: XLA tile loop (packed contraction) vs oracle sums
        sums = {
            "ingress": int(want[..., 0].sum()),
            "egress": int(want[..., 1].sum()),
            "combined": int(want[..., 2].sum()),
        }
        counts = packed.evaluate_grid_counts(fc.cases, block=8, backend="xla")
        assert {k: counts[k] for k in sums} == sums, f"seed {seed}: xla counts"
        # pallas counts (the packed kernel; fused tier epilogue when the
        # case is tiered) — explicit backend, so a tiered case that
        # cannot ride the fused kernel would raise rather than reroute
        pcounts = packed.evaluate_grid_counts(fc.cases, backend="pallas")
        assert {k: pcounts[k] for k in sums} == sums, (
            f"seed {seed}: pallas packed counts"
        )

        # sharded route: the packed bundle rides the ppermute ring
        ring = _table_from_grid(
            packed.evaluate_grid_sharded(fc.cases, schedule="ring")
        )
        assert np.array_equal(ring, want), f"seed {seed}: packed ring grid"

    @pytest.mark.parametrize("seed", [0, 3, 5])
    def test_compressed_route(self, seed, monkeypatch):
        from cyclonus_tpu.tiers.fuzz import (
            _engine_table,
            _oracle_table,
            build_fuzz_case,
        )

        fc = build_fuzz_case(seed)
        policy = build_network_policies(fc.simplify, fc.netpols)
        want = _oracle_table(policy, fc.tiers, fc.pods, fc.namespaces, fc.cases)
        monkeypatch.setenv("CYCLONUS_CLASS_COMPRESS", "1")
        packed, unpacked = _engines_packed_unpacked(
            monkeypatch, policy, fc.pods, fc.namespaces, tiers=fc.tiers
        )
        assert packed._class_state is not None
        got = _engine_table(packed, fc.cases)
        assert np.array_equal(got, want), f"seed {seed}: packed compressed grid"
        assert np.array_equal(_engine_table(unpacked, fc.cases), want)
        sums = {
            "ingress": int(want[..., 0].sum()),
            "egress": int(want[..., 1].sum()),
            "combined": int(want[..., 2].sum()),
        }
        counts = packed.evaluate_grid_counts(fc.cases, block=8)
        assert {k: counts[k] for k in sums} == sums


class TestPackedFixtureParity:
    """Bundled example fixtures + the feature fixtures through the
    packed/unpacked pair (the same clusters the main parity gate
    uses)."""

    def test_feature_fixture_grids(self, monkeypatch):
        from test_engine_parity import default_cluster, oracle_grid

        for seed in (2, 9, 17):
            policy, pods, namespaces = fuzz_problem(seed, n_extra_pods=7)
            packed, unpacked = _engines_packed_unpacked(
                monkeypatch, policy, pods, namespaces
            )
            want = oracle_grid(policy, pods, namespaces, CASES)
            for engine in (packed, unpacked):
                grid = engine.evaluate_grid(CASES)
                for qi, case in enumerate(CASES):
                    for si in range(len(pods)):
                        for di in range(len(pods)):
                            got = grid.job_verdict(qi, si, di)
                            assert got == want[(qi, si, di)], (
                                f"{case} {si}->{di}: {got} != "
                                f"{want[(qi, si, di)]}"
                            )
        # the feature cluster itself exercises ip/selector variety;
        # default_cluster is the shared base those fixtures extend
        assert len(default_cluster()[0]) > 0

    def test_bundled_example_fixtures(self, monkeypatch):
        """The bundled example-policy library (all 21 reference canned
        policies at once) + the pathological set through both plans:
        packed and unpacked grids and counts must agree exactly."""
        from cyclonus_tpu.kube import pathological as pa
        from cyclonus_tpu.kube.examples import all_examples
        from test_engine_parity import default_cluster

        pods, namespaces = default_cluster()
        namespaces["other"] = dict(pa.LABELS_AB)
        pods = pods + [
            (pa.NAMESPACE, "pp-a", dict(pa.LABELS_AB), "10.0.0.1"),
            ("other", "pp-c", dict(pa.LABELS_EF), "192.168.242.1"),
        ]
        namespaces.setdefault(pa.NAMESPACE, {"ns": pa.NAMESPACE})
        for netpols in (
            all_examples(),
            list(pa.ALL_PATHOLOGICAL_POLICIES),
        ):
            policy = build_network_policies(True, netpols)
            packed, unpacked = _engines_packed_unpacked(
                monkeypatch, policy, pods, namespaces
            )
            a = packed.evaluate_grid_counts(CASES, block=8, backend="xla")
            b = unpacked.evaluate_grid_counts(CASES, block=8, backend="xla")
            assert a == b
            ga = packed.evaluate_grid(CASES)
            gb = unpacked.evaluate_grid(CASES)
            for name in ("ingress", "egress", "combined"):
                assert np.array_equal(
                    np.asarray(getattr(ga, name)),
                    np.asarray(getattr(gb, name)),
                )


class TestFusedEpilogues:
    """Fused-epilogue vs split-epilogue bit-identity: the Pallas kernel
    that resolves the tier lattice / applies the class-gather weighting
    in VMEM must reproduce the split XLA programs exactly."""

    def test_fused_tier_counts_equal_split(self, monkeypatch):
        from cyclonus_tpu.tiers.fuzz import build_fuzz_case

        tiered_seeds = []
        for seed in range(32):
            fc = build_fuzz_case(seed)
            if fc.tiers is not None:
                tiered_seeds.append(fc)
            if len(tiered_seeds) >= 3:
                break
        assert tiered_seeds, "generator produced no tiered case in 32 seeds"
        monkeypatch.setenv("CYCLONUS_PACK", "1")
        for fc in tiered_seeds:
            policy = build_network_policies(fc.simplify, fc.netpols)
            engine = TpuPolicyEngine(
                policy, fc.pods, fc.namespaces, tiers=fc.tiers
            )
            split = engine.evaluate_grid_counts(
                fc.cases, block=8, backend="xla"
            )
            fused = engine.evaluate_grid_counts(fc.cases, backend="pallas")
            assert fused == split, f"seed {fc.seed}"

    def test_fused_class_rowsums_equal_split(self, monkeypatch):
        from cyclonus_tpu.engine.tiled import evaluate_grid_counts_classes

        monkeypatch.setenv("CYCLONUS_PACK", "1")
        monkeypatch.setenv("CYCLONUS_CLASS_COMPRESS", "1")
        policy, pods, namespaces = fuzz_problem(21, n_extra_pods=12)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        assert engine._class_state is not None
        pc = engine._class_state["classes"]
        tensors = engine._ctensors_with_cases(CASES)
        n = len(pods)
        split, _ = evaluate_grid_counts_classes(
            tensors, pc.n_classes, pc.class_size, n, kernel="xla"
        )
        fused, _ = evaluate_grid_counts_classes(
            tensors, pc.n_classes, pc.class_size, n, kernel="pallas"
        )
        assert fused == split
        # and both equal the dense truth
        ing, egr, comb = full_grids(engine, CASES)
        assert split["combined"] == int(comb.sum())

    def test_fused_class_route_respects_tier_ceiling(self, monkeypatch):
        """The class-counts route shares the SAME static-unroll ceiling
        as the dense route (one packed_tier_eligible implementation):
        an oversized tier rule axis must refuse the fused kernel."""
        import cyclonus_tpu.engine.pallas_kernel as pk

        from cyclonus_tpu.engine.tiled import evaluate_grid_counts_classes
        from cyclonus_tpu.tiers.fuzz import build_fuzz_case

        fc = None
        for seed in range(32):
            c = build_fuzz_case(seed)
            if c.tiers is not None:
                fc = c
                break
        assert fc is not None
        monkeypatch.setenv("CYCLONUS_PACK", "1")
        monkeypatch.setenv("CYCLONUS_CLASS_COMPRESS", "1")
        policy = build_network_policies(fc.simplify, fc.netpols)
        engine = TpuPolicyEngine(policy, fc.pods, fc.namespaces, tiers=fc.tiers)
        if engine._class_state is None:
            pytest.skip("fuzz case compressed to nothing")
        pc = engine._class_state["classes"]
        tensors = engine._ctensors_with_cases(fc.cases)
        monkeypatch.setattr(pk, "PACKED_TIER_MAX_ROWS", 1)
        with pytest.raises(ValueError, match="static-unroll ceiling"):
            evaluate_grid_counts_classes(
                tensors, pc.n_classes, pc.class_size, len(fc.pods),
                kernel="pallas",
            )
        # auto routes to the XLA body and stays correct
        counts, _ = evaluate_grid_counts_classes(
            tensors, pc.n_classes, pc.class_size, len(fc.pods)
        )
        want = engine.evaluate_grid_counts(fc.cases, block=8, backend="xla")
        assert counts["combined"] == want["combined"]

    def test_fused_tier_rejects_oversized_rule_axis(self, monkeypatch):
        """Past the static-unroll ceiling the fused kernel must NOT
        engage: auto reroutes to XLA, explicit pallas fails loudly."""
        import cyclonus_tpu.engine.pallas_kernel as pk

        from cyclonus_tpu.tiers.fuzz import build_fuzz_case

        fc = None
        for seed in range(32):
            c = build_fuzz_case(seed)
            if c.tiers is not None:
                fc = c
                break
        assert fc is not None
        monkeypatch.setenv("CYCLONUS_PACK", "1")
        monkeypatch.setattr(pk, "PACKED_TIER_MAX_ROWS", 1)
        policy = build_network_policies(fc.simplify, fc.netpols)
        engine = TpuPolicyEngine(policy, fc.pods, fc.namespaces, tiers=fc.tiers)
        with pytest.raises(ValueError, match="precedence-tier"):
            engine.evaluate_grid_counts(fc.cases, backend="pallas")
        auto = engine.evaluate_grid_counts(fc.cases, block=8)
        xla = engine.evaluate_grid_counts(fc.cases, block=8, backend="xla")
        assert auto == xla


class TestKillSwitch:
    """The CYCLONUS_PACK=0 regression: the legacy representation comes
    back exactly — no packed twins anywhere, identical verdicts."""

    def test_unpacked_engine_has_no_packed_twins(self, monkeypatch):
        from cyclonus_tpu.engine.tiled import _precompute

        policy, pods, namespaces = fuzz_problem(4, n_extra_pods=5)
        monkeypatch.setenv("CYCLONUS_PACK", "0")
        engine = TpuPolicyEngine(policy, pods, namespaces)
        assert engine._pack is False
        pre = _precompute(engine._tensors_with_cases(CASES), False)
        assert "tallow_pk" not in pre["egress"]
        assert "tallow_bf" in pre["egress"]
        pre_packed = _precompute(engine._tensors_with_cases(CASES), True)
        assert "tallow_pk" in pre_packed["egress"]
        assert "tallow_bf" not in pre_packed["egress"]

    def test_kill_switch_counts_identical(self, monkeypatch):
        policy, pods, namespaces = fuzz_problem(13, n_extra_pods=9)
        packed, unpacked = _engines_packed_unpacked(
            monkeypatch, policy, pods, namespaces
        )
        for backend in ("xla", "pallas"):
            a = packed.evaluate_grid_counts(CASES, block=8, backend=backend)
            b = unpacked.evaluate_grid_counts(CASES, block=8, backend=backend)
            assert a == b, backend
        # pack detail reflects the plan either way
        assert packed.pack_stats()["active"] is True
        assert unpacked.pack_stats()["active"] is False
        assert packed.pack_stats()["dtype"] == "packed32"


class TestPersistedAutotune:
    """The tile autotuner's persistence contract: the first process
    searches (min-of-N, noise-floored) and persists the winner keyed by
    (shape bucket, mesh, dtype plan); a second process ADOPTS it with
    ZERO candidate searches; a corrupt or stale cache file degrades to
    a fresh search, never an error."""

    def _tuned_engine(self, monkeypatch, tmp_path, seed=35):
        import cyclonus_tpu.engine.pallas_kernel as pk

        cache = tmp_path / "autotune.json"
        monkeypatch.setenv("CYCLONUS_AUTOTUNE_CACHE", str(cache))
        monkeypatch.setenv("CYCLONUS_AUTOTUNE", "1")
        monkeypatch.setenv("CYCLONUS_AUTOTUNE_REPS", "1")
        monkeypatch.setenv("CYCLONUS_AUTOTUNE_ROUNDS", "2")
        monkeypatch.setenv("CYCLONUS_PACK", "1")
        # tiny tile candidates so a test-sized cluster has a real
        # 2-candidate search space
        monkeypatch.setattr(pk, "PACKED_TILE_CANDIDATES", ((8, 8), (16, 8)))
        policy, pods, namespaces = fuzz_problem(seed, n_extra_pods=10)
        return cache, policy, pods, namespaces

    def _reach_steady(self, engine):
        out = None
        for _ in range(4):
            out = engine.evaluate_grid_counts(CASES, backend="pallas")
        return out

    def test_search_persists_and_restart_adopts(self, monkeypatch, tmp_path):
        from cyclonus_tpu.telemetry.instruments import (
            AUTOTUNE_CACHE,
            AUTOTUNE_SEARCHES,
        )

        cache, policy, pods, namespaces = self._tuned_engine(
            monkeypatch, tmp_path
        )
        engine = TpuPolicyEngine(policy, pods, namespaces)
        want = engine.evaluate_grid_counts(CASES, block=8, backend="xla")
        searches0 = AUTOTUNE_SEARCHES.value()
        assert self._reach_steady(engine) == want
        assert AUTOTUNE_SEARCHES.value() == searches0 + 1
        choice = engine.pack_stats()["winner"]
        assert choice is not None and choice["kernel"] == "packed"
        assert engine._autotune_stats["source"] == "search"
        assert engine._autotune_stats["search_s"] >= 0
        assert len(engine._autotune_stats["candidates"]) == 2
        # the winner landed on disk under the versioned schema
        doc = json.loads(cache.read_text())
        assert doc["v"] >= 1
        (entry,) = doc["entries"].values()
        assert entry["winner"]["kernel"] == "packed"
        assert entry["winner"]["bs"] == choice["bs"]

        # "second process": a fresh engine over the same problem adopts
        # the persisted winner with NO candidate search
        hits0 = AUTOTUNE_CACHE.value(outcome="hit")
        engine2 = TpuPolicyEngine(policy, pods, namespaces)
        assert self._reach_steady(engine2) == want
        assert AUTOTUNE_SEARCHES.value() == searches0 + 1  # zero new searches
        assert AUTOTUNE_CACHE.value(outcome="hit") == hits0 + 1
        assert engine2.pack_stats()["winner"] == choice
        assert engine2._autotune_stats["source"] == "cache"

    def test_corrupt_cache_degrades_to_fresh_search(
        self, monkeypatch, tmp_path
    ):
        from cyclonus_tpu.telemetry.instruments import AUTOTUNE_SEARCHES

        cache, policy, pods, namespaces = self._tuned_engine(
            monkeypatch, tmp_path, seed=36
        )
        # truncated JSON — the tunnel_wait discipline: degrade, don't die
        cache.write_text('{"v": 1, "entries": {"x": {"winn')
        engine = TpuPolicyEngine(policy, pods, namespaces)
        want = engine.evaluate_grid_counts(CASES, block=8, backend="xla")
        s0 = AUTOTUNE_SEARCHES.value()
        assert self._reach_steady(engine) == want
        assert AUTOTUNE_SEARCHES.value() == s0 + 1  # fresh search ran
        # and the search REPLACED the corrupt file with a valid one
        doc = json.loads(cache.read_text())
        assert doc["v"] >= 1 and doc["entries"]

    def test_stale_version_and_malformed_winner_ignored(
        self, monkeypatch, tmp_path
    ):
        from cyclonus_tpu.engine import autotune as at

        cache = tmp_path / "autotune.json"
        monkeypatch.setenv("CYCLONUS_AUTOTUNE_CACHE", str(cache))
        key = at.make_key({"n": 1}, "cpu", "packed32")
        # stale version
        cache.write_text(json.dumps({"v": 9999, "entries": {key: {
            "winner": {"kernel": "packed", "bs": 8, "bd": 8}}}}))
        assert at.load_winner(key) is None
        # right version, unknown kernel
        cache.write_text(json.dumps({"v": at.CACHE_VERSION, "entries": {key: {
            "winner": {"kernel": "warp-drive"}}}}))
        assert at.load_winner(key) is None
        # right version, malformed tile
        cache.write_text(json.dumps({"v": at.CACHE_VERSION, "entries": {key: {
            "winner": {"kernel": "packed", "bs": "big"}}}}))
        assert at.load_winner(key) is None
        # valid entry round-trips
        assert at.store_winner(key, {"kernel": "packed", "bs": 8, "bd": 8})
        assert at.load_winner(key) == {"kernel": "packed", "bs": 8, "bd": 8}
        # disabled path: no reads, no writes
        monkeypatch.setenv("CYCLONUS_AUTOTUNE_CACHE", "0")
        assert at.cache_path() is None
        assert at.load_winner(key) is None
        assert at.store_winner(key, {"kernel": "default"}) is False

    def test_tuned_tile_dispatch_matches_default(self, monkeypatch, tmp_path):
        """The tuned-tile steady-state program produces the same counts
        as the default tile (the autotune can only change SPEED)."""
        cache, policy, pods, namespaces = self._tuned_engine(
            monkeypatch, tmp_path, seed=37
        )
        engine = TpuPolicyEngine(policy, pods, namespaces)
        want = engine.evaluate_grid_counts(CASES, block=8, backend="xla")
        assert self._reach_steady(engine) == want
        # post-tune steady dispatches run the winner and stay identical
        for _ in range(2):
            assert (
                engine.evaluate_grid_counts(CASES, backend="pallas") == want
            )
        piped = engine.counts_pipelined_eval_s(CASES, reps=2)
        assert piped is not None
        _dt, counts = piped
        assert {k: counts[k] for k in ("ingress", "egress", "combined")} == {
            k: want[k] for k in ("ingress", "egress", "combined")
        }
