"""tools/statelint.py tests: seeded-violation gates for ST001–ST005
(each defect class must fire, each suppression must be honored), the
planted forgotten-field fixture (one missing field fires ST002 + ST003
+ ST005 together — the composite drift a real omission produces), the
clean-run + annotation-floor acceptance gate over serve/ + audit/, the
static-vs-runtime manifest identity (the AST-extracted registry must
equal stateregistry.manifest() byte for byte), digest tier-object
coverage (states differing only in an ANP/BANP must digest unequal),
and the tier-1 slice of the state-surface harness
(tests/stateharness.py)."""

import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import statelint

STATE_PACKAGES = [
    os.path.join(REPO, "cyclonus_tpu", p) for p in ("serve", "audit")
]

GOOD_REGISTRY = """
FIELDS = (
    StateField("pods", attr="pods", container="dict",
               kinds=("pod_add", "pod_remove"),
               digest_key="pods", state_key="pods"),
    StateField("banp", attr="banp", container="optional",
               kinds=("banp_upsert",),
               digest_key="banp", state_key="banp"),
)
KINDS = (
    KindSpec("pod_add", field="pods", gate="tests/test_ok.py"),
    KindSpec("pod_remove", field="pods", gate="tests/test_ok.py"),
    KindSpec("banp_upsert", field="banp", gate="tests/test_ok.py"),
)
COMMIT = {
    "class": "Svc",
    "commit": "apply_pending",
    "validator": "_validate_delta",
    "applier": "_apply_to_state",
    "epoch_attr": "_epoch",
    "lock": "self._lock",
    "audit_note": "note_epoch",
}
"""

GOOD_SERVICE = """
class Svc:
    def __init__(self):
        self._lock = None
        self._audit = None
        self._queue = []
        self._epoch = 0
        self.pods = {}
        self.banp = None

    def _validate_delta(self, d):
        if d.kind not in Delta.KINDS:
            return "unknown kind", None
        return None, None

    def _apply_to_state(self, d):
        if d.kind == "pod_add":
            self.pods[d.key] = d
            return ("pod", d.key)
        if d.kind == "pod_remove":
            del self.pods[d.key]
            return ("pod", d.key)
        if d.kind == "banp_upsert":
            self.banp = d
            return ("tier", "banp")
        raise ValueError(d.kind)

    def apply_pending(self):
        with self._lock:
            valid = []
            for d in self._queue:
                reason, pol = self._validate_delta(d)
                if reason is None:
                    valid.append(d)
            snap = (dict(self.pods), self.banp)
            try:
                for d in valid:
                    self._apply_to_state(d)
            except Exception:
                (self.pods, self.banp) = snap
                raise
            self._epoch += 1
            self._audit.note_epoch(
                self._epoch, pods=dict(self.pods), banp=self.banp,
            )
            return {"epoch": self._epoch}

    def state(self):
        with self._lock:
            return {"pods": len(self.pods), "banp": self.banp is not None}
"""

GOOD_DIGEST = """
def canonical_state(pods, banp):
    return {"pods": sorted(pods.items()), "banp": banp}
"""

GOOD_MODEL = """
class Delta:
    KINDS = ("pod_add", "pod_remove", "banp_upsert")
"""


def _mini_repo(tmp_path, registry_src=GOOD_REGISTRY,
               service_src=GOOD_SERVICE, digest_src=GOOD_DIGEST,
               model_src=GOOD_MODEL, tests=("test_ok.py",),
               makefile=None):
    """A scratch repo tree carrying every surface statelint
    cross-checks: serve/{stateregistry,service}.py, audit/digest.py,
    worker/model.py, the tests/ gate files, and optionally a
    Makefile."""
    serve = tmp_path / "cyclonus_tpu" / "serve"
    audit = tmp_path / "cyclonus_tpu" / "audit"
    worker = tmp_path / "cyclonus_tpu" / "worker"
    for d in (serve, audit, worker):
        d.mkdir(parents=True)
    (tmp_path / "tests").mkdir()
    for t in tests:
        (tmp_path / "tests" / t).write_text("")
    if makefile is not None:
        (tmp_path / "Makefile").write_text(makefile)
    (serve / "stateregistry.py").write_text(textwrap.dedent(registry_src))
    (serve / "service.py").write_text(textwrap.dedent(service_src))
    (audit / "digest.py").write_text(textwrap.dedent(digest_src))
    (worker / "model.py").write_text(textwrap.dedent(model_src))
    return str(serve)


def _codes(findings):
    return [f.code for f in findings]


class TestST001MutationDiscipline:
    def test_good_service_clean(self, tmp_path):
        serve = _mini_repo(tmp_path)
        findings, stats = statelint.lint_paths([serve])
        assert findings == [], [f.render() for f in findings]
        assert stats["fields"] == 2 and stats["kinds"] == 3

    def test_unlocked_mutation_fires(self, tmp_path):
        serve = _mini_repo(tmp_path, service_src=GOOD_SERVICE + """
    def sneaky(self):
        self.pods["x"] = 1
""")
        findings, _ = statelint.lint_paths([serve])
        assert _codes(findings) == ["ST001"]
        assert "'sneaky'" in findings[0].message

    def test_mutating_method_call_fires(self, tmp_path):
        serve = _mini_repo(tmp_path, service_src=GOOD_SERVICE + """
    def sneaky(self):
        self.pods.clear()
""")
        findings, _ = statelint.lint_paths([serve])
        assert _codes(findings) == ["ST001"]

    def test_one_level_lock_inference_covers_callee(self, tmp_path):
        """A helper mutating state is clean when its only call sites
        hold the lock (the _apply_to_state pattern)."""
        serve = _mini_repo(tmp_path, service_src=GOOD_SERVICE + """
    def _drop_pod(self, key):
        del self.pods[key]

    def evict(self):
        with self._lock:
            self._drop_pod("x")
""")
        findings, _ = statelint.lint_paths([serve])
        assert findings == [], [f.render() for f in findings]

    def test_holds_docstring_covers_method(self, tmp_path):
        serve = _mini_repo(tmp_path, service_src=GOOD_SERVICE + """
    def _wipe(self):
        \"\"\"holds-lock: self._lock\"\"\"
        self.pods = {}
""")
        findings, _ = statelint.lint_paths([serve])
        assert findings == [], [f.render() for f in findings]

    def test_apply_before_validate_fires(self, tmp_path):
        svc = GOOD_SERVICE.replace(
            """            valid = []
            for d in self._queue:
                reason, pol = self._validate_delta(d)
                if reason is None:
                    valid.append(d)
            snap = (dict(self.pods), self.banp)
            try:
                for d in valid:
                    self._apply_to_state(d)""",
            """            valid = list(self._queue)
            snap = (dict(self.pods), self.banp)
            try:
                for d in valid:
                    self._apply_to_state(d)
                for d in valid:
                    self._validate_delta(d)""",
        )
        serve = _mini_repo(tmp_path, service_src=svc)
        findings, _ = statelint.lint_paths([serve])
        assert "ST001" in _codes(findings)
        assert any("before its validator" in f.message for f in findings)

    def test_suppression_honored(self, tmp_path):
        serve = _mini_repo(tmp_path, service_src=GOOD_SERVICE + """
    def sneaky(self):
        self.pods["x"] = 1  # statelint: ignore[ST001]
""")
        findings, _ = statelint.lint_paths([serve])
        assert findings == []


class TestST002RollbackSnapshot:
    def test_field_missing_from_snapshot_fires(self, tmp_path):
        svc = GOOD_SERVICE.replace(
            "snap = (dict(self.pods), self.banp)",
            "snap = (dict(self.pods),)",
        ).replace(
            "(self.pods, self.banp) = snap",
            "(self.pods,) = snap",
        )
        serve = _mini_repo(tmp_path, service_src=svc)
        findings, _ = statelint.lint_paths([serve])
        assert "ST002" in _codes(findings)
        assert any(
            "'banp'" in f.message and "rollback snapshot" in f.message
            for f in findings
        )

    def test_snapshotted_but_not_restored_fires(self, tmp_path):
        svc = GOOD_SERVICE.replace(
            "(self.pods, self.banp) = snap",
            "(self.pods, _unused) = snap",
        )
        serve = _mini_repo(tmp_path, service_src=svc)
        findings, _ = statelint.lint_paths([serve])
        assert "ST002" in _codes(findings)
        assert any("never restored" in f.message for f in findings)

    def test_no_snapshot_at_all_fires(self, tmp_path):
        svc = GOOD_SERVICE.replace(
            "            snap = (dict(self.pods), self.banp)\n", ""
        ).replace(
            "                (self.pods, self.banp) = snap\n", ""
        )
        serve = _mini_repo(tmp_path, service_src=svc)
        findings, _ = statelint.lint_paths([serve])
        assert "ST002" in _codes(findings)
        assert any("no rollback snapshot" in f.message for f in findings)

    def test_registry_driven_snapshot_clean(self, tmp_path):
        """The real service's shape: stateregistry.snapshot/restore are
        covered by construction (they iterate FIELDS)."""
        svc = GOOD_SERVICE.replace(
            "snap = (dict(self.pods), self.banp)",
            "snap = stateregistry.snapshot(self)",
        ).replace(
            "(self.pods, self.banp) = snap",
            "stateregistry.restore(self, snap)",
        )
        serve = _mini_repo(tmp_path, service_src=svc)
        findings, stats = statelint.lint_paths([serve])
        assert findings == [], [f.render() for f in findings]

    def test_registry_snapshot_without_restore_fires(self, tmp_path):
        svc = GOOD_SERVICE.replace(
            "snap = (dict(self.pods), self.banp)",
            "snap = stateregistry.snapshot(self)",
        ).replace(
            "(self.pods, self.banp) = snap",
            "pass",
        )
        serve = _mini_repo(tmp_path, service_src=svc)
        findings, _ = statelint.lint_paths([serve])
        assert "ST002" in _codes(findings)
        assert any(
            "never calls stateregistry.restore" in f.message
            for f in findings
        )


class TestST003DigestAuditCoverage:
    def test_field_missing_from_canonical_state_fires(self, tmp_path):
        serve = _mini_repo(tmp_path, digest_src="""
def canonical_state(pods):
    return {"pods": sorted(pods.items())}
""")
        findings, _ = statelint.lint_paths([serve])
        assert "ST003" in _codes(findings)
        assert any(
            "canonical_state" in f.message and "'banp'" in f.message
            for f in findings
        )

    def test_field_missing_from_note_epoch_fires(self, tmp_path):
        svc = GOOD_SERVICE.replace(
            "self._epoch, pods=dict(self.pods), banp=self.banp,",
            "self._epoch, pods=dict(self.pods),",
        )
        serve = _mini_repo(tmp_path, service_src=svc)
        findings, _ = statelint.lint_paths([serve])
        assert "ST003" in _codes(findings)
        assert any("note_epoch snapshot" in f.message for f in findings)

    def test_field_missing_from_state_payload_fires(self, tmp_path):
        svc = GOOD_SERVICE.replace(
            'return {"pods": len(self.pods), "banp": self.banp is not None}',
            'return {"pods": len(self.pods)}',
        )
        serve = _mini_repo(tmp_path, service_src=svc)
        findings, _ = statelint.lint_paths([serve])
        assert "ST003" in _codes(findings)
        assert any("state() payload" in f.message for f in findings)

    def test_registry_driven_audit_and_state_clean(self, tmp_path):
        svc = GOOD_SERVICE.replace(
            "self._epoch, pods=dict(self.pods), banp=self.banp,",
            "self._epoch, **stateregistry.audit_state(self),",
        ).replace(
            'return {"pods": len(self.pods), "banp": self.banp is not None}',
            'return {"e": self._epoch, **stateregistry.state_counts(self)}',
        )
        serve = _mini_repo(tmp_path, service_src=svc)
        findings, _ = statelint.lint_paths([serve])
        assert findings == [], [f.render() for f in findings]


class TestST004EpochDiscipline:
    def test_double_bump_fires(self, tmp_path):
        svc = GOOD_SERVICE.replace(
            "self._epoch += 1",
            "self._epoch += 1\n            self._epoch += 1",
        )
        serve = _mini_repo(tmp_path, service_src=svc)
        findings, _ = statelint.lint_paths([serve])
        assert "ST004" in _codes(findings)
        assert any("2 times" in f.message for f in findings)

    def test_bump_outside_commit_fires(self, tmp_path):
        serve = _mini_repo(tmp_path, service_src=GOOD_SERVICE + """
    def fudge(self):
        with self._lock:
            self._epoch += 1
""")
        findings, _ = statelint.lint_paths([serve])
        assert "ST004" in _codes(findings)
        assert any("outside the commit path" in f.message for f in findings)

    def test_bump_before_mutations_fires(self, tmp_path):
        svc = GOOD_SERVICE.replace(
            """            snap = (dict(self.pods), self.banp)""",
            """            self._epoch += 1
            snap = (dict(self.pods), self.banp)""",
        ).replace(
            """            self._epoch += 1
            self._audit.note_epoch""",
            """            self._audit.note_epoch""",
        )
        serve = _mini_repo(tmp_path, service_src=svc)
        findings, _ = statelint.lint_paths([serve])
        assert "ST004" in _codes(findings)
        assert any(
            "before state mutations complete" in f.message
            for f in findings
        )

    def test_missing_bump_fires(self, tmp_path):
        svc = GOOD_SERVICE.replace(
            "            self._epoch += 1\n", ""
        )
        serve = _mini_repo(tmp_path, service_src=svc)
        findings, _ = statelint.lint_paths([serve])
        assert "ST004" in _codes(findings)
        assert any("never increments" in f.message for f in findings)

    def test_unlocked_epoch_state_pair_fires(self, tmp_path):
        serve = _mini_repo(tmp_path, service_src=GOOD_SERVICE + """
    def peek(self):
        return (self._epoch, len(self.pods))
""")
        findings, _ = statelint.lint_paths([serve])
        assert "ST004" in _codes(findings)
        assert any(
            "outside a consistent locked snapshot" in f.message
            for f in findings
        )

    def test_locked_epoch_state_pair_clean(self, tmp_path):
        serve = _mini_repo(tmp_path, service_src=GOOD_SERVICE + """
    def peek(self):
        with self._lock:
            return (self._epoch, len(self.pods))
""")
        findings, _ = statelint.lint_paths([serve])
        assert findings == [], [f.render() for f in findings]


class TestST005KindLifecycle:
    def test_dangling_gate_fires(self, tmp_path):
        reg = GOOD_REGISTRY.replace(
            'KindSpec("banp_upsert", field="banp", gate="tests/test_ok.py")',
            'KindSpec("banp_upsert", field="banp", gate="tests/test_gone.py")',
        )
        serve = _mini_repo(tmp_path, registry_src=reg)
        findings, _ = statelint.lint_paths([serve])
        assert "ST005" in _codes(findings)
        assert any("test_gone.py" in f.message for f in findings)

    def test_make_target_gate_resolves(self, tmp_path):
        reg = GOOD_REGISTRY.replace(
            'KindSpec("banp_upsert", field="banp", gate="tests/test_ok.py")',
            'KindSpec("banp_upsert", field="banp", gate="make stateharness")',
        )
        serve = _mini_repo(
            tmp_path, registry_src=reg,
            makefile="stateharness:\n\ttrue\n",
        )
        findings, _ = statelint.lint_paths([serve])
        assert findings == [], [f.render() for f in findings]

    def test_kind_without_wire_kind_fires(self, tmp_path):
        model = GOOD_MODEL.replace(
            '("pod_add", "pod_remove", "banp_upsert")',
            '("pod_add", "pod_remove")',
        )
        serve = _mini_repo(tmp_path, model_src=model)
        findings, _ = statelint.lint_paths([serve])
        assert "ST005" in _codes(findings)
        assert any("no wire Delta kind" in f.message for f in findings)

    def test_wire_kind_without_lifecycle_row_fires(self, tmp_path):
        model = GOOD_MODEL.replace(
            '("pod_add", "pod_remove", "banp_upsert")',
            '("pod_add", "pod_remove", "banp_upsert", "tenant_upsert")',
        )
        serve = _mini_repo(tmp_path, model_src=model)
        findings, _ = statelint.lint_paths([serve])
        assert "ST005" in _codes(findings)
        assert any(
            "'tenant_upsert'" in f.message
            and "no KindSpec lifecycle row" in f.message
            for f in findings
        )

    def test_kind_never_applied_fires(self, tmp_path):
        svc = GOOD_SERVICE.replace(
            """        if d.kind == "banp_upsert":
            self.banp = d
            return ("tier", "banp")
""", "")
        serve = _mini_repo(tmp_path, service_src=svc)
        findings, _ = statelint.lint_paths([serve])
        assert "ST005" in _codes(findings)
        assert any("never applied" in f.message for f in findings)

    def test_validator_without_membership_vet_fires(self, tmp_path):
        svc = GOOD_SERVICE.replace(
            """        if d.kind not in Delta.KINDS:
            return "unknown kind", None
        return None, None""",
            "        return None, None",
        )
        serve = _mini_repo(tmp_path, service_src=svc)
        findings, _ = statelint.lint_paths([serve])
        assert "ST005" in _codes(findings)
        assert any(
            "never vets kind membership" in f.message for f in findings
        )

    def test_field_kind_without_row_fires(self, tmp_path):
        reg = GOOD_REGISTRY.replace(
            '    KindSpec("pod_remove", field="pods", gate="tests/test_ok.py"),\n',
            "",
        )
        serve = _mini_repo(tmp_path, registry_src=reg)
        findings, _ = statelint.lint_paths([serve])
        assert "ST005" in _codes(findings)
        assert any(
            "'pod_remove'" in f.message and "no declared KindSpec" in f.message
            for f in findings
        )

    def test_registry_suppression_honored(self, tmp_path):
        reg = GOOD_REGISTRY.replace(
            'KindSpec("banp_upsert", field="banp", gate="tests/test_ok.py")',
            'KindSpec("banp_upsert", field="banp",'
            ' gate="tests/test_gone.py")  # statelint: ignore[ST005]',
        )
        serve = _mini_repo(tmp_path, registry_src=reg)
        findings, _ = statelint.lint_paths([serve])
        assert findings == []


class TestForgottenFieldFixture:
    def test_forgotten_field_fires_st002_st003_st005(self, tmp_path):
        """The planted composite fixture ISSUE 19 demands: a service
        grown a THIRD registered field ('slabs') whose author forgot
        the rollback snapshot, the digest/audit/state surfaces, and the
        wire kind — one omission, every guard fires."""
        reg = GOOD_REGISTRY.replace(
            ")\nKINDS",
            """    StateField("slabs", attr="slabs", container="dict",
               kinds=("slab_upsert",),
               digest_key="slabs", state_key="slabs"),
)
KINDS""",
        ).replace(
            ")\nCOMMIT",
            """    KindSpec("slab_upsert", field="slabs", gate="tests/test_ok.py"),
)
COMMIT""",
        )
        serve = _mini_repo(tmp_path, registry_src=reg)
        findings, _ = statelint.lint_paths([serve])
        codes = set(_codes(findings))
        assert {"ST002", "ST003", "ST005"} <= codes, [
            f.render() for f in findings
        ]
        # ST002: slabs missing from the rollback snapshot
        assert any(
            f.code == "ST002" and "'slabs'" in f.message for f in findings
        )
        # ST003: slabs missing from canonical_state, note_epoch AND state()
        st3 = [f.message for f in findings if f.code == "ST003"]
        assert any("canonical_state" in m for m in st3)
        assert any("note_epoch" in m for m in st3)
        assert any("state() payload" in m for m in st3)
        # ST005: slab_upsert has no wire kind and is never applied
        st5 = [f.message for f in findings if f.code == "ST005"]
        assert any("no wire Delta kind" in m for m in st5)
        assert any("never applied" in m for m in st5)


class TestCleanRunAcceptance:
    def test_state_packages_clean(self):
        """The acceptance gate: 0 findings over serve/ + audit/ with
        the annotation floor ISSUE 19 demands (>= 20 live registry
        annotations; every field and kind declared)."""
        findings, stats = statelint.lint_paths(STATE_PACKAGES)
        assert findings == [], [f.render() for f in findings]
        assert stats["fields"] >= 5
        assert stats["kinds"] >= 10
        assert stats["annotations"] >= 20

    def test_cli_clean(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "statelint.py"),
             *STATE_PACKAGES],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert proc.stdout == ""
        assert "statelint:" in proc.stderr


class TestStateManifest:
    def test_static_extraction_equals_runtime_manifest(self):
        """The lint's AST-extracted registry and the live module's
        manifest() must be IDENTICAL — the proof the static twin lints
        the real state declarations, not a drifted copy."""
        from cyclonus_tpu.serve import stateregistry

        reg = statelint.load_registry(os.path.join(
            REPO, "cyclonus_tpu", "serve", "stateregistry.py"
        ))
        assert statelint.build_manifest(reg) == stateregistry.manifest()

    def test_registry_kinds_consistent(self):
        """Registry self-consistency: the KindSpec rows and the
        per-field kinds tuples describe the same set."""
        from cyclonus_tpu.serve import stateregistry

        row_kinds = set(stateregistry.delta_kinds())
        field_kinds = {
            k for f in stateregistry.FIELDS for k in f.kinds
        }
        assert row_kinds == field_kinds

    def test_recorder_stripped_when_unarmed(self):
        """The strip contract: with CYCLONUS_STATEHARNESS unset (every
        pytest run — conftest does not arm it) _record is a no-op and
        drain() is empty."""
        from cyclonus_tpu.serve import stateregistry

        assert stateregistry.ACTIVE is False
        stateregistry._record("snapshot")
        assert stateregistry.drain() == []

    def test_restore_is_strict(self):
        """ST002's runtime twin, directly: a snapshot missing a
        registered field raises KeyError instead of committing
        poison."""
        import pytest

        from cyclonus_tpu.serve import stateregistry

        class Shell:
            pass

        svc = Shell()
        for f in stateregistry.FIELDS:
            setattr(svc, f.attr, {} if f.container == "dict" else None)
        snap = stateregistry.snapshot(svc)
        snap.pop("pods")
        with pytest.raises(KeyError):
            stateregistry.restore(svc, snap)


class TestDigestTierCoverage:
    """Satellite: the PR 18 digest must separate states differing ONLY
    in tier objects (the gap class ISSUE 19 names — two replicas
    differing only in an ANP must never digest equal)."""

    def _anp(self, name="t", priority=5):
        from cyclonus_tpu.tiers.model import (
            AdminNetworkPolicy,
            TierRule,
            TierScope,
        )

        return AdminNetworkPolicy(
            name=name, priority=priority, subject=TierScope(),
            ingress=[TierRule(action="Deny", peers=[TierScope()])],
        )

    def test_anp_changes_state_digest(self):
        from cyclonus_tpu.audit import digest as dg

        pods = {"x/p0": ("x", "p0", {"app": "a"}, "10.0.0.1")}
        namespaces = {"x": {"ns": "x"}}
        base = dg.state_digest(
            dg.canonical_state(pods, namespaces, {}, {}, None)
        )
        with_anp = dg.state_digest(dg.canonical_state(
            pods, namespaces, {}, {"t": self._anp()}, None
        ))
        assert base != with_anp
        # a semantic edit INSIDE the ANP must also separate
        edited = dg.state_digest(dg.canonical_state(
            pods, namespaces, {}, {"t": self._anp(priority=6)}, None
        ))
        assert with_anp != edited

    def test_banp_changes_state_digest(self):
        from cyclonus_tpu.audit import digest as dg
        from cyclonus_tpu.tiers.model import (
            BaselineAdminNetworkPolicy,
            TierRule,
            TierScope,
        )

        pods = {"x/p0": ("x", "p0", {"app": "a"}, "10.0.0.1")}
        namespaces = {"x": {"ns": "x"}}
        base = dg.state_digest(
            dg.canonical_state(pods, namespaces, {}, {}, None)
        )
        banp = BaselineAdminNetworkPolicy(
            subject=TierScope(),
            ingress=[TierRule(action="Deny", peers=[TierScope()])],
        )
        assert base != dg.state_digest(
            dg.canonical_state(pods, namespaces, {}, {}, banp)
        )


class TestStateHarnessTier1:
    def test_quick_slice(self):
        """The tier-1 state-surface gate: the harness quick slice in a
        fresh subprocess (the recorder arms at import), including its
        field/kind coverage census — every registered field's kinds
        must drive a digest change through the live service."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-m", "tests.stateharness"],
            capture_output=True, text=True, timeout=600, cwd=REPO, env=env,
        )
        assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
        assert "coverage_census" in proc.stderr


class TestMakefileWiring:
    def test_statelint_in_lint_and_check(self):
        mk = open(os.path.join(REPO, "Makefile")).read()
        assert "statelint:" in mk
        assert "stateharness:" in mk
        # statelint rides the aggregate lint target
        import re

        lint_line = re.search(r"^lint:.*$", mk, re.MULTILINE).group(0)
        assert "statelint" in lint_line
