"""L1 tests: label-selector and CIDR matching semantics.

Golden cases ported from the reference's kube/ipaddress_tests.go and
kube/labelselector_tests.go, plus extra coverage for the operator traps."""

import pytest

from cyclonus_tpu.kube import (
    IPBlock,
    LabelSelector,
    LabelSelectorRequirement,
    is_ip_address_match_for_ip_block,
    is_ip_in_cidr,
    is_labels_match_label_selector,
    is_match_expression_match,
    make_ipv4_cidr,
)
from cyclonus_tpu.kube.netpol import OP_DOES_NOT_EXIST, OP_EXISTS, OP_IN, OP_NOT_IN


class TestIPInCIDR:
    # ipaddress_tests.go:14-47
    @pytest.mark.parametrize(
        "ip,cidr,member",
        [
            ("1.2.3.3", "1.2.3.0/24", True),
            ("1.2.3.3", "1.2.3.0/28", True),
            ("1.2.3.3", "1.2.3.0/30", True),
            ("1.2.3.3", "1.2.3.0/31", False),
        ],
    )
    def test_membership(self, ip, cidr, member):
        assert is_ip_in_cidr(ip, cidr) == member

    def test_ipv6(self):
        # The reference's IPv6 spec is an empty stub (ipaddress_tests.go:49-53);
        # we actually cover it.
        assert is_ip_in_cidr("2001:db8::68", "2001:db8::/32")
        assert not is_ip_in_cidr("2001:db9::68", "2001:db8::/32")
        # cross-family: no match
        assert not is_ip_in_cidr("1.2.3.4", "2001:db8::/32")

    def test_malformed(self):
        with pytest.raises(ValueError):
            is_ip_address_match_for_ip_block(
                "abc", IPBlock.make(cidr="1.2.3.4")
            )

    def test_host_bits_masked(self):
        # Go's ParseCIDR masks host bits: 1.2.3.4 parses as net 1.2.3.4/32?? no:
        # "10.0.0.1/24" is the 10.0.0.0/24 network.
        assert is_ip_in_cidr("10.0.0.99", "10.0.0.1/24")


class TestIPBlock:
    # ipaddress_tests.go:63-108
    @pytest.mark.parametrize(
        "ip,cidr,match",
        [
            ("1.2.3.3", "1.2.3.0/24", True),
            ("1.2.3.3", "1.2.3.0/28", True),
            ("1.2.3.3", "1.2.3.0/30", True),
            ("1.2.3.3", "1.2.3.0/31", False),
        ],
    )
    def test_no_except(self, ip, cidr, match):
        assert is_ip_address_match_for_ip_block(ip, IPBlock.make(cidr=cidr)) == match

    # ipaddress_tests.go:110-155
    @pytest.mark.parametrize(
        "ip,cidr,excepts,match",
        [
            ("1.2.3.3", "1.2.3.0/28", ["1.2.3.0/30"], False),
            ("1.2.3.4", "1.2.3.0/28", ["1.2.3.4/30"], False),
            ("1.2.3.3", "1.2.3.0/28", ["1.2.3.4/30"], True),
        ],
    )
    def test_with_except(self, ip, cidr, excepts, match):
        assert is_ip_address_match_for_ip_block(
            ip, IPBlock.make(cidr=cidr)
        ), "sanity: should match without except"
        assert (
            is_ip_address_match_for_ip_block(
                ip, IPBlock.make(cidr=cidr, except_=excepts)
            )
            == match
        )


class TestMakeCIDR:
    # ipaddress_tests.go:158-202
    @pytest.mark.parametrize(
        "ip,bits,expected",
        [
            ("255.255.255.255", 32, "255.255.255.255/32"),
            ("255.255.255.255", 31, "255.255.255.254/31"),
            ("255.255.255.255", 30, "255.255.255.252/30"),
            ("255.255.255.255", 28, "255.255.255.240/28"),
            ("255.255.255.255", 24, "255.255.255.0/24"),
            ("255.255.255.255", 16, "255.255.0.0/16"),
        ],
    )
    def test_normalized(self, ip, bits, expected):
        assert make_ipv4_cidr(ip, bits) == expected


class TestLabelSelector:
    def test_empty_selector_matches_all(self):
        # labelselector.go:84-85
        assert is_labels_match_label_selector({}, LabelSelector.make())
        assert is_labels_match_label_selector({"a": "b"}, LabelSelector.make())

    def test_match_labels_anded(self):
        sel = LabelSelector.make(match_labels={"a": "b", "c": "d"})
        assert is_labels_match_label_selector({"a": "b", "c": "d", "e": "f"}, sel)
        assert not is_labels_match_label_selector({"a": "b"}, sel)
        assert not is_labels_match_label_selector({"a": "x", "c": "d"}, sel)

    def test_in_operator(self):
        exp = LabelSelectorRequirement("k", OP_IN, ("v1", "v2"))
        assert is_match_expression_match({"k": "v1"}, exp)
        assert is_match_expression_match({"k": "v2"}, exp)
        assert not is_match_expression_match({"k": "v3"}, exp)
        assert not is_match_expression_match({}, exp)

    def test_not_in_operator_absent_key_is_no_match(self):
        # The trap: NotIn with absent key => NOT a match
        # (labelselector.go:37-49).
        exp = LabelSelectorRequirement("k", OP_NOT_IN, ("v1",))
        assert not is_match_expression_match({}, exp)
        assert not is_match_expression_match({"k": "v1"}, exp)
        assert is_match_expression_match({"k": "v2"}, exp)

    def test_exists(self):
        exp = LabelSelectorRequirement("k", OP_EXISTS)
        assert is_match_expression_match({"k": "anything"}, exp)
        assert not is_match_expression_match({"j": "x"}, exp)

    def test_does_not_exist(self):
        exp = LabelSelectorRequirement("k", OP_DOES_NOT_EXIST)
        assert not is_match_expression_match({"k": "anything"}, exp)
        assert is_match_expression_match({"j": "x"}, exp)

    def test_combined_labels_and_expressions(self):
        sel = LabelSelector.make(
            match_labels={"a": "b"},
            match_expressions=[LabelSelectorRequirement("k", OP_EXISTS)],
        )
        assert is_labels_match_label_selector({"a": "b", "k": "z"}, sel)
        assert not is_labels_match_label_selector({"a": "b"}, sel)
        assert not is_labels_match_label_selector({"k": "z"}, sel)


class TestCondensedModelParity:
    """Field-for-field coverage of the reference's condensed policy type
    model (pkg/kube/netpol/condensed-model.go:1-73): every type, field,
    and constant in that standalone redeclaration of the k8s netpol API
    must have a counterpart in kube/netpol.py, so a reference user finds
    the full model surface here.  (The reference file is a TYPE corpus,
    not fixtures — basic.go / complicated.go / pathological.go are ported
    as fixture corpora in kube/pathological.py.)"""

    def test_type_surface(self):
        import dataclasses

        from cyclonus_tpu.kube import netpol as m

        want = {
            # condensed-model.go type -> (our class, Go field -> our field)
            "NetworkPolicySpec": (
                m.NetworkPolicySpec,
                {
                    "PodSelector": "pod_selector",
                    "Ingress": "ingress",
                    "Egress": "egress",
                    "PolicyTypes": "policy_types",
                },
            ),
            "NetworkPolicyIngressRule": (
                m.NetworkPolicyIngressRule,
                {"Ports": "ports", "From": "from_"},
            ),
            "NetworkPolicyEgressRule": (
                m.NetworkPolicyEgressRule,
                {"Ports": "ports", "To": "to"},
            ),
            "NetworkPolicyPort": (
                m.NetworkPolicyPort,
                {"Protocol": "protocol", "Port": "port"},
            ),
            "NetworkPolicyPeer": (
                m.NetworkPolicyPeer,
                {
                    "PodSelector": "pod_selector",
                    "NamespaceSelector": "namespace_selector",
                    "IPBlock": "ip_block",
                },
            ),
            "IPBlock": (m.IPBlock, {"CIDR": "cidr", "Except": "except_"}),
            "LabelSelector": (
                m.LabelSelector,
                {"MatchExpressions": "match_expressions"},
            ),
            "LabelSelectorRequirement": (
                m.LabelSelectorRequirement,
                {"Key": "key", "Operator": "operator", "Values": "values"},
            ),
        }
        for go_type, (cls, fields) in want.items():
            names = {f.name for f in dataclasses.fields(cls)}
            for go_field, our_field in fields.items():
                assert our_field in names, (go_type, go_field, our_field)
        # MatchLabels is stored order-preserving as items; the make()
        # constructor and accessor expose the map form
        sel = m.LabelSelector.make(match_labels={"a": "b"})
        assert sel.match_labels == {"a": "b"}

    def test_constants(self):
        from cyclonus_tpu.kube import netpol as m

        # Protocol consts (condensed-model.go:41-46)
        assert m.PROTOCOL_TCP == "TCP"
        assert m.PROTOCOL_UDP == "UDP"
        assert m.PROTOCOL_SCTP == "SCTP"
        # PolicyType consts (:48-53) are plain strings in specs
        assert m.POLICY_TYPE_INGRESS == "Ingress"
        assert m.POLICY_TYPE_EGRESS == "Egress"
        # LabelSelectorOperator consts (:66-72)
        assert m.OP_IN == "In"
        assert m.OP_NOT_IN == "NotIn"
        assert m.OP_EXISTS == "Exists"
        assert m.OP_DOES_NOT_EXIST == "DoesNotExist"
