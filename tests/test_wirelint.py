"""tools/wirelint.py tests: seeded-violation gates for WR001–WR005
(each defect class must fire, each suppression must be honored), the
golden-drift gate (every non-additive mutation of wire_schema.json
fires WR003 — removal, re-type, optionality flip, version drift), the
clean-run + annotation-floor acceptance gate over worker/ + serve/,
the static-vs-runtime manifest identity (the AST-extracted registry
must equal wireregistry.manifest() byte for byte), the committed
golden's freshness against the live registry, and the tier-1 slice of
the peer version-skew harness (tests/skewharness.py)."""

import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import wirelint

WIRE_PACKAGES = [
    os.path.join(REPO, "cyclonus_tpu", p) for p in ("worker", "serve")
]

GOOD_REGISTRY = """
PROTOCOL_VERSION = 2
VERSIONS = {
    1: "base",
    2: "latency + tags",
}
MESSAGES = (
    Message(
        "Ping", since=1,
        keys=(
            Key("Id", "str", sample="a"),
            Key("Seq", "int", sample=1),
            Key("LatencyMs", "float", optional=True, since=2,
                canon="round-ms", portable=False, sample=1.5),
            Key("Tag", "str", optional=True, since=2, sample="t"),
            Key("Sub", "str", optional=True, since=2,
                guard="set,with=Tag", sample="s"),
        ),
    ),
    Message(
        "Pong", since=1, epoch="from-verdicts",
        keys=(
            Key("Epoch", "int", optional=True, since=1, sample=3),
            Key("Verdicts", "list", optional=True, since=1, sample=[]),
            Key("Error", "str", optional=True, since=1, sample="x"),
        ),
    ),
    Message(
        "Stamp", since=1, epoch="stamp",
        keys=(
            Key("Epoch", "int", optional=True, since=1, sample=1),
        ),
    ),
)
"""

GOOD_GOLDEN = {
    "schema_version": 2,
    "versions": {"1": "base", "2": "latency + tags"},
    "messages": {
        "Ping": {"since": 1, "epoch": "", "keys": {
            "Id": {"type": "str", "optional": False, "since": 1},
            "Seq": {"type": "int", "optional": False, "since": 1},
            "LatencyMs": {"type": "float", "optional": True, "since": 2},
            "Tag": {"type": "str", "optional": True, "since": 2},
            "Sub": {"type": "str", "optional": True, "since": 2},
        }},
        "Pong": {"since": 1, "epoch": "from-verdicts", "keys": {
            "Epoch": {"type": "int", "optional": True, "since": 1},
            "Verdicts": {"type": "list", "optional": True, "since": 1},
            "Error": {"type": "str", "optional": True, "since": 1},
        }},
        "Stamp": {"since": 1, "epoch": "stamp", "keys": {
            "Epoch": {"type": "int", "optional": True, "since": 1},
        }},
    },
}

GOOD_MODEL = '''
class Ping:
    def __init__(self, id, seq, latency=None, tag="", sub=""):
        self.id = id
        self.seq = seq
        self.latency = latency
        self.tag = tag
        self.sub = sub

    def to_dict(self):
        d = {"Id": self.id, "Seq": self.seq}
        if self.latency is not None:
            d["LatencyMs"] = self.latency
        if self.tag:
            d["Tag"] = self.tag
            if self.sub:
                d["Sub"] = self.sub
        return d

    @staticmethod
    def from_dict(d):
        return Ping(d["Id"], d["Seq"], d.get("LatencyMs"),
                    d.get("Tag", ""), d.get("Sub", ""))


def build_reply(verdicts, report):
    reply = {}  # wire-emit: Pong
    if verdicts:
        reply["Verdicts"] = verdicts
        reply["Epoch"] = report["epoch"]
    if "Epoch" not in reply:
        reply["Epoch"] = report["epoch"]
    return reply
'''


def _mini_repo(tmp_path, registry_src=GOOD_REGISTRY,
               model_src=GOOD_MODEL, golden="default"):
    """A scratch wire package: wireregistry.py (the declarations),
    model.py (emit/read sites), and the frozen golden alongside."""
    pkg = tmp_path / "wirepkg"
    pkg.mkdir()
    (pkg / "wireregistry.py").write_text(textwrap.dedent(registry_src))
    (pkg / "model.py").write_text(textwrap.dedent(model_src))
    if golden == "default":
        golden = GOOD_GOLDEN
    if golden is not None:
        (pkg / "wire_schema.json").write_text(json.dumps(golden))
    return str(pkg)


def _codes(findings):
    return [f.code for f in findings]


class TestWR001EmitDiscipline:
    def test_good_package_clean(self, tmp_path):
        pkg = _mini_repo(tmp_path)
        findings, stats = wirelint.lint_paths([pkg])
        assert findings == [], [f.render() for f in findings]
        assert stats["messages"] == 3 and stats["keys"] == 9
        assert stats["emit_sites"] == 2 and stats["read_sites"] == 1

    def test_undeclared_key_fires(self, tmp_path):
        pkg = _mini_repo(tmp_path, model_src=GOOD_MODEL.replace(
            "        return d",
            '        d["Extra"] = 1\n        return d',
        ))
        findings, _ = wirelint.lint_paths([pkg])
        assert _codes(findings) == ["WR001"]
        assert "'Extra'" in findings[0].message

    def test_optional_emitted_unconditionally_fires(self, tmp_path):
        pkg = _mini_repo(tmp_path, model_src=GOOD_MODEL.replace(
            """        if self.latency is not None:
            d["LatencyMs"] = self.latency""",
            '        d["LatencyMs"] = self.latency',
        ))
        findings, _ = wirelint.lint_paths([pkg])
        assert _codes(findings) == ["WR001"]
        assert "unconditionally" in findings[0].message

    def test_required_emitted_conditionally_fires(self, tmp_path):
        pkg = _mini_repo(tmp_path, model_src=GOOD_MODEL.replace(
            '        d = {"Id": self.id, "Seq": self.seq}',
            """        d = {"Id": self.id}
        if self.seq:
            d["Seq"] = self.seq""",
        ))
        findings, _ = wirelint.lint_paths([pkg])
        assert _codes(findings) == ["WR001"]
        assert "conditionally" in findings[0].message

    def test_with_guard_violation_fires(self, tmp_path):
        """Sub declares guard 'with=Tag': emitting it from a branch
        that never writes Tag fires (the ParentSpan/TraceId rule)."""
        pkg = _mini_repo(tmp_path, model_src=GOOD_MODEL.replace(
            """        if self.tag:
            d["Tag"] = self.tag
            if self.sub:
                d["Sub"] = self.sub""",
            """        if self.tag:
            d["Tag"] = self.tag
        if self.sub:
            d["Sub"] = self.sub""",
        ))
        findings, _ = wirelint.lint_paths([pkg])
        assert _codes(findings) == ["WR001"]
        assert "'with=Tag'" in findings[0].message

    def test_marker_naming_unregistered_message_fires(self, tmp_path):
        pkg = _mini_repo(tmp_path, model_src=GOOD_MODEL.replace(
            "# wire-emit: Pong", "# wire-emit: Nope",
        ))
        findings, _ = wirelint.lint_paths([pkg])
        assert _codes(findings) == ["WR001"]
        assert "'Nope'" in findings[0].message

    def test_suppression_honored(self, tmp_path):
        pkg = _mini_repo(tmp_path, model_src=GOOD_MODEL.replace(
            "        return d",
            '        d["Extra"] = 1  # wirelint: ignore[WR001]\n'
            "        return d",
        ))
        findings, _ = wirelint.lint_paths([pkg])
        assert findings == []


class TestWR002OptionalReads:
    def test_unguarded_subscript_fires(self, tmp_path):
        pkg = _mini_repo(tmp_path, model_src=GOOD_MODEL.replace(
            'd.get("LatencyMs")', 'd["LatencyMs"]',
        ))
        findings, _ = wirelint.lint_paths([pkg])
        assert _codes(findings) == ["WR002"]
        assert "LatencyMs" in findings[0].message

    def test_presence_guarded_subscript_clean(self, tmp_path):
        pkg = _mini_repo(tmp_path, model_src=GOOD_MODEL.replace(
            """    @staticmethod
    def from_dict(d):
        return Ping(d["Id"], d["Seq"], d.get("LatencyMs"),
                    d.get("Tag", ""), d.get("Sub", ""))""",
            """    @staticmethod
    def from_dict(d):
        latency = None
        if "LatencyMs" in d:
            latency = d["LatencyMs"]
        return Ping(d["Id"], d["Seq"], latency,
                    d.get("Tag", ""), d.get("Sub", ""))""",
        ))
        findings, _ = wirelint.lint_paths([pkg])
        assert findings == [], [f.render() for f in findings]

    def test_required_subscript_clean(self, tmp_path):
        """d["Id"] / d["Seq"] are the frozen required shape: subscript
        reads of them are legal (an old peer always emits them)."""
        pkg = _mini_repo(tmp_path)
        findings, _ = wirelint.lint_paths([pkg])
        assert findings == []


class TestWR003GoldenDrift:
    """The satellite golden-drift gate: every non-additive mutation of
    the frozen schema fires WR003, in BOTH directions."""

    def _mutated(self, fn):
        golden = json.loads(json.dumps(GOOD_GOLDEN))
        fn(golden)
        return golden

    def test_key_removed_from_registry_fires(self, tmp_path):
        reg = GOOD_REGISTRY.replace(
            '            Key("Tag", "str", optional=True, since=2,'
            ' sample="t"),\n', "",
        )
        model = GOOD_MODEL.replace(
            """        if self.tag:
            d["Tag"] = self.tag
            if self.sub:
                d["Sub"] = self.sub""",
            """        if self.tag:
            if self.sub:
                d["Sub"] = self.sub""",
        )
        pkg = _mini_repo(tmp_path, registry_src=reg, model_src=model)
        findings, _ = wirelint.lint_paths([pkg])
        assert "WR003" in _codes(findings)
        assert any(
            "Ping.Tag" in f.message and "removed from the registry"
            in f.message for f in findings
        )

    def test_new_key_without_golden_row_fires(self, tmp_path):
        golden = self._mutated(
            lambda g: g["messages"]["Ping"]["keys"].pop("Sub")
        )
        pkg = _mini_repo(tmp_path, golden=golden)
        findings, _ = wirelint.lint_paths([pkg])
        assert _codes(findings) == ["WR003"]
        assert "no golden row" in findings[0].message

    def test_retyped_key_fires(self, tmp_path):
        golden = self._mutated(
            lambda g: g["messages"]["Ping"]["keys"]["Seq"].update(
                type="str"
            )
        )
        pkg = _mini_repo(tmp_path, golden=golden)
        findings, _ = wirelint.lint_paths([pkg])
        assert _codes(findings) == ["WR003"]
        assert "re-typed" in findings[0].message

    def test_optionality_flip_fires(self, tmp_path):
        golden = self._mutated(
            lambda g: g["messages"]["Ping"]["keys"]["LatencyMs"].update(
                optional=False
            )
        )
        pkg = _mini_repo(tmp_path, golden=golden)
        findings, _ = wirelint.lint_paths([pkg])
        assert _codes(findings) == ["WR003"]
        assert "optionality flipped" in findings[0].message

    def test_version_pin_drift_fires(self, tmp_path):
        golden = self._mutated(
            lambda g: g["messages"]["Ping"]["keys"]["Tag"].update(
                since=1
            )
        )
        pkg = _mini_repo(tmp_path, golden=golden)
        findings, _ = wirelint.lint_paths([pkg])
        assert _codes(findings) == ["WR003"]
        assert "version pin drifted" in findings[0].message

    def test_schema_version_mismatch_fires(self, tmp_path):
        golden = self._mutated(
            lambda g: g.update(schema_version=1)
        )
        pkg = _mini_repo(tmp_path, golden=golden)
        findings, _ = wirelint.lint_paths([pkg])
        assert "WR003" in _codes(findings)
        assert any("schema_version" in f.message for f in findings)

    def test_missing_golden_fires(self, tmp_path):
        pkg = _mini_repo(tmp_path, golden=None)
        findings, _ = wirelint.lint_paths([pkg])
        assert _codes(findings) == ["WR003"]
        assert "unreadable" in findings[0].message

    def test_key_without_version_row_fires(self, tmp_path):
        reg = GOOD_REGISTRY.replace(
            'Key("Tag", "str", optional=True, since=2, sample="t")',
            'Key("Tag", "str", optional=True, since=3, sample="t")',
        )
        golden = self._mutated(
            lambda g: g["messages"]["Ping"]["keys"]["Tag"].update(
                since=3
            )
        )
        pkg = _mini_repo(tmp_path, registry_src=reg, golden=golden)
        findings, _ = wirelint.lint_paths([pkg])
        assert _codes(findings) == ["WR003"]
        assert "no VERSIONS row" in findings[0].message

    def test_later_required_key_fires(self, tmp_path):
        """A key added after the message's debut must be optional —
        a peer at the debut version could never have emitted it."""
        reg = GOOD_REGISTRY.replace(
            'Key("Tag", "str", optional=True, since=2, sample="t")',
            'Key("Tag", "str", since=2, sample="t")',
        )
        golden = self._mutated(
            lambda g: g["messages"]["Ping"]["keys"]["Tag"].update(
                optional=False
            )
        )
        model = GOOD_MODEL.replace(
            """        if self.tag:
            d["Tag"] = self.tag
            if self.sub:
                d["Sub"] = self.sub""",
            """        d["Tag"] = self.tag
        if self.tag:
            if self.sub:
                d["Sub"] = self.sub""",
        )
        pkg = _mini_repo(
            tmp_path, registry_src=reg, model_src=model, golden=golden
        )
        findings, _ = wirelint.lint_paths([pkg])
        assert "WR003" in _codes(findings)
        assert any(
            "but is required" in f.message for f in findings
        )


class TestWR004EpochDiscipline:
    def test_verdicts_without_epoch_fires(self, tmp_path):
        pkg = _mini_repo(tmp_path, model_src=GOOD_MODEL.replace(
            """    if verdicts:
        reply["Verdicts"] = verdicts
        reply["Epoch"] = report["epoch"]
    if "Epoch" not in reply:
        reply["Epoch"] = report["epoch"]
    return reply""",
            """    if verdicts:
        reply["Verdicts"] = verdicts
    return reply""",
        ))
        findings, _ = wirelint.lint_paths([pkg])
        assert _codes(findings) == ["WR004"]
        assert "never stamps an Epoch" in findings[0].message

    def test_epoch_from_constant_fires(self, tmp_path):
        pkg = _mini_repo(tmp_path, model_src=GOOD_MODEL.replace(
            '    if "Epoch" not in reply:\n'
            '        reply["Epoch"] = report["epoch"]',
            '    if "Epoch" not in reply:\n'
            '        reply["Epoch"] = 7',
        ))
        findings, _ = wirelint.lint_paths([pkg])
        assert _codes(findings) == ["WR004"]
        assert "epoch accessor" in findings[0].message

    def test_unguarded_double_stamp_fires(self, tmp_path):
        pkg = _mini_repo(tmp_path, model_src=GOOD_MODEL.replace(
            '    if "Epoch" not in reply:\n'
            '        reply["Epoch"] = report["epoch"]',
            '    reply["Epoch"] = report["epoch"]',
        ))
        findings, _ = wirelint.lint_paths([pkg])
        assert _codes(findings) == ["WR004"]
        assert "more than once" in findings[0].message

    def test_stamp_ctor_without_epoch_fires(self, tmp_path):
        pkg = _mini_repo(tmp_path, model_src=GOOD_MODEL + """

def make():
    return Stamp(1)
""")
        findings, _ = wirelint.lint_paths([pkg])
        assert _codes(findings) == ["WR004"]
        assert "passes no epoch=" in findings[0].message

    def test_stamp_ctor_with_epoch_clean(self, tmp_path):
        pkg = _mini_repo(tmp_path, model_src=GOOD_MODEL + """

def make(e):
    return Stamp(epoch=e)
""")
        findings, _ = wirelint.lint_paths([pkg])
        assert findings == [], [f.render() for f in findings]


class TestWR005Portability:
    def test_float_without_canon_fires(self, tmp_path):
        reg = GOOD_REGISTRY.replace('canon="round-ms", ', "")
        pkg = _mini_repo(tmp_path, registry_src=reg)
        findings, _ = wirelint.lint_paths([pkg])
        assert _codes(findings) == ["WR005"]
        assert "no canonicalization" in findings[0].message

    def test_timestamp_in_portable_key_fires(self, tmp_path):
        pkg = _mini_repo(tmp_path, model_src=GOOD_MODEL.replace(
            '            d["Tag"] = self.tag',
            '            d["Tag"] = str(time.time())',
        ))
        findings, _ = wirelint.lint_paths([pkg])
        assert _codes(findings) == ["WR005"]
        assert "time()" in findings[0].message

    def test_nonportable_key_may_carry_timestamp(self, tmp_path):
        """LatencyMs declares portable=False: a clock read there is
        the point, not a finding."""
        pkg = _mini_repo(tmp_path, model_src=GOOD_MODEL.replace(
            '            d["LatencyMs"] = self.latency',
            '            d["LatencyMs"] = time.time()',
        ))
        findings, _ = wirelint.lint_paths([pkg])
        assert findings == [], [f.render() for f in findings]


class TestCleanRunAcceptance:
    def test_wire_packages_clean(self):
        """The acceptance gate: 0 findings over worker/ + serve/ with
        the floors ISSUE 20 demands (>= 20 live annotations; every
        message and key declared)."""
        findings, stats = wirelint.lint_paths(WIRE_PACKAGES)
        assert findings == [], [f.render() for f in findings]
        assert stats["messages"] >= 7
        assert stats["keys"] >= 30
        assert stats["emit_sites"] >= 6
        assert stats["read_sites"] >= 6
        assert stats["annotations"] >= 20

    def test_cli_clean(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "wirelint.py"),
             *WIRE_PACKAGES],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert proc.stdout == ""
        assert "wirelint:" in proc.stderr


class TestWireManifest:
    def test_static_extraction_equals_runtime_manifest(self):
        """The lint's AST-extracted registry and the live module's
        manifest() must be IDENTICAL — the proof the static twin lints
        the real wire declarations, not a drifted copy."""
        from cyclonus_tpu.worker import wireregistry

        reg = wirelint.load_registry(os.path.join(
            REPO, "cyclonus_tpu", "worker", "wireregistry.py"
        ))
        assert wirelint.build_manifest(reg) == wireregistry.manifest()

    def test_committed_golden_is_current(self):
        """wire_schema.json must be the registry's own projection —
        a protocol change without a golden regeneration is exactly the
        silent drift WR003 exists to catch."""
        from cyclonus_tpu.worker import wireregistry

        with open(wireregistry.golden_path()) as f:
            committed = json.load(f)
        assert committed == wireregistry.build_golden()

    def test_recorder_stripped_when_unarmed(self):
        """The strip contract: with CYCLONUS_SKEWHARNESS unset (every
        pytest run — conftest does not arm it) _record is a no-op and
        drain() is empty."""
        from cyclonus_tpu.worker import wireregistry

        assert wireregistry.ACTIVE is False
        wireregistry._record("legacy_view")
        assert wireregistry.drain() == []

    def test_wire_tables_are_registry_derived(self):
        """model.py's WIRE ClassVars must BE the registry projection
        (satellite 1: one declaration, everything derives)."""
        from cyclonus_tpu.worker import model, wireregistry

        for name, cls in (
            ("Request", model.Request), ("Batch", model.Batch),
            ("Result", model.Result), ("Delta", model.Delta),
            ("FlowQuery", model.FlowQuery), ("Verdict", model.Verdict),
        ):
            assert cls.WIRE == wireregistry.wire_table(name), name


class TestSkewHarnessTier1:
    def test_quick_slice(self):
        """The tier-1 wire-skew gate: the harness quick slice in a
        fresh subprocess (the recorder arms at import), including its
        coverage census — both skew directions for every registered
        message, no optional key unexercised."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-m", "tests.skewharness"],
            capture_output=True, text=True, timeout=600, cwd=REPO, env=env,
        )
        assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
        assert "coverage_census" in proc.stderr


class TestMakefileWiring:
    def test_wirelint_in_lint_and_check(self):
        mk = open(os.path.join(REPO, "Makefile")).read()
        assert "wirelint:" in mk
        assert "skewharness:" in mk
        # wirelint rides the aggregate lint target
        import re

        lint_line = re.search(r"^lint:.*$", mk, re.MULTILINE).group(0)
        assert "wirelint" in lint_line

    def test_wirelint_leg_in_lint_changed(self):
        src = open(
            os.path.join(REPO, "tools", "lint_changed.py")
        ).read()
        assert "wirelint" in src
