"""tools/planlint.py tests: seeded-violation gates for PL001–PL005
(each defect class must fire, each suppression must be honored), the
clean-run + declaration-count acceptance gate over the dispatch
packages, the static-vs-runtime manifest identity (the AST-extracted
registry must equal planspec.manifest() byte for byte), the plan
manifest schema, predict()'s route/raise semantics, and the tier-1
slice of the dispatch-route harness (tests/planharness.py)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import planlint

DISPATCH_PACKAGES = [
    os.path.join(REPO, "cyclonus_tpu", p)
    for p in ("engine", "serve", "tiers", "audit")
]

GOOD_REGISTRY = """
PATHS = (
    PathSpec(name="a.path", entry="counts", gate="tests/test_ok.py"),
    PathSpec(name="b.path", entry="counts", gate="tests/test_ok.py"),
)
INTERACTIONS = (
    Interaction("tiers", "backend=pallas", "fallback"),
)
"""


def _mini_repo(tmp_path, registry_src=GOOD_REGISTRY, module_src="",
               tests=("test_ok.py",), makefile=None):
    """A scratch repo tree: cyclonus_tpu/engine/{planspec,api}.py plus
    the tests/ gate files planlint's PL002 resolves against."""
    eng = tmp_path / "cyclonus_tpu" / "engine"
    eng.mkdir(parents=True)
    (tmp_path / "tests").mkdir()
    for t in tests:
        (tmp_path / "tests" / t).write_text("")
    if makefile is not None:
        (tmp_path / "Makefile").write_text(makefile)
    (eng / "planspec.py").write_text(textwrap.dedent(registry_src))
    (eng / "api.py").write_text(textwrap.dedent(module_src))
    return str(eng)


def _codes(findings):
    return [f.code for f in findings]


RECORD_BOTH = """
from . import planspec
def f():
    planspec.record("a.path")
    planspec.record("b.path")
"""


class TestPL001RouteLiterals:
    def test_undeclared_literal_fires(self, tmp_path):
        eng = _mini_repo(tmp_path, module_src=RECORD_BOTH + """
def g():
    planspec.record("c.bogus")
""")
        findings, _ = planlint.lint_paths([eng])
        assert _codes(findings) == ["PL001"]
        assert "'c.bogus'" in findings[0].message

    def test_dynamic_argument_fires(self, tmp_path):
        eng = _mini_repo(tmp_path, module_src=RECORD_BOTH + """
def g(name):
    planspec.record(name)
""")
        findings, _ = planlint.lint_paths([eng])
        assert _codes(findings) == ["PL001"]
        assert "not a string literal" in findings[0].message

    def test_declared_literals_clean(self, tmp_path):
        eng = _mini_repo(tmp_path, module_src=RECORD_BOTH)
        findings, stats = planlint.lint_paths([eng])
        assert findings == []
        assert stats["records"] == 2

    def test_suppression_honored(self, tmp_path):
        eng = _mini_repo(tmp_path, module_src=RECORD_BOTH + """
def g():
    planspec.record("c.bogus")  # planlint: ignore[PL001]
""")
        findings, _ = planlint.lint_paths([eng])
        assert findings == []


class TestPL002DifferentialGates:
    def test_missing_gate_fires(self, tmp_path):
        eng = _mini_repo(tmp_path, registry_src="""
PATHS = (PathSpec(name="a.path", entry="counts"),)
""", module_src="""
from . import planspec
def f():
    planspec.record("a.path")
""")
        findings, _ = planlint.lint_paths([eng])
        assert _codes(findings) == ["PL002"]
        assert "no differential gate" in findings[0].message

    def test_dangling_test_file_fires(self, tmp_path):
        eng = _mini_repo(tmp_path, registry_src="""
PATHS = (
    PathSpec(name="a.path", entry="counts", gate="tests/test_missing.py"),
)
""", module_src="""
from . import planspec
def f():
    planspec.record("a.path")
""")
        findings, _ = planlint.lint_paths([eng])
        assert _codes(findings) == ["PL002"]
        assert "test_missing.py" in findings[0].message

    def test_make_target_gate_resolves(self, tmp_path):
        eng = _mini_repo(tmp_path, registry_src="""
PATHS = (
    PathSpec(name="a.path", entry="counts", gate="make planharness"),
    PathSpec(name="b.path", entry="counts", gate="make nosuch"),
)
""", module_src=RECORD_BOTH,
            makefile="planharness:\n\techo ok\n")
        findings, _ = planlint.lint_paths([eng])
        assert _codes(findings) == ["PL002"]
        assert "'make nosuch'" in findings[0].message


class TestPL003CompatibilityMatrix:
    def test_resolver_without_cell_fires(self, tmp_path):
        eng = _mini_repo(tmp_path, registry_src="""
PATHS = (
    PathSpec(name="a.path", entry="counts", gate="tests/test_ok.py"),
    PathSpec(name="b.path", entry="counts", gate="tests/test_ok.py"),
)
INTERACTIONS = ()
""", module_src=RECORD_BOTH + """
def g(backend):
    return planspec.resolve_counts_backend(
        backend=backend, explicit=True, tiers=True, pack=False,
        packed_tier_ok=lambda: False,
    )
""")
        findings, _ = planlint.lint_paths([eng])
        assert _codes(findings) == ["PL003"]
        assert "backend=pallas" in findings[0].message

    def test_feature_pair_without_cell_fires(self, tmp_path):
        eng = _mini_repo(tmp_path, module_src=RECORD_BOTH + """
def g(self, backend):
    if self.tiers is not None and backend == "pallas":
        return 1
""")
        # the mini registry declares (tiers, backend=pallas) — drop it
        eng2 = _mini_repo(
            tmp_path / "bare",
            registry_src="""
PATHS = (
    PathSpec(name="a.path", entry="counts", gate="tests/test_ok.py"),
    PathSpec(name="b.path", entry="counts", gate="tests/test_ok.py"),
)
INTERACTIONS = ()
""",
            module_src=RECORD_BOTH + """
def g(self, backend):
    if self.tiers is not None and backend == "pallas":
        return 1
""")
        findings, _ = planlint.lint_paths([eng])
        assert findings == []  # declared cell: clean
        findings2, _ = planlint.lint_paths([eng2])
        assert _codes(findings2) == ["PL003"]
        assert "'backend=pallas' x 'tiers'" in findings2[0].message


class TestPL004DeterminismHazards:
    def test_set_iteration_feeding_tensor_fires(self, tmp_path):
        eng = _mini_repo(tmp_path, module_src=RECORD_BOTH + """
import numpy as np
def build(keys):
    rows = []
    for k in set(keys):
        rows.append(k)
    return np.asarray(rows)
""")
        findings, _ = planlint.lint_paths([eng])
        assert _codes(findings) == ["PL004"]
        assert "set-iteration" in findings[0].message

    def test_unseeded_rng_fires_seeded_instance_clean(self, tmp_path):
        eng = _mini_repo(tmp_path, module_src=RECORD_BOTH + """
import random
import random as _random
import numpy as np
def bad(keys):
    return np.asarray(random.sample(keys, 2))
def good(keys, rng=None):
    rng = rng or _random.Random(0)
    return np.asarray(rng.sample(keys, 2))
""")
        findings, _ = planlint.lint_paths([eng])
        assert _codes(findings) == ["PL004"]
        assert "random.sample" in findings[0].message
        assert "'bad'" in findings[0].message

    def test_wall_clock_and_set_sum_fire(self, tmp_path):
        eng = _mini_repo(tmp_path, module_src=RECORD_BOTH + """
import time
import numpy as np
def bad(xs):
    t = time.time()
    s = sum({x for x in xs})
    return np.full((2,), s + t)
""")
        findings, _ = planlint.lint_paths([eng])
        assert sorted(_codes(findings)) == ["PL004", "PL004"]

    def test_hazard_outside_tensor_function_clean(self, tmp_path):
        eng = _mini_repo(tmp_path, module_src=RECORD_BOTH + """
import time
def telemetry_stamp():
    return time.time()
""")
        findings, _ = planlint.lint_paths([eng])
        assert findings == []


class TestPL005DeadDeclarations:
    def test_unrecorded_path_fires(self, tmp_path):
        eng = _mini_repo(tmp_path, module_src="""
from . import planspec
def f():
    planspec.record("a.path")
""")
        findings, _ = planlint.lint_paths([eng])
        assert _codes(findings) == ["PL005"]
        assert "'b.path'" in findings[0].message


class TestCleanRunAcceptance:
    def test_dispatch_packages_clean(self):
        """The acceptance gate: 0 findings over engine/ + serve/ +
        tiers/, with the declaration floor the issue demands (>= 20
        PathSpec/Interaction declarations, every one recorded)."""
        findings, stats = planlint.lint_paths(DISPATCH_PACKAGES)
        assert findings == [], [f.render() for f in findings]
        assert stats["paths"] >= 20
        assert stats["paths"] + stats["interactions"] >= 30
        assert stats["records"] >= stats["paths"]

    def test_cli_clean(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "planlint.py"),
             *DISPATCH_PACKAGES],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert proc.stdout == ""
        assert "planlint:" in proc.stderr


class TestPlanManifest:
    def test_static_extraction_equals_runtime_manifest(self):
        """The lint's AST-extracted registry and the live module's
        manifest() must be IDENTICAL — the proof the static twin lints
        the real dispatch declarations, not a drifted copy."""
        from cyclonus_tpu.engine import planspec

        reg = planlint.load_registry(
            os.path.join(REPO, "cyclonus_tpu", "engine", "planspec.py")
        )
        assert planlint.build_manifest(reg) == planspec.manifest()

    def test_manifest_schema(self, tmp_path):
        out = tmp_path / "plan_manifest.json"
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "planlint.py"),
             "--manifest", str(out), *DISPATCH_PACKAGES],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        m = json.loads(out.read_text())
        assert m["version"] == 1
        assert m["stages"] == [
            "pre-classify", "pack", "contract", "tier-resolve", "epilogue",
        ]
        assert len(m["paths"]) >= 20
        names = [p["name"] for p in m["paths"]]
        assert len(names) == len(set(names))
        for p in m["paths"]:
            assert p["entry"] in m["entries"]
            assert set(p["stages"]) <= set(m["stages"])
            assert p["coverage"] in ("tier1", "slow", "device_only")
            assert p["gate"]
        for i in m["interactions"]:
            assert i["verdict"] in ("legal", "fallback", "raise")
            if "raise" in (i["verdict"], i["on_explicit"]):
                assert i["message"]


class TestPredict:
    """predict() is the harness's twin of the live dispatch — pin its
    route and raise semantics directly (the harness pins them against
    the real engine)."""

    def test_entry_defaults(self):
        from cyclonus_tpu.engine import planspec

        assert planspec.predict("grid", {}) == "grid.dense"
        assert planspec.predict("grid", {"classes": True}) == "grid.classes"
        assert planspec.predict("grid_sharded", {}) == "grid.sharded.ring"
        assert planspec.predict("counts", {"platform": "cpu"}) == "counts.xla"
        assert (
            planspec.predict("counts", {"platform": "tpu"}) == "counts.pallas"
        )
        assert (
            planspec.predict("counts_steady", {"pack": True})
            == "counts.steady.default"
        )
        assert (
            planspec.predict("serve_query", {"warming": True})
            == "serve.query.degraded"
        )

    def test_matrix_fallbacks_and_raises(self):
        from cyclonus_tpu.engine import planspec

        # auto pallas under tiers falls back to xla...
        assert (
            planspec.predict("counts", {"platform": "tpu", "tiers": True})
            == "counts.xla"
        )
        # ...unless the packed plan fuses the tier epilogue
        assert (
            planspec.predict("counts", {
                "platform": "tpu", "tiers": True,
                "pack": True, "packed_tier_ok": True,
            })
            == "counts.pallas"
        )
        # an explicit request raises the declared cell's message
        with pytest.raises(planspec.PlanError) as exc:
            planspec.predict("counts", {"backend": "pallas", "tiers": True})
        assert str(exc.value) == planspec.interaction(
            "tiers", "backend=pallas"
        ).message
        # pack retires the slab path before the steady dispatch sees it
        assert (
            planspec.predict("counts_steady", {"pack": True, "slab": True})
            == "counts.steady.default"
        )
        assert (
            planspec.predict("counts_steady", {"pack": False, "slab": True})
            == "counts.steady.slab"
        )

    def test_recorder_stripped_when_unarmed(self):
        """The strip contract: with CYCLONUS_PLANHARNESS unset (every
        pytest run — conftest does not arm it) record() is a no-op and
        drain() is empty."""
        from cyclonus_tpu.engine import planspec

        assert planspec.ACTIVE is False
        planspec.record("grid.dense")
        assert planspec.drain() == []
        assert planspec.dropped() == 0


class TestPlanHarnessTier1:
    def test_quick_slice(self):
        """The tier-1 dispatch-route gate: the harness quick slice in a
        fresh subprocess (the recorder arms at import), including its
        route-coverage census — every tier1-coverage PathSpec must be
        recorded and match its prediction."""
        env = dict(os.environ)
        env.pop("CYCLONUS_CLASS_COMPRESS", None)  # harness forces per-engine
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-m", "tests.planharness"],
            capture_output=True, text=True, timeout=600, cwd=REPO, env=env,
        )
        assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
        assert "coverage_census" in proc.stderr
