"""Key-mutation harness: the dynamic proof behind tools/cachelint.py
(docs/DESIGN.md "Cache discipline"), mirroring tests/raceharness.py's
role for the lock lint.

The static pass proves every trace-baked value APPEARS in its declared
cache key; this harness proves the keys actually DISCRIMINATE: for
every registered key component it perturbs that one component, asserts
the cache misses (a new key string, a new program entry, a fresh
compile), then reverts and asserts a hit.  A component that can be
mutated without a miss is an incomplete key — the engine would serve a
program compiled for a different value: the stale-verdict failure
mode, strictly worse than a crash.

Covered cache families (the acceptance list in ISSUE 13):

  * the persistent AOT executable cache (engine/aot_cache.py) — key
    fields in-process, plus a SUBPROCESS restart leg: a warm cache is
    adopted with zero fresh compiles, and a mutated dtype-plan
    component (CYCLONUS_PACK) misses every entry while the verdicts
    stay bit-identical;
  * the persisted autotune winner cache (engine/autotune.py) — every
    shape-bucket field, the mesh signature, and the dtype plan;
  * the in-process sharded-program cache (engine/sharded.py
    _SHARDED_PROGRAMS) — schedule / pack / mesh;
  * the serve pair program (engine/api.py _pairs_aot) and the grid
    program — per-signature dispatch entries.

Run modes: `python -m tests.keyharness` (quick slice, the tier-1 gate
via tests/test_cachelint.py), `--full` adds the engine-behavior,
sharded, restart-subprocess, and registry-census legs (`make
keyharness`, slow).
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import random
import subprocess
import sys
from typing import Callable, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# multi-device CPU mesh + CPU pin BEFORE any jax import, for standalone
# `python -m tests.keyharness` runs (pytest runs get this from
# tests/conftest.py; setting it twice is harmless)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")


class HarnessFailure(AssertionError):
    """A key component failed its miss-on-mutate / hit-on-revert proof;
    the message names the cache and the component."""


def _check(cond: bool, cache: str, component: str, detail: str) -> None:
    if not cond:
        raise HarnessFailure(
            f"{cache}: key component {component!r} failed — {detail}"
        )


@contextlib.contextmanager
def _env(**kv: Optional[str]):
    """Set/unset env vars, restoring exactly on exit (mutate/revert is
    the harness's whole contract — it must apply to its own state)."""
    old = {k: os.environ.get(k) for k in kv}
    try:
        for k, v in kv.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


class Ctx:
    """Shared scenario context: tmp dir, rng, one lazily built small
    engine (24 pods — enough to exercise every program family, small
    enough for the tier-1 budget)."""

    def __init__(self, tmp: str, seed: int):
        self.tmp = tmp
        self.rng = random.Random(seed)
        self._engine = None
        self._cases = None

    def engine(self):
        if self._engine is None:
            from bench import build_synthetic
            from cyclonus_tpu.engine import PortCase, TpuPolicyEngine
            from cyclonus_tpu.matcher import build_network_policies

            pods, namespaces, policies = build_synthetic(
                24, 6, random.Random(7)
            )
            policy = build_network_policies(True, policies)
            self._engine = TpuPolicyEngine(policy, pods, namespaces)
            self._cases = [PortCase(80, "serve-80-tcp", "TCP")]
        return self._engine

    def cases(self, q: int = 1):
        from cyclonus_tpu.engine import PortCase

        base = [
            PortCase(80, "serve-80-tcp", "TCP"),
            PortCase(81, "serve-81-udp", "UDP"),
            PortCase(8080, "", "TCP"),
        ]
        return base[:q]


# --- scenarios -------------------------------------------------------------


def scenario_aot_key_fields(ctx: Ctx) -> Dict:
    """Every field of the persisted AOT key discriminates: name,
    signature, schedule, plan, and the platform stamp (including the
    jaxlib leg the cachelint audit added)."""
    from cyclonus_tpu.engine import aot_cache

    base = aot_cache.make_key("grid", "sig0", schedule="single", plan="p0")
    muts = 0

    def prove(component: str, **kw) -> None:
        nonlocal muts
        name = kw.pop("_name", "grid")
        sig = kw.pop("_sig", "sig0")
        args = {"schedule": "single", "plan": "p0"}
        args.update(kw)
        mutated = aot_cache.make_key(name, sig, **args)
        _check(mutated != base, "aot", component, "mutation did not miss")
        muts += 1

    prove("name", _name="grid2")
    prove("signature", _sig="sig1")
    prove("schedule", schedule="ring")
    prove("plan", plan="p1")
    # revert: identical inputs produce the identical key (hit)
    again = aot_cache.make_key("grid", "sig0", schedule="single", plan="p0")
    _check(again == base, "aot", "revert", "revert did not hit")
    # platform stamp: jax and jaxlib versions each discriminate
    import jax

    stamp0 = aot_cache.platform_stamp()
    orig = jax.__version__
    try:
        jax.__version__ = orig + ".mut"
        _check(
            aot_cache.platform_stamp() != stamp0,
            "aot", "platform.jax", "jax version mutation did not miss",
        )
        muts += 1
    finally:
        jax.__version__ = orig
    _check(
        aot_cache.platform_stamp() == stamp0,
        "aot", "platform.revert", "platform revert did not hit",
    )
    try:
        import jaxlib

        jorig = jaxlib.__version__
        try:
            jaxlib.__version__ = jorig + ".mut"
            _check(
                aot_cache.platform_stamp() != stamp0,
                "aot", "platform.jaxlib",
                "jaxlib version mutation did not miss (the PR-13 key "
                "omission fix)",
            )
            muts += 1
        finally:
            jaxlib.__version__ = jorig
    except ImportError:  # pragma: no cover - jaxlib always rides jax here
        pass
    return {"mutations": muts}


def scenario_autotune_key_fields(ctx: Ctx) -> Dict:
    """Persisted autotune winner: every shape-bucket field, the mesh,
    and the dtype plan each miss when mutated and hit on revert —
    through the real store/load path against a real cache file."""
    from cyclonus_tpu.engine import autotune as at

    path = os.path.join(ctx.tmp, "autotune.json")
    shape = {
        "n": 256, "te": 16, "ti": 16, "q": 2,
        "tiered": False, "classes": False,
    }
    with _env(CYCLONUS_AUTOTUNE_CACHE=path):
        key = at.make_key(shape, "cpu:host:8", "packed32")
        winner = {"kernel": "packed", "bs": 256, "bd": 512}
        assert at.store_winner(key, winner, {"default_s": 0.1})
        got = at.load_winner(key)
        _check(got == winner, "autotune", "baseline", f"store/load broke: {got}")
        muts = 0
        for field, mutated in [
            ("shape.n", dict(shape, n=512)),
            ("shape.te", dict(shape, te=32)),
            ("shape.ti", dict(shape, ti=32)),
            ("shape.q", dict(shape, q=3)),
            ("shape.tiered", dict(shape, tiered=True)),
            ("shape.classes", dict(shape, classes=True)),
        ]:
            miss = at.load_winner(at.make_key(mutated, "cpu:host:8", "packed32"))
            _check(miss is None, "autotune", field, "mutation did not miss")
            muts += 1
        miss = at.load_winner(at.make_key(shape, "tpu:v5e:4", "packed32"))
        _check(miss is None, "autotune", "mesh", "mutation did not miss")
        muts += 1
        miss = at.load_winner(at.make_key(shape, "cpu:host:8", "int8"))
        _check(miss is None, "autotune", "dtype_plan", "mutation did not miss")
        muts += 1
        # revert → hit
        _check(
            at.load_winner(at.make_key(shape, "cpu:host:8", "packed32"))
            == winner,
            "autotune", "revert", "revert did not hit",
        )
    return {"mutations": muts}


def scenario_invalidate_derived_contract(ctx: Ctx) -> Dict:
    """Runtime cross-check of the CC002 contract: every attribute
    api.py declares value-derived (`# derived-from:` with a value
    token) is actually overwritten by invalidate_after_patch — the
    static declaration list drives the runtime assertion, so the two
    sides can never drift."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import ast

    import cachelint

    path = os.path.join(REPO, "cyclonus_tpu", "engine", "api.py")
    src = open(path).read()
    tree = ast.parse(src)
    model = cachelint.ModuleModel(path, tree, src.splitlines())
    cls = model.classes["TpuPolicyEngine"]
    decls, invalidate, reset = cachelint.derived_model(model, cls)
    assert invalidate is not None
    value_attrs = sorted(
        attr
        for attr, (tokens, _ln) in decls.items()
        if any(t not in cachelint.DERIVED_EXEMPT_TOKENS for t in tokens)
    )
    _check(
        len(value_attrs) >= 10,
        "invalidate", "census",
        f"expected >=10 declared value-derived attrs, found {value_attrs}",
    )
    eng = ctx.engine()
    sentinels = {}
    for attr in value_attrs:
        # a sentinel the reset must overwrite; _kernel_choice keeps a
        # tuned PACKED tile by design, so plant a non-packed choice
        sentinel = (
            {"kernel": "slab"} if attr == "_kernel_choice" else object()
        )
        setattr(eng, attr, sentinel)
        sentinels[attr] = sentinel
    eng.invalidate_after_patch()
    stale = [
        attr
        for attr, sentinel in sentinels.items()
        if getattr(eng, attr, None) is sentinel
    ]
    _check(
        not stale, "invalidate", ",".join(stale) or "-",
        "declared value-derived attr(s) survived invalidate_after_patch",
    )
    return {"value_attrs": len(value_attrs)}


def scenario_pairs_program_key(ctx: Ctx) -> Dict:
    """The serve pair program dispatches per argument signature: a
    changed pair-batch bucket misses (new entry), the original batch
    reverts to a hit (no growth)."""
    eng = ctx.engine()
    cases = ctx.cases(1)
    with _env(CYCLONUS_AOT_CACHE=os.path.join(ctx.tmp, "aot-pairs")):
        eng._pairs_aot = None  # fresh wrapper under the tmp cache
        eng.evaluate_pairs(cases, [(0, 1)] * 4)
        progs = eng._pairs_aot._programs
        n1 = len(progs)
        eng.evaluate_pairs(cases, [(1, 2)] * 4)
        _check(
            len(progs) == n1, "pairs", "values-not-keys",
            "same-shape batch with different VALUES must hit (values are "
            "arguments, not key components)",
        )
        eng.evaluate_pairs(cases, [(0, 1)] * 12)  # new pair-count bucket
        _check(len(progs) == n1 + 1, "pairs", "k", "mutation did not miss")
        eng.evaluate_pairs(cases, [(0, 1)] * 4)  # revert
        _check(len(progs) == n1 + 1, "pairs", "revert", "revert did not hit")
        q2 = ctx.cases(2)
        eng.evaluate_pairs(q2, [(0, 1)] * 4)  # case-count component
        _check(len(progs) == n1 + 2, "pairs", "q", "mutation did not miss")
    return {"programs": len(progs)}


def scenario_grid_program_key(ctx: Ctx) -> Dict:
    """The grid AOT program: same case set hits, a different case count
    misses, revert hits."""
    import numpy as np

    eng = ctx.engine()
    with _env(CYCLONUS_AOT_CACHE=os.path.join(ctx.tmp, "aot-grid")):
        eng._grid_aot = None
        g1 = np.asarray(eng.evaluate_grid(ctx.cases(1)).combined)
        progs = eng._grid_aot._programs
        n1 = len(progs)
        g2 = np.asarray(eng.evaluate_grid(ctx.cases(1)).combined)
        _check(len(progs) == n1, "grid", "steady", "repeat did not hit")
        _check((g1 == g2).all(), "grid", "determinism", "repeat changed verdicts")
        eng.evaluate_grid(ctx.cases(2))
        _check(len(progs) == n1 + 1, "grid", "q", "mutation did not miss")
        eng.evaluate_grid(ctx.cases(1))
        _check(len(progs) == n1 + 1, "grid", "revert", "revert did not hit")
    return {"programs": len(progs)}


def scenario_sharded_program_key(ctx: Ctx) -> Dict:
    """_SHARDED_PROGRAMS (the compiled ring/allgather shard_map pair):
    schedule, pack, and mesh each miss when mutated; reverting each
    reuses the existing entry (no growth — the zero-recompile elastic
    contract's cache)."""
    import jax
    import numpy as np

    from cyclonus_tpu.engine import sharded

    eng = ctx.engine()
    cases = ctx.cases(1)
    sharded._SHARDED_PROGRAMS.clear()
    base = np.asarray(eng.evaluate_grid_sharded(cases, schedule="ring").combined)
    n1 = len(sharded._SHARDED_PROGRAMS)
    _check(n1 >= 1, "sharded", "baseline", "no program cached")
    eng.evaluate_grid_sharded(cases, schedule="ring")
    _check(
        len(sharded._SHARDED_PROGRAMS) == n1,
        "sharded", "steady", "repeat did not hit",
    )
    got = np.asarray(
        eng.evaluate_grid_sharded(cases, schedule="allgather").combined
    )
    _check(
        len(sharded._SHARDED_PROGRAMS) == n1 + 1,
        "sharded", "schedule", "mutation did not miss",
    )
    _check(
        (got == base).all(), "sharded", "schedule",
        "ring and allgather diverged (parity, not key, is broken)",
    )
    eng.evaluate_grid_sharded(cases, schedule="ring")
    _check(
        len(sharded._SHARDED_PROGRAMS) == n1 + 1,
        "sharded", "schedule-revert", "revert did not hit",
    )
    # pack flip: evaluate_grid_sharded resolves pack_enabled() per call
    pack_now = os.environ.get("CYCLONUS_PACK", "")
    flipped = "0" if pack_now != "0" else "1"
    with _env(CYCLONUS_PACK=flipped):
        got = np.asarray(
            eng.evaluate_grid_sharded(cases, schedule="ring").combined
        )
        _check(
            len(sharded._SHARDED_PROGRAMS) == n1 + 2,
            "sharded", "pack", "mutation did not miss",
        )
        _check((got == base).all(), "sharded", "pack", "pack flip changed verdicts")
    eng.evaluate_grid_sharded(cases, schedule="ring")
    _check(
        len(sharded._SHARDED_PROGRAMS) == n1 + 2,
        "sharded", "pack-revert", "revert did not hit",
    )
    # mesh: a smaller device subset is a different key
    cpus = jax.devices("cpu")
    if len(cpus) >= 4:
        from jax.sharding import Mesh

        small = Mesh(np.array(cpus[:4]), ("x",))
        got = np.asarray(
            eng.evaluate_grid_sharded(cases, mesh=small, schedule="ring").combined
        )
        _check(
            len(sharded._SHARDED_PROGRAMS) == n1 + 3,
            "sharded", "mesh", "mutation did not miss",
        )
        _check((got == base).all(), "sharded", "mesh", "mesh change broke parity")
    return {"programs": len(sharded._SHARDED_PROGRAMS)}


_RESTART_DRIVER = """
import json, os, random, sys
sys.path.insert(0, {repo!r})
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from bench import build_synthetic
from cyclonus_tpu.engine import PortCase, TpuPolicyEngine, aot_cache
from cyclonus_tpu.matcher import build_network_policies

pods, namespaces, policies = build_synthetic(24, 6, random.Random(7))
policy = build_network_policies(True, policies)
engine = TpuPolicyEngine(policy, pods, namespaces)
cases = [PortCase(80, "serve-80-tcp", "TCP")]
grid = np.asarray(engine.evaluate_grid(cases).combined)
pairs = engine.evaluate_pairs(cases, [(0, 1), (2, 3)])
print(json.dumps({{
    "digest": int(grid.sum()),
    "pairs": int(pairs.sum()),
    "aot": aot_cache.counters(),
}}))
"""


def _run_restart_child(cache_dir: str, extra_env: Dict[str, str]) -> Dict:
    env = dict(os.environ)
    env["CYCLONUS_AOT_CACHE"] = cache_dir
    env["CYCLONUS_AUTOTUNE_CACHE"] = "0"
    env["CYCLONUS_JAX_CACHE"] = "0"
    env.update(extra_env)
    proc = subprocess.run(
        [sys.executable, "-c", _RESTART_DRIVER.format(repo=REPO)],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
    )
    if proc.returncode != 0:
        raise HarnessFailure(
            "restart child failed: "
            + proc.stdout[-600:] + proc.stderr[-600:]
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def scenario_aot_restart_subprocess(ctx: Ctx) -> Dict:
    """The restart leg: a fresh process adopts the warm AOT cache with
    ZERO fresh compiles (hit on every component unchanged); a third
    process with ONE key component mutated (the dtype plan, via
    CYCLONUS_PACK) misses every entry — and still produces bit-identical
    verdicts, because the key discriminates programs, not answers."""
    cache = os.path.join(ctx.tmp, "aot-restart")
    pack_now = os.environ.get("CYCLONUS_PACK", "")
    flipped = "0" if pack_now != "0" else "1"
    cold = _run_restart_child(cache, {})
    if cold["aot"]["compiles"] == 0 or cold["aot"]["stores"] == 0:
        raise HarnessFailure(f"cold child did not populate: {cold['aot']}")
    warm = _run_restart_child(cache, {})
    _check(
        warm["aot"]["compiles"] == 0 and warm["aot"]["misses"] == 0,
        "aot-restart", "hit-on-revert",
        f"warm restart recompiled: {warm['aot']}",
    )
    _check(
        warm["digest"] == cold["digest"] and warm["pairs"] == cold["pairs"],
        "aot-restart", "verdicts", "adopted executables changed verdicts",
    )
    mutated = _run_restart_child(cache, {"CYCLONUS_PACK": flipped})
    _check(
        mutated["aot"]["hits"] == 0 and mutated["aot"]["compiles"] > 0,
        "aot-restart", "plan(pack)",
        f"mutated dtype plan still adopted: {mutated['aot']}",
    )
    _check(
        mutated["digest"] == cold["digest"],
        "aot-restart", "pack-parity", "pack flip changed verdicts",
    )
    return {"cold_compiles": cold["aot"]["compiles"]}


def scenario_registry_census(ctx: Ctx) -> Dict:
    """Under CYCLONUS_KEYHARNESS=1 every cache family the acceptance
    list names registers its key components (subprocess: ACTIVE is
    read at import)."""
    code = """
import json, os, random, sys
sys.path.insert(0, {repo!r})
os.environ["CYCLONUS_KEYHARNESS"] = "1"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
    " --xla_force_host_platform_device_count=8").strip()
import jax
jax.config.update("jax_platforms", "cpu")
from bench import build_synthetic
from cyclonus_tpu.engine import PortCase, TpuPolicyEngine, autotune
from cyclonus_tpu.matcher import build_network_policies
from cyclonus_tpu.utils import cachekeys

pods, namespaces, policies = build_synthetic(24, 6, random.Random(7))
policy = build_network_policies(True, policies)
engine = TpuPolicyEngine(policy, pods, namespaces)
cases = [PortCase(80, "serve-80-tcp", "TCP")]
engine.evaluate_grid(cases)
engine.evaluate_pairs(cases, [(0, 1)])
engine.evaluate_grid_sharded(cases, schedule="ring")
autotune.make_key({{"n": 1}}, "cpu", "packed32")
reg = cachekeys.registered()
print(json.dumps({{
    "names": sorted(reg),
    "components": {{k: list(v.components) for k, v in reg.items()}},
    "count": cachekeys.registered_count(),
}}))
"""
    env = dict(os.environ)
    env["CYCLONUS_AOT_CACHE"] = os.path.join(ctx.tmp, "aot-census")
    env["CYCLONUS_AUTOTUNE_CACHE"] = "0"
    proc = subprocess.run(
        [sys.executable, "-c", code.format(repo=REPO)],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
    )
    if proc.returncode != 0:
        raise HarnessFailure(
            "census child failed: " + proc.stdout[-600:] + proc.stderr[-600:]
        )
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    names = out["names"]
    _check(
        any(n.startswith("aot:") for n in names),
        "registry", "aot", f"no AOT families registered: {names}",
    )
    for family in ("autotune", "sharded.programs"):
        _check(family in names, "registry", family, f"not registered: {names}")
    _check(
        "aot:pairs" in names, "registry", "aot:pairs",
        f"serve pair program not registered: {names}",
    )
    _check(out["count"] == len(names), "registry", "count", "census mismatch")
    for name, comps in out["components"].items():
        _check(bool(comps), "registry", name, "registered with no components")
    return {"registered": out["count"]}


#: (name, fn, in_quick_slice)
SCENARIOS: List[Tuple[str, Callable[[Ctx], Dict], bool]] = [
    ("aot_key_fields", scenario_aot_key_fields, True),
    ("autotune_key_fields", scenario_autotune_key_fields, True),
    ("invalidate_derived_contract", scenario_invalidate_derived_contract, True),
    ("pairs_program_key", scenario_pairs_program_key, True),
    ("grid_program_key", scenario_grid_program_key, False),
    ("sharded_program_key", scenario_sharded_program_key, False),
    ("aot_restart_subprocess", scenario_aot_restart_subprocess, False),
    ("registry_census", scenario_registry_census, False),
]


def run(
    tmp: str,
    *,
    quick: bool = True,
    only: Optional[List[str]] = None,
    seed: int = 0,
    log: Optional[Callable[[str], None]] = None,
) -> Dict[str, Dict]:
    """Run the scenario set; raises HarnessFailure on the first
    violation.  Returns per-scenario stats."""
    ctx = Ctx(tmp, seed)
    results: Dict[str, Dict] = {}
    for name, fn, in_quick in SCENARIOS:
        if only is not None:
            if name not in only:
                continue
        elif quick and not in_quick:
            continue
        stats = fn(ctx)
        results[name] = stats
        if log is not None:
            log(f"keyharness {name}: OK {stats}")
    return results


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--full", action="store_true", help="all scenarios")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--scenarios", nargs="*", default=None,
        help=f"subset (choices: {[n for n, _f, _q in SCENARIOS]})",
    )
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    import tempfile

    with tempfile.TemporaryDirectory(prefix="keyharness-") as tmp:
        results = run(
            tmp,
            quick=not args.full,
            only=args.scenarios,
            seed=args.seed,
            log=print if args.verbose else None,
        )
    print(
        f"keyharness: {len(results)} scenario(s) passed "
        f"({', '.join(sorted(results))})",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
