"""Schedule-fuzzing race harness: the dynamic half of the lock-discipline
gate (tools/locklint.py is the static half).

The reference project leans on `go test -race`; CPython has no TSan, so
this harness makes its own schedules: for each seeded SCHEDULE, every
scenario spins up 8-16 threads that rendezvous on a barrier and then
interleave mutations with randomized yields (sleep(0) forces a GIL
switch point, the occasional microsecond sleep moves it), and the main
thread asserts the scenario's invariants afterwards — no lost updates,
ring length bound, monotone counters, cache/choice coherence.

Scenarios (one per shared-mutable-state subsystem):

  spans         SpanRegistry.record from all threads: flat counts must
                sum exactly (a lost update under the registry lock is
                the bug class this exists for)
  metrics       Counter/Histogram mutation + concurrent Prometheus
                render: final values exact, reader sees counters
                monotone
  ring          BoundedRing append vs snapshot/len/appended readers:
                length bound holds, lifetime count exact, per-thread
                order preserved in the window
  events_since  single writer + mark()/since() readers: since(m) must
                never return a PRE-marker event (regression for the
                snapshot/appended atomicity fix in utils/bounded.py
                snapshot_with_count)
  worker_ingest concurrent worker-client batches shipping foreign-pid
                trace events: every event ingested exactly once, ring
                stays bounded
  engine_cache  the PR-1 TOCTOU family: _slab_ops_for fills racing an
                autotune rejection — `choice is False` must imply the
                ops cache is empty, and the fast path must never crash
                on a concurrent clear

Run it with CYCLONUS_GUARD_CHECK=1 so the guards.Guarded descriptors
(utils/guards.py) also assert the declared locks are really held on
every access the schedules reach:

    CYCLONUS_GUARD_CHECK=1 python -m tests.raceharness \
        --schedules 50 --threads 8 --seed 1234

tests/test_locklint.py runs exactly that as a tier-1 gate; `make race`
runs the extended 16-thread sweep (also pytest -m slow).
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time
import traceback
from types import SimpleNamespace
from typing import Callable, List, Optional, Sequence


class Pacing:
    """Per-thread randomized yield points, pre-generated on the main
    thread so a (seed, schedule) pair is reproducible."""

    def __init__(self, jitters: Sequence[float]):
        self.jitters = list(jitters)
        self.i = 0

    def step(self) -> None:
        j = self.jitters[self.i % len(self.jitters)]
        self.i += 1
        if j >= 0:
            time.sleep(j)  # sleep(0) = forced GIL switch point


def _make_pacing(rng: random.Random) -> Pacing:
    # mostly free-running, frequent sleep(0) switch points, occasional
    # real microsleeps to push threads across critical-section edges
    choices = (-1.0, -1.0, 0.0, 0.0, 0.0, 1e-5, 5e-5)
    return Pacing([rng.choice(choices) for _ in range(64)])


def run_threads(
    n: int, rng: random.Random, body: Callable[[int, Pacing], None]
) -> None:
    """Barrier-start n threads on `body(thread_idx, pacing)`; re-raise
    the first failure with its traceback."""
    barrier = threading.Barrier(n)
    failures: List[str] = []
    flock = threading.Lock()

    def runner(idx: int, pacing: Pacing) -> None:
        try:
            barrier.wait(timeout=30)
            body(idx, pacing)
        except BaseException:
            with flock:
                failures.append(f"thread {idx}:\n{traceback.format_exc()}")

    threads = [
        threading.Thread(
            target=runner, args=(i, _make_pacing(rng)), daemon=True
        )
        for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "harness thread wedged (possible deadlock)"
    assert not failures, "\n".join(failures)


# --------------------------------------------------------------------------
# scenarios
# --------------------------------------------------------------------------

OPS = 120  # mutations per thread per scenario


def scenario_spans(rng: random.Random, nthreads: int) -> None:
    from cyclonus_tpu.telemetry.spans import SpanRegistry

    reg = SpanRegistry()

    def body(idx: int, pacing: Pacing) -> None:
        for k in range(OPS):
            name = f"n{k % 4}"
            reg.record(f"root/{name}", name, 0.001, {"t": idx})
            if k % 16 == 0:
                pacing.step()
                reg.stats()  # concurrent reader of the same lock

    run_threads(nthreads, rng, body)
    stats = reg.stats()
    total = sum(int(rec["count"]) for rec in stats.values())
    assert total == nthreads * OPS, f"lost span updates: {total}"
    tree = reg.tree()
    assert sum(int(rec["count"]) for rec in tree.values()) == nthreads * OPS


def scenario_metrics(rng: random.Random, nthreads: int) -> None:
    from cyclonus_tpu.telemetry.metrics import MetricRegistry

    reg = MetricRegistry()
    ctr = reg.counter("race_total", "t", labelnames=("lane",))
    hist = reg.histogram("race_seconds", "t")
    monotone_failures: List[str] = []

    def body(idx: int, pacing: Pacing) -> None:
        if idx == 0:
            # dedicated reader: counters must only ever go up, and the
            # exposition renderer must be safe against live mutation
            last = 0.0
            for _ in range(OPS):
                v = ctr.value(lane="a")
                if v < last:
                    monotone_failures.append(f"{v} < {last}")
                last = v
                reg.render_prometheus()
                pacing.step()
            return
        for k in range(OPS):
            ctr.inc(lane="a")
            hist.observe(0.01)
            if k % 8 == 0:
                pacing.step()

    run_threads(nthreads, rng, body)
    writers = nthreads - 1
    assert not monotone_failures, monotone_failures[:3]
    assert ctr.value(lane="a") == writers * OPS, "lost counter increments"
    (_labels, st) = hist.samples()[0]
    assert st["count"] == writers * OPS, "lost histogram observations"
    assert abs(st["sum"] - 0.01 * writers * OPS) < 1e-6


def scenario_ring(rng: random.Random, nthreads: int) -> None:
    from cyclonus_tpu.utils.bounded import BoundedRing

    cap = 64
    ring = BoundedRing(cap)

    def body(idx: int, pacing: Pacing) -> None:
        if idx == 0:
            seen = 0
            for _ in range(OPS):
                assert len(ring) <= cap, "ring exceeded its bound"
                snap, appended = ring.snapshot_with_count()
                assert len(snap) <= cap
                assert appended >= seen, "lifetime count went backwards"
                seen = appended
                pacing.step()
            return
        for k in range(OPS):
            ring.append((idx, k))
            if k % 8 == 0:
                pacing.step()

    run_threads(nthreads, rng, body)
    writers = nthreads - 1
    assert ring.appended == writers * OPS, "lost appends"
    assert len(ring) == min(cap, writers * OPS)
    # within the surviving window, each writer's items stay in order
    last_per_writer = {}
    for w, k in ring.snapshot():
        assert last_per_writer.get(w, -1) < k, "per-thread order broken"
        last_per_writer[w] = k


def scenario_events_since(rng: random.Random, nthreads: int) -> None:
    from cyclonus_tpu.telemetry import events

    events.reset()
    events.enable()
    violations: List[str] = []

    def body(idx: int, pacing: Pacing) -> None:
        if idx == 0:
            # the single writer: append order == stamp order, so the
            # marker contract is exactly "returned k must exceed m"
            for k in range(1, OPS * 4 + 1):
                events.record("B", "w", "p/w", {"k": k})
                if k % 8 == 0:
                    pacing.step()
            return
        for _ in range(OPS):
            m = events.mark()
            pacing.step()
            for e in events.since(m):
                if e["args"]["k"] <= m:
                    violations.append(
                        f"since({m}) returned pre-marker event k={e['args']['k']}"
                    )
            pacing.step()

    try:
        run_threads(nthreads, rng, body)
    finally:
        events.disable()
    assert not violations, violations[:3]
    assert events.RING.appended == OPS * 4
    events.reset()


def scenario_worker_ingest(rng: random.Random, nthreads: int) -> None:
    from cyclonus_tpu.telemetry import events
    from cyclonus_tpu.worker.client import Client
    from cyclonus_tpu.worker.model import Batch, Request

    events.reset()
    events.disable()  # only ingest() may touch the ring in this scenario
    base_appended = events.RING.appended

    class FakeKube:
        """Echoes one ok Result per request, each carrying one
        foreign-pid trace event (pid varies per call so dedup-by-own-pid
        never triggers)."""

        def execute_remote_command(self, namespace, pod, container, command):
            payload = json.loads(command[2])
            results = []
            for i, r in enumerate(payload["Requests"]):
                results.append(
                    {
                        "Request": r,
                        "Output": "ok",
                        "Error": "",
                        "TraceEvents": [
                            {
                                "ph": "B",
                                "name": "worker.batch",
                                "path": "step/worker.batch",
                                "ts": 1.0 + i,
                                "pid": 10_000_000 + i,
                                "tid": 1,
                            }
                        ],
                    }
                )
            return json.dumps(results), "", None

    client = Client(FakeKube())
    per_batch = 3

    def body(idx: int, pacing: Pacing) -> None:
        for k in range(OPS // 4):
            batch = Batch(
                namespace="ns",
                pod=f"pod{idx}",
                container="c",
                requests=[
                    Request(key=f"{idx}/{k}/{j}", protocol="TCP", host="h", port=80)
                    for j in range(per_batch)
                ],
                trace_id="race-harness",
                parent_span="step",
            )
            results = client.batch(batch)
            assert len(results) == per_batch
            assert all(r.is_success() for r in results)
            if k % 4 == 0:
                pacing.step()

    run_threads(nthreads, rng, body)
    expected = nthreads * (OPS // 4) * per_batch
    delta = events.RING.appended - base_appended
    assert delta == expected, f"ingest lost/duplicated events: {delta} != {expected}"
    assert len(events.RING) <= events.RING.maxlen
    events.reset()


def scenario_engine_cache(rng: random.Random, nthreads: int) -> None:
    import numpy as np

    from cyclonus_tpu.engine import api

    from cyclonus_tpu.utils import guards

    eng = object.__new__(api.TpuPolicyEngine)
    # guards.lock(), as the real __init__ uses: under CYCLONUS_GUARD_CHECK=1
    # this is the ownership-checkable RLock — a plain Lock would blind the
    # Guarded assertions exactly under the contended schedules fuzzed here
    eng._slab_lock = guards.lock()
    eng._slab_choice = None
    eng._slab_ops_cache = None
    eng._slab_plan_state = {
        "egress": np.zeros((2, 2), dtype=np.int32),
        "ingress": np.zeros((2, 2), dtype=np.int32),
        "w": 8,
    }
    eng._pre_cache = ("key", {"x": np.zeros((4,), dtype=np.float32)})
    eng.encoding = SimpleNamespace(cluster=SimpleNamespace(n_pods=4))
    builds = [0]
    build_lock = threading.Lock()

    def fake_ops(pre, n32, egress, ingress, w=None):
        with build_lock:
            builds[0] += 1
        time.sleep(1e-5)  # widen the build window the rejection races
        return {"a": np.zeros((8,), dtype=np.float32)}

    eng._slab_ops_jit = fake_ops

    def body(idx: int, pacing: Pacing) -> None:
        if idx == 0:
            # the autotune-rejection thread (api._autotune_slab's
            # contained-failure path), fired at a random point
            pacing.step()
            with eng._slab_lock:
                eng._slab_choice = False
                eng._slab_ops_cache = None
            return
        for k in range(OPS // 4):
            ops = eng._slab_ops_for("key")
            assert ops is not None
            if k % 4 == 0:
                pacing.step()

    run_threads(nthreads, rng, body)
    with eng._slab_lock:
        choice, cached = eng._slab_choice, eng._slab_ops_cache
    assert choice is False
    assert cached is None, (
        "rejected slab kernel left operands pinned (the PR-1 TOCTOU)"
    )
    assert builds[0] >= 1


SCENARIOS = {
    "spans": scenario_spans,
    "metrics": scenario_metrics,
    "ring": scenario_ring,
    "events_since": scenario_events_since,
    "worker_ingest": scenario_worker_ingest,
    "engine_cache": scenario_engine_cache,
}


def run(
    schedules: int,
    threads: int,
    seed: int,
    scenarios: Optional[List[str]] = None,
    verbose: bool = False,
) -> int:
    names = scenarios or list(SCENARIOS)
    t0 = time.perf_counter()
    for s in range(schedules):
        rng = random.Random(seed + s)
        # at least 8 ways; the extended sweep raises the ceiling to 16
        nthreads = rng.randint(min(8, threads), threads)
        for name in names:
            SCENARIOS[name](rng, nthreads)
        if verbose:
            print(
                f"schedule {s + 1}/{schedules} ok "
                f"({nthreads} threads, {time.perf_counter() - t0:.1f}s)",
                file=sys.stderr,
            )
    print(
        f"raceharness: {schedules} schedule(s) x {len(names)} scenario(s) "
        f"passed in {time.perf_counter() - t0:.1f}s "
        f"(seed={seed}, threads<={threads})"
    )
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--schedules", type=int, default=50)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument(
        "--scenario",
        action="append",
        choices=sorted(SCENARIOS),
        help="run only these scenarios (default: all)",
    )
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    return run(
        args.schedules, args.threads, args.seed, args.scenario, args.verbose
    )


if __name__ == "__main__":
    sys.exit(main())
