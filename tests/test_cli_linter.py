"""L6 + side-component tests: CLI commands end-to-end (mock paths), linter
checks, example policies."""

import json
import subprocess
import sys

import pytest

from cyclonus_tpu.kube.examples import all_examples
from cyclonus_tpu.kube.netpol import (
    IntOrString,
    LabelSelector,
    NetworkPolicy,
    NetworkPolicyIngressRule,
    NetworkPolicyPort,
    NetworkPolicySpec,
)
from cyclonus_tpu.linter import lint
from cyclonus_tpu.linter.checks import (
    CHECK_DNS_BLOCKED_ON_TCP,
    CHECK_DNS_BLOCKED_ON_UDP,
    CHECK_SOURCE_DUPLICATE_POLICY_NAME,
    CHECK_SOURCE_MISSING_NAMESPACE,
    CHECK_SOURCE_MISSING_POLICY_TYPES,
    CHECK_SOURCE_MISSING_POLICY_TYPE_INGRESS,
    CHECK_SOURCE_PORT_MISSING_PROTOCOL,
    CHECK_TARGET_ALL_EGRESS_BLOCKED,
    CHECK_TARGET_ALL_INGRESS_BLOCKED,
)


class TestExamples:
    def test_all_examples_count_and_buildable(self):
        from cyclonus_tpu.matcher import build_network_policies

        examples = all_examples()
        assert len(examples) == 21  # policies.go:699-728
        policy = build_network_policies(True, examples)
        assert len(policy.ingress) > 0 and len(policy.egress) > 0

    def test_accidental_and_vs_or(self):
        from cyclonus_tpu.matcher import (
            InternalPeer,
            Traffic,
            TrafficPeer,
            build_network_policies,
        )
        from cyclonus_tpu.kube.examples import accidental_and, accidental_or

        def q(policy, pod_labels, ns_labels):
            t = Traffic(
                source=TrafficPeer(
                    internal=InternalPeer(pod_labels, ns_labels, "other"),
                    ip="10.0.0.1",
                ),
                destination=TrafficPeer(
                    internal=InternalPeer({"a": "b"}, {}, "default"), ip="10.0.0.2"
                ),
                resolved_port=80,
                protocol="TCP",
            )
            return policy.is_traffic_allowed(t).ingress.is_allowed

        and_pol = build_network_policies(
            True, [accidental_and("default", {"a": "b"}, {"user": "alice"}, {"role": "client"})]
        )
        or_pol = build_network_policies(
            True, [accidental_or("default", {"a": "b"}, {"user": "alice"}, {"role": "client"})]
        )
        # AND: both must match
        assert q(and_pol, {"role": "client"}, {"user": "alice"})
        assert not q(and_pol, {"role": "client"}, {})
        assert not q(and_pol, {}, {"user": "alice"})
        # OR: either suffices (pod peer is in policy ns 'default', so use
        # matching ns labels for the ns-peer side)
        assert q(or_pol, {}, {"user": "alice"})
        assert not q(or_pol, {"role": "client"}, {})  # wrong ns for pod peer


class TestLinter:
    def test_source_checks(self):
        policies = [
            NetworkPolicy(
                name="dup",
                namespace="",
                spec=NetworkPolicySpec(
                    pod_selector=LabelSelector.make(),
                    policy_types=[],
                    ingress=[
                        NetworkPolicyIngressRule(
                            ports=[NetworkPolicyPort(port=IntOrString(80))]
                        )
                    ],
                ),
            ),
            NetworkPolicy(
                name="dup",
                namespace="",
                spec=NetworkPolicySpec(
                    pod_selector=LabelSelector.make(), policy_types=["Ingress"]
                ),
            ),
        ]
        checks = {w.check for w in lint(policies)}
        assert CHECK_SOURCE_MISSING_NAMESPACE in checks
        assert CHECK_SOURCE_MISSING_POLICY_TYPES in checks
        assert CHECK_SOURCE_MISSING_POLICY_TYPE_INGRESS in checks
        assert CHECK_SOURCE_DUPLICATE_POLICY_NAME in checks
        assert CHECK_SOURCE_PORT_MISSING_PROTOCOL in checks

    def test_resolved_checks(self):
        deny_all = NetworkPolicy(
            name="deny",
            namespace="x",
            spec=NetworkPolicySpec(
                pod_selector=LabelSelector.make(),
                policy_types=["Ingress", "Egress"],
            ),
        )
        checks = {w.check for w in lint([deny_all])}
        assert CHECK_TARGET_ALL_INGRESS_BLOCKED in checks
        assert CHECK_TARGET_ALL_EGRESS_BLOCKED in checks
        assert CHECK_DNS_BLOCKED_ON_TCP in checks
        assert CHECK_DNS_BLOCKED_ON_UDP in checks

    def test_skip_filter(self):
        deny_all = NetworkPolicy(
            name="deny",
            namespace="x",
            spec=NetworkPolicySpec(
                pod_selector=LabelSelector.make(), policy_types=["Ingress"]
            ),
        )
        warnings = lint([deny_all], skip={CHECK_TARGET_ALL_INGRESS_BLOCKED})
        assert CHECK_TARGET_ALL_INGRESS_BLOCKED not in {w.check for w in warnings}


def run_cli(*args, timeout=120):
    # 120s ceiling: no CLI subprocess here touches an accelerator backend
    # (version prints static info; analyze/generate/probe --mock run the
    # oracle engine), so anything past 2 minutes is a hang, and the suite
    # must fail fast with a diagnosis instead of serializing dead air.
    return subprocess.run(
        [sys.executable, "-m", "cyclonus_tpu"] + list(args),
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd="/root/repo",
    )


class TestCLI:
    def test_version(self):
        proc = run_cli("version")
        assert proc.returncode == 0
        assert "cyclonus-tpu version" in proc.stdout

    def test_analyze_explain_examples(self):
        proc = run_cli("analyze", "--use-example-policies", "--mode", "explain")
        assert proc.returncode == 0, proc.stderr
        assert "all-namespaces" in proc.stdout or "all pods" in proc.stdout

    def test_analyze_parse_and_lint(self, tmp_path):
        from cyclonus_tpu.kube.yaml_io import policies_to_yaml

        path = tmp_path / "pols.yaml"
        path.write_text(policies_to_yaml(all_examples()[:3]))
        proc = run_cli(
            "analyze", "--policy-path", str(path), "--mode", "parse", "--mode", "lint"
        )
        assert proc.returncode == 0, proc.stderr
        assert "allow-nothing-to-app-web" in proc.stdout

    def test_analyze_query_target(self):
        """query-target mode (reference analyze.go:170-207): per-pod
        matching targets + combined rules against the bundled example
        pod file."""
        proc = run_cli(
            "analyze",
            "--use-example-policies",
            "--mode",
            "query-target",
            "--target-pod-path",
            "examples/targets.json",
        )
        assert proc.returncode == 0, proc.stderr
        # one block per pod in examples/targets.json
        assert proc.stdout.count("Matching targets:") == 4
        assert proc.stdout.count("Combined rules:") == 4
        # the pod in ns z carries labels; the header must echo their content
        assert "'tier': 'web'" in proc.stdout

    def test_analyze_query_traffic(self, tmp_path):
        traffic = [
            {
                "Source": {"IP": "8.8.8.8"},
                "Destination": {
                    "Internal": {
                        "PodLabels": {"app": "web"},
                        "NamespaceLabels": {"ns": "default"},
                        "Namespace": "default",
                    },
                    "IP": "192.168.1.10",
                },
                "Protocol": "TCP",
                "ResolvedPort": 80,
                "ResolvedPortName": "serve-80-tcp",
            }
        ]
        path = tmp_path / "traffic.json"
        path.write_text(json.dumps(traffic))
        proc = run_cli(
            "analyze",
            "--use-example-policies",
            "--mode",
            "query-traffic",
            "--traffic-path",
            str(path),
        )
        assert proc.returncode == 0, proc.stderr
        assert "Is traffic allowed?" in proc.stdout

    def test_analyze_probe_reference_model(self):
        proc = run_cli(
            "analyze",
            "--policy-path",
            "examples/networkpolicies/getting-started",
            "--mode",
            "probe",
            "--probe-path",
            "examples/probe.json",
        )
        assert proc.returncode == 0, proc.stderr
        assert "Combined:" in proc.stdout

    def test_generate_dry_run(self):
        proc = run_cli("generate", "--mock", "--dry-run")
        assert proc.returncode == 0, proc.stderr
        assert "total: 112 test cases" in proc.stdout

    def test_generate_mock_perfect_cni_subset(self):
        proc = run_cli(
            "generate",
            "--mock",
            "--perfect-cni",
            "--include",
            "deny-all",
            "--retries",
            "0",
            "--max-cases",
            "3",
            timeout=600,
        )
        assert proc.returncode == 0, proc.stderr
        assert "passed" in proc.stdout
        assert "| Tag | Result |" in proc.stdout

    def test_probe_multi_port_protocol(self):
        """Reference-parity probe flags (probe.go:123-130): repeatable
        --port/--protocol run one probe per combination."""
        proc = run_cli(
            "probe",
            "--mock",
            "--perfect-cni",
            "--port",
            "80",
            "--port",
            "81",
            "--protocol",
            "tcp",
            "--protocol",
            "udp",
            timeout=600,
        )
        assert proc.returncode == 0, proc.stderr
        for combo in ("80/TCP", "80/UDP", "81/TCP", "81/UDP"):
            assert f"one-off probe {combo}" in proc.stdout

    def test_probe_mock(self):
        proc = run_cli(
            "probe",
            "--mock",
            "--perfect-cni",
            "--probe-port",
            "80",
            "--probe-protocol",
            "tcp",
            "--policy-path",
            "examples/networkpolicies/getting-started",
            timeout=600,
        )
        assert proc.returncode == 0, proc.stderr
        assert "0 wrong" in proc.stdout
