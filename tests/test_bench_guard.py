"""Regression tests for bench.py's bounded-time failure paths (VERDICT
r3: a wedged TPU tunnel turned the driver's bench into rc=124 with no
output; every failure mode must now print ONE parseable JSON line with
an "error" field and per-phase wall-clock history)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_bench(env_extra, timeout=120):
    env = dict(os.environ)
    # hermetic persistent caches: the stall/watchdog premises assume the
    # subprocess actually PAYS its compiles — a developer/CI home dir
    # whose JAX disk cache (or AOT executable cache) is already warm at
    # these shapes would silently collapse warmup below the stall bound
    # and flip the expected rc (observed: the cache warmed by one run
    # broke the next).  Tests that exercise the caches point them at a
    # tmp path explicitly.
    env.setdefault("CYCLONUS_JAX_CACHE", "0")
    env.setdefault("CYCLONUS_AOT_CACHE", "0")
    env.setdefault("CYCLONUS_AUTOTUNE_CACHE", "0")
    # hermetic cache-key registry too: a developer shell that exported
    # CYCLONUS_KEYHARNESS=1 (the key-mutation harness env) would arm the
    # registry in the subprocess and flip the key_audit/strip-proof
    # asserts — hard-pin, not setdefault, because an exported "1"
    # survives setdefault
    env["CYCLONUS_KEYHARNESS"] = "0"
    # pin CPU inside the subprocess: the env var alone is overridden by
    # the axon sitecustomize on TPU machines (tests/conftest.py docstring)
    env.update(env_extra)
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import bench; bench.main()"
    )
    return subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
        env=env,
    )


def last_json_line(stdout):
    sys.path.insert(0, REPO)
    from bench import last_json_line as parse

    out = parse(stdout)
    assert out is not None, f"no JSON line in output: {stdout[-500:]}"
    return out


class TestBenchGuards:
    def test_watchdog_emits_error_json(self):
        proc = run_bench(
            {
                "BENCH_DEADLINE_S": "2",
                "BENCH_PODS": "30000",
                "BENCH_POLICIES": "3000",
            }
        )
        assert proc.returncode == 2
        out = last_json_line(proc.stdout)
        assert "watchdog" in out["error"]
        assert out["value"] == 0
        assert out["vs_baseline"] == 0.0
        # the perfobs ledger gates on this: a watchdog kill inside the
        # measured pipeline is an ENGINE-side failure class
        assert out["failure_class"] == "watchdog_stall"
        phases = [p[0] for p in out["detail"]["phase_history_s"]]
        assert "startup" in phases  # history present and labeled
        # detail.pack rides FAILURE lines too (env-resolved plan; no
        # engine means no winner/autotune forensics yet)
        pack = out["detail"]["pack"]
        assert pack["active"] is True  # CYCLONUS_PACK default
        assert pack["dtype"] == "packed32"
        assert pack["winner"] is None

    def test_stall_bound_fires_inside_one_phase(self):
        """The per-phase stall trigger: total deadline generous, but a
        phase that stops advancing (here: a CPU warmup that takes >2s)
        trips BENCH_STALL_S with the phase named in the error."""
        proc = run_bench(
            {
                "BENCH_STALL_S": "2",
                "BENCH_DEADLINE_S": "600",
                "BENCH_PODS": "20000",
                "BENCH_POLICIES": "2000",
                "BENCH_MESH": "0",
                "BENCH_PARITY": "0",
            }
        )
        assert proc.returncode == 2
        out = last_json_line(proc.stdout)
        assert "stalled" in out["error"]

    def test_crash_emits_error_json_then_raises(self):
        # an invalid counts backend crashes inside _bench: the JSON error
        # line must still be printed before the traceback propagates
        proc = run_bench(
            {
                "BENCH_COUNTS_BACKEND": "not-a-backend",
                "BENCH_PODS": "64",
                "BENCH_POLICIES": "8",
                "BENCH_DEADLINE_S": "0",
                "BENCH_MESH": "0",
                "BENCH_PARITY": "0",
            }
        )
        assert proc.returncode != 0
        out = last_json_line(proc.stdout)
        assert "error" in out
        assert "not-a-backend" in out["error"]

    def test_init_timeout_attaches_cpu_fallback_leg(self):
        """A dead tunnel (simulated via BENCH_FAKE_INIT_HANG) must still
        produce an artifact with SIGNAL: value 0 for the TPU metric, but
        a small identical-pipeline CPU leg under detail.cpu_fallback."""
        proc = run_bench(
            {
                "BENCH_FAKE_INIT_HANG": "1",
                "BENCH_INIT_DEADLINE_S": "2",
                "BENCH_PODS": "64",
                "BENCH_POLICIES": "8",
                "BENCH_FALLBACK_PODS": "128",
                "BENCH_FALLBACK_POLICIES": "16",
                "BENCH_MESH": "0",
                "BENCH_PARITY": "0",
                "BENCH_DEADLINE_S": "0",
                "BENCH_STALL_S": "0",
            },
            timeout=400,
        )
        assert proc.returncode == 3
        out = last_json_line(proc.stdout)
        assert "backend init did not complete" in out["error"]
        assert out["value"] == 0
        # classified INFRA (the tunnel never answered), with the
        # cold-start forensics riding the artifact — what lets the
        # perfobs sentinel keep r03/r04-style runs out of the
        # engine-regression lane
        assert out["failure_class"] == "tunnel"
        cold = out["detail"]["cold_start"]
        assert cold["outcome"] == "tunnel"
        assert cold["attempts"] >= 1
        # detail.pack present on the init-failure line (shape only)
        assert "pack" in out["detail"]
        assert "active" in out["detail"]["pack"]
        leg = out["detail"]["cpu_fallback"]
        assert leg["backend"] == "cpu"
        assert leg["value"] > 0
        assert leg["unit"] == "cells/sec"
        assert "128 pods" in leg["metric"]

    def test_init_error_midretry_classifies_backend_init(self):
        """An init attempt that FAILED (backend answered) followed by a
        join deadline mid-backoff must classify backend_init with the
        captured error — not 'tunnel dead', which would discard the
        evidence (the r03-vs-r04 distinction)."""
        proc = run_bench(
            {
                "BENCH_FAKE_INIT_ERROR": "1",
                "BENCH_INIT_RETRIES": "3",
                "BENCH_INIT_BACKOFF_S": "30",  # deadline fires mid-backoff
                "BENCH_INIT_DEADLINE_S": "2",
                "BENCH_PODS": "64",
                "BENCH_POLICIES": "8",
                "BENCH_MESH": "0",
                "BENCH_PARITY": "0",
                "BENCH_CPU_FALLBACK": "0",
                "BENCH_DEADLINE_S": "0",
                "BENCH_STALL_S": "0",
            },
            timeout=120,
        )
        assert proc.returncode == 4
        out = last_json_line(proc.stdout)
        assert out["failure_class"] == "backend_init"
        assert "fake backend init error" in out["error"]
        cold = out["detail"]["cold_start"]
        assert cold["outcome"] == "backend_init"
        assert cold["attempts"] >= 1
        assert cold["backoff_s"] > 0

    def test_trace_dir_records_written_artifact(self, tmp_path):
        """BENCH_TRACE_DIR (= bench.py --trace-dir) wraps the eval phase
        in jax.profiler.trace; the JSON line's detail.trace block must
        point at the dir and confirm the profiler left an artifact."""
        cap_dir = str(tmp_path / "cap")
        proc = run_bench(
            {
                "BENCH_TRACE_DIR": cap_dir,
                "BENCH_PODS": "64",
                "BENCH_POLICIES": "8",
                "BENCH_SAMPLE": "3",
                "BENCH_MESH": "0",
                "BENCH_PARITY": "0",
                "BENCH_COUNTS_BACKEND": "xla",
            },
            timeout=300,
        )
        assert proc.returncode == 0, proc.stdout[-800:] + proc.stderr[-500:]
        out = last_json_line(proc.stdout)
        assert out["detail"]["trace"] == {"dir": cap_dir, "written": True}
        assert any(files for _, _, files in os.walk(cap_dir))

    def test_success_line_parses_with_detail_blocks(self):
        proc = run_bench(
            {
                "BENCH_PODS": "256",
                "BENCH_POLICIES": "20",
                "BENCH_SAMPLE": "3",
                "BENCH_MESH": "0",
                "BENCH_PARITY": "0",
                "BENCH_COUNTS_BACKEND": "xla",
            },
            timeout=300,
        )
        assert proc.returncode == 0, proc.stdout[-800:] + proc.stderr[-500:]
        out = last_json_line(proc.stdout)
        assert "error" not in out
        assert out["unit"] == "cells/sec"
        assert out["value"] > 0
        # healthy runs SAY so — the perfobs ledger never infers "ok"
        # from an absent error field
        assert out["failure_class"] == "ok"
        detail = out["detail"]
        # the per-phase wall-clock history now rides success lines too
        # (perfobs per-phase bounds need it from healthy runs)
        phases = [p[0] for p in detail["phase_history_s"]]
        assert phases[0] == "startup"
        assert "warmup" in phases and "eval" in phases
        # cold-start forensics: the overlapped init thread attached on
        # a counted attempt
        cold = detail["cold_start"]
        assert cold["outcome"] == "ok"
        assert cold["attempts"] >= 1
        assert cold["backend_init_s"] is not None
        # structured last-error: None on a clean first-attempt attach
        assert cold["last_error"] is None
        # AOT executable-cache forensics ride every cold_start block
        # (here: cache pinned off by the hermetic run_bench env)
        aot = cold["aot_cache"]
        for k in ("hits", "misses", "adopted", "compiles"):
            assert aot[k] == 0
        assert aot["dir"] is None
        # the cache-key registry census (utils/cachekeys.py): inert
        # outside the key-mutation harness env, so the audit records
        # inactive with zero registrations
        assert cold["key_audit"] == {"active": False, "registered": 0}
        # detail.chaos rides EVERY line like detail.mesh: on this CPU
        # run the auto mode skips the leg but the schema still appears
        chaos_detail = detail["chaos"]
        assert chaos_detail["ttfv_s"] is None
        assert "make chaos" in chaos_detail["skipped"]
        # detail.wire rides EVERY line: the wire-protocol generation
        # plus the live registry skew sweep (worker/wireregistry.py) —
        # both skew directions for every registered message through the
        # real codecs, asserted clean inside the bench
        wire = detail["wire"]
        assert wire["schema_version"] >= 5
        assert wire["keys"] >= 30
        assert wire["skew_pairs_checked"] >= 10
        assert "eval_reps" in detail and len(detail["eval_reps"]) == 5
        # roofline only reports for the pallas backend
        assert detail["roofline"] is None
        # detail.pack rides EVERY success line: the dtype plan, the
        # packed word depths, and the autotune forensics slot (None on
        # CPU, where the auto search never engages)
        pack = detail["pack"]
        assert pack["active"] is True
        assert pack["dtype"] == "packed32"
        assert isinstance(pack["words"], list) and len(pack["words"]) == 2
        assert all(w >= 1 for w in pack["words"])
        assert "winner" in pack and "autotune" in pack
        # class compression rides EVERY line (perfobs reads its ratio);
        # at 256 pods the auto mode stays on the legacy paths
        cc = detail["class_compression"]
        assert cc["active"] is False and cc["pods"] == 256
        assert cc["ratio"] is None
        # BENCH_MEGA defaults to auto = TPU-only; on this CPU run the
        # block records as absent-by-default
        assert detail["mega_class"] is None
        # detail.mesh rides EVERY line (perfobs' scaling gate parses its
        # rows); with BENCH_MESH=0 the leg is skipped but the block —
        # and its schema — still appears, rows empty
        mesh = detail["mesh"]
        assert mesh["rows"] == [] and mesh["skipped"] == "BENCH_MESH=0"
        assert mesh["schedule"] == "ring"
        # the precedence-tier leg rides EVERY line (perfobs reads
        # detail.tiers warn-only): a deterministic ANP/BANP lattice
        # with oracle spot parity enforced inside the leg
        tiers = detail["tiers"]
        assert tiers["active"] is True
        assert tiers["anp_count"] == 3 and tiers["banp"] is True
        assert tiers["resolve_s"] > 0
        assert tiers["parity_spot_checks"] >= 1
        # the TSS/LPM CIDR pre-classification leg rides EVERY line
        # (perfobs reads detail.cidr warn-only): a forced-TSS engine on
        # an ipBlock-heavy synthetic cluster with oracle spot parity and
        # the dense-counts cross-check enforced inside the leg
        cidr = detail["cidr"]
        assert cidr["active"] is True
        assert cidr["distinct_cidrs"] >= 1
        assert cidr["partitions"] >= 1
        assert cidr["classes"] >= 1
        assert cidr["ratio"] >= 1
        assert cidr["lpm_s"] is not None
        assert cidr["parity_spot_checks"] >= 1
        assert "speedup_vs_dense" in cidr
        # the telemetry block rides every BENCH line (and thus every
        # tunnel_wait round file): metrics incl. cache hit/miss counters
        # + HBM watermarks, span aggregates, and the flight window
        tel = detail["telemetry"]
        assert "cyclonus_tpu_pre_cache_hits_total" in tel["metrics"]
        assert "cyclonus_tpu_slab_hbm_bytes" in tel["metrics"]
        # the lock-discipline annotations (guarded _slab_choice /
        # _slab_ops_cache, locked reads in the dispatch path) must not
        # cost the telemetry block its cache-counter schema — the
        # counters live on exactly the code paths that were annotated
        assert "cyclonus_tpu_slab_ops_cache_hits_total" in tel["metrics"]
        assert "cyclonus_tpu_slab_ops_cache_misses_total" in tel["metrics"]
        # the tensor-contract counter only exists under
        # CYCLONUS_SHAPE_CHECK=1 (utils/contracts.py registers it on
        # first check) — its ABSENCE here proves the production strip
        # is real, not just cheap
        assert "cyclonus_tpu_contract_checks_total" not in tel["metrics"]
        # same strip proof for the cache-key registry instruments
        # (utils/cachekeys.py): they register only under
        # CYCLONUS_KEYHARNESS=1, so a production BENCH line never
        # carries them
        assert not any(
            name.startswith("cyclonus_tpu_cachekey")
            for name in tel["metrics"]
        )
        assert "engine.dispatch" in tel["phases"]
        assert any(
            e["path"].startswith("counts.") for e in tel["flight_recorder"]
        )
        # warmup_phases now sources from the same span registry (encode
        # happens before the warmup-start reset, so dispatch is the
        # marker phase)
        assert "engine.dispatch" in detail["warmup_phases"]
        # every BENCH line must record its device-profile provenance:
        # whether a --trace-dir/BENCH_TRACE_DIR jax-profiler artifact
        # was written this run (here: no capture requested)
        assert detail["trace"] == {"dir": None, "written": False}

    def test_chaos_injection_and_forced_chaos_leg(self, tmp_path):
        """End to end through bench: an injected backend-init fault
        (CYCLONUS_CHAOS) retries with the structured last_error
        retained, and the FORCED chaos leg kills/restarts a real serve
        subprocess with a bounded time-to-first-verdict recorded in
        detail.chaos."""
        proc = run_bench(
            {
                "BENCH_PODS": "64",
                "BENCH_POLICIES": "8",
                "BENCH_SAMPLE": "2",
                "BENCH_MESH": "0",
                "BENCH_PARITY": "0",
                "BENCH_SERVE": "0",
                "BENCH_TIERS": "0",
                "BENCH_COUNTS_BACKEND": "xla",
                "BENCH_CHAOS": "1",
                "BENCH_CHAOS_PODS": "12",
                "BENCH_CHAOS_DELTAS": "2",
                "CYCLONUS_CHAOS": "backend_init:1",
                # the serve children must not inherit the armed spec
                # beyond the one budgeted fault (backend_init is not a
                # serve point, so inheritance is harmless — pinned here
                # for clarity) and they may use a warm tmp AOT cache
                "CYCLONUS_AOT_CACHE": str(tmp_path / "aot"),
            },
            timeout=420,
        )
        assert proc.returncode == 0, proc.stdout[-800:] + proc.stderr[-500:]
        out = last_json_line(proc.stdout)
        assert out["failure_class"] == "ok"
        cold = out["detail"]["cold_start"]
        # one injected failure, recovered on the counted retry, the
        # structured forensics naming the injected class
        assert cold["attempts"] == 2
        assert cold["last_error"]["type"] == "ChaosError"
        assert "backend_init" in cold["last_error"]["message"]
        chaos_detail = out["detail"]["chaos"]
        assert chaos_detail["ok"] is True
        assert 0 < chaos_detail["ttfv_s"] <= chaos_detail["ttfv_bound_s"]
        assert chaos_detail["oracle_checked"] >= 16

    def test_mega_class_case_records_compression(self):
        """BENCH_MEGA=1 (shrunk for CI) runs the synthetic-cluster
        compression case: detail.mega_class.class_compression carries
        pods/classes/ratio/gather_s, the HBM-budget check, the oracle
        spot parity, and the class-reduction audit — the same block the
        1M-pod TPU run records."""
        proc = run_bench(
            {
                "BENCH_PODS": "128",
                "BENCH_POLICIES": "12",
                "BENCH_SAMPLE": "3",
                "BENCH_MESH": "0",
                "BENCH_PARITY": "0",
                "BENCH_COUNTS_BACKEND": "xla",
                "BENCH_MEGA": "1",
                "BENCH_MEGA_PODS": "4096",
                "BENCH_MEGA_POLICIES": "32",
                "BENCH_MEGA_NS": "8",
                "BENCH_MEGA_SAMPLE": "4",
            },
            timeout=300,
        )
        assert proc.returncode == 0, proc.stdout[-800:] + proc.stderr[-500:]
        out = last_json_line(proc.stdout)
        mega = out["detail"]["mega_class"]
        assert mega is not None and "status" not in mega, mega
        cc = mega["class_compression"]
        assert cc["active"] is True
        assert cc["pods"] == 4096
        assert 0 < cc["classes"] < 4096
        assert cc["ratio"] > 1.0
        assert cc["gather_s"] is not None
        assert mega["hbm_budget_ok"] is True
        assert mega["audit"]["ok"] is True
        assert mega["parity_spot_checks"] == 4
        assert mega["cells"] == 2 * 4096 * 4096
