"""Dispatch-route harness: the dynamic proof behind tools/planlint.py
(docs/DESIGN.md "Plan surface"), mirroring tests/keyharness.py's role
for the cache lint.

The static pass proves every dispatch leaf records a DECLARED path and
every reachable feature interaction has a matrix cell; this harness
proves the declarations PREDICT: it arms the route recorder
(CYCLONUS_PLANHARNESS=1, read once at import — the strip contract),
sweeps the governing flag/argument matrix through the real public
entry points, and asserts the drained routes equal what
``planspec.predict`` derives from the PathSpec registry alone.  Where
the compatibility matrix says "raise", the harness asserts the live
dispatch raises the cell's EXACT declared message.  A route the
declarations cannot predict is a silent dispatch change — the planlint
failure mode planlint itself cannot see.

The quick slice (tier-1, via tests/test_planlint.py) must exercise
every PathSpec whose coverage is "tier1" — that census is asserted
here, not in the test, so `python -m tests.planharness` fails the same
way.  `--full` adds the slow ring-pipeline leg (`make planharness`).
"""

from __future__ import annotations

import argparse
import contextlib
import os
import random
import sys
from typing import Callable, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the recorder is armed at planspec IMPORT (strip contract) — set the
# flag before any cyclonus_tpu import, plus the standalone-run env the
# pytest path gets from tests/conftest.py
os.environ["CYCLONUS_PLANHARNESS"] = "1"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("CYCLONUS_AUTOTUNE_CACHE", "0")
os.environ.setdefault("CYCLONUS_AOT_CACHE", "0")


class HarnessFailure(AssertionError):
    """A recorded route diverged from the registry's prediction; the
    message names the scenario and both routes."""


def _check(cond: bool, scenario: str, detail: str) -> None:
    if not cond:
        raise HarnessFailure(f"{scenario}: {detail}")


def _expect(scenario: str, actual: List[str], expected: List[str]) -> None:
    _check(
        actual == expected, scenario,
        f"recorded routes {actual} != predicted {expected}",
    )


@contextlib.contextmanager
def _env(**kv: Optional[str]):
    """Set/unset env vars, restoring exactly on exit."""
    old = {k: os.environ.get(k) for k in kv}
    try:
        for k, v in kv.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


class Ctx:
    """Shared scenario context: one lazily built engine per flag
    configuration (24 pods — every program family, inside the tier-1
    budget), plus the recorded-route union for the coverage census."""

    def __init__(self, seed: int):
        self.rng = random.Random(seed)
        self._engines: Dict[Tuple, object] = {}
        self.covered: set = set()

    def _fixture(self):
        from bench import build_synthetic
        from cyclonus_tpu.matcher import build_network_policies

        pods, namespaces, policies = build_synthetic(24, 6, random.Random(7))
        return build_network_policies(True, policies), pods, namespaces

    def engine(self, *, class_compress=None, tiers=False, env=()):
        key = (class_compress, tiers, tuple(env))
        if key not in self._engines:
            from cyclonus_tpu.engine import TpuPolicyEngine

            policy, pods, namespaces = self._fixture()
            kwargs = {}
            if class_compress is not None:
                kwargs["class_compress"] = class_compress
            if tiers:
                kwargs["tiers"] = self._tierset()
            with _env(**dict(env)):
                self._engines[key] = TpuPolicyEngine(
                    policy, pods, namespaces, **kwargs
                )
        return self._engines[key]

    def _tierset(self):
        from cyclonus_tpu.tiers.model import (
            AdminNetworkPolicy,
            TierRule,
            TierScope,
            TierSet,
        )

        return TierSet(anps=[
            AdminNetworkPolicy(
                name="harness-tier", priority=1, subject=TierScope(),
                ingress=[TierRule(action="Allow", peers=[TierScope()])],
            )
        ])

    def cases(self, q: int = 1):
        from cyclonus_tpu.engine import PortCase

        base = [
            PortCase(80, "serve-80-tcp", "TCP"),
            PortCase(81, "serve-81-udp", "UDP"),
        ]
        return base[:q]

    def drain(self) -> List[str]:
        from cyclonus_tpu.engine import planspec

        routes = planspec.drain()
        self.covered.update(routes)
        return routes


# --- scenarios -------------------------------------------------------------


def scenario_grid_routes(ctx: Ctx) -> Dict:
    """evaluate_grid routes on the dense engine and the class-compressed
    engine exactly as the `classes` feature predicts."""
    from cyclonus_tpu.engine import planspec

    eng = ctx.engine()
    ctx.drain()
    eng.evaluate_grid(ctx.cases(1))
    _expect("grid.dense", ctx.drain(), [planspec.predict("grid", {})])

    ceng = ctx.engine(class_compress="1")
    _check(
        ceng.class_compression_stats()["active"],
        "grid.classes", "forced class compression did not activate",
    )
    ctx.drain()
    ceng.evaluate_grid(ctx.cases(1))
    _expect(
        "grid.classes", ctx.drain(),
        [planspec.predict("grid", {"classes": True})],
    )
    return {"routes": 2}


def scenario_sharded_grid_routes(ctx: Ctx) -> Dict:
    """evaluate_grid_sharded: explicit ring / allgather, the default
    (auto) schedule, and the class-compressed route."""
    from cyclonus_tpu.engine import planspec

    eng = ctx.engine()
    cases = ctx.cases(1)
    ctx.drain()
    for schedule in ("ring", "allgather", None):
        kw = {} if schedule is None else {"schedule": schedule}
        eng.evaluate_grid_sharded(cases, **kw)
        feats = {} if schedule is None else {"schedule": schedule}
        _expect(
            f"grid.sharded[{schedule}]", ctx.drain(),
            [planspec.predict("grid_sharded", feats)],
        )
    ceng = ctx.engine(class_compress="1")
    ctx.drain()
    ceng.evaluate_grid_sharded(cases)
    _expect(
        "grid.sharded.classes", ctx.drain(),
        [planspec.predict("grid_sharded", {"classes": True})],
    )
    return {"routes": 4}


def scenario_counts_routes(ctx: Ctx) -> Dict:
    """evaluate_grid_counts backend routing: explicit xla, auto on a
    CPU host, the compressed route — and the tiers x pallas matrix
    cell: auto-fallback silently, explicit request raises the cell's
    exact declared message (live AND predicted)."""
    from cyclonus_tpu.engine import planspec

    eng = ctx.engine()
    cases = ctx.cases(1)
    ctx.drain()
    eng.evaluate_grid_counts(cases, backend="xla")
    _expect(
        "counts.xla", ctx.drain(),
        [planspec.predict("counts", {"backend": "xla"})],
    )
    eng.evaluate_grid_counts(cases)  # auto on CPU resolves to xla
    _expect(
        "counts.auto", ctx.drain(),
        [planspec.predict("counts", {"platform": "cpu"})],
    )
    ceng = ctx.engine(class_compress="1")
    ctx.drain()
    ceng.evaluate_grid_counts(cases)
    _expect(
        "counts.classes", ctx.drain(),
        [planspec.predict("counts", {"classes": True, "platform": "cpu"})],
    )
    # tiers x backend=pallas, explicit: both sides raise the SAME text
    teng = ctx.engine(tiers=True, env=(("CYCLONUS_PACK", "0"),))
    ctx.drain()
    live_msg = pred_msg = None
    try:
        teng.evaluate_grid_counts(cases, backend="pallas")
    except ValueError as e:
        live_msg = str(e)
    try:
        planspec.predict(
            "counts", {"backend": "pallas", "tiers": True, "pack": False}
        )
    except planspec.PlanError as e:
        pred_msg = str(e)
    _check(live_msg is not None, "counts.tiers-pallas", "live did not raise")
    _check(pred_msg is not None, "counts.tiers-pallas", "predict did not raise")
    _check(
        live_msg == pred_msg == planspec.interaction(
            "tiers", "backend=pallas"
        ).message,
        "counts.tiers-pallas",
        f"raise text diverged from the declared cell: live={live_msg!r} "
        f"predicted={pred_msg!r}",
    )
    ctx.drain()  # the raise recorded no route
    # auto on the tiered engine falls back to the xla tile body
    teng.evaluate_grid_counts(cases)
    _expect(
        "counts.tiers-auto", ctx.drain(),
        [planspec.predict(
            "counts", {"platform": "cpu", "tiers": True, "pack": False}
        )],
    )
    return {"routes": 5}


def scenario_counts_steady_routes(ctx: Ctx) -> Dict:
    """The pallas counts path and its steady-state sub-dispatch: the
    cold fused call and the split call record only counts.pallas; the
    third (pinned-precompute) call adds the counts.steady.* leaf —
    default, tuned-packed (via a planted kernel choice), and the slab
    kernel on a CYCLONUS_PACK=0 engine (the pack x slab matrix cell
    retires slab under the packed plan)."""
    from cyclonus_tpu.engine import planspec

    cases = ctx.cases(1)
    with _env(CYCLONUS_AUTOTUNE="0"):
        eng = ctx.engine(env=(("CYCLONUS_AUTOTUNE", "0"),))
        ctx.drain()
        cp = planspec.predict("counts", {"backend": "pallas", "pack": True})
        for _ in range(2):  # cold fused, then split
            eng.evaluate_grid_counts(cases, backend="pallas")
        _expect("counts.pallas.warmup", ctx.drain(), [cp, cp])
        eng.evaluate_grid_counts(cases, backend="pallas")  # steady
        _expect(
            "counts.steady.default", ctx.drain(),
            [cp, planspec.predict("counts_steady", {"pack": True})],
        )
        # a tuned packed choice routes the steady dispatch to the tuned
        # tile (what the autotune's winner adoption sets)
        with eng._slab_lock:
            eng._kernel_choice = {"kernel": "packed", "bs": 8, "bd": 128}
        eng.evaluate_grid_counts(cases, backend="pallas")
        _expect(
            "counts.steady.packed_tuned", ctx.drain(),
            [cp, planspec.predict(
                "counts_steady", {"pack": True, "tuned": True}
            )],
        )
        with eng._slab_lock:
            eng._kernel_choice = None
    # slab kernel: only reachable with the packed plan OFF
    slab_env = (
        ("CYCLONUS_PACK", "0"),
        ("CYCLONUS_PALLAS_SLAB", "1"),
        ("CYCLONUS_AUTOTUNE", "0"),
    )
    import cyclonus_tpu.engine.pallas_kernel as pk

    tiles = {"SLAB_BS": pk.SLAB_BS, "SLAB_BD": pk.SLAB_BD, "SLAB_W": pk.SLAB_W}
    try:
        # tiny tile overrides so the 24-pod cluster spans multiple src
        # tiles (the same trick tests/test_engine_pallas.py uses)
        pk.SLAB_BS = pk.SLAB_BD = pk.SLAB_W = 8
        with _env(**dict(slab_env)):
            seng = ctx.engine(env=slab_env)
            ctx.drain()
            cp0 = planspec.predict(
                "counts", {"backend": "pallas", "pack": False}
            )
            for _ in range(2):
                seng.evaluate_grid_counts(cases, backend="pallas")
            _expect("counts.slab.warmup", ctx.drain(), [cp0, cp0])
            _check(
                isinstance(seng._slab_plan_state, dict),
                "counts.steady.slab",
                f"slab plan did not engage: {seng._slab_plan_state!r}",
            )
            with seng._slab_lock:
                seng._kernel_choice = {"kernel": "slab"}
            seng.evaluate_grid_counts(cases, backend="pallas")
            _expect(
                "counts.steady.slab", ctx.drain(),
                [cp0, planspec.predict(
                    "counts_steady", {"pack": False, "slab": True}
                )],
            )
    finally:
        for k, v in tiles.items():
            setattr(pk, k, v)
    return {"routes": 3}


def scenario_counts_sharded_routes(ctx: Ctx) -> Dict:
    """evaluate_grid_counts_sharded kernel routing: explicit xla, auto
    on CPU, the compressed route, and the tiers x kernel=pallas cell's
    exact raise."""
    from cyclonus_tpu.engine import planspec

    eng = ctx.engine()
    cases = ctx.cases(1)
    ctx.drain()
    eng.evaluate_grid_counts_sharded(cases, kernel="xla")
    _expect(
        "counts.sharded.xla", ctx.drain(),
        [planspec.predict("counts_sharded", {"kernel": "xla"})],
    )
    eng.evaluate_grid_counts_sharded(cases)
    _expect(
        "counts.sharded.auto", ctx.drain(),
        [planspec.predict("counts_sharded", {"platform": "cpu"})],
    )
    ceng = ctx.engine(class_compress="1")
    ctx.drain()
    ceng.evaluate_grid_counts_sharded(cases)
    _expect(
        "counts.sharded.classes", ctx.drain(),
        [planspec.predict("counts_sharded", {"classes": True})],
    )
    teng = ctx.engine(tiers=True, env=(("CYCLONUS_PACK", "0"),))
    ctx.drain()
    live_msg = pred_msg = None
    try:
        teng.evaluate_grid_counts_sharded(cases, kernel="pallas")
    except ValueError as e:
        live_msg = str(e)
    try:
        planspec.predict(
            "counts_sharded", {"kernel": "pallas", "tiers": True}
        )
    except planspec.PlanError as e:
        pred_msg = str(e)
    _check(
        live_msg is not None and live_msg == pred_msg,
        "counts.sharded.tiers-pallas",
        f"raise text diverged: live={live_msg!r} predicted={pred_msg!r}",
    )
    ctx.drain()
    # auto under tiers resolves to the XLA tile body (fallback cell)
    teng.evaluate_grid_counts_sharded(cases)
    _expect(
        "counts.sharded.tiers-auto", ctx.drain(),
        [planspec.predict("counts_sharded", {"tiers": True})],
    )
    return {"routes": 4}


def scenario_ring_family_routes(ctx: Ctx) -> Dict:
    """The ring-rotation counts family: single-axis ring and the
    hierarchical 2D ring (the pipelined leg is the slow scenario)."""
    from cyclonus_tpu.engine import planspec

    eng = ctx.engine()
    cases = ctx.cases(1)
    ctx.drain()
    eng.evaluate_grid_counts_ring(cases)
    _expect(
        "counts.ring", ctx.drain(), [planspec.predict("counts_ring", {})]
    )
    eng.evaluate_grid_counts_ring2d(cases)
    _expect(
        "counts.ring2d", ctx.drain(), [planspec.predict("counts_ring2d", {})]
    )
    return {"routes": 2}


def scenario_analysis_routes(ctx: Ctx) -> Dict:
    """The point / streaming / analysis entries: blocked grid stream,
    the serve pair program, and the raw firing components."""
    from cyclonus_tpu.engine import planspec

    eng = ctx.engine()
    cases = ctx.cases(1)
    ctx.drain()
    for _ in eng.iter_grid_blocks(cases, block=8):
        pass
    _expect(
        "grid.blocks", ctx.drain(), [planspec.predict("grid_blocks", {})]
    )
    eng.evaluate_pairs(cases, [(0, 1), (2, 3)])
    _expect("pairs.aot", ctx.drain(), [planspec.predict("pairs", {})])
    eng.firing_components(cases)
    _expect("firing.raw", ctx.drain(), [planspec.predict("firing", {})])
    return {"routes": 3}


def scenario_serve_routes(ctx: Ctx) -> Dict:
    """serve's query routing: a deferred-readiness replica answers from
    the degraded scalar oracle; after mark_ready the live engine path
    (which itself dispatches the pair program) takes over — the
    warming x query matrix cell."""
    from cyclonus_tpu.engine import planspec
    from cyclonus_tpu.serve import VerdictService
    from cyclonus_tpu.worker.model import FlowQuery

    namespaces = {ns: {"ns": ns} for ns in ("x", "y")}
    pods = [
        ("x", "p0", {"app": "a0"}, "10.0.0.1"),
        ("y", "p1", {"app": "a1"}, "10.0.0.2"),
    ]
    svc = VerdictService(pods, namespaces, [], defer_ready=True)
    queries = [FlowQuery(src="x/p0", dst="y/p1", port=80, protocol="TCP")]
    ctx.drain()
    svc.query(queries)
    degraded = ctx.drain()
    _check(
        degraded[:1] == [planspec.predict("serve_query", {"warming": True})],
        "serve.query.degraded",
        f"warming query routed {degraded}",
    )
    svc.mark_ready()
    svc.query(queries)
    live = ctx.drain()
    _check(
        live[:1] == [planspec.predict("serve_query", {})],
        "serve.query.live",
        f"live query routed {live}",
    )
    # shed: pin the query_p99 objective exhausted on an armed controller
    # and the same query comes back as a typed refusal route
    from cyclonus_tpu.slo import EXHAUSTED, SloController

    svc2 = VerdictService(pods, namespaces, [], slo=SloController(enforce=True))
    svc2.slo.force_state("query_p99", EXHAUSTED)
    ctx.drain()
    out = svc2.query(queries)
    shed = ctx.drain()
    _check(
        all(v.shed for v in out)
        and shed[:1] == [planspec.predict("serve_query", {"shed": True})],
        "serve.query.shed",
        f"exhausted query routed {shed}",
    )
    return {"routes": 3}


def scenario_audit_routes(ctx: Ctx) -> Dict:
    """The audit plane's shadow-oracle check: a sampled verdict from a
    live service drains through the scalar re-evaluation route."""
    from cyclonus_tpu.audit import AuditController
    from cyclonus_tpu.engine import planspec
    from cyclonus_tpu.serve import VerdictService
    from cyclonus_tpu.worker.model import FlowQuery

    namespaces = {ns: {"ns": ns} for ns in ("x", "y")}
    pods = [
        ("x", "p0", {"app": "a0"}, "10.0.0.1"),
        ("y", "p1", {"app": "a1"}, "10.0.0.2"),
    ]
    svc = VerdictService(
        pods, namespaces, [],
        audit=AuditController(rate=1.0, seed=7, start_worker=False),
    )
    svc.query([FlowQuery(src="x/p0", dst="y/p1", port=80, protocol="TCP")])
    ctx.drain()
    checked = svc.audit.drain()
    routes = ctx.drain()
    _check(
        checked == 1
        and routes[:1] == [planspec.predict("serve_audit", {})],
        "serve.audit.check",
        f"audit check routed {routes} ({checked} checked)",
    )
    return {"routes": 1}


def scenario_ring_pipelined_route(ctx: Ctx) -> Dict:
    """The donation/feed-forward ring pipeline (coverage: slow — the
    sweep is bench-scale, the route proof is not)."""
    from cyclonus_tpu.engine import planspec

    eng = ctx.engine()
    ctx.drain()
    eng.mesh_counts_pipelined_eval_s(ctx.cases(1), reps=2)
    routes = ctx.drain()
    _check(
        routes[:1] == [planspec.predict("counts_ring_pipelined", {})],
        "counts.ring.pipelined",
        f"pipelined ring routed {routes}",
    )
    return {"routes": 1}


#: (name, fn, in_quick_slice)
SCENARIOS: List[Tuple[str, Callable[[Ctx], Dict], bool]] = [
    ("grid_routes", scenario_grid_routes, True),
    ("sharded_grid_routes", scenario_sharded_grid_routes, True),
    ("counts_routes", scenario_counts_routes, True),
    ("counts_steady_routes", scenario_counts_steady_routes, True),
    ("counts_sharded_routes", scenario_counts_sharded_routes, True),
    ("ring_family_routes", scenario_ring_family_routes, True),
    ("analysis_routes", scenario_analysis_routes, True),
    ("serve_routes", scenario_serve_routes, True),
    ("audit_routes", scenario_audit_routes, True),
    ("ring_pipelined_route", scenario_ring_pipelined_route, False),
]


def coverage_census(ctx: Ctx, *, quick: bool) -> Dict:
    """Every PathSpec whose coverage tier the run claims must have been
    recorded — the tier-1 route-coverage acceptance gate.  device_only
    paths are exempt everywhere (no TPU in this harness)."""
    from cyclonus_tpu.engine import planspec

    want_tiers = {"tier1"} if quick else {"tier1", "slow"}
    missing = sorted(
        p.name for p in planspec.PATHS
        if p.coverage in want_tiers and p.name not in ctx.covered
    )
    _check(
        not missing, "coverage",
        f"declared {sorted(want_tiers)} path(s) never recorded: {missing}",
    )
    return {"covered": len(ctx.covered)}


def run(
    *,
    quick: bool = True,
    only: Optional[List[str]] = None,
    seed: int = 0,
    log: Optional[Callable[[str], None]] = None,
) -> Dict[str, Dict]:
    """Run the scenario set; raises HarnessFailure on the first route
    divergence.  Returns per-scenario stats."""
    ctx = Ctx(seed)
    results: Dict[str, Dict] = {}
    for name, fn, in_quick in SCENARIOS:
        if only is not None:
            if name not in only:
                continue
        elif quick and not in_quick:
            continue
        stats = fn(ctx)
        results[name] = stats
        if log is not None:
            log(f"planharness {name}: OK {stats}")
    if only is None:
        results["coverage_census"] = coverage_census(ctx, quick=quick)
        if log is not None:
            log(f"planharness coverage_census: OK {results['coverage_census']}")
    return results


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--full", action="store_true", help="all scenarios")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--scenarios", nargs="*", default=None,
        help=f"subset (choices: {[n for n, _f, _q in SCENARIOS]})",
    )
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    results = run(
        quick=not args.full,
        only=args.scenarios,
        seed=args.seed,
        log=print if args.verbose else None,
    )
    print(
        f"planharness: {len(results)} scenario(s) passed "
        f"({', '.join(sorted(results))})",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
