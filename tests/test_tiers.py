"""Precedence-tier subsystem tests (cyclonus_tpu/tiers + the lattice
plumbing through matcher/engine/serve/analysis):

  * model round-trips and validation (dict/YAML, action vocabularies,
    priority bounds, port-range sanity);
  * lattice unit tests on the scalar oracle (matcher/tiered.py): verdict
    precedence, Pass-fallthrough, BANP-never-after-NP, equal-priority
    name tiebreak, external-peer passthrough;
  * property tests: priority-order invariance under ANP list shuffle,
    all-Pass transparency, and the zero-tier byte-identity acceptance
    criterion (empty TierSet == tiers=None == the networkingv1-only
    tensor set);
  * the differential gate on fixtures + >= 8 fuzz seeds (dense AND
    class-compressed engine tables bit-identical to the tiered oracle);
  * endPort ranges and SCTP through matcher -> encoding -> kernel;
  * the serve layer: tier deltas patch like rule slabs (incremental on
    shape-preserving changes, full-rebuild fallback on tier-structure
    changes), plus the shared-selector-table regression the lattice
    exposed in IncrementalEngine.patch_policy;
  * the audit layer: audit_class_reduction under `tiers` fires on a
    merge only the ADMIN tiers distinguish (the plain-oracle
    under-assertion regression), and audit_policy_set stays sound on a
    tiered engine (the tier-composition note in analysis/audit.py).
"""

import random

import numpy as np
import pytest

from cyclonus_tpu.analysis.classes import audit_class_reduction
from cyclonus_tpu.engine.api import PortCase, TpuPolicyEngine
from cyclonus_tpu.kube.netpol import (
    IntOrString,
    LabelSelector,
    NetworkPolicy,
    NetworkPolicyIngressRule,
    NetworkPolicyPeer,
    NetworkPolicyPort,
    NetworkPolicySpec,
)
from cyclonus_tpu.matcher.builder import build_network_policies
from cyclonus_tpu.matcher.core import InternalPeer, Traffic, TrafficPeer
from cyclonus_tpu.matcher.tiered import TieredPolicy, tiered_oracle_verdicts
from cyclonus_tpu.serve import VerdictService
from cyclonus_tpu.tiers import fuzz
from cyclonus_tpu.tiers.model import (
    AdminNetworkPolicy,
    BaselineAdminNetworkPolicy,
    TierPort,
    TierRule,
    TierScope,
    TierSet,
    load_tier_set_from_yaml,
    parse_tier_object,
)
from cyclonus_tpu.worker.model import Delta, FlowQuery

CASES = [
    PortCase(80, "serve-80-tcp", "TCP"),
    PortCase(81, "serve-81-udp", "UDP"),
    PortCase(82, "serve-82-sctp", "SCTP"),
]


def mk_cluster():
    """Three namespaces, labeled pods — small enough for full oracle
    truth tables, labeled richly enough that tier scopes can split it."""
    namespaces = {
        "x": {"ns": "x", "team": "red"},
        "y": {"ns": "y", "team": "blue"},
        "z": {"ns": "z"},
    }
    pods = []
    i = 0
    for ns in namespaces:
        for name, labels in (
            ("a", {"pod": "a", "app": "web"}),
            ("b", {"pod": "b", "app": "db"}),
            ("c", {"pod": "c"}),
        ):
            pods.append((ns, name, dict(labels), f"10.0.0.{i + 1}"))
            i += 1
    return pods, namespaces


def traffic_between(pods, namespaces, case, si, di):
    sns, _sn, sl, sip = pods[si]
    dns, _dn, dl, dip = pods[di]
    return Traffic(
        source=TrafficPeer(
            internal=InternalPeer(
                pod_labels=sl, namespace_labels=namespaces[sns], namespace=sns
            ),
            ip=sip,
        ),
        destination=TrafficPeer(
            internal=InternalPeer(
                pod_labels=dl, namespace_labels=namespaces[dns], namespace=dns
            ),
            ip=dip,
        ),
        resolved_port=case.port,
        resolved_port_name=case.port_name,
        protocol=case.protocol,
    )


def pod_sel(**labels):
    return LabelSelector.make(match_labels=dict(labels))


def anp(name, priority, subject, ingress=(), egress=()):
    return AdminNetworkPolicy(
        name=name,
        priority=priority,
        subject=subject,
        ingress=list(ingress),
        egress=list(egress),
    )


def rule(action, peers=None, ports=None):
    return TierRule(
        action=action,
        peers=list(peers) if peers is not None else [TierScope()],
        ports=ports,
    )


def oracle_table(policy, tiers, pods, namespaces, cases=CASES):
    return fuzz._oracle_table(policy, tiers, pods, namespaces, cases)


def engine_table(policy, tiers, pods, namespaces, cases=CASES, mode="0"):
    engine = TpuPolicyEngine(
        policy, pods, namespaces, tiers=tiers, class_compress=mode
    )
    return fuzz._engine_table(engine, cases)


# --- model -----------------------------------------------------------------


class TestModel:
    def test_anp_dict_round_trip(self):
        a = anp(
            "a1",
            7,
            TierScope(
                namespace_selector=pod_sel(ns="x"),
                pod_selector=pod_sel(app="web"),
            ),
            ingress=[
                rule(
                    "Deny",
                    peers=[TierScope(namespace_selector=pod_sel(team="red"))],
                    ports=[
                        TierPort(protocol="TCP", port=IntOrString(80)),
                        TierPort(
                            protocol="SCTP",
                            port=IntOrString(80),
                            end_port=90,
                        ),
                        TierPort(protocol="TCP", port=IntOrString("http")),
                    ],
                )
            ],
            egress=[rule("Pass")],
        )
        assert AdminNetworkPolicy.from_dict(a.to_dict()) == a
        assert parse_tier_object(a.to_dict()) == a

    def test_banp_dict_round_trip(self):
        b = BaselineAdminNetworkPolicy(
            subject=TierScope(namespace_selector=pod_sel(ns="x")),
            ingress=[rule("Deny")],
        )
        assert BaselineAdminNetworkPolicy.from_dict(b.to_dict()) == b

    def test_nil_vs_empty_scope_survives_round_trip(self):
        # namespaces variant (pod_selector None = every pod of matching
        # namespaces) must not collapse into the pods variant with an
        # empty selector — both match everything, but the distinction
        # is API-visible
        ns_variant = TierScope(namespace_selector=pod_sel(ns="x"))
        rt = TierScope.from_dict(ns_variant.to_dict())
        assert rt.pod_selector is None
        pods_variant = TierScope(
            namespace_selector=pod_sel(ns="x"),
            pod_selector=LabelSelector.make(),
        )
        rt = TierScope.from_dict(pods_variant.to_dict())
        assert rt.pod_selector is not None

    def test_validation_rejects_bad_objects(self):
        with pytest.raises(ValueError, match="priority"):
            anp("p", 1001, TierScope()).validate()
        with pytest.raises(ValueError, match="invalid action"):
            anp("a", 1, TierScope(), ingress=[rule("Accept")]).validate()
        with pytest.raises(ValueError, match="invalid action"):
            # Pass is an ANP-only verb: BANP has nothing below to pass to
            BaselineAdminNetworkPolicy(ingress=[rule("Pass")]).validate()
        with pytest.raises(ValueError, match="end 79 < start"):
            TierPort(
                protocol="TCP", port=IntOrString(80), end_port=79
            ).validate()
        with pytest.raises(ValueError, match="must be numeric"):
            TierPort(
                protocol="TCP", port=IntOrString("http"), end_port=90
            ).validate()
        with pytest.raises(ValueError, match="duplicate"):
            TierSet(
                anps=[anp("dup", 1, TierScope()), anp("dup", 2, TierScope())]
            ).validate()
        # spec.priority is REQUIRED upstream: a payload without it must
        # be rejected at parse, never silently become priority 0 (the
        # cluster's highest) — the serve layer's pre-mutation validation
        # rides on this
        with pytest.raises(ValueError, match="priority is required"):
            AdminNetworkPolicy.from_dict(
                {
                    "kind": "AdminNetworkPolicy",
                    "metadata": {"name": "no-prio"},
                    "spec": {"ingress": [{"action": "Deny", "from": []}]},
                }
            )

    def test_yaml_loading(self):
        text = """
apiVersion: policy.networking.k8s.io/v1alpha1
kind: AdminNetworkPolicy
metadata: {name: deny-web}
spec:
  priority: 3
  subject: {pods: {namespaceSelector: {}, podSelector: {matchLabels: {app: web}}}}
  ingress:
    - action: Deny
      from:
        - namespaces: {matchLabels: {team: red}}
---
apiVersion: policy.networking.k8s.io/v1alpha1
kind: BaselineAdminNetworkPolicy
metadata: {name: default}
spec:
  subject: {namespaces: {}}
  ingress:
    - action: Allow
      from:
        - namespaces: {}
"""
        ts = load_tier_set_from_yaml(text)
        assert [a.name for a in ts.anps] == ["deny-web"]
        assert ts.banp is not None
        banp_only = text[text.index("---") :]
        with pytest.raises(ValueError, match="singleton"):
            load_tier_set_from_yaml(banp_only + banp_only)
        with pytest.raises(ValueError, match="unknown tier object kind"):
            load_tier_set_from_yaml("kind: NetworkPolicy\nmetadata: {name: x}")

    def test_ordered_rules_totalizes_priority_ties(self):
        ts = TierSet(
            anps=[
                anp("bbb", 5, TierScope(), ingress=[rule("Deny")]),
                anp("aaa", 5, TierScope(), ingress=[rule("Allow")]),
                anp("zzz", 1, TierScope(), ingress=[rule("Pass")]),
            ]
        )
        ordered = ts.ordered_rules(True, "anp")
        assert [o.policy.name for o in ordered] == ["zzz", "aaa", "bbb"]
        assert [o.rank for o in ordered] == [0, 1, 2]


# --- scalar lattice --------------------------------------------------------


class TestLatticeOracle:
    def _pods(self):
        return mk_cluster()

    def _idx(self, pods, ns, name):
        return next(
            i for i, p in enumerate(pods) if p[0] == ns and p[1] == name
        )

    def test_anp_deny_beats_default_allow(self):
        pods, namespaces = self._pods()
        ts = TierSet(
            anps=[
                anp(
                    "deny-web",
                    1,
                    TierScope(pod_selector=pod_sel(app="web")),
                    ingress=[rule("Deny")],
                )
            ]
        )
        oracle = TieredPolicy(build_network_policies(True, []), ts)
        web = self._idx(pods, "x", "a")
        db = self._idx(pods, "x", "b")
        t = traffic_between(pods, namespaces, CASES[0], db, web)
        assert oracle.is_traffic_allowed(t) == (False, True, False)
        assert oracle.explain(t) == {"ingress": "anp", "egress": "default"}
        # non-subject pods untouched
        t = traffic_between(pods, namespaces, CASES[0], web, db)
        assert oracle.is_traffic_allowed(t) == (True, True, True)

    def test_priority_orders_conflicting_anps(self):
        pods, namespaces = self._pods()
        deny = anp(
            "deny", 2, TierScope(), ingress=[rule("Deny")]
        )
        allow = anp(
            "allow", 1, TierScope(), ingress=[rule("Allow")]
        )
        policy = build_network_policies(True, [])
        t = traffic_between(pods, namespaces, CASES[0], 0, 4)
        assert TieredPolicy(policy, TierSet(anps=[deny, allow])).is_traffic_allowed(t)[0] is True
        # flip the priorities: deny now wins
        deny.priority, allow.priority = 1, 2
        assert TieredPolicy(policy, TierSet(anps=[deny, allow])).is_traffic_allowed(t)[0] is False

    def test_equal_priority_resolves_by_name(self):
        pods, namespaces = self._pods()
        policy = build_network_policies(True, [])
        t = traffic_between(pods, namespaces, CASES[0], 0, 4)
        ts = TierSet(
            anps=[
                anp("a-allow", 5, TierScope(), ingress=[rule("Allow")]),
                anp("b-deny", 5, TierScope(), ingress=[rule("Deny")]),
            ]
        )
        assert TieredPolicy(policy, ts).is_traffic_allowed(t)[0] is True
        ts = TierSet(
            anps=[
                anp("a-deny", 5, TierScope(), ingress=[rule("Deny")]),
                anp("b-allow", 5, TierScope(), ingress=[rule("Allow")]),
            ]
        )
        assert TieredPolicy(policy, ts).is_traffic_allowed(t)[0] is False

    def test_pass_falls_through_np_then_banp_then_default(self):
        pods, namespaces = self._pods()
        # ANP Pass over everything; NP denies x/a's non-80 ingress;
        # BANP denies db pods; everything else default-allows
        ts = TierSet(
            anps=[anp("pass-all", 0, TierScope(), ingress=[rule("Pass")])],
            banp=BaselineAdminNetworkPolicy(
                subject=TierScope(pod_selector=pod_sel(app="db")),
                ingress=[rule("Deny")],
            ),
        )
        np_pol = NetworkPolicy(
            name="allow-80",
            namespace="x",
            spec=NetworkPolicySpec(
                pod_selector=pod_sel(pod="a"),
                policy_types=["Ingress"],
                ingress=[
                    NetworkPolicyIngressRule(
                        ports=[
                            NetworkPolicyPort(
                                protocol="TCP", port=IntOrString(80)
                            )
                        ],
                        from_=[],
                    )
                ],
            ),
        )
        oracle = TieredPolicy(build_network_policies(True, [np_pol]), ts)
        xa = self._idx(pods, "x", "a")
        xb = self._idx(pods, "x", "b")  # app=db -> BANP subject
        zc = self._idx(pods, "z", "c")
        # NP tier decides for x/a: TCP 80 allowed, UDP 81 denied
        t80 = traffic_between(pods, namespaces, CASES[0], zc, xa)
        t81 = traffic_between(pods, namespaces, CASES[1], zc, xa)
        assert oracle.is_traffic_allowed(t80)[0] is True
        assert oracle.is_traffic_allowed(t81)[0] is False
        assert oracle.explain(t81)["ingress"] == "np"
        # no NP target for x/b -> falls to BANP deny
        t = traffic_between(pods, namespaces, CASES[0], zc, xb)
        assert oracle.is_traffic_allowed(t)[0] is False
        assert oracle.explain(t)["ingress"] == "banp"
        # no NP, no BANP match -> default allow
        t = traffic_between(pods, namespaces, CASES[0], xa, zc)
        assert oracle.is_traffic_allowed(t)[0] is True
        assert oracle.explain(t)["ingress"] == "default"

    def test_banp_never_fires_for_np_selected_pods(self):
        pods, namespaces = self._pods()
        # NP allows everything into x/a; BANP would deny it — NP is final
        np_pol = NetworkPolicy(
            name="allow-all",
            namespace="x",
            spec=NetworkPolicySpec(
                pod_selector=pod_sel(pod="a"),
                policy_types=["Ingress"],
                ingress=[NetworkPolicyIngressRule(ports=[], from_=[])],
            ),
        )
        ts = TierSet(
            banp=BaselineAdminNetworkPolicy(
                subject=TierScope(), ingress=[rule("Deny")]
            )
        )
        oracle = TieredPolicy(build_network_policies(True, [np_pol]), ts)
        xa = self._idx(pods, "x", "a")
        zc = self._idx(pods, "z", "c")
        t = traffic_between(pods, namespaces, CASES[0], zc, xa)
        assert oracle.is_traffic_allowed(t)[0] is True
        assert oracle.explain(t)["ingress"] == "np"
        # the unselected pod gets the BANP deny
        t = traffic_between(pods, namespaces, CASES[0], xa, zc)
        assert oracle.is_traffic_allowed(t)[0] is False
        assert oracle.explain(t)["ingress"] == "banp"

    def test_external_peer_passes_admin_tiers(self):
        pods, namespaces = self._pods()
        ts = TierSet(
            anps=[anp("deny-all", 0, TierScope(), ingress=[rule("Deny")])]
        )
        oracle = TieredPolicy(build_network_policies(True, []), ts)
        # external destination: ingress verdict is "external" allow
        t = Traffic(
            source=TrafficPeer(
                internal=InternalPeer(
                    pod_labels={"pod": "a"},
                    namespace_labels=namespaces["x"],
                    namespace="x",
                ),
                ip="10.0.0.1",
            ),
            destination=TrafficPeer(internal=None, ip="8.8.8.8"),
            resolved_port=80,
            resolved_port_name="",
            protocol="TCP",
        )
        assert oracle.direction_allowed(t, True) == (True, "external")
        # external SOURCE against an internal target: admin scopes are
        # cluster-internal, the deny-all never matches the peer -> the
        # verdict falls through to default
        t2 = Traffic(
            source=TrafficPeer(internal=None, ip="8.8.8.8"),
            destination=t.source,
            resolved_port=80,
            resolved_port_name="",
            protocol="TCP",
        )
        assert oracle.direction_allowed(t2, True) == (True, "default")

    def test_tiered_oracle_verdicts_defers_to_plain_without_tiers(self):
        pods, namespaces = self._pods()
        policy = build_network_policies(True, [])
        t = traffic_between(pods, namespaces, CASES[0], 0, 1)
        assert tiered_oracle_verdicts(policy, None, t) == (True, True, True)
        assert tiered_oracle_verdicts(policy, TierSet(), t) == (
            True,
            True,
            True,
        )


# --- properties ------------------------------------------------------------


class TestProperties:
    def test_priority_order_invariant_under_anp_shuffle(self):
        """The verdict lattice depends on (priority, name), never on the
        declaration order of the ANP list."""
        checked = 0
        for seed in range(12):
            fc = fuzz.build_fuzz_case(seed)
            if fc.tiers is None or len(fc.tiers.anps) < 2:
                continue
            policy = build_network_policies(fc.simplify, fc.netpols)
            want = oracle_table(
                policy, fc.tiers, fc.pods, fc.namespaces, fc.cases
            )
            shuffled = fc.tiers.copy()
            random.Random(seed ^ 0xFACE).shuffle(shuffled.anps)
            got = oracle_table(
                policy, shuffled, fc.pods, fc.namespaces, fc.cases
            )
            assert np.array_equal(got, want), f"seed {seed}"
            checked += 1
        assert checked >= 2
        # engine-side twin on one seed: the slab rank order is also
        # declaration-order independent
        fc = fuzz.build_fuzz_case(5)
        assert fc.tiers is not None and len(fc.tiers.anps) >= 2
        policy = build_network_policies(fc.simplify, fc.netpols)
        shuffled = fc.tiers.copy()
        random.Random(0xFACE).shuffle(shuffled.anps)
        want = engine_table(policy, fc.tiers, fc.pods, fc.namespaces, fc.cases)
        got = engine_table(policy, shuffled, fc.pods, fc.namespaces, fc.cases)
        assert np.array_equal(got, want)

    def test_all_pass_anps_are_transparent(self):
        """An ANP tier of only Pass rules (and no BANP) must leave every
        verdict exactly as the plain networkingv1 oracle computes it."""
        checked = 0
        for seed in range(10):
            fc = fuzz.build_fuzz_case(seed)
            if fc.tiers is None or not fc.tiers.anps:
                continue
            passthrough = fc.tiers.copy()
            passthrough.banp = None
            for a in passthrough.anps:
                for r in a.ingress + a.egress:
                    r.action = "Pass"
            policy = build_network_policies(fc.simplify, fc.netpols)
            want = oracle_table(
                policy, None, fc.pods, fc.namespaces, fc.cases
            )
            got = oracle_table(
                policy, passthrough, fc.pods, fc.namespaces, fc.cases
            )
            assert np.array_equal(got, want), f"seed {seed}"
            checked += 1
        assert checked >= 2

    def test_zero_tier_encoding_byte_identical(self):
        """The acceptance criterion: zero ANP/BANP objects keep the
        networkingv1-only fast path — the tensor set (and therefore
        every compiled program) is byte-identical, tiers=None and an
        empty TierSet included."""
        pods, namespaces = mk_cluster()
        netpols = [
            NetworkPolicy(
                name="np0",
                namespace="x",
                spec=NetworkPolicySpec(
                    pod_selector=pod_sel(app="web"),
                    policy_types=["Ingress"],
                    ingress=[
                        NetworkPolicyIngressRule(
                            ports=[],
                            from_=[
                                NetworkPolicyPeer(
                                    pod_selector=pod_sel(pod="b")
                                )
                            ],
                        )
                    ],
                ),
            )
        ]
        policy = build_network_policies(True, netpols)
        plain = TpuPolicyEngine(policy, pods, namespaces)
        empty = TpuPolicyEngine(policy, pods, namespaces, tiers=TierSet())
        assert empty.tiers is None
        assert plain.encoding.tiers is None and empty.encoding.tiers is None
        assert "tiers" not in plain._tensors
        assert "tiers" not in empty._tensors

        def flatten(prefix, tree, out):
            for k in sorted(tree):
                v = tree[k]
                if isinstance(v, dict):
                    flatten(f"{prefix}{k}.", v, out)
                else:
                    out[f"{prefix}{k}"] = v
            return out

        a = flatten("", plain._tensors, {})
        b = flatten("", empty._tensors, {})
        assert list(a) == list(b)
        for k in a:
            assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), k
        assert plain.tier_stats() == {
            "active": False,
            "anp_count": 0,
            "rule_rows": 0,
            "banp": False,
            "resolve_s": None,
        }

    def test_tier_stats_reports_active_lattice(self):
        pods, namespaces = mk_cluster()
        ts = TierSet(
            anps=[
                anp(
                    "a",
                    1,
                    TierScope(),
                    ingress=[
                        rule(
                            "Deny",
                            peers=[
                                TierScope(),
                                TierScope(pod_selector=pod_sel(app="db")),
                            ],
                        )
                    ],
                )
            ],
            banp=BaselineAdminNetworkPolicy(ingress=[rule("Allow")]),
        )
        engine = TpuPolicyEngine(
            build_network_policies(True, []), pods, namespaces, tiers=ts
        )
        st = engine.tier_stats()
        assert st["active"] is True and st["anp_count"] == 1
        assert st["banp"] is True
        # flat rows: 2 peer rows (ANP ingress) + 1 (BANP ingress), both
        # directions counted — egress contributes none here
        assert st["rule_rows"] == 3
        assert st["resolve_s"] is None
        engine.evaluate_grid(CASES)
        assert engine.tier_stats()["resolve_s"] > 0


# --- the differential gate -------------------------------------------------


class TestDifferentialGate:
    def test_conformance_fixtures_dense_and_compressed(self):
        """The generator's ANP/BANP family through the same
        kernel-vs-oracle gate `cyclonus-tpu fuzz --conformance` runs."""
        assert fuzz.run_conformance() >= 8

    @pytest.mark.parametrize("seed", range(8))
    def test_fuzz_seed(self, seed):
        """>= 8 seeded adversarial scenarios, each checked dense AND
        class-compressed against the tiered scalar oracle (truth tables
        bit-identical, counts equal to oracle sums, pair spot checks).
        A failure reproduces with `cyclonus-tpu fuzz --seed N --seeds
        1`."""
        fuzz.run_seed(seed, pair_samples=8)

    def test_fuzz_runs_reference_linter_and_reports_warnings(self):
        """Every seed's generated NetworkPolicy set runs the ported
        reference linter (cyclonus_tpu/linter/checks.py) non-crashing,
        and the warning census rides the fuzz report — the pkg/linter
        parity pass exercised at generator scale.  The per-seed stats
        and the aggregated report must agree."""
        report = fuzz.run(
            seeds=4, check_counts=False, check_mesh=False, pair_samples=0
        )
        d = report.to_dict()
        assert "lint_warnings" in d and "lint_warnings_by_check" in d
        assert d["lint_warnings"] == sum(
            d["lint_warnings_by_check"].values()
        )
        # the adversarial generator reliably produces lintable shapes
        # (protocol-less ports, all-allowed/blocked targets) across a
        # few seeds — an always-zero census would mean the leg is dead
        assert d["lint_warnings"] > 0, d
        per_seed = [
            fuzz.run_seed(
                s, check_counts=False, check_mesh=False, pair_samples=0
            )["lint_warnings"]
            for s in range(4)
        ]
        assert sum(per_seed) == d["lint_warnings"]


# --- endPort + SCTP --------------------------------------------------------


class TestEndPortSctp:
    def _netpol_endport(self, proto="TCP"):
        return NetworkPolicy(
            name="range",
            namespace="x",
            spec=NetworkPolicySpec(
                pod_selector=pod_sel(app="web"),
                policy_types=["Ingress"],
                ingress=[
                    NetworkPolicyIngressRule(
                        ports=[
                            NetworkPolicyPort(
                                protocol=proto,
                                port=IntOrString(80),
                                end_port=85,
                            )
                        ],
                        from_=[],
                    )
                ],
            ),
        )

    def test_np_endport_range_engine_vs_oracle(self):
        pods, namespaces = mk_cluster()
        cases = [
            PortCase(79, "", "TCP"),
            PortCase(80, "", "TCP"),
            PortCase(85, "", "TCP"),
            PortCase(86, "", "TCP"),
            PortCase(80, "", "UDP"),  # protocol axis respected
        ]
        policy = build_network_policies(True, [self._netpol_endport()])
        want = oracle_table(policy, None, pods, namespaces, cases)
        for mode in ("0", "1"):
            got = engine_table(
                policy, None, pods, namespaces, cases, mode=mode
            )
            assert np.array_equal(got, want), f"mode {mode}"
        # boundary semantics, pinned explicitly: [80, 85] inclusive
        web = next(
            i for i, p in enumerate(pods) if p[0] == "x" and p[1] == "a"
        )
        other = (web + 1) % len(pods)
        assert want[0, other, web, 0] == False  # 79  # noqa: E712
        assert want[1, other, web, 0] == True  # 80  # noqa: E712
        assert want[2, other, web, 0] == True  # 85  # noqa: E712
        assert want[3, other, web, 0] == False  # 86  # noqa: E712
        assert want[4, other, web, 0] == False  # UDP  # noqa: E712

    def test_tier_port_range_and_sctp_engine_vs_oracle(self):
        pods, namespaces = mk_cluster()
        cases = [
            PortCase(79, "", "SCTP"),
            PortCase(80, "", "SCTP"),
            PortCase(81, "", "SCTP"),
            PortCase(82, "", "SCTP"),
            PortCase(80, "", "TCP"),
        ]
        ts = TierSet(
            anps=[
                anp(
                    "deny-sctp-window",
                    1,
                    TierScope(),
                    ingress=[
                        rule(
                            "Deny",
                            ports=[
                                TierPort(
                                    protocol="SCTP",
                                    port=IntOrString(80),
                                    end_port=81,
                                )
                            ],
                        )
                    ],
                )
            ]
        )
        policy = build_network_policies(True, [])
        want = oracle_table(policy, ts, pods, namespaces, cases)
        for mode in ("0", "1"):
            got = engine_table(policy, ts, pods, namespaces, cases, mode=mode)
            assert np.array_equal(got, want), f"mode {mode}"
        # SCTP [80, 81] denied; 79/82 and TCP 80 untouched
        assert not want[1, 0, 4, 0] and not want[2, 0, 4, 0]
        assert want[0, 0, 4, 0] and want[3, 0, 4, 0] and want[4, 0, 4, 0]


# --- serve layer -----------------------------------------------------------


def _tiny_serve(tiers=None, netpols=()):
    namespaces = {"x": {"ns": "x"}, "y": {"ns": "y"}}
    pods = []
    for i in range(8):
        ns = "x" if i % 2 == 0 else "y"
        pods.append(
            (
                ns,
                f"p{i}",
                {"app": "web" if i % 4 < 2 else "db"},
                f"10.0.0.{i + 1}",
            )
        )
    return VerdictService(pods, namespaces, list(netpols), tiers=tiers), pods


def _q(svc, src, dst, port=80, proto="TCP", name="serve-80-tcp"):
    [v] = svc.query(
        [FlowQuery(src=src, dst=dst, port=port, protocol=proto,
                   port_name=name)]
    )
    assert not v.error, v.error
    return v.combined


class TestServeTiers:
    def test_anp_upsert_same_shape_patches_incrementally(self):
        """Tier slabs patch like rule slabs: an action flip keeps every
        bucketed shape, so the delta takes the incremental path — and
        the patched engine stays bit-identical to a fresh rebuild."""
        ts = TierSet(
            anps=[
                anp(
                    "flip",
                    1,
                    TierScope(pod_selector=pod_sel(app="web")),
                    ingress=[rule("Deny")],
                )
            ]
        )
        svc, pods = _tiny_serve(tiers=ts)
        web = f"{pods[0][0]}/{pods[0][1]}"
        db = f"{pods[2][0]}/{pods[2][1]}"
        assert _q(svc, db, web) is False
        flipped = ts.anps[0].to_dict()
        flipped["spec"]["ingress"][0]["action"] = "Allow"
        report = svc.apply(
            [Delta(kind="anp_upsert", name="flip", policy=flipped)]
        )
        assert report["mode"] in ("incremental", "class_rebuild"), report
        assert _q(svc, db, web) is True
        svc.verify_parity(CASES[:2], oracle_samples=16)

    def test_np_delta_on_tiered_service_re_encodes_shared_table(self):
        """The shared-selector-table regression: a PURE NetworkPolicy
        delta on a tiered engine must re-encode the tier slabs too
        (their selector ids index the table the NP re-encode rebuilds).
        Before the fix, patch_policy dropped the tier slabs' table —
        verify_parity catches any drift bit-exactly."""
        ts = TierSet(
            anps=[
                anp(
                    "deny-db",
                    1,
                    TierScope(pod_selector=pod_sel(app="db")),
                    ingress=[rule("Deny")],
                )
            ]
        )
        netpol = NetworkPolicy(
            name="allow-80",
            namespace="x",
            spec=NetworkPolicySpec(
                pod_selector=pod_sel(app="web"),
                policy_types=["Ingress"],
                ingress=[
                    NetworkPolicyIngressRule(
                        ports=[
                            NetworkPolicyPort(
                                protocol="TCP", port=IntOrString(80)
                            )
                        ],
                        from_=[
                            NetworkPolicyPeer(pod_selector=pod_sel(app="web"))
                        ],
                    )
                ],
            ),
        )
        svc, pods = _tiny_serve(tiers=ts, netpols=[netpol])
        from cyclonus_tpu.kube.yaml_io import policy_to_dict

        changed = netpol
        changed.spec.ingress[0].from_ = [
            NetworkPolicyPeer(pod_selector=pod_sel(app="db"))
        ]
        report = svc.apply(
            [
                Delta(
                    kind="policy_upsert",
                    namespace="x",
                    name="allow-80",
                    policy=policy_to_dict(changed),
                )
            ]
        )
        # mode may be incremental or full depending on bucketed shapes;
        # correctness is the invariant — incremental engine == fresh
        # rebuild == tiered oracle
        svc.verify_parity(CASES[:2], oracle_samples=16)
        # the ANP deny must still be live after the NP-only delta
        db = f"{pods[2][0]}/{pods[2][1]}"
        web = f"{pods[0][0]}/{pods[0][1]}"
        assert _q(svc, web, db) is False, report

    def test_tier_structure_change_falls_back_to_full_rebuild(self):
        """ANP objects appearing on a tier-less engine (or the tier
        slabs vanishing) is a tensor-structure change only the full
        rebuild can make — and the rebuilt engine is correct."""
        svc, pods = _tiny_serve()  # no tiers
        web = f"{pods[0][0]}/{pods[0][1]}"
        db = f"{pods[2][0]}/{pods[2][1]}"
        assert _q(svc, db, web) is True
        new_anp = anp(
            "deny-web",
            1,
            TierScope(pod_selector=pod_sel(app="web")),
            ingress=[rule("Deny")],
        )
        report = svc.apply(
            [Delta(kind="anp_upsert", name="deny-web",
                   policy=new_anp.to_dict())]
        )
        assert report["mode"] == "full", report
        assert _q(svc, db, web) is False
        svc.verify_parity(CASES[:2], oracle_samples=16)
        # ... and vanishing again is also structural
        report = svc.apply([Delta(kind="anp_delete", name="deny-web")])
        assert report["mode"] == "full", report
        assert _q(svc, db, web) is True

    def test_banp_upsert_delete_round_trip(self):
        svc, pods = _tiny_serve()
        web = f"{pods[0][0]}/{pods[0][1]}"
        db = f"{pods[2][0]}/{pods[2][1]}"
        banp = BaselineAdminNetworkPolicy(
            subject=TierScope(pod_selector=pod_sel(app="web")),
            ingress=[rule("Deny")],
        )
        svc.apply([Delta(kind="banp_upsert", policy=banp.to_dict())])
        assert _q(svc, db, web) is False
        assert svc.state()["tiers"]["banp"] is True
        svc.apply([Delta(kind="banp_delete")])
        assert _q(svc, db, web) is True
        assert svc.state()["tiers"]["active"] is False

    def test_malformed_tier_delta_rejected_before_state_mutates(self):
        svc, _pods = _tiny_serve()
        bad = {
            "kind": "AdminNetworkPolicy",
            "metadata": {"name": "bad"},
            "spec": {"priority": 9999, "ingress": [{"action": "Deny"}]},
        }
        report = svc.apply([Delta(kind="anp_upsert", name="bad",
                                  policy=bad)])
        assert report["rejected"] and not report["applied"]
        assert "bad" not in svc.anps
        assert svc.state()["tiers"]["active"] is False
        # spec.priority is required — a payload without it must never
        # silently install at priority 0
        no_prio = {
            "kind": "AdminNetworkPolicy",
            "metadata": {"name": "sneaky"},
            "spec": {"ingress": [{"action": "Deny", "from": []}]},
        }
        report = svc.apply([Delta(kind="anp_upsert", name="sneaky",
                                  policy=no_prio)])
        assert report["rejected"] and "priority is required" in \
            report["rejected"][0]
        assert "sneaky" not in svc.anps

    def test_misrouted_tier_payload_rejected_by_kind(self):
        """from_dict ignores `kind`, so the wire path checks it like
        the YAML path's parse_tier_object: an ANP sent as banp_upsert
        (or junk) must be rejected, never installed as the baseline."""
        ts = TierSet(
            banp=BaselineAdminNetworkPolicy(
                subject=TierScope(pod_selector=pod_sel(app="web")),
                ingress=[rule("Deny")],
            )
        )
        svc, pods = _tiny_serve(tiers=ts)
        web = f"{pods[0][0]}/{pods[0][1]}"
        db = f"{pods[2][0]}/{pods[2][1]}"
        assert _q(svc, db, web) is False  # the real baseline deny
        mis = anp(
            "mis", 1, TierScope(), ingress=[rule("Allow")]
        ).to_dict()  # kind: AdminNetworkPolicy
        report = svc.apply([Delta(kind="banp_upsert", policy=mis)])
        assert report["rejected"], report
        assert "kind" in report["rejected"][0]
        report = svc.apply(
            [Delta(kind="banp_upsert", policy={"kind": "x"})]
        )
        assert report["rejected"], report
        # the real baseline survived both
        assert svc.banp == ts.banp
        assert _q(svc, db, web) is False


class TestMeshTieredCounts:
    """The mesh-parallel counts paths (sharded all-gather, ring /
    ring2d ppermute rotation of the dst-side tier arrays) carry the
    same resolution epilogue — differentially gated here against the
    tiered oracle on the CPU 8-virtual-device mesh."""

    def test_sharded_and_ring_counts_match_oracle_under_tiers(self):
        pods, namespaces = mk_cluster()
        netpol = NetworkPolicy(
            name="allow-80",
            namespace="x",
            spec=NetworkPolicySpec(
                pod_selector=pod_sel(app="web"),
                policy_types=["Ingress"],
                ingress=[
                    NetworkPolicyIngressRule(
                        ports=[
                            NetworkPolicyPort(
                                protocol="TCP", port=IntOrString(80)
                            )
                        ],
                        from_=[],
                    )
                ],
            ),
        )
        ts = TierSet(
            anps=[
                anp(
                    "deny-db",
                    1,
                    TierScope(pod_selector=pod_sel(app="db")),
                    ingress=[
                        rule(
                            "Deny",
                            peers=[
                                TierScope(
                                    namespace_selector=pod_sel(team="red")
                                )
                            ],
                        )
                    ],
                ),
                anp("pass-web", 2,
                    TierScope(pod_selector=pod_sel(app="web")),
                    ingress=[rule("Pass")]),
            ],
            banp=BaselineAdminNetworkPolicy(
                subject=TierScope(namespace_selector=pod_sel(ns="z")),
                ingress=[rule("Deny")],
                egress=[rule("Allow")],
            ),
        )
        policy = build_network_policies(True, [netpol])
        want = oracle_table(policy, ts, pods, namespaces, CASES)
        sums = {
            "ingress": int(want[..., 0].sum()),
            "egress": int(want[..., 1].sum()),
            "combined": int(want[..., 2].sum()),
        }
        engine = TpuPolicyEngine(policy, pods, namespaces, tiers=ts)
        for name in ("sharded", "ring", "ring2d"):
            fn = getattr(engine, f"evaluate_grid_counts_{name}")
            counts = fn(CASES, block=4)
            assert {k: counts[k] for k in sums} == sums, name
        # the mesh-sharded GRID path too (shard_map tier all-gathers):
        # full truth table bit-identical to the tiered oracle
        grid = engine.evaluate_grid_sharded(CASES)
        got = np.stack(
            [
                np.swapaxes(np.asarray(grid.ingress), 1, 2),
                np.asarray(grid.egress),
                np.asarray(grid.combined),
            ],
            axis=-1,
        )
        assert np.array_equal(got, want)

    def test_explicit_pallas_counts_request_fails_loudly(self, monkeypatch):
        """Under the legacy (CYCLONUS_PACK=0) dtype plan the dense
        pallas kernel cannot express the lattice: the auto default
        routes tiered counts to the XLA tile body and an EXPLICIT
        pallas request must raise, not silently publish the XLA rate
        under the pallas label.  Under the PACKED plan the fused tier
        epilogue serves pallas counts directly — and stays bit-identical
        to the oracle."""
        pods, namespaces = mk_cluster()
        ts = TierSet(
            anps=[anp("d", 1, TierScope(), ingress=[rule("Deny")])]
        )
        monkeypatch.setenv("CYCLONUS_PACK", "0")
        engine = TpuPolicyEngine(
            build_network_policies(True, []), pods, namespaces, tiers=ts
        )
        with pytest.raises(ValueError, match="precedence-tier"):
            engine.evaluate_grid_counts(CASES, backend="pallas")
        with pytest.raises(ValueError, match="precedence-tier"):
            engine.evaluate_grid_counts_sharded(CASES, kernel="pallas")
        # auto stays routed and correct
        want = oracle_table(
            build_network_policies(True, []), ts, pods, namespaces, CASES
        )
        counts = engine.evaluate_grid_counts(CASES, block=8)
        assert counts["combined"] == int(want[..., 2].sum())
        # packed plan: the fused tier epilogue serves an explicit
        # pallas request, counts pinned to the oracle; the sharded
        # per-device kernel keeps the loud failure (no fused tier there)
        monkeypatch.setenv("CYCLONUS_PACK", "1")
        packed = TpuPolicyEngine(
            build_network_policies(True, []), pods, namespaces, tiers=ts
        )
        pcounts = packed.evaluate_grid_counts(CASES, backend="pallas")
        assert pcounts["combined"] == int(want[..., 2].sum())
        assert pcounts == counts
        with pytest.raises(ValueError, match="precedence-tier"):
            packed.evaluate_grid_counts_sharded(CASES, kernel="pallas")


# --- audit layer -----------------------------------------------------------


class TestAuditTierComposition:
    def test_class_audit_plain_oracle_under_asserts_without_tiers(self):
        """The bool-OR regression the lattice exposed: merge two pods
        only the ADMIN tiers distinguish — the plain-oracle audit passes
        (no NetworkPolicy separates them) while the tiered audit fires.
        audit_class_reduction(tiers=...) is the fix."""
        from cyclonus_tpu.engine.encoding import PodClasses

        pods, namespaces = mk_cluster()
        ts = TierSet(
            anps=[
                anp(
                    "deny-web",
                    1,
                    TierScope(pod_selector=pod_sel(app="web")),
                    ingress=[rule("Deny")],
                )
            ]
        )
        policy = build_network_policies(True, [])
        engine = TpuPolicyEngine(
            policy, pods, namespaces, tiers=ts, class_compress="1"
        )
        pc = engine.pod_classes()
        assert pc is not None
        # x/a (app=web, ANP-denied ingress) vs x/c (no app label): no
        # NetworkPolicy exists, so the plain oracle sees them identical
        a = next(
            i for i, p in enumerate(pods) if p[0] == "x" and p[1] == "a"
        )
        c = next(
            i for i, p in enumerate(pods) if p[0] == "x" and p[1] == "c"
        )
        of = np.asarray(pc.class_of_pod)
        # the REAL classifier must already keep them apart (tier
        # selectors ride the shared selector table the signature packs)
        assert of[a] != of[c]
        corrupt_of = of.copy()
        corrupt_of[c] = corrupt_of[a]
        sizes = np.bincount(corrupt_of, minlength=pc.n_classes).astype(
            np.int32
        )
        corrupted = PodClasses(
            n_pods=pc.n_pods,
            n_classes=pc.n_classes,
            class_of_pod=corrupt_of,
            class_rep=pc.class_rep,
            class_size=sizes,
        )
        plain = audit_class_reduction(
            policy, pods, namespaces, CASES[:1], corrupted,
            max_classes=32, peers_per_class=len(pods),
        )
        assert plain["ok"], "plain oracle cannot see the tier split"
        tiered = audit_class_reduction(
            policy, pods, namespaces, CASES[:1], corrupted,
            max_classes=32, peers_per_class=len(pods), tiers=ts,
        )
        assert not tiered["ok"]
        assert tiered["violations"]

    def test_class_audit_passes_on_real_tiered_classes(self):
        pods, namespaces = mk_cluster()
        ts = TierSet(
            anps=[
                anp(
                    "deny-web",
                    1,
                    TierScope(pod_selector=pod_sel(app="web")),
                    ingress=[rule("Deny")],
                )
            ],
            banp=BaselineAdminNetworkPolicy(ingress=[rule("Allow")]),
        )
        policy = build_network_policies(True, [])
        engine = TpuPolicyEngine(
            policy, pods, namespaces, tiers=ts, class_compress="1"
        )
        pc = engine.pod_classes()
        assert pc is not None
        report = audit_class_reduction(
            policy, pods, namespaces, CASES, pc,
            max_classes=32, peers_per_class=len(pods), tiers=ts,
        )
        assert report["ok"], report["violations"][:3]

    def test_np_audit_stays_sound_on_tiered_engine(self):
        """The tier-composition note in analysis/audit.py: firing masks
        are an NP-tier concept; on a tiered engine the audit's findings
        must match the tier-less engine's exactly (firing_components
        excludes the tier slabs)."""
        from cyclonus_tpu.analysis.audit import audit_policy_set

        pods, namespaces = mk_cluster()
        shadowing = NetworkPolicy(
            name="wide",
            namespace="x",
            spec=NetworkPolicySpec(
                pod_selector=pod_sel(app="web"),
                policy_types=["Ingress"],
                ingress=[
                    NetworkPolicyIngressRule(ports=[], from_=[]),
                    NetworkPolicyIngressRule(
                        ports=[],
                        from_=[
                            NetworkPolicyPeer(pod_selector=pod_sel(pod="b"))
                        ],
                    ),
                ],
            ),
        )
        policy = build_network_policies(False, [shadowing])
        ts = TierSet(
            anps=[anp("pass", 1, TierScope(), ingress=[rule("Pass")])]
        )
        plain_engine = TpuPolicyEngine(policy, pods, namespaces)
        tiered_engine = TpuPolicyEngine(policy, pods, namespaces, tiers=ts)
        plain = audit_policy_set(
            policy, pods, namespaces, CASES[:2], engine=plain_engine
        )
        tiered = audit_policy_set(
            policy, pods, namespaces, CASES[:2], engine=tiered_engine
        )

        def key(f):
            return (f.kind, f.rule.label, f.fire_cells, f.oracle)

        assert [key(f) for f in plain.findings] == [
            key(f) for f in tiered.findings
        ]
        assert plain.findings  # the shadowed rule IS found
