"""Worker subsystem tests (reference leaves pkg/worker untested — SURVEY
§4 "What's NOT tested"; here the wire model, the in-pod prober loop, the
driver-side client, and the CLI entry all get coverage)."""

import json
import subprocess
import sys

import pytest

from cyclonus_tpu.worker.client import Client
from cyclonus_tpu.worker.model import Batch, Request, Result
from cyclonus_tpu.worker import worker as worker_mod
from cyclonus_tpu.worker.worker import issue_batch, run_worker
from cyclonus_tpu.kube.ikubernetes import KubeError


def make_batch(n=2):
    return Batch(
        namespace="x",
        pod="a",
        container="cont-80-tcp",
        requests=[
            Request(key=f"k{i}", protocol="tcp", host="192.168.1.2", port=80 + i)
            for i in range(n)
        ],
    )


class TestModel:
    def test_batch_json_roundtrip(self):
        b = make_batch()
        b2 = Batch.from_json(b.to_json())
        assert b2 == b
        assert b2.key() == "x/a/cont-80-tcp"

    def test_result_roundtrip(self):
        r = Result(request=make_batch().requests[0], output="ok", error="")
        assert Result.from_dict(r.to_dict()) == r
        assert r.is_success()
        assert not Result(request=r.request, error="boom").is_success()

    def test_request_command_shape(self):
        cmd = Request(key="k", protocol="tcp", host="h", port=80).command()
        assert cmd[0] == "/agnhost" and "h:80" in cmd
        assert any(a.startswith("--protocol=") for a in cmd)

    def test_request_invalid_protocol(self):
        with pytest.raises(ValueError):
            Request(key="k", protocol="icmp", host="h", port=80).command()


class TestWireCompatibility:
    """Both directions of the optional-field policy documented in
    worker/model.py: optional keys are omitted when unset and tolerated
    when missing, and unknown keys from a NEWER peer are ignored."""

    def test_old_peer_json_still_parses(self):
        # direction 1: an OLD peer omits every extension — the frozen
        # reference keys alone must parse, extensions defaulting to unset
        legacy_result = Result.from_dict(
            {
                "Request": {
                    "Key": "k", "Protocol": "tcp", "Host": "h", "Port": 1,
                },
                "Output": "",
                "Error": "",
            }
        )
        assert legacy_result.latency_ms is None
        assert legacy_result.trace_events is None
        legacy_batch = Batch.from_json(
            '{"Namespace":"x","Pod":"a","Container":"c","Requests":[]}'
        )
        assert legacy_batch.trace_id == "" and legacy_batch.parent_span == ""

    def test_unset_extensions_are_omitted_on_the_wire(self):
        # direction 1 (writer side): we never emit unset optional keys,
        # so an old consumer sees exactly the frozen reference shape
        r = Result(request=make_batch().requests[0])
        assert set(r.to_dict().keys()) == {"Request", "Output", "Error"}
        b = make_batch()
        assert set(json.loads(b.to_json()).keys()) == {
            "Namespace", "Pod", "Container", "Requests",
        }
        # ParentSpan rides only alongside TraceId (context is one unit)
        b.parent_span = "orphan"
        assert "ParentSpan" not in json.loads(b.to_json())

    def test_unknown_fields_from_newer_peer_are_ignored(self):
        # direction 2: a NEWER peer's extra keys must not break us
        d = Result(request=make_batch().requests[0], output="ok").to_dict()
        d["FutureField"] = {"nested": True}
        d["Request"]["FutureKey"] = 1
        parsed = Result.from_dict(d)
        assert parsed.output == "ok" and parsed.is_success()
        bd = json.loads(make_batch().to_json())
        bd["FutureBatchField"] = [1, 2, 3]
        assert Batch.from_json(json.dumps(bd)) == make_batch()

    def test_set_extensions_roundtrip(self):
        b = make_batch(1)
        b.trace_id, b.parent_span = "t123", "interpreter.step"
        b2 = Batch.from_json(b.to_json())
        assert b2.trace_id == "t123" and b2.parent_span == "interpreter.step"
        r = Result(
            request=b.requests[0],
            latency_ms=7.25,
            trace_events=[{"ph": "B", "name": "n", "path": "n", "ts": 1.0,
                           "pid": 9, "tid": 1}],
        )
        r2 = Result.from_dict(r.to_dict())
        assert r2.latency_ms == 7.25
        assert r2.trace_events == r.trace_events

    def test_wire_contract_golden(self):
        """The dtype-contract half of the compat rules: the versioned
        registry (worker/wireregistry.py) IS the protocol, and its
        committed projection worker/wire_schema.json is the frozen
        golden.  This census pins every live WIRE table to the frozen
        schema's (type, optional) rows — changing a contract without
        regenerating the golden (`python -m cyclonus_tpu.worker.
        wireregistry --write-golden`, the explicit diffable protocol
        change) fails here AND in wirelint's WR003 before it can ship a
        silent wire break."""
        import json as _json

        from cyclonus_tpu.worker import wireregistry
        from cyclonus_tpu.worker.model import (
            Batch,
            Delta,
            FlowQuery,
            Request,
            Result,
            Verdict,
        )

        with open(wireregistry.golden_path()) as f:
            frozen = _json.load(f)
        assert frozen["schema_version"] == wireregistry.PROTOCOL_VERSION
        for cls in (Request, Batch, Result, Delta, FlowQuery, Verdict):
            rows = frozen["messages"][cls.__name__]["keys"]
            got = {
                k: (wf.type.__name__, wf.optional)
                for k, wf in cls.WIRE.items()
            }
            want = {k: (r["type"], r["optional"]) for k, r in rows.items()}
            assert got == want, (
                f"{cls.__name__} wire contract drifted from "
                "wire_schema.json"
            )
        # every registered message is frozen, Reply included (it has no
        # model class — the serve loop emits it as a plain dict)
        assert set(frozen["messages"]) == set(wireregistry.message_names())

    def test_serve_messages_roundtrip(self):
        """The verdict-service payloads (Deltas/Queries) ride the Batch
        envelope as optional keys and round-trip exactly."""
        from cyclonus_tpu.worker.model import Delta, FlowQuery, Verdict

        b = make_batch(0)
        b.deltas = [
            Delta(kind="pod_add", namespace="x", name="p1",
                  labels={"app": "a"}, ip="10.0.0.9"),
            Delta(kind="ns_labels", namespace="y", labels={"team": "t"}),
            Delta(kind="policy_delete", namespace="x", name="deny-all"),
        ]
        b.queries = [
            FlowQuery(src="x/a", dst="y/b", port=80, protocol="TCP",
                      port_name="serve-80-tcp"),
            FlowQuery(src="x/a", dst="x/a", port=81, protocol="UDP"),
        ]
        b2 = Batch.from_json(b.to_json())
        assert b2 == b
        # unused optional payload keys are omitted per-delta
        d = b.deltas[2].to_dict()
        assert set(d) == {"Kind", "Namespace", "Name"}
        v = Verdict(query=b.queries[0], ingress=True, egress=False,
                    combined=False, epoch=7, latency_ms=0.5)
        v2 = Verdict.from_dict(v.to_dict())
        assert v2 == v
        verr = Verdict(query=b.queries[1], error="unknown pod key")
        assert Verdict.from_dict(verr.to_dict()) == verr
        assert "Epoch" not in verr.to_dict()

    def test_tier_delta_kinds_ride_existing_keys(self):
        """The precedence-tier delta kinds (anp_upsert/anp_delete/
        banp_upsert/banp_delete) are data VALUES of the existing Kind
        key — the k8s-shaped ANP/BANP dict rides the optional Policy
        key and cluster-scoped objects leave Namespace empty, so the
        wire envelope (and the golden above) is unchanged."""
        from cyclonus_tpu.tiers.model import (
            AdminNetworkPolicy,
            TierRule,
            TierScope,
        )
        from cyclonus_tpu.worker.model import Delta

        a = AdminNetworkPolicy(
            name="deny-all", priority=3, subject=TierScope(),
            ingress=[TierRule(action="Deny", peers=[TierScope()])],
        )
        b = make_batch(0)
        b.deltas = [
            Delta(kind="anp_upsert", name="deny-all", policy=a.to_dict()),
            Delta(kind="anp_delete", name="deny-all"),
            Delta(kind="banp_upsert", policy={"kind": "x"}),
            Delta(kind="banp_delete"),
        ]
        b2 = Batch.from_json(b.to_json())
        assert b2 == b
        # cluster-scoped: no NEW wire keys appear, Namespace serializes
        # empty, unused optional payload keys are omitted per-delta
        d = b.deltas[0].to_dict()
        assert set(d) == {"Kind", "Namespace", "Name", "Policy"}
        assert d["Namespace"] == ""
        assert set(b.deltas[3].to_dict()) == {"Kind", "Namespace"}
        # the payload survives the wire as a parseable ANP
        rt = AdminNetworkPolicy.from_dict(b2.deltas[0].policy)
        assert rt == a

    def test_serve_batch_ignored_by_old_worker(self):
        """Forward compat: a serve batch fed to the probe loop (an OLD
        worker that predates Deltas/Queries would parse the same way —
        unknown keys dropped, empty Requests) must answer cleanly with
        zero results instead of crashing."""
        import json as _json

        from cyclonus_tpu.worker import wireregistry
        from cyclonus_tpu.worker.model import Delta

        b = make_batch(0)
        b.deltas = [Delta(kind="pod_remove", namespace="x", name="p")]
        raw = _json.loads(b.to_json())
        # what an OLD peer sees, synthesized by the registry itself: a
        # v3 Batch reader predates Deltas/Queries (since=4) and drops
        # them, keeping the frozen required shape
        legacy_view = wireregistry.legacy_view("Batch", raw, 3)
        assert "Deltas" not in legacy_view
        assert set(legacy_view) >= {"Namespace", "Pod", "Container",
                                    "Requests"}
        out = run_worker(_json.dumps(legacy_view))
        assert _json.loads(out) == []
        # and the NEW parser round-trips the legacy view without deltas
        assert Batch.from_json(_json.dumps(legacy_view)).deltas == []

    def test_wire_drift_mutation_is_caught(self, monkeypatch):
        """The drift-mutation half of the golden: with runtime checks on,
        a PRESENT key whose type drifted from the WIRE declaration must
        raise on parse — for the serve messages just like the probe
        ones."""
        from cyclonus_tpu.utils import contracts
        from cyclonus_tpu.worker.model import Delta, FlowQuery, Verdict

        monkeypatch.setattr(contracts, "CHECK", True)
        with pytest.raises(contracts.ContractViolation):
            Delta.from_dict({"Kind": "pod_add", "Namespace": "x",
                             "Labels": ["not", "a", "dict"]})
        with pytest.raises(contracts.ContractViolation):
            FlowQuery.from_dict({"Src": "x/a", "Dst": "x/b",
                                 "Port": "eighty", "Protocol": "TCP"})
        with pytest.raises(contracts.ContractViolation):
            Verdict.from_dict({"Query": {}, "Ingress": "yes",
                               "Egress": False, "Combined": False})
        # emit side: a required key missing fails the full check
        with pytest.raises(contracts.ContractViolation):
            contracts.check_wire("Delta", {"Namespace": "x"}, Delta.WIRE)

    def test_wire_contract_statically_linted(self):
        """wirelint's emit/read-side checks run over worker/ + serve/
        in `make lint`; assert the wire surfaces stay clean here too so
        a local edit can't land between lint runs.  (shapelint no
        longer extracts the WIRE tables — they are registry projections
        now, not literals — so the wire-protocol lint leg is wirelint.)
        """
        import os
        import sys as _sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        _sys.path.insert(0, os.path.join(repo, "tools"))
        import wirelint

        findings, stats = wirelint.lint_paths(
            [os.path.join(repo, "cyclonus_tpu", p)
             for p in ("worker", "serve")]
        )
        assert findings == [], "\n".join(f.render() for f in findings)
        assert stats["messages"] >= 7, stats
        assert stats["keys"] >= 30, stats

    def test_registry_delta_kinds_all_on_the_wire(self):
        """Every delta Kind the state registry declares (and that
        statelint's ST005 lifecycle check walks) must be a member of
        the wire model's Delta.KINDS — and vice versa, so a Kind added
        to the wire cannot ship without a registry lifecycle row.  This
        is the wire-side twin of statelint's registry-vs-model check:
        it fails in plain pytest even when the lint legs don't run."""
        from cyclonus_tpu.serve import stateregistry
        from cyclonus_tpu.worker.model import Delta

        registry_kinds = set(stateregistry.delta_kinds())
        wire_kinds = set(Delta.KINDS)
        missing_on_wire = registry_kinds - wire_kinds
        assert not missing_on_wire, (
            f"registry declares kinds absent from Delta.KINDS: "
            f"{sorted(missing_on_wire)}"
        )
        unregistered = wire_kinds - registry_kinds
        assert not unregistered, (
            f"Delta.KINDS carries kinds with no stateregistry "
            f"lifecycle row: {sorted(unregistered)}"
        )
        # the registry is the union of its per-field kind tuples
        per_field = {k for f in stateregistry.FIELDS for k in f.kinds}
        assert per_field == registry_kinds


class _FakeProc:
    def __init__(self, returncode=0, stdout="CONNECTED", stderr=""):
        self.returncode = returncode
        self.stdout = stdout
        self.stderr = stderr


class TestWorkerLoop:
    def test_run_worker_success(self, monkeypatch):
        monkeypatch.setattr(
            worker_mod.subprocess, "run", lambda *a, **k: _FakeProc()
        )
        out = run_worker(make_batch().to_json())
        parsed = [Result.from_dict(d) for d in json.loads(out)]
        assert len(parsed) == 2 and all(r.is_success() for r in parsed)

    def test_run_worker_failure_records_error(self, monkeypatch):
        monkeypatch.setattr(
            worker_mod.subprocess,
            "run",
            lambda *a, **k: _FakeProc(returncode=1, stderr="REFUSED"),
        )
        results = issue_batch(make_batch(1))
        assert results[0].error == "REFUSED"

    def test_run_worker_timeout_records_error(self, monkeypatch):
        def boom(*a, **k):
            raise subprocess.TimeoutExpired(cmd=a[0], timeout=5)

        monkeypatch.setattr(worker_mod.subprocess, "run", boom)
        results = issue_batch(make_batch(1))
        assert results[0].error == "timeout"

    def test_empty_batch(self):
        assert issue_batch(Batch(namespace="x", pod="a", container="c")) == []


class _StubKube:
    """IKubernetes stub: returns a canned exec result."""

    def __init__(self, stdout="", stderr="", err=None):
        self._ret = (stdout, stderr, err)
        self.calls = []

    def execute_remote_command(self, namespace, pod, container, command):
        self.calls.append((namespace, pod, container, command))
        return self._ret


class TestClient:
    def test_batch_roundtrip(self):
        batch = make_batch(1)
        results = [Result(request=batch.requests[0], output="ok")]
        kube = _StubKube(stdout=json.dumps([r.to_dict() for r in results]))
        got = Client(kube).batch(batch)
        assert got == results
        # the exec'd command is the in-pod worker invocation
        (_, _, _, command), = kube.calls
        assert command[0] == "/worker" and command[1] == "--jobs"
        assert Batch.from_json(command[2]) == batch

    def test_batch_exec_error(self):
        kube = _StubKube(err=KubeError("exec failed"))
        with pytest.raises(KubeError):
            Client(kube).batch(make_batch(1))

    def test_batch_bad_json(self):
        kube = _StubKube(stdout="not-json{")
        with pytest.raises(KubeError):
            Client(kube).batch(make_batch(1))


class TestCLI:
    def test_main_empty_batch(self):
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "cyclonus_tpu.worker",
                "--jobs",
                '{"Namespace":"x","Pod":"a","Container":"c","Requests":[]}',
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0
        assert json.loads(proc.stdout) == []
