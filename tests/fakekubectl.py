"""A recording fake kubectl for PATH-shim tests (shared by
test_kubectl.py and the kube-sourced CLI tests).

Each invocation appends {argv, stdin} to calls.jsonl and pops the next
canned {rc, stdout, stderr} response from a queue of resp_NNNN.json
files — tests enqueue responses in call order and assert the recorded
argv afterward."""

import json
import stat
import sys
from pathlib import Path

FAKE_KUBECTL = """#!{python}
import json, os, sys
root = {root!r}
calls = os.path.join(root, "calls.jsonl")
with open(calls, "a") as f:
    f.write(json.dumps({{"argv": sys.argv[1:], "stdin": sys.stdin.read()
                        if not sys.stdin.isatty() else ""}}) + "\\n")
queue = sorted(p for p in os.listdir(root) if p.startswith("resp_"))
if not queue:
    sys.stderr.write("fake kubectl: no canned response left")
    sys.exit(9)
path = os.path.join(root, queue[0])
with open(path) as f:
    resp = json.load(f)
os.unlink(path)
sys.stdout.write(resp.get("stdout", ""))
sys.stderr.write(resp.get("stderr", ""))
sys.exit(resp.get("rc", 0))
"""


class FakeKubectl:
    """Manages the PATH shim: enqueue responses, read back recorded calls."""

    def __init__(self, root: Path):
        self.root = root
        self._n = 0
        shim = root / "kubectl"
        shim.write_text(
            FAKE_KUBECTL.format(python=sys.executable, root=str(root))
        )
        shim.chmod(shim.stat().st_mode | stat.S_IEXEC)

    def enqueue(self, stdout="", rc=0, stderr=""):
        if not isinstance(stdout, str):
            stdout = json.dumps(stdout)
        (self.root / f"resp_{self._n:04d}.json").write_text(
            json.dumps({"stdout": stdout, "rc": rc, "stderr": stderr})
        )
        self._n += 1

    def calls(self):
        path = self.root / "calls.jsonl"
        if not path.exists():
            return []
        return [
            json.loads(line)
            for line in path.read_text().splitlines()
            if line.strip()
        ]

    def last(self):
        return self.calls()[-1]


def pod_json(ns="x", name="a", labels=None, phase="Running", ip="10.0.0.9"):
    """A minimal kubectl-shaped pod object with one agnhost-like container."""
    return {
        "metadata": {"namespace": ns, "name": name, "labels": labels or {"pod": name}},
        "spec": {
            "containers": [
                {
                    "name": "cont-80-tcp",
                    "image": "img",
                    "ports": [
                        {"containerPort": 80, "name": "serve-80-tcp", "protocol": "TCP"}
                    ],
                }
            ]
        },
        "status": {"phase": phase, "podIP": ip},
    }
