"""Serve slab-headroom pre-reservation (ROADMAP 1b) and the
zero-recompile elastic-resize contract at the service level.

The serve path builds its engine with CYCLONUS_SERVE_HEADROOM (default
1) extra rule-slab buckets, so a policy upsert that crosses the natural
bucket boundary pads into the reservation and stays on the INCREMENTAL
patch path — counted in cyclonus_tpu_serve_headroom_saves_total — where
a zero-headroom engine must fall back to a full rebuild.  Pod churn
within the bucketed pod axis must never retrace the query path's
compiled programs."""

import random

import pytest

from cyclonus_tpu.kube.netpol import (
    IntOrString,
    LabelSelector,
    NetworkPolicy,
    NetworkPolicyIngressRule,
    NetworkPolicyPeer,
    NetworkPolicyPort,
    NetworkPolicySpec,
)
from cyclonus_tpu.kube.yaml_io import policy_to_dict
from cyclonus_tpu.serve import VerdictService
from cyclonus_tpu.telemetry import instruments as ti
from cyclonus_tpu.worker.model import Delta, FlowQuery


def mkpol(i):
    """One ingress policy contributing exactly one distinct target and
    one distinct peer row (no partition-compression merging)."""
    return NetworkPolicy(
        name=f"p{i}",
        namespace="x",
        spec=NetworkPolicySpec(
            pod_selector=LabelSelector.make({"app": f"app{i}"}),
            policy_types=["Ingress"],
            ingress=[
                NetworkPolicyIngressRule(
                    ports=[
                        NetworkPolicyPort(
                            protocol="TCP", port=IntOrString(80)
                        )
                    ],
                    from_=[
                        NetworkPolicyPeer(
                            pod_selector=LabelSelector.make(
                                {"tier": f"tier{i}"}
                            )
                        )
                    ],
                )
            ],
        ),
    )


def boundary_cluster():
    """12 pods + 15 policies: the ingress target axis sits exactly at a
    bucket boundary (_bucket_dim(16) - 1 = 15 rows), so one more policy
    crosses it."""
    pods = [
        (
            "x",
            f"pod-{i}",
            {"app": f"app{i % 20}", "tier": f"tier{i % 20}"},
            f"10.0.0.{i + 1}",
        )
        for i in range(12)
    ]
    namespaces = {"x": {"ns": "x"}}
    return pods, namespaces, [mkpol(i) for i in range(15)]


def upsert(i):
    return Delta(
        kind="policy_upsert",
        namespace="x",
        name=f"p{i}",
        policy=policy_to_dict(mkpol(i)),
    )


class TestServeHeadroom:
    def test_bucket_boundary_upsert_stays_incremental(self, monkeypatch):
        """With the default headroom (1), a +1-rule upsert at the
        bucket boundary patches the live buffer (no full rebuild), the
        saves counter increments, and the patched engine stays
        bit-identical to a fresh rebuild."""
        monkeypatch.delenv("CYCLONUS_SERVE_HEADROOM", raising=False)
        pods, namespaces, policies = boundary_cluster()
        svc = VerdictService(pods, namespaces, policies)
        # the reservation is real: one extra bucket on the target axis
        assert (
            svc.engine._tensors["ingress"]["target_ns"].shape[0] == 31
        )  # _bucket_up(16, 1) - 1
        saves0 = ti.SERVE_HEADROOM_SAVES.value()
        report = svc.apply([upsert(15)])
        assert report["mode"] in ("incremental", "class_rebuild"), report
        assert ti.SERVE_HEADROOM_SAVES.value() - saves0 == 1
        # differential: patched engine == fresh rebuild == oracle
        svc.verify_parity(oracle_samples=8)
        # the new policy actually enforces: app15 pods only admit tier15
        keys = list(svc.pods)
        verdicts = svc.query(
            [
                FlowQuery(
                    src="x/pod-0",
                    dst="x/pod-15" if "x/pod-15" in svc.pods else keys[0],
                    port=80,
                    protocol="TCP",
                    port_name="serve-80-tcp",
                )
            ]
        )
        assert verdicts and verdicts[0].error == ""
        # the save counts ONCE: a later within-bucket change at the
        # already-grown size is not another rebuild avoided (the
        # counterfactual zero-headroom engine would have rebuilt once
        # and then fit) — the counter must not move again
        changed = mkpol(3)
        changed.spec.ingress[0].from_[0] = NetworkPolicyPeer(
            pod_selector=LabelSelector.make({"tier": "tier9"})
        )
        report2 = svc.apply(
            [
                Delta(
                    kind="policy_upsert",
                    namespace="x",
                    name="p3",
                    policy=policy_to_dict(changed),
                )
            ]
        )
        assert report2["mode"] in ("incremental", "class_rebuild"), report2
        assert ti.SERVE_HEADROOM_SAVES.value() - saves0 == 1
        svc.verify_parity(oracle_samples=8)

    def test_without_headroom_falls_back_to_rebuild(self, monkeypatch):
        """CYCLONUS_SERVE_HEADROOM=0 restores exact-fit buckets: the
        same boundary upsert is Ineligible and takes the full-rebuild
        fallback (still correct, just not incremental)."""
        monkeypatch.setenv("CYCLONUS_SERVE_HEADROOM", "0")
        pods, namespaces, policies = boundary_cluster()
        svc = VerdictService(pods, namespaces, policies)
        assert svc.engine._tensors["ingress"]["target_ns"].shape[0] == 15
        saves0 = ti.SERVE_HEADROOM_SAVES.value()
        report = svc.apply([upsert(15)])
        assert report["mode"] == "full", report
        assert ti.SERVE_HEADROOM_SAVES.value() == saves0
        svc.verify_parity(oracle_samples=8)

    def test_within_bucket_upsert_counts_no_save(self, monkeypatch):
        """A policy CHANGE that stays inside the natural bucket patches
        incrementally without touching the saves counter — the counter
        records only rebuilds the reservation avoided."""
        monkeypatch.delenv("CYCLONUS_SERVE_HEADROOM", raising=False)
        pods, namespaces, policies = boundary_cluster()
        svc = VerdictService(pods, namespaces, policies)
        saves0 = ti.SERVE_HEADROOM_SAVES.value()
        changed = NetworkPolicy(
            name="p0",
            namespace="x",
            spec=NetworkPolicySpec(
                pod_selector=LabelSelector.make({"app": "app0"}),
                policy_types=["Ingress"],
                ingress=[
                    NetworkPolicyIngressRule(
                        ports=[
                            NetworkPolicyPort(
                                protocol="UDP", port=IntOrString(81)
                            )
                        ],
                        from_=[
                            NetworkPolicyPeer(
                                pod_selector=LabelSelector.make(
                                    {"tier": "tier3"}
                                )
                            )
                        ],
                    )
                ],
            ),
        )
        report = svc.apply(
            [
                Delta(
                    kind="policy_upsert",
                    namespace="x",
                    name="p0",
                    policy=policy_to_dict(changed),
                )
            ]
        )
        assert report["mode"] in ("incremental", "class_rebuild"), report
        assert ti.SERVE_HEADROOM_SAVES.value() == saves0
        svc.verify_parity(oracle_samples=8)


class TestServeElasticResize:
    def test_pod_resize_within_bucket_zero_retrace(self):
        """±10% pod churn inside the bucketed pod axis: every apply
        stays incremental (no re-encode, no re-device_put) and the
        query path's compiled pair program is reused — the serve-level
        zero-recompile resize contract."""
        from cyclonus_tpu import telemetry
        from cyclonus_tpu.engine.tiled import evaluate_pairs_kernel

        rng = random.Random(5)
        n = 56  # buckets to 64: room for the +10% growth below
        pods = [
            (
                "x",
                f"pod-{i}",
                {"app": f"app{i % 5}", "tier": f"tier{i % 3}"},
                f"10.0.1.{i + 1}",
            )
            for i in range(n)
        ]
        namespaces = {"x": {"ns": "x"}}
        svc = VerdictService(pods, namespaces, [mkpol(i) for i in range(4)])
        warm = FlowQuery(
            src="x/pod-0", dst="x/pod-1", port=80, protocol="TCP",
            port_name="serve-80-tcp",
        )
        svc.query([warm])
        traces0 = evaluate_pairs_kernel._cache_size()
        spans = telemetry.SPANS.stats()
        encodes0 = spans.get("engine.encode", {}).get("count", 0)
        puts0 = spans.get("engine.device_put", {}).get("count", 0)
        # grow ~10%, then shrink back — all inside the 64-row bucket
        for i in range(6):
            report = svc.apply(
                [
                    Delta(
                        kind="pod_add",
                        namespace="x",
                        name=f"extra-{i}",
                        labels={"app": f"app{rng.randrange(5)}"},
                        ip=f"10.0.2.{i + 1}",
                    )
                ]
            )
            assert report["mode"] in ("incremental", "class_rebuild")
        for i in range(6):
            report = svc.apply(
                [Delta(kind="pod_remove", namespace="x", name=f"extra-{i}")]
            )
            assert report["mode"] in ("incremental", "class_rebuild")
        svc.query([warm])
        assert evaluate_pairs_kernel._cache_size() == traces0
        spans = telemetry.SPANS.stats()
        assert spans.get("engine.encode", {}).get("count", 0) == encodes0
        assert spans.get("engine.device_put", {}).get("count", 0) == puts0
        svc.verify_parity(oracle_samples=8)
