"""TSS/LPM CIDR pre-classification (docs/DESIGN.md "CIDR tuple-space
pre-classification"; engine/cidrspace.py).

Five layers of proof:

  * PARTITIONS: the tuple-space builder — masks in LPM (longest prefix
    first) order, bases sorted per bucket, atom dedup across primaries
    and excepts, and the _mask_for_prefix(0) / /32 boundary pins.
  * TWINS: the numpy LPM walk and the device kernel
    (kernel.lpm_partition_signature) are BIT-IDENTICAL, including the
    0.0.0.0 / 255.255.255.255 / invalid-IP edges, and the partition
    signature mechanically reproduces the dense per-spec membership
    bits (spec_membership_words — the soundness bridge).
  * ROUTING: host-evaluated (IPv6 / mixed-family) rows never reach the
    trie — they keep their per-pod match columns, pinned against the
    scalar oracle.
  * PARITY: dense == class-compressed(bit signature) ==
    class-compressed(forced TSS) == scalar oracle across the
    adversarial CIDR fuzz family, grid + counts + the overlapped mesh
    path (tiers/fuzz.run_cidr_seed — the same gate `make parity-cidr`
    and `make fuzz` run).
  * GATING/SERVE: CYCLONUS_CIDR_TSS=0 restores byte-identical
    signatures, auto mode respects the distinct-spec floor and the HBM
    budget (aux accounting included), and a serve policy delta that
    changes the partition mask structure goes Ineligible -> full
    rebuild instead of patching over a stale map.
"""

import random

import numpy as np
import pytest

from cyclonus_tpu.engine import PortCase, TpuPolicyEngine
from cyclonus_tpu.engine import cidrspace
from cyclonus_tpu.engine.encoding import (
    _mask_for_prefix,
    pack_bool_words,
    pod_signatures,
)
from cyclonus_tpu.telemetry import instruments as ti
from cyclonus_tpu.kube.netpol import (
    IPBlock,
    LabelSelector,
    NetworkPolicy,
    NetworkPolicyEgressRule,
    NetworkPolicyIngressRule,
    NetworkPolicyPeer,
    NetworkPolicySpec,
)
from cyclonus_tpu.matcher import build_network_policies

CASES = [
    PortCase(80, "serve-80-tcp", "TCP"),
    PortCase(81, "serve-81-udp", "UDP"),
]


def mk_np(name, ns, blocks, selector=None, ingress=True, egress=True):
    peers = [NetworkPolicyPeer(ip_block=b) for b in blocks]
    spec = NetworkPolicySpec(
        pod_selector=selector or LabelSelector.make(),
        policy_types=(["Ingress"] if ingress else [])
        + (["Egress"] if egress else []),
    )
    if ingress:
        spec.ingress = [NetworkPolicyIngressRule(ports=[], from_=peers)]
    if egress:
        spec.egress = [NetworkPolicyEgressRule(ports=[], to=peers)]
    return NetworkPolicy(name=name, namespace=ns, spec=spec)


def mk_cluster(ips):
    namespaces = {"x": {"ns": "x"}}
    pods = [
        ("x", f"p{i}", {"app": f"a{i % 2}"}, ip) for i, ip in enumerate(ips)
    ]
    return pods, namespaces


def build_engine(blocks, ips, **kw):
    pods, namespaces = mk_cluster(ips)
    policy = build_network_policies(True, [mk_np("np0", "x", blocks)])
    return TpuPolicyEngine(policy, pods, namespaces, **kw), policy, pods, namespaces


class TestPartitions:
    def test_mask_for_prefix_boundaries(self):
        # the /0 full cover and the /32 exact match are the two mask
        # boundary values the partition builder leans on
        assert _mask_for_prefix(0) == 0
        assert _mask_for_prefix(32) == 0xFFFFFFFF
        assert _mask_for_prefix(31) == 0xFFFFFFFE
        assert _mask_for_prefix(8) == 0xFF000000

    def _space(self, blocks, ips=("10.0.1.1",)):
        engine, *_ = build_engine(
            blocks, list(ips), class_compress="1", cidr_tss="1"
        )
        st = engine._class_state
        return st.get("cidr") if st is not None else None, engine

    def test_partitions_lpm_order_and_dedup(self):
        space, _ = self._space([
            IPBlock.make("10.0.0.0/8", ["10.0.1.0/24"]),
            IPBlock.make("10.0.1.0/24", []),   # dup atom with the except
            IPBlock.make("0.0.0.0/0", []),
            IPBlock.make("10.0.1.7/32", []),
        ])
        assert space is not None
        # masks longest-prefix-first: /32, /24, /8, /0
        assert list(space.pprefix) == [32, 24, 8, 0]
        assert space.pmask[-1] == 0  # the /0 partition
        # 10.0.1.0/24 appears as a primary AND an except: one atom
        assert space.n_atoms == 4
        assert space.n_specs == 4
        # bucket rows sorted ascending with -1-index pads
        for k in range(space.n_partitions):
            row = space.pbases[k]
            real = row[space.pindex[k] >= 0]
            assert np.all(np.diff(real.astype(np.int64)) > 0) or real.size <= 1

    def test_annihilation_and_full_cover(self):
        # except == cidr annihilation: membership empty; /0 matches all
        blocks = [
            IPBlock.make("10.0.1.0/24", ["10.0.1.0/24"]),
            IPBlock.make("0.0.0.0/0", []),
        ]
        space, engine = self._space(blocks, ips=("10.0.1.9", "9.9.9.9"))
        t = engine._tensors
        sig = space.signature_host(t["pod_ip"][:2], t["pod_ip_valid"][:2])
        ann = [
            s
            for s, (p, exs) in enumerate(space.spec_atoms)
            if exs and p in exs  # primary annihilated by its own except
        ]
        full = [
            s
            for s, (p, exs) in enumerate(space.spec_atoms)
            if not exs and space.atom_mask[p] == 0
        ]
        assert ann and full
        valid = t["pod_ip_valid"][:2]
        ip = t["pod_ip"][:2]
        dense = cidrspace.dense_spec_membership(space, ip, valid)
        assert not dense[ann[0]].any()  # annihilated
        assert dense[full[0]].all()  # /0 covers every valid pod
        assert np.array_equal(
            cidrspace.spec_membership_words(space, sig),
            pack_bool_words(dense, axis=0),
        )


class TestSignatureTwins:
    def _random_space(self, seed):
        rng = random.Random(seed)
        blocks = []
        for _ in range(rng.randint(4, 10)):
            p = rng.choice((0, 8, 12, 16, 24, 31, 32, 32))
            base = (
                f"{rng.randrange(256)}.{rng.randrange(256)}"
                f".{rng.randrange(256)}.{rng.randrange(256)}"
            )
            exs = []
            if p <= 24 and rng.random() < 0.5:
                exs = [f"{base.rsplit('.', 1)[0]}.0/{rng.choice((31, 32))}"]
            blocks.append(IPBlock.make(f"{base}/{p}", exs))
        ips = ["0.0.0.0", "255.255.255.255"] + [
            f"{rng.randrange(256)}.{rng.randrange(256)}"
            f".{rng.randrange(256)}.{rng.randrange(256)}"
            for _ in range(10)
        ] + ["fd00::1"]  # one invalid-v4 (v6) pod: pod_ip_valid False
        engine, *_ = build_engine(
            blocks, ips, class_compress="1", cidr_tss="1"
        )
        st = engine._class_state
        return (st.get("cidr") if st else None), engine

    @pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
    def test_host_device_bit_identity(self, seed):
        space, engine = self._random_space(seed)
        if space is None:
            pytest.skip("seed generated no in-kernel v4 atoms")
        t = engine._tensors
        n = engine.encoding.cluster.n_pods
        ip, valid = t["pod_ip"][:n], t["pod_ip_valid"][:n]
        host = space.signature_host(ip, valid)
        dev = space.signature(ip, valid, device=True)
        assert host.dtype == np.int32 and dev.dtype == np.int32
        assert np.array_equal(host, dev)
        assert space.last_device is True

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_signature_reproduces_dense_membership(self, seed):
        """The soundness bridge: per-spec membership recovered from the
        partition signature == the dense mask-compare membership."""
        space, engine = self._random_space(seed)
        if space is None:
            pytest.skip("seed generated no in-kernel v4 atoms")
        t = engine._tensors
        n = engine.encoding.cluster.n_pods
        ip, valid = t["pod_ip"][:n], t["pod_ip_valid"][:n]
        sig = space.signature_host(ip, valid)
        dense = cidrspace.dense_spec_membership(space, ip, valid)
        assert np.array_equal(
            cidrspace.spec_membership_words(space, sig),
            pack_bool_words(dense, axis=0),
        )

    def test_invalid_ip_signs_minus_one(self):
        space, engine = self._random_space(0)
        if space is None:
            pytest.skip("seed generated no in-kernel v4 atoms")
        sig = space.signature_host(
            np.array([0], dtype=np.uint32), np.array([False])
        )
        assert (sig == -1).all()

    def test_max_base_vs_pad_tie(self):
        """A REAL 255.255.255.255/32 base ties the 0xFFFFFFFF bucket pad;
        reals sort first, so the leftmost search still resolves it."""
        engine, *_ = build_engine(
            [IPBlock.make("255.255.255.255/32", [])],
            ["255.255.255.255", "255.255.255.254"],
            class_compress="1",
            cidr_tss="1",
        )
        space = engine._class_state["cidr"]
        sig = space.signature_host(
            np.array([0xFFFFFFFF, 0xFFFFFFFE], dtype=np.uint32),
            np.array([True, True]),
        )
        assert sig[0, 0] >= 0  # the real /32 hit
        assert sig[0, 1] == -1


class TestHostRowRouting:
    def test_v6_rows_never_reach_the_trie(self):
        """IPv6 CIDRs and v4-with-v6-except rows route to the host
        column path; the trie sees only the clean v4 rows."""
        blocks = [
            IPBlock.make("fd00::/64", []),
            IPBlock.make("10.0.0.0/16", ["fd00::/96"]),  # mixed family
            IPBlock.make("10.0.1.0/24", []),
        ]
        engine, policy, pods, namespaces = build_engine(
            blocks,
            ["10.0.1.5", "10.0.2.5", "fd00::5"],
            class_compress="1",
            cidr_tss="1",
        )
        enc = engine.encoding
        assert len(enc.ingress.host_ip_rows) == 2  # v6 + mixed
        space = engine._class_state["cidr"]
        assert space is not None
        # only the clean /24 contributes an atom
        assert space.n_atoms == 1
        assert space.n_host_rows >= 2
        # verdict parity against the oracle on the full table
        from cyclonus_tpu.tiers.fuzz import _oracle_table, _engine_table

        want = _oracle_table(policy, None, pods, namespaces, CASES)
        got = _engine_table(engine, CASES)
        assert np.array_equal(got, want)

    def test_host_ip_mask_boundary_vs_oracle(self):
        """The host_ip_mask columns (v6 rows) pinned against the oracle
        with v6 pods on both sides of the block."""
        blocks = [IPBlock.make("fd00:aa::/32", [])]
        engine, policy, pods, namespaces = build_engine(
            blocks,
            ["fd00:aa::1", "fd00:bb::1", "10.0.0.1"],
            class_compress="1",
            cidr_tss="1",
        )
        from cyclonus_tpu.tiers.fuzz import _oracle_table, _engine_table

        want = _oracle_table(policy, None, pods, namespaces, CASES)
        got = _engine_table(engine, CASES)
        assert np.array_equal(got, want)


class TestParity:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_cidr_fuzz_family(self, seed):
        """dense == compressed(bit) == compressed(TSS) == oracle, grid +
        counts + the overlapped mesh leg — the `make parity-cidr` gate."""
        from cyclonus_tpu.tiers.fuzz import run_cidr_seed

        r = run_cidr_seed(seed, check_mesh=True, check_counts=True)
        assert r["cells"] > 0

    def test_forced_tss_matches_bit_classes_verdicts(self):
        """TSS classes may be FINER than bit-signature classes (except-
        only atoms split pods) but verdicts are identical."""
        blocks = [
            IPBlock.make("10.0.0.0/8", ["10.0.1.0/24", "10.0.2.0/24"]),
            IPBlock.make("10.0.1.0/24", []),
        ]
        ips = [f"10.0.{i % 4}.{i + 1}" for i in range(12)]
        e_bit, policy, pods, namespaces = build_engine(
            blocks, ips, class_compress="1", cidr_tss="0"
        )
        e_tss, *_ = build_engine(
            blocks, ips, class_compress="1", cidr_tss="1"
        )
        assert e_tss._class_state["cidr"] is not None
        assert e_bit._class_state.get("cidr") is None
        assert e_tss.pod_classes().n_classes >= e_bit.pod_classes().n_classes
        for name in ("ingress", "egress", "combined"):
            a = np.asarray(getattr(e_bit.evaluate_grid(CASES), name))
            b = np.asarray(getattr(e_tss.evaluate_grid(CASES), name))
            assert np.array_equal(a, b), name


class TestGating:
    BLOCKS = [
        IPBlock.make("10.0.0.0/8", []),
        IPBlock.make("10.0.1.0/24", []),
        IPBlock.make("10.0.1.7/32", []),
    ]
    IPS = ["10.0.1.7", "10.0.1.8", "10.0.2.1", "11.0.0.1"]

    def test_off_is_byte_identical(self, monkeypatch):
        """CYCLONUS_CIDR_TSS=0 restores today's signature bytes exactly
        (the acceptance criterion's kill switch)."""
        monkeypatch.setenv("CYCLONUS_CIDR_TSS", "0")
        e_off, *_ = build_engine(self.BLOCKS, self.IPS, class_compress="1")
        monkeypatch.delenv("CYCLONUS_CIDR_TSS")
        e_env, *_ = build_engine(self.BLOCKS, self.IPS, class_compress="1")
        # 3 distinct specs < the 256 auto floor: auto stays on bits too
        assert e_env._class_state.get("cidr") is None
        t_off = e_off._tensors
        t_env = e_env._tensors
        n = e_off.encoding.cluster.n_pods
        sel_off = np.zeros((0, n), bool)
        view_off = {
            k: t_off[k][:n]
            for k in ("pod_ns_id", "pod_ip", "pod_ip_valid")
        }
        view_off["ingress"] = t_off["ingress"]
        view_off["egress"] = t_off["egress"]
        view_env = {
            k: t_env[k][:n]
            for k in ("pod_ns_id", "pod_ip", "pod_ip_valid")
        }
        view_env["ingress"] = t_env["ingress"]
        view_env["egress"] = t_env["egress"]
        s_off = pod_signatures(view_off, sel_off, cidr=None)
        s_env = pod_signatures(view_env, sel_off, cidr=None)
        assert np.array_equal(s_off, s_env)
        assert (
            e_off.pod_classes().n_classes == e_env.pod_classes().n_classes
        )

    def test_auto_floor_and_force(self, monkeypatch):
        monkeypatch.setenv("CYCLONUS_CIDR_TSS", "auto")
        pods, namespaces = mk_cluster(self.IPS)
        policy = build_network_policies(
            True, [mk_np("np0", "x", self.BLOCKS)]
        )
        e_auto = TpuPolicyEngine(policy, pods, namespaces, class_compress="1")
        assert e_auto._class_state.get("cidr") is None  # under the floor
        monkeypatch.setenv("CYCLONUS_CIDR_TSS_MIN", "1")
        e_low = TpuPolicyEngine(policy, pods, namespaces, class_compress="1")
        assert e_low._class_state.get("cidr") is not None
        assert not e_low.cidr_stats()["device"]  # small: numpy twin ran

    def test_budget_fallback(self, monkeypatch):
        """Partition tensors past CYCLONUS_SLAB_MAX_BYTES degrade to the
        dense bit path (never over-commit), verdicts unchanged."""
        monkeypatch.setenv("CYCLONUS_SLAB_MAX_BYTES", "64")
        e, policy, pods, namespaces = build_engine(
            self.BLOCKS, self.IPS, class_compress="1", cidr_tss="1"
        )
        assert e._class_state.get("cidr") is None
        assert not e.cidr_stats()["active"]
        monkeypatch.delenv("CYCLONUS_SLAB_MAX_BYTES")
        e2, *_ = build_engine(
            self.BLOCKS, self.IPS, class_compress="1", cidr_tss="1"
        )
        for name in ("ingress", "egress", "combined"):
            assert np.array_equal(
                np.asarray(getattr(e.evaluate_grid(CASES), name)),
                np.asarray(getattr(e2.evaluate_grid(CASES), name)),
            )

    def test_aux_bytes_counts_partition_tensors(self):
        e_tss, *_ = build_engine(
            self.BLOCKS, self.IPS, class_compress="1", cidr_tss="1"
        )
        e_bit, *_ = build_engine(
            self.BLOCKS, self.IPS, class_compress="1", cidr_tss="0"
        )
        space = e_tss._class_state["cidr"]
        assert space is not None
        assert space.nbytes() > 0
        assert e_tss.cidr_stats()["bytes"] == space.nbytes()
        # the TSS engine charges the partition tensors on top of its
        # class tensors (class sets differ slightly, so compare against
        # its OWN ctensors sum, not the bit engine's)
        st = e_tss._class_state
        from cyclonus_tpu.engine.api import _np_leaves

        base = int(
            e_tss.encoding.cluster.n_pods * 4
            + st["ctensors"]["pod_ns_id"].shape[0] * 4
            + sum(a.nbytes for a in _np_leaves(st["ctensors"]))
        )
        assert st["aux_bytes"] == base + space.nbytes()

    def test_bad_mode_raises(self):
        with pytest.raises(ValueError):
            cidrspace.tss_mode("bogus")


class TestServeDeltas:
    def _service(self, monkeypatch, blocks):
        from cyclonus_tpu.serve import VerdictService

        monkeypatch.setenv("CYCLONUS_CIDR_TSS", "1")
        pods, namespaces = mk_cluster(
            ["10.0.1.5", "10.0.1.6", "10.0.2.7", "10.0.3.8"]
        )
        policies = [mk_np("np0", "x", blocks)]
        svc = VerdictService(
            pods, namespaces, policies, class_compress="1"
        )
        assert svc.engine._class_state.get("cidr") is not None
        return svc

    def _upsert(self, blocks):
        from cyclonus_tpu.kube.yaml_io import policy_to_dict
        from cyclonus_tpu.worker.model import Delta

        pol = mk_np("np0", "x", blocks)
        return Delta(
            kind="policy_upsert",
            namespace="x",
            name="np0",
            policy=policy_to_dict(pol),
        )

    BASE_BLOCKS = [
        IPBlock.make("10.0.0.0/8", ["10.0.9.0/24"]),
        IPBlock.make("10.0.1.0/24", []),
    ]

    def test_same_structure_delta_stays_incremental(self, monkeypatch):
        svc = self._service(monkeypatch, self.BASE_BLOCKS)
        # swap one /24 for another: same mask structure (/8, /24), new
        # atom — patchable; the class state rebuilds with a fresh map
        report = svc.apply([
            self._upsert([
                IPBlock.make("10.0.0.0/8", ["10.0.9.0/24"]),
                IPBlock.make("10.0.2.0/24", []),
            ])
        ])
        assert report["mode"] in ("incremental", "class_rebuild")
        svc.verify_parity(CASES)
        space = svc.engine._class_state["cidr"]
        assert space is not None
        # the new /24 atom is in the refreshed map
        assert int(space.n_atoms) == 3

    def test_new_mask_structure_forces_full_rebuild(self, monkeypatch):
        svc = self._service(monkeypatch, self.BASE_BLOCKS)
        before = int(ti.SERVE_FALLBACKS.value(reason="ineligible"))
        # a /28 appears: new partition -> signature layout change ->
        # Ineligible -> full rebuild, never a patched-over stale map
        report = svc.apply([
            self._upsert([
                IPBlock.make("10.0.0.0/8", ["10.0.9.0/24"]),
                IPBlock.make("10.0.1.0/24", []),
                IPBlock.make("10.0.1.16/28", []),
            ])
        ])
        assert report["mode"] == "full"
        assert int(ti.SERVE_FALLBACKS.value(reason="ineligible")) > before
        svc.verify_parity(CASES)
        space = svc.engine._class_state["cidr"]
        assert space is not None and 28 in list(space.pprefix)

    def test_empty_cluster_rebuild_survives(self, monkeypatch):
        """Removing every pod under TSS-active class state must keep
        rebuilding (n=0 signature matrix) — the zero-size reshape in
        _ip_signature_tss regressed this once (review finding)."""
        from cyclonus_tpu.worker.model import Delta

        svc = self._service(monkeypatch, self.BASE_BLOCKS)
        for key in list(svc.pods):
            ns, name = key.split("/", 1)
            svc.apply([Delta(kind="pod_remove", namespace=ns, name=name)])
        assert not svc.pods
        report = svc.apply([
            Delta(kind="pod_add", namespace="x", name="fresh",
                  labels={"app": "a0"}, ip="10.0.9.50")
        ])
        assert report["applied"] == 1
        svc.verify_parity(CASES)

    def test_pod_delta_uses_cached_map(self, monkeypatch):
        svc = self._service(monkeypatch, self.BASE_BLOCKS)
        from cyclonus_tpu.worker.model import Delta

        report = svc.apply([
            Delta(
                kind="pod_add",  # existing key: an in-place pod update
                namespace="x",
                name="p0",
                labels={"app": "a1"},
                ip="10.0.9.77",  # moves INTO the except: membership flips
            )
        ])
        assert report["mode"] in ("incremental", "class_rebuild")
        svc.verify_parity(CASES)
