"""Tests for the perf observatory (cyclonus_tpu/perfobs/): ledger
ingestion + failure classification over the REAL round artifacts,
seeded regression/no-regression gate cases, round-trip, the Prometheus
exposition golden, and the CLI/Makefile wiring.

The five BENCH_r0*.json / MULTICHIP_r0*.json blobs in the repo root are
the acceptance fixtures: they must ingest UNCHANGED, r03/r04 must
classify as infra (backend_init/tunnel), and the r01->r05 trajectory
must pass the gate."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from cyclonus_tpu.perfobs import (  # noqa: E402
    Ledger,
    PerfRun,
    classify,
    gate,
    ingest_bench,
    ingest_multichip,
    load_ledger,
)
from cyclonus_tpu.perfobs import report as perf_report  # noqa: E402


# --- fixture builders ----------------------------------------------------


def healthy_line(
    value=100e9, warmup=5.0, encode=1.0, mesh_rows=None, virtual=True,
    serve=None, tiers=None, pack=None, roofline=None, cidr=None,
):
    detail = {
        "build_s": 0.5,
        "encode_s": encode,
        "backend_init_s": 0.1,
        "phase_history_s": [
            ["startup", 0.1],
            ["synthetic_build", 0.4],
            ["matcher_build", 0.5],
            ["encode", encode],
            ["backend_init_join", 0.1],
            ["warmup", warmup],
            ["eval", 1.0],
        ],
        "cold_start": {
            "attempts": 1,
            "backoff_s": 0.0,
            "backend_init_s": 0.1,
            "outcome": "ok",
        },
        "warmup_s": warmup,
        "warmup_phases": {"engine.dispatch": warmup * 0.4},
        "eval_s": 0.2,
        "telemetry": {
            "metrics": {
                "cyclonus_tpu_pre_cache_hits_total": {
                    "type": "counter",
                    "help": "h",
                    "samples": [{"labels": {}, "value": 4.0}],
                }
            }
        },
    }
    if mesh_rows is not None:
        detail["mesh_scaling"] = {
            "pods": 64,
            "virtual": virtual,
            "rows": mesh_rows,
        }
    if serve is not None:
        detail["serve"] = serve
    if tiers is not None:
        detail["tiers"] = tiers
    if cidr is not None:
        detail["cidr"] = cidr
    if pack is not None:
        detail["pack"] = pack
    if roofline is not None:
        detail["roofline"] = roofline
    return {
        "metric": "simulated connectivity cells/sec (bench)",
        "value": value,
        "unit": "cells/sec",
        "vs_baseline": value / 1e9,
        "failure_class": "ok",
        "detail": detail,
    }


def wrap(n, parsed, rc=0, tail=""):
    return {"n": n, "cmd": "python bench.py", "rc": rc,
            "tail": tail, "parsed": parsed}


def write_rounds(tmp_path, docs):
    for i, doc in enumerate(docs, start=1):
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(json.dumps(doc))
    return str(tmp_path)


R03_STYLE_TAIL = (
    "WARNING: Platform 'axon' is experimental\n"
    "/opt/venv/.../compiler.py:783: UserWarning: Error reading persistent "
    "compilation cache entry for 'jit__lambda': JaxRuntimeError: "
    "UNAVAILABLE: TPU backend setup/compile error (Unavailable).\n"
    "  warnings.warn(\n"
)


# --- classification over the REAL round artifacts ------------------------


class TestRealArtifacts:
    """The acceptance fixtures: the five committed BENCH/MULTICHIP blobs
    ingest unchanged and classify the way the rounds actually went."""

    def test_bench_rounds_classify(self):
        led = load_ledger(REPO)
        by_id = {r.run_id: r for r in led.bench_runs()}
        assert set(by_id) >= {"r01", "r02", "r03", "r04", "r05"}
        assert by_id["r01"].failure_class == "ok"
        assert by_id["r02"].failure_class == "ok"
        # r03 died on the backend/compile service answering Unavailable;
        # r04 timed out joining a tunnel that never answered — INFRA,
        # not engine regressions
        assert by_id["r03"].failure_class == "backend_init"
        assert by_id["r04"].failure_class == "tunnel"
        assert by_id["r03"].is_infra_failure
        assert by_id["r04"].is_infra_failure
        assert by_id["r05"].failure_class == "ok"
        assert by_id["r05"].cells_per_sec == 132717279525.0
        # r04 recorded the phase it died in
        assert list(by_id["r04"].phases)[-1] == "backend_init_join"

    def test_multichip_rounds_classify(self):
        led = load_ledger(REPO)
        by_id = {r.run_id: r for r in led.multichip_runs()}
        assert by_id["multichip_r03"].failure_class == "tunnel"
        assert by_id["multichip_r04"].failure_class == "ok"
        assert by_id["multichip_r05"].failure_class == "ok"
        # r01 was a real libtpu/code mismatch at device_put — backend
        assert by_id["multichip_r01"].failure_class == "backend_init"

    def test_gate_passes_on_real_trajectory(self):
        led = load_ledger(REPO)
        result = gate(led)
        assert result.status == "pass", result.report()
        assert result.exit_code == 0
        assert result.candidate == "r05"
        # the trajectory gated on rate and warmup with r01/r02 baselines
        metrics = {d.metric for d in result.deltas}
        assert "cells_per_sec" in metrics
        assert "warmup_s" in metrics


# --- ledger unit behavior ------------------------------------------------


class TestLedger:
    def test_classify_explicit_wins(self):
        assert classify({"failure_class": "tunnel", "value": 5}) == "tunnel"

    def test_classify_watchdog(self):
        assert (
            classify({"error": "watchdog: stalled 300s in phase 'warmup'"})
            == "watchdog_stall"
        )

    def test_truncated_json_is_failed_run(self, tmp_path):
        p = tmp_path / "BENCH_r01.json"
        p.write_text('{"n": 1, "rc": 2, "tail": "x", "par')
        run = ingest_bench(str(p))
        assert run.ok is False
        assert "unparseable JSON" in run.error
        assert run.failure_class == "engine"  # no infra evidence

    def test_r03_style_wrapper(self, tmp_path):
        p = tmp_path / "BENCH_r03.json"
        p.write_text(json.dumps(wrap(3, None, rc=124, tail=R03_STYLE_TAIL)))
        run = ingest_bench(str(p))
        assert run.failure_class == "backend_init"
        assert run.rc == 124
        # the quoted error is the signature line, not warnings.warn(
        assert "UNAVAILABLE" in run.error

    def test_silent_rc124_hang_is_tunnel(self, tmp_path):
        p = tmp_path / "BENCH_r09.json"
        p.write_text(json.dumps(wrap(9, None, rc=124, tail="WARNING: axon\n")))
        assert ingest_bench(str(p)).failure_class == "tunnel"

    def test_bare_tunnel_wait_artifact(self, tmp_path):
        doc = healthy_line(value=9e9)
        doc["bench_rc"] = 0
        doc["at"] = "2026-08-03T00:00:00"
        p = tmp_path / "bench_watchdog_latest.json"
        p.write_text(json.dumps(doc))
        run = ingest_bench(str(p))
        assert run.failure_class == "ok"
        assert run.cells_per_sec == 9e9
        assert run.run_id == "bench_watchdog_latest"

    def test_normalized_run_fields(self, tmp_path):
        root = write_rounds(tmp_path, [wrap(1, healthy_line())])
        run = load_ledger(root).bench_runs()[0]
        assert run.warmup_s == 5.0
        assert run.phases["encode"] == 1.0
        assert run.phases["startup"] == 0.1  # from phase_history_s
        assert run.warmup_phases == {"engine.dispatch": 2.0}
        assert run.telemetry_counters == {
            "cyclonus_tpu_pre_cache_hits_total": 4.0
        }
        assert run.retries["attempts"] == 1

    def test_round_trip(self, tmp_path):
        root = write_rounds(
            tmp_path,
            [wrap(1, healthy_line()), wrap(2, None, rc=124, tail="x")],
        )
        led = load_ledger(root)
        led2 = Ledger.from_dict(led.to_dict())
        assert led2.to_dict() == led.to_dict()
        assert [r.run_id for r in led2.runs] == [r.run_id for r in led.runs]

    def test_from_dict_rejects_unknown_class(self):
        with pytest.raises(ValueError, match="failure_class"):
            PerfRun.from_dict(
                {"run_id": "x", "kind": "bench", "source": "s",
                 "failure_class": "gremlins", "ok": False}
            )

    def test_multichip_per_chip_line_parsed(self, tmp_path):
        tail = (
            "dryrun_multichip OK: 8-device mesh\n"
            + json.dumps(
                {"metric": "multichip sharded counts cells/sec",
                 "n_devices": 8, "cells_per_sec": 8.0e9,
                 "cells_per_sec_per_chip": 1.0e9, "virtual": False}
            )
            + "\n"
        )
        p = tmp_path / "MULTICHIP_r01.json"
        p.write_text(json.dumps(
            {"n_devices": 8, "rc": 0, "ok": True, "tail": tail}
        ))
        run = ingest_multichip(str(p))
        assert run.failure_class == "ok"
        assert run.cells_per_sec_per_chip == 1.0e9
        assert run.n_devices == 8
        assert run.virtual_mesh is False

    def test_multichip_mesh_row_schema_shared_parser(self, tmp_path):
        """The new dryrun emits the SAME row schema as a bench
        detail.mesh row; both ingest through _ingest_mesh_row, so the
        ring fields land on the PerfRun either way."""
        row = {
            "metric": "multichip ring counts cells/sec",
            "path": "ring", "devices": 8, "n_devices": 8,
            "eval_s": 0.5, "pipelined_eval_s": 0.08,
            "cells_per_sec": 8.0e9, "cells_per_sec_per_chip": 1.0e9,
            "ring_step_s": 0.01, "overlap_efficiency": 0.9,
            "counts_ok": True, "virtual": True,
        }
        p = tmp_path / "MULTICHIP_r02.json"
        p.write_text(json.dumps(
            {"n_devices": 8, "rc": 0, "ok": True,
             "tail": "dryrun_multichip OK\n" + json.dumps(row) + "\n"}
        ))
        run = ingest_multichip(str(p))
        assert run.cells_per_sec_per_chip == 1.0e9
        assert run.mesh_ring_step_s == 0.01
        assert run.mesh_overlap_efficiency == 0.9
        assert run.virtual_mesh is True
        # round-trips through the schema
        assert PerfRun.from_dict(run.to_dict()).mesh_ring_step_s == 0.01

    def test_bench_detail_mesh_preferred_over_legacy(self, tmp_path):
        """A bench line with the new detail.mesh block ingests its
        rows (ring fields included); legacy detail.mesh_scaling remains
        the fallback for old artifacts."""
        line = healthy_line(value=1e9)
        line["detail"]["mesh"] = {
            "pods": 64, "virtual": False, "schedule": "ring",
            "rows": [
                {"path": "ring", "devices": 1, "eval_s": 1.0,
                 "cells_per_sec": 10e9, "cells_per_sec_per_chip": 10e9,
                 "ring_step_s": 0.2, "overlap_efficiency": 1.0,
                 "counts_ok": True, "virtual": False},
                {"path": "ring", "devices": 8, "eval_s": 1.0,
                 "cells_per_sec": 64e9, "cells_per_sec_per_chip": 8e9,
                 "ring_step_s": 0.025, "overlap_efficiency": 0.8,
                 "counts_ok": True, "virtual": False},
            ],
        }
        p = tmp_path / "BENCH_r09.json"
        p.write_text(json.dumps(wrap(9, line)))
        run = ingest_bench(str(p))
        assert run.n_devices == 8
        assert run.cells_per_sec_per_chip == 8e9
        assert run.scaling_efficiency == pytest.approx(0.8)
        assert run.virtual_mesh is False
        assert run.mesh_ring_step_s == 0.025
        assert run.mesh_overlap_efficiency == 0.8


# --- the regression sentinel ---------------------------------------------


class TestGate:
    def _ledger(self, *docs, tmp_path):
        return load_ledger(write_rounds(tmp_path, list(docs)))

    def test_no_regression_passes(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(value=90e9, warmup=6.0)),
            wrap(2, healthy_line(value=100e9, warmup=5.0)),
            wrap(3, healthy_line(value=110e9, warmup=5.5)),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass", result.report()
        assert not result.regressions

    def test_rate_regression_fails(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(value=100e9)),
            wrap(2, healthy_line(value=110e9)),
            wrap(3, healthy_line(value=50e9)),  # 55% drop vs best
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "engine_regression"
        assert result.exit_code == 1
        bad = {d.metric for d in result.regressions}
        assert "cells_per_sec" in bad
        assert "REGRESSED] cells_per_sec" in result.report()

    def test_warmup_regression_fails_named(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(warmup=5.0)),
            wrap(2, healthy_line(warmup=6.0)),
            wrap(3, healthy_line(value=120e9, warmup=60.0)),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "engine_regression"
        assert {d.metric for d in result.regressions} == {"warmup_s"}
        assert "warmup_s" in result.report()

    def test_phase_regression_names_phase(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(encode=1.0)),
            wrap(2, healthy_line(encode=1.2)),
            wrap(3, healthy_line(value=120e9, encode=30.0)),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "engine_regression"
        assert {d.metric for d in result.regressions} == {"phase:encode"}
        # the delta report NAMES the offending phase
        assert "phase:encode" in result.report()

    def test_noise_within_tolerance_passes(self, tmp_path):
        # -25% rate and +40% warmup are inside the default envelope
        led = self._ledger(
            wrap(1, healthy_line(value=100e9, warmup=5.0)),
            wrap(2, healthy_line(value=75e9, warmup=7.0)),
            tmp_path=tmp_path,
        )
        assert gate(led).status == "pass"

    def test_infra_flake_gates_separately(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(value=100e9)),
            wrap(2, None, rc=3, tail=""),
            tmp_path=tmp_path,
        )
        # make round 2 an init-timeout artifact like r04
        led.runs[1].failure_class = "tunnel"
        led.runs[1].ok = False
        result = gate(led)
        assert result.status == "infra_flake"
        assert result.exit_code == 2
        assert result.infra["failure_class"] == "tunnel"
        assert "NOT an engine regression" in result.report()

    def test_infra_runs_never_pollute_baselines(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(value=100e9)),
            wrap(2, None, rc=124, tail=R03_STYLE_TAIL),  # backend_init
            wrap(3, healthy_line(value=95e9)),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass"
        rate = next(d for d in result.deltas if d.metric == "cells_per_sec")
        assert rate.baseline_runs == ["r01"]  # r02 excluded

    def test_first_run_is_admitted(self, tmp_path):
        led = self._ledger(wrap(1, healthy_line()), tmp_path=tmp_path)
        result = gate(led)
        assert result.status == "pass"
        assert any("first baseline" in n for n in result.notes)

    def test_engine_crash_is_regression(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line()),
            wrap(2, {"metric": "m (FAILED)", "value": 0,
                     "error": "AssertionError: PARITY FAILURE",
                     "failure_class": "engine", "detail": {}}, rc=1),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "engine_regression"
        assert result.exit_code == 1

    def test_scaling_gate_real_mesh(self, tmp_path):
        rows_bad = [
            {"path": "ring", "devices": 1, "eval_s": 1.0,
             "cells_per_sec": 100e9, "cells_per_sec_per_chip": 100e9,
             "counts_ok": True},
            {"path": "ring", "devices": 8, "eval_s": 1.0,
             "cells_per_sec": 160e9, "cells_per_sec_per_chip": 20e9,
             "counts_ok": True},
        ]
        led = self._ledger(
            wrap(1, healthy_line(value=100e9)),
            wrap(2, healthy_line(value=100e9, mesh_rows=rows_bad,
                                 virtual=False)),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "engine_regression"
        (delta,) = [d for d in result.regressions]
        assert delta.metric.startswith("scaling_efficiency")
        assert "@8chip" in delta.metric

    def test_scaling_gate_healthy_real_mesh_passes(self, tmp_path):
        rows_ok = [
            {"path": "ring", "devices": 1, "eval_s": 1.0,
             "cells_per_sec": 100e9, "cells_per_sec_per_chip": 100e9,
             "counts_ok": True},
            {"path": "ring", "devices": 8, "eval_s": 1.0,
             "cells_per_sec": 640e9, "cells_per_sec_per_chip": 80e9,
             "counts_ok": True},
        ]
        led = self._ledger(
            wrap(1, healthy_line(value=100e9)),
            wrap(2, healthy_line(value=100e9, mesh_rows=rows_ok,
                                 virtual=False)),
            tmp_path=tmp_path,
        )
        assert led.runs[-1].scaling_efficiency == pytest.approx(0.8)
        result = gate(led)
        assert result.status == "pass", result.report()
        assert any(
            d.metric.startswith("scaling_efficiency") for d in result.deltas
        )

    def test_efficiency_is_same_workload_only(self, tmp_path):
        """Without a 1-device row of the SAME workload there is no
        efficiency — the gate must never divide an N-dev per-chip rate
        by the (different-problem-size) headline single-chip rate."""
        rows = [
            {"path": "ring", "devices": 8, "eval_s": 1.0,
             "cells_per_sec": 8e6, "cells_per_sec_per_chip": 1e6,
             "counts_ok": True},
        ]
        led = self._ledger(
            wrap(1, healthy_line(value=100e9)),
            wrap(2, healthy_line(value=100e9, mesh_rows=rows,
                                 virtual=False)),
            tmp_path=tmp_path,
        )
        assert led.runs[-1].scaling_efficiency is None
        result = gate(led)
        # the tiny per-chip rate (1e6 vs the 100e9 headline) must NOT
        # read as a scaling regression — different workloads
        assert result.status == "pass", result.report()

    def test_virtual_mesh_reported_not_gated(self, tmp_path):
        rows = [
            {"path": "ring", "devices": 1, "eval_s": 1.0,
             "cells_per_sec": 100e6, "cells_per_sec_per_chip": 100e6,
             "counts_ok": True},
            {"path": "ring", "devices": 8, "eval_s": 1.0,
             "cells_per_sec": 100e6, "cells_per_sec_per_chip": 12.5e6,
             "counts_ok": True},
        ]
        led = self._ledger(
            wrap(1, healthy_line(value=100e9)),
            wrap(2, healthy_line(value=100e9, mesh_rows=rows)),  # virtual
            tmp_path=tmp_path,
        )
        # efficiency 0.125 exists (one core timeshared 8 ways) but the
        # block is virtual: reported in a note, never a delta
        assert led.runs[-1].scaling_efficiency == pytest.approx(0.125)
        result = gate(led)
        assert result.status == "pass", result.report()
        assert not any(
            d.metric.startswith("scaling_efficiency") for d in result.deltas
        )
        assert any("VIRTUAL" in n for n in result.notes)

    def _multichip(self, tmp_path, name, per_chip, n_devices=8,
                   virtual=False):
        tail = (
            "dryrun_multichip OK\n"
            + json.dumps(
                {"metric": "multichip sharded counts cells/sec",
                 "n_devices": n_devices, "cells_per_sec":
                 per_chip * n_devices,
                 "cells_per_sec_per_chip": per_chip,
                 "virtual": virtual}
            )
            + "\n"
        )
        (tmp_path / name).write_text(json.dumps(
            {"n_devices": n_devices, "rc": 0, "ok": True, "tail": tail}
        ))

    def test_multichip_trend_gate_same_device_count(self, tmp_path):
        """Real multichip per-chip rates gate against prior real runs
        at the SAME device count (same dryrun workload)."""
        write_rounds(tmp_path, [wrap(1, healthy_line(value=100e9))])
        self._multichip(tmp_path, "MULTICHIP_r01.json", 10e9)
        self._multichip(tmp_path, "MULTICHIP_r02.json", 2e9)  # -80%
        led = load_ledger(str(tmp_path))
        result = gate(led)
        assert result.status == "engine_regression", result.report()
        (delta,) = result.regressions
        assert delta.metric.startswith("cells_per_sec_per_chip")
        assert "@8chip" in delta.metric

    def test_first_real_multichip_is_admitted(self, tmp_path):
        """A lone tiny real-mesh dryrun must not spuriously fail any
        absolute gate — it becomes the first per-chip baseline."""
        write_rounds(tmp_path, [wrap(1, healthy_line(value=100e9))])
        self._multichip(tmp_path, "MULTICHIP_r01.json", 1e6)  # tiny
        led = load_ledger(str(tmp_path))
        result = gate(led)
        assert result.status == "pass", result.report()
        assert any("first real multichip" in n for n in result.notes)

    def test_backend_init_join_phase_not_engine_gated(self, tmp_path):
        """Attach wait is INFRA: a healthy run on a cold/contended
        tunnel (long backend_init_join) must not read as an engine
        regression — the cold-start forensics cover it."""
        slow = healthy_line(value=120e9)
        slow["detail"]["backend_init_s"] = 45.0
        led = self._ledger(
            wrap(1, healthy_line()),
            wrap(2, healthy_line()),
            wrap(3, slow),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass", result.report()
        assert not any(
            d.metric == "phase:backend_init_join" for d in result.deltas
        )


# --- report + Prometheus golden ------------------------------------------


def serve_detail(apply_s=0.003, rebuild_s=1.2, qps=5000.0):
    return {
        "pods": 1024,
        "policies": 128,
        "deltas": 32,
        "full_rebuild_s": rebuild_s,
        "incremental_apply_s": apply_s,
        "queries_per_sec": qps,
        "no_reencode": True,
    }


class TestServeFields:
    """detail.serve rides every BENCH line; the ledger parses the three
    trend fields and the sentinel treats them WARN-ONLY (the serve leg's
    own assertions are the hard gate)."""

    def _ledger(self, *docs, tmp_path):
        return load_ledger(write_rounds(tmp_path, list(docs)))

    def test_ledger_parses_serve_fields(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(serve=serve_detail())), tmp_path=tmp_path
        )
        run = led.runs[0]
        assert run.serve_incremental_apply_s == 0.003
        assert run.serve_full_rebuild_s == 1.2
        assert run.serve_queries_per_sec == 5000.0
        # and the fields round-trip through the PerfRun dict form
        from cyclonus_tpu.perfobs.schema import PerfRun

        again = PerfRun.from_dict(run.to_dict())
        assert again.serve_incremental_apply_s == 0.003

    def test_ledger_without_serve_is_none(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line()), tmp_path=tmp_path
        )
        assert led.runs[0].serve_incremental_apply_s is None
        assert led.runs[0].serve_queries_per_sec is None

    def test_serve_degradation_warns_never_fails(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(serve=serve_detail(apply_s=0.002,
                                                    qps=8000.0))),
            wrap(2, healthy_line(serve=serve_detail(apply_s=0.003,
                                                    qps=7000.0))),
            wrap(3, healthy_line(value=120e9,
                                 serve=serve_detail(apply_s=0.02,
                                                    qps=1000.0))),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass", result.report()
        report = result.report()
        assert "serve_incremental_apply_s degraded" in report
        assert "serve_queries_per_sec degraded" in report
        assert "warn, not fail" in report

    def test_serve_within_tolerance_no_warning(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(serve=serve_detail(apply_s=0.002,
                                                    qps=8000.0))),
            wrap(2, healthy_line(value=110e9,
                                 serve=serve_detail(apply_s=0.003,
                                                    qps=6000.0))),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass"
        assert "serve_" not in result.report()

    def test_serve_churn_phase_not_generically_gated(self, tmp_path):
        # a slow serve_churn phase must not trip the per-phase rule —
        # the leg's knobs (BENCH_SERVE_*) legitimately vary per round
        base = healthy_line()
        slow = healthy_line(value=120e9)
        base["detail"]["phase_history_s"].append(["serve_churn", 1.0])
        slow["detail"]["phase_history_s"].append(["serve_churn", 60.0])
        led = self._ledger(
            wrap(1, base), wrap(2, healthy_line()), wrap(3, slow),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass", result.report()


def tiers_detail(resolve_s=0.0002, anp_count=3, active=True):
    return {
        "active": active,
        "anp_count": anp_count,
        "rule_rows": 4,
        "banp": True,
        "resolve_s": resolve_s,
        "pods": 1024,
        "parity_spot_checks": 16,
    }


class TestTiersFields:
    """detail.tiers rides every BENCH line; the ledger parses
    active/anp_count/resolve_s and the sentinel treats resolve_s
    WARN-ONLY (the tiers leg's own oracle spot-parity assertion is the
    hard gate) — same discipline as the serve fields."""

    def _ledger(self, *docs, tmp_path):
        return load_ledger(write_rounds(tmp_path, list(docs)))

    def test_ledger_parses_tiers_fields(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(tiers=tiers_detail())), tmp_path=tmp_path
        )
        run = led.runs[0]
        assert run.tiers_active is True
        assert run.tiers_anp_count == 3
        assert run.tiers_resolve_s == 0.0002
        # ledger round-trip keeps the fields
        rt = PerfRun.from_dict(run.to_dict())
        assert rt.tiers_resolve_s == run.tiers_resolve_s

    def test_old_artifacts_without_tiers_parse(self, tmp_path):
        led = self._ledger(wrap(1, healthy_line()), tmp_path=tmp_path)
        run = led.runs[0]
        assert run.tiers_active is False
        assert run.tiers_resolve_s is None

    def test_tiers_degradation_warns_never_fails(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(tiers=tiers_detail(resolve_s=0.0002))),
            wrap(2, healthy_line(tiers=tiers_detail(resolve_s=0.0003))),
            wrap(3, healthy_line(value=120e9,
                                 tiers=tiers_detail(resolve_s=0.002))),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass", result.report()
        report = result.report()
        assert "tiers_resolve_s degraded" in report
        assert "warn, not fail" in report

    def test_tiers_phase_not_generically_gated(self, tmp_path):
        # a slow tiers phase must not trip the per-phase rule — the
        # leg's knobs (BENCH_TIERS_*) legitimately vary per round
        base = healthy_line()
        slow = healthy_line(value=120e9)
        base["detail"]["phase_history_s"].append(["tiers", 1.0])
        slow["detail"]["phase_history_s"].append(["tiers", 60.0])
        led = self._ledger(
            wrap(1, base), wrap(2, healthy_line()), wrap(3, slow),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass", result.report()


def cidr_detail(lpm_s=0.002, distinct=1024, partitions=7, active=True):
    return {
        "active": active,
        "pods": 2048,
        "distinct_cidrs": distinct,
        "atoms": distinct + 12,
        "partitions": partitions,
        "classes": 96,
        "ratio": 21.33,
        "lpm_s": lpm_s,
        "device": False,
        "bytes": 16212,
        "speedup_vs_dense": 12.5,
        "parity_spot_checks": 6,
    }


class TestCidrFields:
    """detail.cidr rides every BENCH line; the ledger parses
    active/distinct/partitions/classes/ratio/lpm_s and the sentinel
    treats lpm_s WARN-ONLY (the leg's own dense-vs-TSS throughput
    assertion and oracle spot parity are the hard gates) — the same
    posture class_compression_ratio took when it landed."""

    def _ledger(self, *docs, tmp_path):
        return load_ledger(write_rounds(tmp_path, list(docs)))

    def test_ledger_parses_cidr_fields(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(cidr=cidr_detail())), tmp_path=tmp_path
        )
        run = led.runs[0]
        assert run.cidr_active is True
        assert run.cidr_distinct == 1024
        assert run.cidr_partitions == 7
        assert run.cidr_classes == 96
        assert run.cidr_ratio == 21.33
        assert run.cidr_lpm_s == 0.002
        rt = PerfRun.from_dict(run.to_dict())
        assert rt.cidr_lpm_s == run.cidr_lpm_s
        assert rt.cidr_distinct == run.cidr_distinct

    def test_old_artifacts_without_cidr_parse(self, tmp_path):
        led = self._ledger(wrap(1, healthy_line()), tmp_path=tmp_path)
        run = led.runs[0]
        assert run.cidr_active is False
        assert run.cidr_lpm_s is None
        assert run.cidr_distinct is None

    def test_cidr_degradation_warns_never_fails(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(cidr=cidr_detail(lpm_s=0.002))),
            wrap(2, healthy_line(cidr=cidr_detail(lpm_s=0.003))),
            wrap(3, healthy_line(value=120e9,
                                 cidr=cidr_detail(lpm_s=0.02))),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass", result.report()
        report = result.report()
        assert "cidr_lpm_s degraded" in report
        assert "warn, not fail" in report

    def test_cidr_within_tolerance_no_warning(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(cidr=cidr_detail(lpm_s=0.002))),
            wrap(2, healthy_line(value=110e9,
                                 cidr=cidr_detail(lpm_s=0.003))),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass", result.report()
        assert "cidr_lpm_s degraded" not in result.report()

    def test_cidr_phase_not_generically_gated(self, tmp_path):
        # a slow cidr phase must not trip the per-phase rule — the
        # leg's knobs (BENCH_CIDR_*) legitimately vary per round
        base = healthy_line()
        slow = healthy_line(value=120e9)
        base["detail"]["phase_history_s"].append(["cidr", 1.0])
        slow["detail"]["phase_history_s"].append(["cidr", 60.0])
        led = self._ledger(
            wrap(1, base), wrap(2, healthy_line()), wrap(3, slow),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass", result.report()


class TestPackAndRooflineFields:
    """detail.pack / detail.roofline: new-format runs gate roofline
    efficiency >= 0.7 and their cells/s against the min-of-N best as a
    HARD floor; old artifacts (no detail.pack) keep the legacy bounds —
    the committed BENCH_r0* fixtures must keep ingesting and the real
    trajectory must keep passing (TestRealArtifacts pins that)."""

    def _ledger(self, *docs, tmp_path):
        return load_ledger(write_rounds(tmp_path, list(docs)))

    PACK = {
        "active": True,
        "dtype": "packed32",
        "words": [2, 1],
        "winner": {"kernel": "packed", "bs": 1024, "bd": 512},
        "autotune": {
            "source": "search",
            "search_s": 3.2,
            "candidates": [{"kernel": "packed", "bs": 512, "bd": 512},
                           {"kernel": "packed", "bs": 1024, "bd": 512}],
        },
        "cache_path": "/tmp/autotune.json",
    }

    def test_ledger_parses_pack_and_roofline(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(
                pack=self.PACK,
                roofline={"efficiency_vs_roofline": 0.81, "bound": "vpu_s"},
            )),
            tmp_path=tmp_path,
        )
        (run,) = led.bench_runs()
        assert run.pack_active is True
        assert run.pack_dtype == "packed32"
        assert run.pack_tile == [1024, 512]
        assert run.pack_search_s == 3.2
        assert run.pack_candidates == 2
        assert run.roofline_efficiency == 0.81

    def test_old_artifacts_parse_with_pack_none(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line()), tmp_path=tmp_path
        )
        (run,) = led.bench_runs()
        assert run.pack_active is None
        assert run.pack_dtype is None
        # legacy runs carry roofline_efficiency when the block exists
        # but are NEVER efficiency-gated (pack_active is the marker)
        assert run.roofline_efficiency is None

    def test_efficiency_gate_fails_below_bound(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(value=100e9)),
            wrap(2, healthy_line(
                value=120e9,
                pack=self.PACK,
                roofline={"efficiency_vs_roofline": 0.43},
            )),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "engine_regression"
        bad = {d.metric for d in result.regressions}
        assert "roofline_efficiency" in bad

    def test_efficiency_gate_passes_at_bound(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(value=100e9)),
            wrap(2, healthy_line(
                value=120e9,
                pack=self.PACK,
                roofline={"efficiency_vs_roofline": 0.74},
            )),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass", result.report()

    def test_legacy_low_efficiency_not_gated(self, tmp_path):
        # an r05-style artifact: roofline present (0.433) but NO pack
        # block — must keep passing (retro-gating would poison history)
        led = self._ledger(
            wrap(1, healthy_line(value=100e9)),
            wrap(2, healthy_line(
                value=110e9,
                roofline={"efficiency_vs_roofline": 0.433},
            )),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass", result.report()
        assert not any(
            d.metric == "roofline_efficiency" for d in result.deltas
        )

    def test_pack_run_without_roofline_notes_skip(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(value=100e9)),
            wrap(2, healthy_line(value=120e9, pack=self.PACK)),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass", result.report()
        assert any("roofline" in n for n in result.notes)

    def test_hard_rate_floor_on_pack_runs(self, tmp_path):
        # a pack-bearing run 10% below the best baseline: inside the
        # legacy 30% tolerance, but the hard floor fails it
        led = self._ledger(
            wrap(1, healthy_line(value=100e9)),
            wrap(2, healthy_line(value=132.7e9)),
            wrap(3, healthy_line(
                value=120e9,
                pack=self.PACK,
                roofline={"efficiency_vs_roofline": 0.8},
            )),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "engine_regression"
        bad = {d.metric for d in result.regressions}
        assert "cells_per_sec[hard-floor]" in bad
        # the same drop WITHOUT a pack block stays inside tolerance
        led2 = self._ledger(
            wrap(1, healthy_line(value=100e9)),
            wrap(2, healthy_line(value=132.7e9)),
            wrap(3, healthy_line(value=120e9)),
            tmp_path=tmp_path,
        )
        assert gate(led2).status == "pass"

    def test_pack_run_at_or_above_best_passes_floor(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(value=132.7e9)),
            wrap(2, healthy_line(
                value=140e9,
                pack=self.PACK,
                roofline={"efficiency_vs_roofline": 0.75},
            )),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass", result.report()

    def test_round_trip_preserves_pack_fields(self, tmp_path):
        led = self._ledger(
            wrap(1, healthy_line(
                pack=self.PACK,
                roofline={"efficiency_vs_roofline": 0.9},
            )),
            tmp_path=tmp_path,
        )
        (run,) = led.bench_runs()
        back = PerfRun.from_dict(json.loads(json.dumps(run.to_dict())))
        assert back.pack_tile == run.pack_tile
        assert back.roofline_efficiency == run.roofline_efficiency
        assert back.pack_active == run.pack_active


class TestReport:
    def _small_ledger(self):
        runs = [
            PerfRun(
                run_id="rA", kind="bench", source="a", failure_class="ok",
                ok=True, n=1, cells_per_sec=100e9, warmup_s=5.0,
                phases={"encode": 1.0},
            ),
            PerfRun(
                run_id="rB", kind="bench", source="b",
                failure_class="tunnel", ok=False, n=2,
                error="backend init did not complete",
            ),
            PerfRun(
                run_id="mc", kind="multichip", source="m",
                failure_class="ok", ok=True, n_devices=8,
                cells_per_sec=100e9, cells_per_sec_per_chip=12.5e9,
                virtual_mesh=True,
            ),
        ]
        return Ledger(runs)

    def test_markdown_trend(self):
        led = self._small_ledger()
        md = perf_report.render_markdown(led, gate(led))
        assert "| rA | bench | ok | 100.0B | 5.0 |" in md
        assert "| rB | bench | tunnel |" in md
        assert "12.5B (virtual)" in md
        assert "best healthy rate: 100.0B cells/s (rA)" in md
        assert "infra flakes excluded from the trajectory: 1" in md

    def test_json_trend(self):
        led = self._small_ledger()
        doc = perf_report.trend(led, gate(led))
        assert doc["best_cells_per_sec"] == 100e9
        assert doc["by_class"]["tunnel"] == 1
        assert doc["gate"]["status"] == "infra_flake"  # rB is latest
        assert doc["healthy_trajectory"] == [
            {"run": "rA", "cells_per_sec": 100e9}
        ]

    def test_prometheus_exposition_golden(self):
        """Byte-stable golden of the cyclonus_tpu_perf_* sample lines
        (the schema a scraper of any --metrics-port process sees after
        publish)."""
        from cyclonus_tpu.telemetry.metrics import REGISTRY

        REGISTRY.reset()
        led = self._small_ledger()
        perf_report.publish(led, gate(led))
        got = [
            line
            for line in REGISTRY.render_prometheus().splitlines()
            if line.startswith("cyclonus_tpu_perf_")
        ]
        assert got == [
            'cyclonus_tpu_perf_best_cells_per_sec 100000000000',
            'cyclonus_tpu_perf_cells_per_sec{run="rA"} 100000000000',
            'cyclonus_tpu_perf_cells_per_sec{run="rB"} 0',
            'cyclonus_tpu_perf_cells_per_sec_per_chip{run="mc",virtual="1"} 12500000000',
            'cyclonus_tpu_perf_gate_status 2',
            'cyclonus_tpu_perf_phase_seconds{run="rA",phase="encode"} 1',
            'cyclonus_tpu_perf_runs{failure_class="backend_init"} 0',
            'cyclonus_tpu_perf_runs{failure_class="engine"} 0',
            'cyclonus_tpu_perf_runs{failure_class="ok"} 2',
            'cyclonus_tpu_perf_runs{failure_class="tunnel"} 1',
            'cyclonus_tpu_perf_runs{failure_class="watchdog_stall"} 0',
            'cyclonus_tpu_perf_warmup_seconds{run="rA"} 5',
        ]

    def test_served_by_metrics_server(self):
        """The gauges ride the EXISTING telemetry server: publish, then
        curl /metrics on an ephemeral port."""
        from urllib.request import urlopen

        from cyclonus_tpu.telemetry.server import (
            start_metrics_server,
            stop_metrics_server,
        )

        led = self._small_ledger()
        perf_report.publish(led)
        srv = start_metrics_server(0)
        try:
            body = urlopen(f"{srv.url}/metrics", timeout=10).read().decode()
        finally:
            stop_metrics_server()
        assert 'cyclonus_tpu_perf_cells_per_sec{run="rA"}' in body


# --- CLI + Makefile wiring -----------------------------------------------


class TestCli:
    def _cli(self, *args, cwd=REPO):
        return subprocess.run(
            [sys.executable, "-m", "cyclonus_tpu", *args],
            capture_output=True,
            text=True,
            timeout=120,
            cwd=cwd,
        )

    def test_gate_passes_in_repo(self):
        proc = self._cli("perf", "gate")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "PASS" in proc.stdout
        assert "candidate r05" in proc.stdout

    def test_gate_json_output(self):
        proc = self._cli("perf", "gate", "--json")
        assert proc.returncode == 0
        doc = json.loads(proc.stdout)
        assert doc["status"] == "pass"
        assert doc["candidate"] == "r05"

    def test_gate_fails_on_regressed_fixture_dir(self, tmp_path):
        write_rounds(
            tmp_path,
            [
                wrap(1, healthy_line(value=100e9)),
                wrap(2, healthy_line(value=30e9, warmup=80.0)),
            ],
        )
        proc = self._cli("perf", "gate", "--dir", str(tmp_path))
        assert proc.returncode == 1
        assert "REGRESSED] cells_per_sec" in proc.stdout
        assert "warmup_s" in proc.stdout

    def test_gate_infra_exit_code_and_allow_infra(self, tmp_path):
        write_rounds(
            tmp_path,
            [
                wrap(1, healthy_line(value=100e9)),
                wrap(2, None, rc=124, tail=R03_STYLE_TAIL),
            ],
        )
        proc = self._cli("perf", "gate", "--dir", str(tmp_path))
        assert proc.returncode == 2
        assert "INFRA_FLAKE" in proc.stdout
        proc = self._cli(
            "perf", "gate", "--dir", str(tmp_path), "--allow-infra"
        )
        assert proc.returncode == 0

    def test_report_json_over_repo(self):
        proc = self._cli("perf", "report", "--format", "json")
        assert proc.returncode == 0, proc.stderr
        doc = json.loads(proc.stdout)
        ids = {r["run_id"] for r in doc["runs"]}
        assert {"r01", "r02", "r03", "r04", "r05"} <= ids
        assert doc["best_cells_per_sec"] == 132717279525.0
        assert doc["gate"]["status"] == "pass"

    def test_report_out_file(self, tmp_path):
        out = tmp_path / "trend.md"
        proc = self._cli("perf", "report", "--out", str(out))
        assert proc.returncode == 0
        assert "# Perf observatory" in out.read_text()

    def test_last_run_flag_is_candidate(self, tmp_path):
        """--run promises argv order decides the candidate, even when
        the file names sort the other way."""
        (tmp_path / "zeta.json").write_text(
            json.dumps(healthy_line(value=100e9))
        )
        (tmp_path / "alpha.json").write_text(
            json.dumps(healthy_line(value=90e9))
        )
        proc = self._cli(
            "perf", "gate", "--dir", str(tmp_path),
            "--run", str(tmp_path / "zeta.json"),
            "--run", str(tmp_path / "alpha.json"),
            "--json",
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert json.loads(proc.stdout)["candidate"] == "alpha"


class TestWiring:
    def test_make_check_runs_perf_gate(self):
        mk = open(os.path.join(REPO, "Makefile")).read()
        assert "perf-gate:" in mk
        assert "perf gate" in mk
        # wired into the one-command CI gate
        check_line = [
            l for l in mk.splitlines() if l.startswith("check:")
        ][0]
        assert "perf-gate" in check_line

    def test_lint_covers_perfobs(self):
        mk = open(os.path.join(REPO, "Makefile")).read()
        assert "cyclonus_tpu/perfobs" in mk
        # and the linters actually come back clean over it
        for tool in ("jaxlint", "shapelint"):
            proc = subprocess.run(
                [sys.executable, f"tools/{tool}.py", "cyclonus_tpu/perfobs"],
                capture_output=True,
                text=True,
                timeout=120,
                cwd=REPO,
            )
            assert proc.returncode == 0, proc.stdout + proc.stderr


# --- bench mesh_scaling per-chip field (in-process, tiny) ----------------


class TestMeshScalingPerChip:
    def test_rows_carry_per_chip_rate(self):
        """detail.mesh rows record the stable fields the scaling gate
        reads (cells_per_sec_per_chip) plus the overlapped-path fields
        (ring_step_s, overlap_efficiency), and the block self-identifies
        as virtual so the sentinel reports without gating."""
        import random as _random

        import bench

        pods, ns, pols = bench.build_synthetic(48, 8, _random.Random(3))
        from cyclonus_tpu.engine import PortCase

        cases = [PortCase(80, "serve-80-tcp", "TCP")]
        detail = bench.mesh_case(pods, ns, pols, cases)
        assert detail["virtual"] is True
        assert detail["schedule"] == "ring"
        assert detail["rows"], "no mesh rows produced"
        for row in detail["rows"]:
            assert row["cells_per_sec_per_chip"] is not None
            assert row["cells_per_sec"] == pytest.approx(
                row["cells_per_sec_per_chip"] * row["devices"], rel=0.01
            )
            assert row["ring_step_s"] is not None
            assert row["counts_ok"] is True
            assert row["virtual"] is True
        assert detail["rows"][0]["overlap_efficiency"] == 1.0
        # the overlapped schedule's peer-buffer watermark undercuts the
        # all-gather schedule's replicated copy on the 8-device mesh
        pb = detail["peer_buffer_bytes"]
        assert pb["ring"] < pb["allgather"]
        assert detail["grid_parity"]["bit_identical"] is True


def aot_block(hits=4, misses=0, adopted=4, compiles=0):
    return {
        "hits": hits,
        "misses": misses,
        "adopted": adopted,
        "stores": misses,
        "compiles": compiles,
        "dir": "/tmp/aot",
    }


class TestAotAndChaosFields:
    """detail.cold_start.aot_cache + detail.chaos (docs/DESIGN.md "Cold
    start & chaos"): the ledger parses them, warmup_s graduates to a
    HARD absolute bound on cache-bearing runs, and the chaos
    time-to-first-verdict rides warn-only."""

    def _ledger(self, *docs, tmp_path):
        return load_ledger(write_rounds(tmp_path, list(docs)))

    def _line(self, value=100e9, warmup=5.0, aot=None, chaos=None):
        line = healthy_line(value=value, warmup=warmup)
        if aot is not None:
            line["detail"]["cold_start"]["aot_cache"] = aot
        if chaos is not None:
            line["detail"]["chaos"] = chaos
        return line

    def test_ledger_parses_aot_and_chaos(self, tmp_path):
        led = self._ledger(
            wrap(1, self._line(
                aot=aot_block(hits=5, adopted=5),
                chaos={"ttfv_s": 3.1, "ttfv_bound_s": 150.0, "ok": True},
            )),
            tmp_path=tmp_path,
        )
        run = led.runs[0]
        assert run.aot_hits == 5 and run.aot_adopted == 5
        assert run.aot_misses == 0 and run.aot_compiles == 0
        assert run.chaos_ttfv_s == 3.1
        from cyclonus_tpu.perfobs.schema import PerfRun

        again = PerfRun.from_dict(run.to_dict())
        assert again.aot_adopted == 5 and again.chaos_ttfv_s == 3.1

    def test_legacy_artifacts_have_no_aot_fields(self, tmp_path):
        led = self._ledger(wrap(1, self._line()), tmp_path=tmp_path)
        run = led.runs[0]
        assert run.aot_adopted is None and run.chaos_ttfv_s is None

    def test_cache_bearing_run_hard_gates_warmup(self, tmp_path):
        """A run that ADOPTED executables gets the absolute ceiling —
        even a warmup inside the legacy relative tolerance fails when
        it exceeds warmup_cached_max_s."""
        led = self._ledger(
            wrap(1, self._line(warmup=6.0)),
            wrap(2, self._line(warmup=6.2)),
            # warmup 7.0 passes the relative bound (6.0 * 1.5 + 2 = 11)
            # but a cache-bearing run must beat the 5s hard ceiling
            wrap(3, self._line(value=110e9, warmup=7.0,
                               aot=aot_block(hits=6, adopted=6))),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "engine_regression", result.report()
        assert "warmup_s[aot-cached]" in result.report()

    def test_cache_bearing_run_within_hard_bound_passes(self, tmp_path):
        led = self._ledger(
            wrap(1, self._line(warmup=6.0)),
            wrap(2, self._line(warmup=6.2)),
            wrap(3, self._line(value=110e9, warmup=2.5,
                               aot=aot_block(hits=6, adopted=6))),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass", result.report()

    def test_half_warm_cache_keeps_relative_posture(self, tmp_path):
        """adopted > 0 but compiles > 0 = a partially warm cache that
        legitimately paid some compiles — the hard ceiling must not
        arm (only fully-warm restarts have no storm left)."""
        led = self._ledger(
            wrap(1, self._line(warmup=6.0)),
            wrap(2, self._line(warmup=6.2)),
            wrap(3, self._line(value=110e9, warmup=7.0,
                               aot=aot_block(hits=3, misses=3,
                                             adopted=3, compiles=3))),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass", result.report()

    def test_uncached_run_keeps_relative_posture(self, tmp_path):
        """No adoption (cold cache: adopted == 0) -> the legacy
        relative bound alone applies; 7.0s still passes."""
        led = self._ledger(
            wrap(1, self._line(warmup=6.0)),
            wrap(2, self._line(warmup=6.2)),
            wrap(3, self._line(value=110e9, warmup=7.0,
                               aot=aot_block(hits=0, misses=6,
                                             adopted=0, compiles=6))),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass", result.report()

    def test_warmup_cached_max_is_tunable(self, tmp_path):
        led = self._ledger(
            wrap(1, self._line(warmup=6.0)),
            wrap(2, self._line(value=110e9, warmup=7.0,
                               aot=aot_block(hits=6, adopted=6))),
            tmp_path=tmp_path,
        )
        result = gate(led, warmup_cached_max_s=8.0)
        assert result.status == "pass", result.report()

    def test_chaos_ttfv_degradation_warns_never_fails(self, tmp_path):
        led = self._ledger(
            wrap(1, self._line(chaos={"ttfv_s": 3.0})),
            wrap(2, self._line(chaos={"ttfv_s": 3.5})),
            wrap(3, self._line(value=120e9, chaos={"ttfv_s": 30.0})),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass", result.report()
        assert "time-to-first-verdict degraded" in result.report()

    def test_chaos_phase_not_generically_gated(self, tmp_path):
        base = self._line()
        slow = self._line(value=120e9)
        base["detail"]["phase_history_s"].append(["chaos", 1.0])
        slow["detail"]["phase_history_s"].append(["chaos", 90.0])
        led = self._ledger(
            wrap(1, base), wrap(2, self._line()), wrap(3, slow),
            tmp_path=tmp_path,
        )
        result = gate(led)
        assert result.status == "pass", result.report()

    def test_report_surfaces_aot_and_ttfv(self, tmp_path):
        from cyclonus_tpu.perfobs import report as report_mod

        led = self._ledger(
            wrap(1, self._line(aot=aot_block(hits=5, adopted=5),
                               chaos={"ttfv_s": 3.1})),
            tmp_path=tmp_path,
        )
        md = report_mod.render_markdown(led)
        assert "(aot)" in md
        assert "time-to-first-verdict" in md
        report_mod.publish(led)
        from cyclonus_tpu.perfobs.report import (
            PERF_AOT_ADOPTED,
            PERF_CHAOS_TTFV,
        )

        run_id = led.runs[0].run_id
        assert PERF_AOT_ADOPTED.value(run=run_id) == 5.0
        assert PERF_CHAOS_TTFV.value(run=run_id) == 3.1
