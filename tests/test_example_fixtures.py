"""The example-policy library and pathological fixtures: every canned
policy must compile, lint, explain, and (for the pathological set) hold
oracle-vs-engine parity (reference: pkg/kube/netpol/{policies,kubedocs,
pathological,basic,complicated}.go; AllExamples at policies.go:699-728)."""

from cyclonus_tpu.engine import PortCase
from cyclonus_tpu.kube import pathological as pa
from cyclonus_tpu.kube.examples import all_examples
from cyclonus_tpu.matcher import build_network_policies, explain_table

from tests.test_engine_parity import assert_parity


class TestAllExamples:
    def test_count_matches_reference(self):
        # policies.go:699-728 AllExamples has exactly 21 entries
        assert len(all_examples()) == 21

    def test_each_example_compiles_and_explains(self):
        for pol in all_examples():
            compiled = build_network_policies(True, [pol])
            text = explain_table(compiled)
            assert pol.namespace in text

    def test_all_together(self):
        compiled = build_network_policies(True, all_examples())
        assert explain_table(compiled)


class TestPathologicalFixtures:
    def _cluster(self):
        ns = pa.NAMESPACE
        namespaces = {ns: {"ns": ns}, "other": pa.LABELS_AB}
        pods = [
            (ns, "a", dict(pa.LABELS_AB), "10.0.0.1"),
            (ns, "b", dict(pa.LABELS_CD), "10.0.0.2"),
            ("other", "c", dict(pa.LABELS_EF), "10.0.0.3"),
            ("other", "d", dict(pa.LABELS_GH), "192.168.242.1"),
        ]
        return pods, namespaces

    def test_policies_compile(self):
        assert len(pa.ALL_PATHOLOGICAL_POLICIES) == 9
        compiled = build_network_policies(True, pa.ALL_PATHOLOGICAL_POLICIES)
        assert explain_table(compiled)

    def test_deny_and_allow_pairs_parity(self):
        """Each pathological policy alone: engine == oracle on a cluster
        crossing the shared-selector labels and the ipblock ranges."""
        pods, namespaces = self._cluster()
        cases = [PortCase(80, "", "TCP"), PortCase(9001, "", "TCP")]
        for pol in pa.ALL_PATHOLOGICAL_POLICIES:
            policy = build_network_policies(True, [pol])
            assert_parity(policy, pods, namespaces, cases)

    def test_peer_fixture_policies_parity(self):
        """Every peer-combination fixture wrapped in an ingress rule:
        engine == oracle (the 6 all-pods shapes + 3 matching shapes + the
        except-carrying ipblock)."""
        from cyclonus_tpu.kube.netpol import (
            NetworkPolicy,
            NetworkPolicyIngressRule,
            NetworkPolicySpec,
        )

        peers = [
            pa.ALLOW_ALL_PODS_IN_POLICY_NAMESPACE_PEER,
            pa.ALLOW_ALL_PODS_IN_ALL_NAMESPACES_PEER,
            pa.ALLOW_ALL_PODS_IN_MATCHING_NAMESPACES_PEER,
            pa.ALLOW_ALL_PODS_IN_POLICY_NAMESPACE_PEER_EMPTY_POD_SELECTOR,
            pa.ALLOW_ALL_PODS_IN_ALL_NAMESPACES_PEER_EMPTY_POD_SELECTOR,
            pa.ALLOW_ALL_PODS_IN_MATCHING_NAMESPACES_PEER_EMPTY_POD_SELECTOR,
            pa.ALLOW_MATCHING_PODS_IN_POLICY_NAMESPACE_PEER,
            pa.ALLOW_MATCHING_PODS_IN_ALL_NAMESPACES_PEER,
            pa.ALLOW_MATCHING_PODS_IN_MATCHING_NAMESPACES_PEER,
            pa.ALLOW_IPBLOCK_PEER,
        ]
        pods, namespaces = self._cluster()
        cases = [PortCase(80, "", "TCP")]
        for i, peer in enumerate(peers):
            pol = NetworkPolicy(
                name=f"peer-fixture-{i}",
                namespace=pa.NAMESPACE,
                spec=NetworkPolicySpec(
                    pod_selector=pa.SELECTOR_EMPTY,
                    policy_types=["Ingress"],
                    ingress=[NetworkPolicyIngressRule(from_=[peer])],
                ),
            )
            policy = build_network_policies(True, [pol])
            assert_parity(policy, pods, namespaces, cases)

    def test_basic_and_complicated_compile_and_parity(self):
        pods, namespaces = self._cluster()
        cases = [PortCase(3333, "", "TCP"), PortCase(80, "", "TCP")]
        pols = [
            pa.allow_nothing_from(pa.NAMESPACE, pa.SELECTOR_AB),
            pa.allow_from_to_ns_labels(pa.NAMESPACE, pa.SELECTOR_AB, {"ns": "other"}),
            pa.allow_all_ingress_policy(pa.NAMESPACE),
            pa.allow_all_egress_policy(pa.NAMESPACE),
            pa.example_complicated_network_policy(),
        ]
        for pol in pols:
            policy = build_network_policies(True, [pol])
            assert_parity(policy, pods, namespaces, cases)
