"""L5 tests: the full conformance loop clusterless — generator cases run
through the Interpreter against a MockKubernetes with a policy-aware
(perfect-CNI) exec hook.  Every sampled case must PASS: the simulated table
(TPU engine) must equal the mock-kube table on every step.

Also: LabelsDiff algebra (ported from testcasestate_tests.go), state
dual-writes, reset/verify."""

import io

import pytest

from cyclonus_tpu.connectivity import (
    CombinedResults,
    Interpreter,
    InterpreterConfig,
    LabelsDiff,
    Printer,
    TestCaseState,
)
from cyclonus_tpu.generator import TestCaseGenerator
from cyclonus_tpu.kube import MockKubernetes
from cyclonus_tpu.kube.mockcni import PolicyAwareMockExec
from cyclonus_tpu.probe import Resources


class TestLabelsDiff:
    # testcasestate_tests.go LabelsDiff specs
    def test_equal(self):
        d = LabelsDiff.compare({"a": "1"}, {"a": "1"})
        assert d.are_labels_equal()
        assert d.same == ["a"]

    def test_different_value(self):
        d = LabelsDiff.compare({"a": "1"}, {"a": "2"})
        assert not d.are_labels_equal()
        assert d.different == ["a"]

    def test_extra_and_missing(self):
        d = LabelsDiff.compare({"a": "1", "b": "2"}, {"a": "1", "c": "3"})
        assert d.extra == ["b"]
        assert d.missing == ["c"]
        assert not d.are_labels_equal()
        assert not d.are_all_expected_labels_present()

    def test_extra_ok_for_expected_present(self):
        d = LabelsDiff.compare({"a": "1", "b": "2"}, {"a": "1"})
        assert d.are_all_expected_labels_present()
        assert not d.are_labels_equal()


def build_harness(engine="tpu"):
    kube = MockKubernetes(1.0)
    resources = Resources.new_default(
        kube,
        ["x", "y", "z"],
        ["a", "b", "c"],
        [80, 81],
        ["TCP", "UDP"],
        pod_creation_timeout_seconds=1,
    )
    kube.exec_verdict_fn = PolicyAwareMockExec(kube)
    config = InterpreterConfig(
        reset_cluster_before_test_case=True,
        verify_cluster_state_before_test_case=True,
        kube_probe_retries=0,
        perturbation_wait_seconds=0,
        simulated_engine=engine,
        pod_wait_timeout_seconds=1,
    )
    return kube, resources, Interpreter(kube, resources, config)


class TestStateDualWrite:
    def test_policy_lifecycle(self):
        kube, resources, _ = build_harness()
        state = TestCaseState(kube, resources, [])
        from cyclonus_tpu.generator.netpol_builder import build_policy

        pol = build_policy().network_policy()
        state.create_policy(pol)
        assert len(state.policies) == 1
        assert len(kube.get_network_policies_in_namespace("x")) == 1
        with pytest.raises(Exception):
            state.create_policy(pol)
        state.update_policy(pol)
        state.delete_policy(pol.namespace, pol.name)
        assert state.policies == []
        assert kube.get_network_policies_in_namespace("x") == []

    def test_pod_lifecycle(self):
        kube, resources, _ = build_harness()
        state = TestCaseState(kube, resources, [])
        state.create_pod("x", "d", {"pod": "d"})
        pod = state.resources.get_pod("x", "d")
        assert pod.ip.startswith("192.168.")
        assert kube.get_pod("x", "d").pod_ip == pod.ip
        state.set_pod_labels("x", "d", {"pod": "d", "extra": "1"})
        assert kube.get_pod("x", "d").labels["extra"] == "1"
        state.delete_pod("x", "d")
        with pytest.raises(Exception):
            kube.get_pod("x", "d")

    def test_reset_and_verify(self):
        kube, resources, _ = build_harness()
        state = TestCaseState(kube, resources, [])
        from cyclonus_tpu.generator.netpol_builder import build_policy

        state.create_policy(build_policy().network_policy())
        with pytest.raises(Exception):
            state.verify_cluster_state()  # policies exist
        state.reset_cluster_state()
        state.verify_cluster_state()


def sample_cases():
    gen = TestCaseGenerator(True, "192.168.0.5", ["x", "y", "z"], [], [])
    cases = []
    cases.extend(gen.rules_test_cases())  # 4
    cases.extend(gen.target_test_cases()[:2])
    cases.extend(gen.peers_test_cases()[:4])
    cases.extend(gen.conflict_test_cases()[:3])
    cases.extend(gen.action_test_cases()[:3])
    cases.extend(gen.upstream_e2e_test_cases()[:2])
    return cases


class TestFullLoopAgainstPerfectCNI:
    def test_sampled_cases_all_pass(self):
        kube, resources, interpreter = build_harness()
        # ipblock cases must derive from a REAL pod ip in the mock
        pod_ip = resources.get_pod("z", "c").ip
        gen = TestCaseGenerator(True, pod_ip, ["x", "y", "z"], [], [])
        cases = (
            gen.rules_test_cases()
            + gen.peers_test_cases()[:6]
            + gen.conflict_test_cases()[:4]
            + gen.action_test_cases()[:2]
        )
        out = io.StringIO()
        printer = Printer(noisy=False, ignore_loopback=False, out=out)
        failed = []
        for tc in cases:
            result = interpreter.execute_test_case(tc)
            printer.print_test_case_result(result)
            if not result.passed(ignore_loopback=False):
                failed.append((tc.description, result.err))
        assert not failed, f"failed cases: {failed}"
        printer.print_summary()
        text = out.getvalue()
        assert "| Tag | Result |" in text
        assert "✅" in text
        # every case passed, so the summary's per-test Result column must
        # contain no lowercase "failed" cell and no markdown cross
        summary_text = text.split("Summary:")[1]
        assert "failed" not in summary_text
        assert "❌" not in summary_text

    def test_summary_counts(self):
        kube, resources, interpreter = build_harness()
        gen = TestCaseGenerator(True, "192.168.0.5", ["x", "y", "z"], [], [])
        results = [
            interpreter.execute_test_case(tc) for tc in gen.rules_test_cases()
        ]
        summary = CombinedResults(results=results).summary(False)
        assert summary.passed == 4
        assert summary.failed == 0
        assert summary.protocol_counts["TCP"]["same"] > 0

    def test_oracle_engine_in_interpreter(self):
        kube, resources, interpreter = build_harness(engine="oracle")
        gen = TestCaseGenerator(True, "192.168.0.5", ["x", "y", "z"], [], [])
        tc = gen.rules_test_cases()[0]
        result = interpreter.execute_test_case(tc)
        assert result.passed(False)

    def test_named_port_case_against_perfect_cni(self):
        # regression: the mock CNI must resolve the traffic port NAME from
        # the (port, protocol) container, or named-port policies diverge
        kube, resources, interpreter = build_harness()
        gen = TestCaseGenerator(True, "192.168.0.5", ["x", "y", "z"], [], [])
        named = [
            tc
            for tc in gen.port_protocol_test_cases()
            if "named-port" in tc.tags and "pathological" not in tc.tags
        ]
        assert named
        for tc in named[:4]:
            result = interpreter.execute_test_case(tc)
            assert result.passed(False), (tc.description, result.err)

    def test_batch_jobs_with_perfect_cni(self):
        # the /worker batch path must produce the same tables
        kube = MockKubernetes(1.0)
        resources = Resources.new_default(
            kube,
            ["x", "y"],
            ["a", "b"],
            [80],
            ["TCP"],
            pod_creation_timeout_seconds=1,
            batch_jobs=True,
        )
        kube.exec_verdict_fn = PolicyAwareMockExec(kube)
        config = InterpreterConfig(
            reset_cluster_before_test_case=True,
            kube_probe_retries=0,
            perturbation_wait_seconds=0,
            batch_jobs=True,
            pod_wait_timeout_seconds=1,
        )
        interpreter = Interpreter(kube, resources, config)
        gen = TestCaseGenerator(True, "192.168.0.5", ["x", "y"], [], [])
        for tc in gen.rules_test_cases():
            result = interpreter.execute_test_case(tc)
            assert result.passed(False), (tc.description, result.err)

    def test_multi_step_action_case(self):
        kube, resources, interpreter = build_harness()
        gen = TestCaseGenerator(True, "192.168.0.5", ["x", "y", "z"], [], [])
        # Create/delete namespace case exercises pod/ns create + delete
        tc = gen.action_test_cases()[2]
        assert tc.description == "Create/delete namespace"
        result = interpreter.execute_test_case(tc)
        assert result.err is None
        assert result.passed(False), "perturbation case should pass vs perfect CNI"
        assert len(result.steps) == 3
