"""analyze with live-cluster pod sourcing (fake kubectl on PATH).

Covers the reference behaviors rebuilt in cli/analyze.py:
  * query-target with pods sourced from the cluster and merged with the
    JSON file (analyze.go:133-140, 170-178)
  * probe mode building probe.Resources from cluster pods/namespaces and
    running an all-available probe without a model file
    (analyze.go:255-299), including the skip-warnings for port-less
    containers / container-less pods
"""

import json
import os
import subprocess
import sys

import pytest

from fakekubectl import FakeKubectl, pod_json

DENY_ALL_X = {
    "apiVersion": "networking.k8s.io/v1",
    "kind": "NetworkPolicy",
    "metadata": {"name": "deny-all", "namespace": "x"},
    "spec": {"podSelector": {}, "policyTypes": ["Ingress"]},
}


def run_cli(fake_root, *args, timeout=120):
    env = dict(os.environ)
    env["PATH"] = f"{fake_root}{os.pathsep}{env.get('PATH', '')}"
    return subprocess.run(
        [sys.executable, "-m", "cyclonus_tpu"] + list(args),
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd="/root/repo",
        env=env,
    )


@pytest.fixture
def fake(tmp_path):
    return FakeKubectl(tmp_path)


def test_query_target_sources_pods_from_cluster(fake):
    # call order for -n x: policies, pods (ns labels are only fetched
    # for probe mode — query-target never consumes them)
    fake.enqueue({"items": [DENY_ALL_X]})
    fake.enqueue({"items": [pod_json(ns="x", name="a", labels={"pod": "a"})]})
    proc = run_cli(fake.root, "analyze", "-n", "x", "--mode", "query-target")
    assert proc.returncode == 0, proc.stderr
    assert "pod in ns x with labels {'pod': 'a'}" in proc.stdout
    assert "x/deny-all" in proc.stdout  # the target matching the pod


def test_query_target_merges_cluster_and_file(fake, tmp_path):
    fake.enqueue({"items": [DENY_ALL_X]})
    fake.enqueue({"items": [pod_json(ns="x", name="a", labels={"pod": "a"})]})
    pod_file = tmp_path / "pods.json"
    pod_file.write_text(
        json.dumps([{"Namespace": "other", "Labels": {"pod": "z"}}])
    )
    proc = run_cli(
        fake.root,
        "analyze", "-n", "x", "--mode", "query-target",
        "--target-pod-path", str(pod_file),
    )
    assert proc.returncode == 0, proc.stderr
    # cluster pod first, file pod appended (analyze.go:171-178)
    out = proc.stdout
    assert out.index("pod in ns x") < out.index("pod in ns other")


def test_probe_builds_resources_from_cluster(fake):
    pods = [
        pod_json(ns="x", name="a", labels={"pod": "a"}, ip="10.0.0.1"),
        pod_json(ns="x", name="b", labels={"pod": "b"}, ip="10.0.0.2"),
    ]
    # a pod whose only container has no ports -> skipped with a warning
    portless = pod_json(ns="x", name="c", ip="10.0.0.3")
    portless["spec"]["containers"][0]["ports"] = []
    fake.enqueue({"items": [DENY_ALL_X]})
    fake.enqueue({"items": pods + [portless]})
    fake.enqueue({"metadata": {"name": "x", "labels": {"ns": "x"}}})
    proc = run_cli(
        fake.root, "analyze", "-n", "x", "--mode", "probe", "--engine", "oracle"
    )
    assert proc.returncode == 0, proc.stderr
    assert "Combined:" in proc.stdout
    # deny-all in x: the 2x2 combined table is all X
    assert "x/a" in proc.stdout and "x/b" in proc.stdout
    combined = proc.stdout.split("Combined:")[1]
    assert "| X   | X   |" in combined
    assert "skipping container x/c/cont-80-tcp, no ports available" in proc.stderr
    assert "skipping pod x/c, no containers available" in proc.stderr
    assert "x/c" not in proc.stdout


def test_probe_without_model_or_cluster_fails(fake):
    proc = run_cli(fake.root, "analyze", "--mode", "probe")
    assert proc.returncode != 0
    assert "probe mode needs a model" in (proc.stderr + proc.stdout)


def test_all_namespaces_sources_everything(fake):
    fake.enqueue({"items": [DENY_ALL_X]})  # netpols -A
    fake.enqueue({"items": [pod_json(ns="x", name="a")]})  # pods -A
    fake.enqueue(
        {"items": [{"metadata": {"name": "x", "labels": {"ns": "x"}}}]}
    )  # namespaces (probe consumes ns labels)
    proc = run_cli(fake.root, "analyze", "-A", "--mode", "probe",
                   "--engine", "oracle")
    assert proc.returncode == 0, proc.stderr
    argvs = [c["argv"] for c in fake.calls()]
    assert argvs == [
        ["get", "networkpolicy", "--all-namespaces", "-o", "json"],
        ["get", "pods", "--all-namespaces", "-o", "json"],
        ["get", "namespaces", "-o", "json"],
    ]


def test_lint_mode_fetches_no_pods(fake):
    # cheap modes must not pull the cluster's pod list (only policies)
    fake.enqueue({"items": [DENY_ALL_X]})
    proc = run_cli(fake.root, "analyze", "-n", "x", "--mode", "lint")
    assert proc.returncode == 0, proc.stderr
    argvs = [c["argv"] for c in fake.calls()]
    assert argvs == [["get", "networkpolicy", "-n", "x", "-o", "json"]]
