"""utils/envflags.py tests: the CYCLONUS_* registry is complete over
every token the tree actually reads (grep-backed, so a new env var
cannot ship undeclared), the never-raise accessor semantics (malformed
degrades to the registered default; the two bool conventions are
selected by the default), the SLAB_MAX_BYTES / AUTOTUNE_TIMEOUT_S
parse-drift regressions (engine paths used to raise on a malformed
value that serve degraded), and the README env-var table staying
generated-not-handwritten."""

import os
import re
from contextlib import contextmanager

from cyclonus_tpu.utils import envflags

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@contextmanager
def _env(**kv):
    old = {k: os.environ.get(k) for k in kv}
    try:
        for k, v in kv.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


class TestRegistryCompleteness:
    def test_every_env_read_in_tree_is_registered(self):
        """Grep cyclonus_tpu/ for CYCLONUS_* tokens; every one must be a
        registered Flag.  (Docstrings mentioning a var count too — a
        documented flag that is not declared is exactly the drift this
        registry exists to prevent.)"""
        pat = re.compile(r"CYCLONUS_[A-Z0-9_]+")
        seen = set()
        pkg = os.path.join(REPO, "cyclonus_tpu")
        for root, _dirs, files in os.walk(pkg):
            for fn in files:
                if not fn.endswith(".py"):
                    continue
                with open(os.path.join(root, fn)) as f:
                    seen.update(pat.findall(f.read()))
        missing = sorted(seen - set(envflags.REGISTRY))
        assert not missing, f"undeclared env vars: {missing}"

    def test_registry_is_nonempty_and_typed(self):
        assert len(envflags.REGISTRY) >= 40
        for flag in envflags.REGISTRY.values():
            assert flag.kind in ("bool", "int", "float", "enum", "str", "path")
            assert flag.owner in (
                "engine", "serve", "worker", "chaos", "telemetry",
                "probe", "harness", "cli", "slo", "audit",
            )
            assert flag.description
            if flag.kind == "enum":
                assert flag.choices, flag.name

    def test_unregistered_name_is_a_programming_error(self):
        import pytest

        with pytest.raises(KeyError):
            envflags.get_int("CYCLONUS_NO_SUCH_FLAG")


class TestAccessorSemantics:
    def test_int_malformed_degrades_to_default(self):
        with _env(CYCLONUS_SERVE_PREWARM_PAIRS="not-a-number"):
            assert envflags.get_int("CYCLONUS_SERVE_PREWARM_PAIRS") == 64
        with _env(CYCLONUS_SERVE_PREWARM_PAIRS="128"):
            assert envflags.get_int("CYCLONUS_SERVE_PREWARM_PAIRS") == 128
        with _env(CYCLONUS_SERVE_PREWARM_PAIRS=None):
            assert envflags.get_int("CYCLONUS_SERVE_PREWARM_PAIRS") == 64

    def test_float_malformed_degrades_to_default(self):
        with _env(CYCLONUS_CHAOS_TTFV_S="soon"):
            assert envflags.get_float("CYCLONUS_CHAOS_TTFV_S") == 150.0
        with _env(CYCLONUS_CHAOS_TTFV_S="2.5"):
            assert envflags.get_float("CYCLONUS_CHAOS_TTFV_S") == 2.5

    def test_bool_opt_in_convention(self):
        # default False => armed only by exactly "1"
        with _env(CYCLONUS_TRACE_EVENTS="1"):
            assert envflags.get_bool("CYCLONUS_TRACE_EVENTS") is True
        with _env(CYCLONUS_TRACE_EVENTS="yes"):
            assert envflags.get_bool("CYCLONUS_TRACE_EVENTS") is False
        with _env(CYCLONUS_TRACE_EVENTS=None):
            assert envflags.get_bool("CYCLONUS_TRACE_EVENTS") is False

    def test_bool_opt_out_convention(self):
        # default True => disarmed only by exactly "0"
        with _env(CYCLONUS_TELEMETRY="0"):
            assert envflags.get_bool("CYCLONUS_TELEMETRY") is False
        with _env(CYCLONUS_TELEMETRY="anything"):
            assert envflags.get_bool("CYCLONUS_TELEMETRY") is True
        with _env(CYCLONUS_TELEMETRY=None):
            assert envflags.get_bool("CYCLONUS_TELEMETRY") is True

    def test_enum_degrades_to_default_on_unknown(self):
        with _env(CYCLONUS_CIDR_TSS="bogus"):
            assert envflags.get_enum("CYCLONUS_CIDR_TSS") == "auto"
        with _env(CYCLONUS_CIDR_TSS="1"):
            assert envflags.get_enum("CYCLONUS_CIDR_TSS") == "1"


class TestSlabBudgetDriftRegression:
    """engine/api.py and engine/cidrspace.py used to parse
    CYCLONUS_SLAB_MAX_BYTES with a bare int() — a malformed value
    raised at evaluate time on engine paths while serve degraded it to
    the 6 GiB default.  All four sites now share envflags.get_int."""

    def test_malformed_budget_degrades_everywhere(self):
        with _env(CYCLONUS_SLAB_MAX_BYTES="6GiB"):
            assert envflags.get_int("CYCLONUS_SLAB_MAX_BYTES") == 6 * 2**30
            from cyclonus_tpu.serve.incremental import patch_byte_budget

            assert patch_byte_budget() == 6 * 2**30

    def test_malformed_budget_does_not_raise_on_cidr_gate(self):
        import random

        from bench import build_synthetic
        from cyclonus_tpu.engine import TpuPolicyEngine, cidrspace
        from cyclonus_tpu.matcher import build_network_policies

        pods, namespaces, policies = build_synthetic(12, 3, random.Random(7))
        policy = build_network_policies(True, policies)
        eng = TpuPolicyEngine(policy, pods, namespaces)
        with _env(CYCLONUS_SLAB_MAX_BYTES="6GiB"):
            # resolve()'s HBM gate used to carry its own try/except copy
            # of the parse; through envflags it must degrade, not raise,
            # whether or not the synthetic set has IPv4 atoms.
            cidrspace.resolve(eng._tensors, mode="1")

    def test_malformed_budget_does_not_raise_on_class_counts_gate(self):
        import random

        from bench import build_synthetic
        from cyclonus_tpu.engine import TpuPolicyEngine
        from cyclonus_tpu.matcher import build_network_policies

        pods, namespaces, policies = build_synthetic(12, 3, random.Random(7))
        policy = build_network_policies(True, policies)
        with _env(CYCLONUS_SLAB_MAX_BYTES="oops", CYCLONUS_CLASS_COMPRESS="1"):
            eng = TpuPolicyEngine(policy, pods, namespaces)
            # the eligibility gate consults the budget; a malformed
            # value must degrade to the default, not raise at dispatch
            assert eng._class_counts_eligible(2) in (True, False)

    def test_autotune_timeout_shared_parse(self):
        with _env(CYCLONUS_AUTOTUNE_TIMEOUT_S="oops"):
            assert envflags.get_float("CYCLONUS_AUTOTUNE_TIMEOUT_S") == 240.0
        with _env(CYCLONUS_AUTOTUNE_TIMEOUT_S="17.5"):
            assert envflags.get_float("CYCLONUS_AUTOTUNE_TIMEOUT_S") == 17.5


class TestReadmeTable:
    def test_markdown_table_covers_registry(self):
        table = envflags.markdown_table()
        for name in envflags.REGISTRY:
            assert f"`{name}`" in table

    def test_readme_env_table_is_generated(self):
        """README's env-var table is the generator's output verbatim —
        regenerate with
        python -c 'from cyclonus_tpu.utils import envflags; print(envflags.markdown_table())'
        when the registry changes."""
        with open(os.path.join(REPO, "README.md")) as f:
            readme = f.read()
        assert envflags.markdown_table() in readme
