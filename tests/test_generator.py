"""L4 tests: golden counts for every case family (the reference's cheap
regression net over the whole generator, testcasegenerator_tests.go:11-24)
plus tag taxonomy and feature extraction checks."""

from cyclonus_tpu.generator import TestCaseGenerator, count_test_cases_by_tag
from cyclonus_tpu.generator.tags import StringSet, TAG_DENY_ALL, TAG_RULE, validate_tags
import pytest


@pytest.fixture(scope="module")
def gen():
    return TestCaseGenerator(True, "1.2.3.4", ["x", "y", "z"], [], [])


class TestGoldenCounts:
    def test_family_counts(self, gen):
        assert len(gen.peers_test_cases()) == 112
        assert len(gen.action_test_cases()) == 6
        assert len(gen.rules_test_cases()) == 4
        assert len(gen.upstream_e2e_test_cases()) == 13
        assert len(gen.target_test_cases()) == 6
        assert len(gen.example_test_cases()) == 1
        assert len(gen.port_protocol_test_cases()) == 58
        assert len(gen.conflict_test_cases()) == 16

    def test_total(self, gen):
        assert len(gen.generate_test_cases()) == 216

    def test_default_cli_excludes(self, gen):
        # cli/generate.go:66 default excludes
        g = TestCaseGenerator(
            True,
            "1.2.3.4",
            ["x", "y", "z"],
            [],
            ["multi-peer", "upstream-e2e", "example", "end-port"],
        )
        # end-port isn't a tag in this taxonomy; filter with the valid ones
        g.excluded_tags = ["multi-peer", "upstream-e2e", "example"]
        filtered = g.generate_test_cases()
        assert len(filtered) == 216 - 90 - 13 - 1

    def test_tag_filter_include(self):
        g = TestCaseGenerator(True, "1.2.3.4", ["x", "y", "z"], [TAG_DENY_ALL], [])
        cases = g.generate_test_cases()
        assert all(TAG_DENY_ALL in tc.tags for tc in cases)
        assert len(cases) > 0


class TestTags:
    def test_sub_adds_primary(self):
        s = StringSet.of(TAG_DENY_ALL)
        assert TAG_RULE in s
        assert TAG_DENY_ALL in s

    def test_validate(self):
        validate_tags(["deny-all", "rule"])
        with pytest.raises(ValueError):
            validate_tags(["nope-not-a-tag"])

    def test_counts_by_tag(self, gen):
        counts = count_test_cases_by_tag(gen.generate_all_test_cases())
        assert counts["deny-all"] > 0
        assert counts["multi-peer"] == 90


class TestFeatures:
    def test_base_policy_features(self, gen):
        tc = gen.action_test_cases()[0]
        features = tc.get_features()
        assert "action: create policy" in features["action"]
        assert "action: delete policy" in features["action"]
        assert "policy with both ingress and egress" in features["general"]
        assert "1 rule" in features["ingress"]
        assert "2+ rules" in features["egress"]
        assert "numbered port" in features["ingress"]

    def test_ipblock_features(self, gen):
        # find a peers case with ipblock-with-except
        for tc in gen.peers_test_cases():
            if "ip-block-with-except" in tc.tags and "multi-peer" not in tc.tags:
                features = tc.get_features()
                direction = (
                    "ingress" if "ingress" in tc.tags else "egress"
                )
                assert "IPBlock with except" in features[direction]
                return
        raise AssertionError("no ipblock-with-except case found")

    def test_descriptions_nonempty(self, gen):
        for tc in gen.generate_all_test_cases():
            assert tc.description


class TestCaseStructure:
    def test_policies_buildable(self, gen):
        # every generated policy must compile through the matcher
        from cyclonus_tpu.matcher import build_network_policies

        for tc in gen.generate_all_test_cases():
            for step in tc.steps:
                for action in step.actions:
                    if action.create_policy is not None:
                        build_network_policies(True, [action.create_policy.policy])
                    if action.update_policy is not None:
                        build_network_policies(True, [action.update_policy.policy])

    def test_ipblock_cases_derive_from_pod_ip(self):
        g = TestCaseGenerator(True, "192.168.3.77", ["x", "y", "z"], [], [])
        found = False
        for tc in g.peers_test_cases():
            for step in tc.steps:
                for action in step.actions:
                    if action.create_policy is None:
                        continue
                    pol = action.create_policy.policy
                    for rule in pol.spec.ingress:
                        for peer in rule.from_:
                            if peer.ip_block is not None:
                                assert peer.ip_block.cidr == "192.168.3.0/24"
                                found = True
        assert found
