"""The overlapped ring-exchange mesh path (engine/sharded.py): parity
against the single-device kernel and the all-gather reference schedule
at 1/2/4/8 virtual devices (uneven pod/device divisions included), the
tiered and class-compressed routes, the peer-buffer HBM watermark claim
(ring < allgather), the double-buffered pipelined counts twin, the
min-of-5 overlapped-vs-allgather throughput differential, and the
zero-recompile elastic-resize contract (same-bucket cluster resizes
reuse every compiled sharded program)."""

import random
import time

import numpy as np
import pytest
from jax.sharding import Mesh

from cyclonus_tpu.engine import PortCase, TpuPolicyEngine
from cyclonus_tpu.engine import sharded as sharded_mod
from cyclonus_tpu.engine.api import _bucket_down, _bucket_pods, _bucket_up
from cyclonus_tpu.matcher import build_network_policies
from cyclonus_tpu.telemetry import instruments as ti

from test_engine_tiled import CASES, fuzz_problem


def cpu_mesh(n_dev):
    import jax

    cpu = jax.devices("cpu")
    if len(cpu) < n_dev:
        pytest.skip(f"needs {n_dev} CPU devices, have {len(cpu)}")
    return Mesh(np.array(cpu[:n_dev]), ("x",))


def grids_equal(a, b):
    return all(
        np.array_equal(
            np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        )
        for name in ("ingress", "egress", "combined")
    )


def synthetic_engine(n_pods, n_pols=6, seed=3, **kw):
    from bench import build_synthetic

    pods, namespaces, policies = build_synthetic(
        n_pods, n_pols, random.Random(seed)
    )
    policy = build_network_policies(True, policies)
    return TpuPolicyEngine(policy, pods, namespaces, **kw), policy, pods


class TestRingParity:
    @pytest.mark.parametrize("n_dev", [1, 2, 4, 8])
    @pytest.mark.parametrize("seed", [0, 3])
    def test_ring_matches_single_device_uneven(self, seed, n_dev):
        """Overlapped ring grid == single-device kernel at every mesh
        width, with pod counts that do NOT divide the device count
        (padded rows must stay inert)."""
        policy, pods, namespaces = fuzz_problem(seed, n_extra_pods=4)
        assert len(pods) % 8 != 0  # 13 pods: uneven over every mesh
        engine = TpuPolicyEngine(policy, pods, namespaces)
        ref = engine.evaluate_grid(CASES)
        ring = engine.evaluate_grid_sharded(
            CASES, mesh=cpu_mesh(n_dev), schedule="ring"
        )
        assert grids_equal(ring, ref)
        # pad rows stripped: the grid is exactly n x n
        n = len(pods)
        assert np.asarray(ring.combined).shape == (len(CASES), n, n)

    @pytest.mark.parametrize("seed", [1, 4])
    def test_ring_bit_identical_to_allgather(self, seed):
        """The overlapped schedule and the all-gather reference must
        produce the SAME truth tables bit for bit."""
        policy, pods, namespaces = fuzz_problem(seed, n_extra_pods=2)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        mesh = cpu_mesh(8)
        ring = engine.evaluate_grid_sharded(CASES, mesh=mesh, schedule="ring")
        ag = engine.evaluate_grid_sharded(
            CASES, mesh=mesh, schedule="allgather"
        )
        assert grids_equal(ring, ag)

    def test_ring_tiered_engine(self):
        """The precedence-tier epilogue resolves INSIDE the ring step:
        a tiered engine's overlapped grid must equal the single-device
        tiered kernel."""
        from cyclonus_tpu.kube.netpol import IntOrString, LabelSelector
        from cyclonus_tpu.tiers.model import (
            AdminNetworkPolicy,
            BaselineAdminNetworkPolicy,
            TierPort,
            TierRule,
            TierScope,
            TierSet,
        )

        policy, pods, namespaces = fuzz_problem(7, n_extra_pods=4)
        tiers = TierSet(
            anps=[
                AdminNetworkPolicy(
                    name="deny-a",
                    priority=5,
                    subject=TierScope(
                        pod_selector=LabelSelector.make({"pod": "a"})
                    ),
                    ingress=[
                        TierRule(
                            action="Deny",
                            peers=[TierScope(
                                pod_selector=LabelSelector.make({"pod": "b"})
                            )],
                            ports=[TierPort(
                                protocol="TCP", port=IntOrString(80)
                            )],
                        )
                    ],
                )
            ],
            banp=BaselineAdminNetworkPolicy(
                subject=TierScope(
                    pod_selector=LabelSelector.make({"pod": "c"})
                ),
                ingress=[TierRule(action="Deny", peers=[TierScope()])],
            ),
        )
        engine = TpuPolicyEngine(policy, pods, namespaces, tiers=tiers)
        ref = engine.evaluate_grid(CASES)
        ring = engine.evaluate_grid_sharded(
            CASES, mesh=cpu_mesh(8), schedule="ring"
        )
        assert grids_equal(ring, ref)

    def test_ring_class_compressed_engine(self):
        """The compressed route is a C x C ring over class
        representatives + the gather epilogue; still bit-identical to
        the dense single-device grid."""
        policy, pods, namespaces = fuzz_problem(2, n_extra_pods=6)
        engine = TpuPolicyEngine(
            policy, pods, namespaces, class_compress="1"
        )
        assert engine.pod_classes() is not None
        ref_engine = TpuPolicyEngine(
            policy, pods, namespaces, class_compress="0"
        )
        ref = ref_engine.evaluate_grid(CASES)
        ring = engine.evaluate_grid_sharded(
            CASES, mesh=cpu_mesh(8), schedule="ring"
        )
        assert grids_equal(ring, ref)

    def test_ring_ipv6_host_rows(self):
        """Host-evaluated (IPv6) peer rows ride the pod-sharded
        host_ip_match columns through the ring like every other per-pod
        array."""
        from cyclonus_tpu.kube.netpol import (
            IPBlock,
            LabelSelector,
            NetworkPolicyIngressRule,
            NetworkPolicyPeer,
        )
        from test_engine_parity import default_cluster, mkpol

        pods, namespaces = default_cluster()
        pods = [
            (ns, name, labels, f"2001:db8::{i + 1}")
            for i, (ns, name, labels, _ip) in enumerate(pods)
        ]
        policy = build_network_policies(
            True,
            [
                mkpol(
                    "v6",
                    "x",
                    LabelSelector.make(),
                    ["Ingress"],
                    ingress=[
                        NetworkPolicyIngressRule(
                            ports=[],
                            from_=[
                                NetworkPolicyPeer(
                                    ip_block=IPBlock.make(
                                        "2001:db8::/112",
                                        ["2001:db8::4/126"],
                                    )
                                )
                            ],
                        )
                    ],
                )
            ],
        )
        engine = TpuPolicyEngine(policy, pods, namespaces)
        ref = engine.evaluate_grid(CASES)
        ring = engine.evaluate_grid_sharded(
            CASES, mesh=cpu_mesh(8), schedule="ring"
        )
        assert grids_equal(ring, ref)


class TestMeshCounts:
    def test_pipelined_twin_matches_counts(self):
        """The double-buffered pipelined mesh twin must return the same
        counts as the sync ring path and the single-device engine."""
        engine, _policy, _pods = synthetic_engine(13)
        want = engine.evaluate_grid_counts(CASES, block=4, backend="xla")
        mesh = cpu_mesh(8)
        sync = engine.evaluate_grid_counts_ring(CASES, block=4, mesh=mesh)
        assert sync == want
        dt, counts = engine.mesh_counts_pipelined_eval_s(
            CASES, reps=3, block=4, mesh=mesh
        )
        assert counts == want
        assert dt > 0
        assert ti.MESH_RING_STEP_SECONDS.value() > 0

    def test_pipelined_twin_tiered(self):
        """Tier slabs rotate with the bundle: the pipelined twin on a
        tiered engine equals the tiered counts engine."""
        from cyclonus_tpu.kube.netpol import LabelSelector
        from cyclonus_tpu.tiers.model import (
            AdminNetworkPolicy,
            TierRule,
            TierScope,
            TierSet,
        )

        policy, pods, namespaces = fuzz_problem(9, n_extra_pods=4)
        tiers = TierSet(
            anps=[
                AdminNetworkPolicy(
                    name="deny-b",
                    priority=3,
                    subject=TierScope(),
                    egress=[
                        TierRule(
                            action="Deny",
                            peers=[TierScope(
                                pod_selector=LabelSelector.make({"pod": "b"})
                            )],
                        )
                    ],
                )
            ]
        )
        engine = TpuPolicyEngine(policy, pods, namespaces, tiers=tiers)
        want = engine.evaluate_grid_counts(CASES, block=4)
        dt, counts = engine.mesh_counts_pipelined_eval_s(
            CASES, reps=2, block=4, mesh=cpu_mesh(4)
        )
        assert counts == want

    def test_overlapped_beats_allgather_throughput_min_of_5(self):
        """The min-of-5 throughput differential: the OVERLAPPED path —
        pipelined ring counts, peer bundle double-buffered and donated,
        per-eval transfer/precompute amortized away — must sustain at
        least the all-gather-style path's throughput (the replicated
        sharded counts, which re-transfers and replicates the full
        peer-side precompute per eval) on the virtual 8-device mesh.
        min-of-5 per leg absorbs scheduler noise; the measured gap is
        several-fold, so the bound has real margin."""
        engine, _policy, _pods = synthetic_engine(512, n_pols=48, seed=11)
        mesh = cpu_mesh(8)

        def run_allgather():
            return engine.evaluate_grid_counts_sharded(
                CASES, block=256, mesh=mesh, kernel="xla"
            )

        want = run_allgather()  # compile outside the timing
        ag_s = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            counts = run_allgather()
            ag_s = min(ag_s, time.perf_counter() - t0)
        # the pipelined twin is already a min-style amortization: reps
        # back-to-back dispatches, one barrier
        ring_s, ring_counts = engine.mesh_counts_pipelined_eval_s(
            CASES, reps=5, block=256, mesh=mesh
        )
        assert ring_counts == want
        assert ring_s <= ag_s, (ring_s, ag_s)


class TestPeerBufferWatermark:
    def test_ring_under_allgather_at_8_devices(self):
        """The scale-out acceptance: the overlapped schedule's peak
        per-device peer-buffer bytes (resident shard bundle + one
        in-flight block) must undercut the all-gather schedule's
        replicated peer copy at 8 devices — asserted through the SAME
        gauge the bench records."""
        engine, _policy, pods = synthetic_engine(64, n_pols=10)
        mesh = cpu_mesh(8)
        engine.evaluate_grid_sharded(CASES, mesh=mesh, schedule="ring")
        ring_bytes = ti.MESH_PEER_BYTES.value(schedule="ring")
        engine.evaluate_grid_sharded(CASES, mesh=mesh, schedule="allgather")
        ag_bytes = ti.MESH_PEER_BYTES.value(schedule="allgather")
        assert 0 < ring_bytes < ag_bytes
        # the host-side estimator agrees with what the gauges recorded
        t = engine._tensors_with_cases(CASES)
        t, _ = sharded_mod._pad_pod_arrays(t, len(pods), 8)
        from cyclonus_tpu.engine.encoding import pack_enabled

        assert ring_bytes == sharded_mod.peer_buffer_bytes(
            t, 8, "ring", pack=pack_enabled()
        )
        assert ag_bytes == sharded_mod.peer_buffer_bytes(t, 8, "allgather")


class TestElasticResize:
    def test_bucket_step_helpers_invert(self):
        for b in (4, 8, 16, 64, 128, 256, 384, 512, 1024):
            assert _bucket_down(_bucket_up(b, 1), 1) == b
            assert _bucket_down(_bucket_up(b, 2), 2) == b
        assert _bucket_down(4, 3) == 4  # floored at the smallest bucket

    def test_same_bucket_resize_zero_retrace(self):
        """The zero-recompile elastic-resize contract: a +-10% pod
        resize within one _bucket_pods bucket must not add a single
        trace to the shared grid kernel or the cached sharded (ring)
        program — the bucketing makes the shapes identical, so the jit
        caches hit."""
        from bench import build_synthetic
        from cyclonus_tpu.engine.kernel import evaluate_grid_kernel

        n_a, n_b = 900, 990  # +10%: both bucket to 1024
        assert _bucket_pods(n_a) == _bucket_pods(int(n_a * 1.1))
        pods, namespaces, policies = build_synthetic(
            n_b, 8, random.Random(11)
        )
        policy = build_network_policies(True, policies)
        mesh = cpu_mesh(8)
        eng_a = TpuPolicyEngine(policy, pods[:n_a], namespaces)
        eng_a.evaluate_grid(CASES)
        eng_a.evaluate_grid_sharded(CASES, mesh=mesh, schedule="ring")
        kernel_traces = evaluate_grid_kernel._cache_size()
        ring_fns = {
            id(fn): fn._cache_size()
            for fn in sharded_mod._SHARDED_PROGRAMS.values()
        }
        eng_b = TpuPolicyEngine(policy, pods, namespaces)
        eng_b.evaluate_grid(CASES)
        eng_b.evaluate_grid_sharded(CASES, mesh=mesh, schedule="ring")
        assert evaluate_grid_kernel._cache_size() == kernel_traces
        for fn in sharded_mod._SHARDED_PROGRAMS.values():
            assert fn._cache_size() == ring_fns.get(id(fn), 0), (
                "same-bucket resize retraced a sharded program"
            )
