"""tools/cachelint.py tests: seeded-violation gates for CC001–CC005
(each defect class must fire, each suppression must be honored), the
clean-run + annotation-count acceptance gate over the cache-bearing
packages, the runtime cachekeys registry strip/overhead contract, the
tier-1 slice of the key-mutation harness (tests/keyharness.py), the
regression tests for the real never-raise gaps the pass surfaced in
engine/autotune.py, and the combined four-leg lint wall-clock budget."""

import os
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import cachelint


def _lint_source(tmp_path, source: str, name: str = "mod.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    findings, _stats = cachelint.lint_paths([str(p)])
    return findings


def _codes(findings):
    return [f.code for f in findings]


class TestCC001TraceBakedKeys:
    def test_uncovered_closure_capture_fires(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import jax
            from cyclonus_tpu.engine.aot_cache import AotProgram

            def build(scale):
                return AotProgram("p", jax.jit(lambda x: x * scale))
            """,
        )
        assert _codes(findings) == ["CC001"]
        assert "'scale'" in findings[0].message

    def test_plan_expression_covers(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import jax
            from cyclonus_tpu.engine.aot_cache import AotProgram

            def build(scale):
                return AotProgram(
                    "p", jax.jit(lambda x: x * scale), plan=f"s={scale}"
                )
            """,
        )
        assert findings == []

    def test_trailing_comment_covers(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import jax
            from cyclonus_tpu.engine.aot_cache import AotProgram

            def build(scale):
                return AotProgram(  # cache-key: scale (caller-bucketed)
                    "p", jax.jit(lambda x: x * scale)
                )
            """,
        )
        assert findings == []

    def test_cachekeys_descriptor_covers(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import jax
            from cyclonus_tpu.engine.aot_cache import AotProgram
            from cyclonus_tpu.utils import cachekeys

            def build(scale):
                comps = cachekeys.program("scale")
                return AotProgram("p", jax.jit(lambda x: x * scale))
            """,
        )
        assert findings == []

    def test_forward_derivation_covers(self, tmp_path):
        """n_dev = mesh.devices.size: baked n_dev is covered because it
        derives from a name the key expression carries."""
        findings = _lint_source(
            tmp_path,
            """
            import jax
            from cyclonus_tpu.engine.aot_cache import AotProgram

            def build(mesh):
                n_dev = mesh.devices.size
                return AotProgram(
                    "p", jax.jit(lambda x: x * n_dev), plan=f"m={mesh}"
                )
            """,
        )
        assert findings == []

    def test_backward_derivation_covers(self, tmp_path):
        """The key embeds a digest OF the baked value: covered."""
        findings = _lint_source(
            tmp_path,
            """
            import jax
            from cyclonus_tpu.engine.aot_cache import AotProgram, digest

            def build(specs):
                spec_digest = digest(specs)
                return AotProgram(
                    "p", jax.jit(lambda x: x + len(specs)),
                    plan=f"d={spec_digest}",
                )
            """,
        )
        assert findings == []

    def test_self_attr_covered_via_method_expansion(self, tmp_path):
        """plan=self._plan() one level in: the self attrs the method
        body reads are key components."""
        findings = _lint_source(
            tmp_path,
            """
            import jax
            from cyclonus_tpu.engine.aot_cache import AotProgram

            class Engine:
                def _plan(self):
                    return f"pack={self._pack}"

                def build(self):
                    pack = self._pack
                    return AotProgram(
                        "p", jax.jit(lambda x: x * pack), plan=self._plan()
                    )
            """,
        )
        assert findings == []

    def test_self_attr_uncovered_fires(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import jax
            from cyclonus_tpu.engine.aot_cache import AotProgram

            class Engine:
                def build(self):
                    pack = self._pack
                    return AotProgram("p", jax.jit(lambda x: x * pack))
            """,
        )
        assert _codes(findings) == ["CC001"]

    def test_undeclared_program_dict_fires(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import jax

            _PROGRAMS = {}

            def get(mesh, shard):
                key = (shard,)
                fn = jax.jit(lambda t: t + shard)
                _PROGRAMS[key] = fn
                return fn
            """,
        )
        assert "CC001" in _codes(findings)
        assert any("no `# cache-key:` declaration" in f.message for f in findings)

    def test_declared_dict_with_incomplete_key_fires(self, tmp_path):
        """mesh is baked into the program but the key tuple only
        carries shard: the same key would serve a program compiled for
        a different mesh."""
        findings = _lint_source(
            tmp_path,
            """
            import jax

            _PROGRAMS = {}  # cache-key: shard

            def get(mesh, shard):
                key = (shard,)
                fn = jax.jit(lambda t: t + mesh.size + shard)
                _PROGRAMS[key] = fn
                return fn
            """,
        )
        assert _codes(findings) == ["CC001"]
        assert "'mesh'" in findings[0].message

    def test_declared_dict_complete_key_clean(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import jax

            _PROGRAMS = {}  # cache-key: mesh, shard

            def get(mesh, shard):
                key = (tuple(mesh.devices.flat), shard)
                fn = jax.jit(lambda t: t + mesh.size + shard)
                _PROGRAMS[key] = fn
                return fn
            """,
        )
        assert findings == []

    def test_module_global_jit_with_bake_fires(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import jax

            _JIT = None

            def get(width):
                global _JIT
                if _JIT is None:
                    _JIT = jax.jit(lambda b: b * width)
                return _JIT
            """,
        )
        assert _codes(findings) == ["CC001"]
        assert "process-lifetime staleness" in findings[0].message

    def test_module_global_jit_without_bake_clean(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import jax

            _JIT = None

            def get():
                global _JIT
                if _JIT is None:
                    _JIT = jax.jit(lambda b, i, v: b.at[i].set(v))
                return _JIT
            """,
        )
        assert findings == []

    def test_suppression(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import jax
            from cyclonus_tpu.engine.aot_cache import AotProgram

            def build(scale):
                return AotProgram("p", jax.jit(lambda x: x * scale))  # cachelint: ignore[CC001]
            """,
        )
        assert findings == []


class TestCC002DerivedInvalidation:
    BASE = """
    class Engine:
        def __init__(self):
            self._pre_cache = None  # derived-from: buffer
            self._grid_jit = None  # derived-from: shapes
            self._packed_buf = None  # derived-from: patched

        def invalidate_after_patch(self):
            {body}
    """

    def test_value_derived_not_reset_fires(self, tmp_path):
        findings = _lint_source(
            tmp_path, self.BASE.format(body="pass")
        )
        assert _codes(findings) == ["CC002"]
        assert "_pre_cache" in findings[0].message

    def test_value_derived_reset_clean(self, tmp_path):
        findings = _lint_source(
            tmp_path, self.BASE.format(body="self._pre_cache = None")
        )
        assert findings == []

    def test_undeclared_cache_attr_fires(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            class Engine:
                def __init__(self):
                    self._foo_cache = None

                def invalidate_after_patch(self):
                    pass
            """,
        )
        assert _codes(findings) == ["CC002"]
        assert "no `# derived-from:` declaration" in findings[0].message

    def test_class_without_invalidate_is_out_of_scope(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            class Widget:
                def __init__(self):
                    self._foo_cache = None
            """,
        )
        assert findings == []

    def test_suppression(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            class Engine:
                def __init__(self):
                    self._foo_cache = None  # cachelint: ignore[CC002]

                def invalidate_after_patch(self):
                    pass
            """,
        )
        assert findings == []


class TestCC003EnvOnCachedPath:
    def test_env_read_in_jit_fires(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import os
            import jax

            @jax.jit
            def body(x):
                if os.environ.get("MODE") == "1":
                    return x
                return x + 1
            """,
        )
        assert _codes(findings) == ["CC003"]

    def test_env_read_one_level_helper_fires(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import os
            import jax

            def mode():
                return os.getenv("MODE", "0")

            @jax.jit
            def body(x):
                return x + int(mode())
            """,
        )
        assert _codes(findings) == ["CC003"]
        assert "reached from jit-traced" in findings[0].message

    def test_eager_resolution_clean(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import os
            import jax

            def build():
                mode = os.environ.get("MODE", "0") == "1"
                return jax.jit(lambda x: x + 1 if mode else x)
            """,
        )
        assert findings == []

    def test_subscript_env_read_fires(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import os
            import jax

            @jax.jit
            def body(x):
                return x + len(os.environ["MODE"])
            """,
        )
        assert _codes(findings) == ["CC003"]


class TestCC004PersistDiscipline:
    def test_direct_write_fires(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import json

            CACHE_VERSION = 1

            def store(key, value, path):
                with open(path, "w") as f:
                    json.dump({"v": CACHE_VERSION, "key": key}, f)
            """,
        )
        assert _codes(findings) == ["CC004"]
        assert "tmp+os.replace" in findings[0].message

    ATOMIC = """
    import json, logging, os, tempfile

    CACHE_VERSION = 1
    log = logging.getLogger(__name__)

    def load(path):  # never-raises
        try:
            with open(path) as f:
                return json.load(f)
        except Exception as e:
            log.info("corrupt: %s", e)
            return None

    def store({params}, path):
        fd, tmp = tempfile.mkstemp(dir=".")
        with os.fdopen(fd, "w") as f:
            json.dump({entry}, f)
        os.replace(tmp, path)
    """

    def test_atomic_versioned_keyed_clean(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            self.ATOMIC.format(
                params="key, value",
                entry='{"v": CACHE_VERSION, "key": key, "value": value}',
            ),
        )
        assert findings == []

    def test_missing_version_fires(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            self.ATOMIC.format(
                params="key, value", entry='{"key": key, "value": value}'
            ),
        )
        assert _codes(findings) == ["CC004"]
        assert "CACHE_VERSION" in findings[0].message

    def test_missing_key_fires(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            self.ATOMIC.format(
                params="value", entry='{"v": CACHE_VERSION, "value": value}'
            ),
        )
        assert _codes(findings) == ["CC004"]
        assert "cache key" in findings[0].message

    def test_missing_mkstemp_fires(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import json, os

            CACHE_VERSION = 1

            def load(path):  # never-raises
                try:
                    with open(path) as f:
                        return json.load(f)
                except Exception:
                    raise

            def store(key, value, path):
                with open(path + ".tmp", "w") as f:
                    json.dump({"v": CACHE_VERSION, "key": key}, f)
                os.replace(path + ".tmp", path)
            """,
        )
        assert _codes(findings) == ["CC004"]
        assert "mkstemp" in findings[0].message

    def test_missing_never_raise_read_twin_fires(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import json, os, tempfile

            CACHE_VERSION = 1

            def store(key, value, path):
                fd, tmp = tempfile.mkstemp(dir=".")
                with os.fdopen(fd, "w") as f:
                    json.dump({"v": CACHE_VERSION, "key": key}, f)
                os.replace(tmp, path)
            """,
        )
        assert _codes(findings) == ["CC004"]
        assert "read twin" in findings[0].message

    def test_non_cache_module_untouched(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import json

            def save(path, data):
                with open(path, "w") as f:
                    json.dump(data, f)
            """,
        )
        assert findings == []


class TestCC005NeverRaise:
    def test_unshielded_call_fires(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import json

            def load(path):  # never-raises
                with open(path) as f:
                    return json.load(f)
            """,
        )
        assert all(c == "CC005" for c in _codes(findings))
        assert findings

    def test_broad_handler_with_counter_clean(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import json

            def load(path, metric):  # never-raises
                try:
                    with open(path) as f:
                        return json.load(f)
                except Exception:
                    metric.inc()
                    return None
            """,
        )
        assert findings == []

    def test_narrow_handler_does_not_shield(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import json

            def load(path):  # never-raises
                try:
                    with open(path) as f:
                        return json.load(f)
                except FileNotFoundError:
                    return None
            """,
        )
        assert all(c == "CC005" for c in _codes(findings))
        assert findings

    def test_swallow_without_evidence_fires(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import json

            def load(path):  # never-raises
                try:
                    with open(path) as f:
                        return json.load(f)
                except Exception:
                    return None
            """,
        )
        assert _codes(findings) == ["CC005"]
        assert "evidence" in findings[0].message

    def test_never_raise_callee_chain_clean(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import os

            def resolve():  # never-raises
                raw = os.environ.get("X")
                if raw is None:
                    return None
                return os.path.expanduser(raw.strip())

            def outer(key):  # never-raises
                base = resolve()
                if base is None:
                    return None
                return os.path.join(base, key)
            """,
        )
        assert findings == []

    def test_plain_index_subscript_fires_slice_clean(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def head(items):  # never-raises
                return items[0]

            def tail(items):  # never-raises
                return items[1:]
            """,
        )
        assert _codes(findings) == ["CC005"]
        assert "subscript" in findings[0].message

    def test_raise_fires(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def load(path):  # never-raises
                raise ValueError(path)
            """,
        )
        assert _codes(findings) == ["CC005"]

    def test_unannotated_function_untouched(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import json

            def load(path):
                with open(path) as f:
                    return json.load(f)
            """,
        )
        assert findings == []

    def test_suppression(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            import json

            def load(path):  # never-raises
                with open(path) as f:  # cachelint: ignore[CC005]
                    return json.load(f)  # cachelint: ignore[CC005]
            """,
        )
        assert findings == []


CACHE_PACKAGES = [
    os.path.join(REPO, "cyclonus_tpu", p)
    for p in ("engine", "serve", "perfobs", "chaos")
]


class TestCleanRun:
    def test_packages_clean_with_live_annotations(self):
        """THE acceptance gate: 0 findings over the cache-bearing
        packages with >= 25 live cache-key / derived-from /
        never-raises annotations."""
        findings, stats = cachelint.lint_paths(CACHE_PACKAGES)
        assert findings == [], "\n".join(f.render() for f in findings)
        assert stats["annotations"] >= 25, stats

    def test_cli_exit_status(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "cachelint.py")],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "never-raises annotation(s)" in proc.stderr

    def test_makefile_wires_cachelint_into_lint(self):
        mk = open(os.path.join(REPO, "Makefile")).read()
        assert "cachelint" in mk
        lint_block = mk.split("lint:", 1)[1]
        assert "cachelint" in lint_block.split("\n\n")[0] or (
            "cachelint" in mk.split("lint:")[0]
        )
        assert "keyharness" in mk


class TestSurfacedGaps:
    """Regression tests for the REAL contract violations the pass
    surfaced (ISSUE 13's fix-with-regression-test requirement)."""

    def test_store_winner_unserializable_degrades(self, tmp_path, monkeypatch):
        """json.dump's TypeError on a non-serializable timing value
        used to ESCAPE store_winner's documented never-raise contract
        (`except OSError` only).  Now it logs and returns False."""
        from cyclonus_tpu.engine import autotune as at

        monkeypatch.setenv(
            "CYCLONUS_AUTOTUNE_CACHE", str(tmp_path / "autotune.json")
        )
        key = at.make_key({"n": 8}, "cpu", "packed32")
        ok = at.store_winner(
            key, {"kernel": "packed", "bs": 8, "bd": 8},
            {"weird": object()},  # not JSON-serializable -> TypeError
        )
        assert ok is False  # degraded, did not raise
        # the file is untouched/absent, and a later good write works
        assert at.load_winner(key) is None
        assert at.store_winner(key, {"kernel": "default"}) is True
        assert at.load_winner(key) == {"kernel": "default"}

    def test_read_all_survives_arbitrary_reader_error(
        self, tmp_path, monkeypatch
    ):
        """_read_all's documented '{} on ANY problem' now holds for
        exceptions outside the old (OSError, ValueError) pair."""
        import json as _json

        from cyclonus_tpu.engine import autotune as at

        path = tmp_path / "autotune.json"
        path.write_text("{}")
        monkeypatch.setenv("CYCLONUS_AUTOTUNE_CACHE", str(path))

        def boom(*a, **k):
            raise RuntimeError("pathological entry")

        monkeypatch.setattr(_json, "load", boom)
        assert at._read_all(str(path)) == {}
        assert at.load_winner("anything") is None

    def test_load_winner_malformed_dims(self, tmp_path, monkeypatch):
        from cyclonus_tpu.engine import autotune as at

        monkeypatch.setenv(
            "CYCLONUS_AUTOTUNE_CACHE", str(tmp_path / "a.json")
        )
        key = at.make_key({"n": 8}, "cpu", "packed32")
        assert at.store_winner(key, {"kernel": "packed", "bs": "wide"})
        assert at.load_winner(key) is None  # malformed dim -> fresh search


class TestCachekeysRegistry:
    def test_inactive_registry_is_inert(self):
        """The suite never sets CYCLONUS_KEYHARNESS: registration is a
        no-op, the registry stays empty, and the cachekey instruments
        never enter the metric registry (the strip proof)."""
        from cyclonus_tpu.telemetry.metrics import REGISTRY
        from cyclonus_tpu.utils import cachekeys

        assert cachekeys.ACTIVE is False
        assert (
            cachekeys.register(
                "t", kind="program", components=("a",), fingerprint="f"
            )
            is None
        )
        assert cachekeys.registered_count() == 0
        assert cachekeys.registered() == {}
        names = set(REGISTRY.snapshot())
        assert not any(n.startswith("cyclonus_tpu_cachekey") for n in names), (
            names
        )

    def test_program_descriptor_passthrough(self):
        from cyclonus_tpu.utils import cachekeys

        assert cachekeys.program("a", "b") == ("a", "b")

    def test_zero_overhead_when_off(self):
        """< 2% (or the measurement's own noise floor) for the inactive
        register() no-op against a plain no-op call — the paired-median
        differential method of test_locklint/test_shapelint."""
        import statistics

        from cyclonus_tpu.utils import cachekeys

        def noop():
            return None

        reps = 20000

        def timed_reg():
            t0 = time.perf_counter()
            for _ in range(reps):
                cachekeys.register(
                    "cache", kind="program", components=("a", "b")
                )
            return (time.perf_counter() - t0) / reps

        def timed_noop():
            t0 = time.perf_counter()
            for _ in range(reps):
                noop()
            return (time.perf_counter() - t0) / reps

        timed_reg(), timed_noop()  # warm
        diffs, bases = [], []
        for i in range(15):
            if i % 2 == 0:
                tr, tn = timed_reg(), timed_noop()
            else:
                tn, tr = timed_noop(), timed_reg()
            diffs.append(tr - tn)
            bases.append(tn)
        med = max(statistics.median(diffs), 0.0)
        base = statistics.median(bases)
        mad = statistics.median([abs(d - statistics.median(diffs)) for d in diffs])
        floor = 3 * mad / max(len(diffs) ** 0.5, 1)
        # the no-op path is one module-attr read + return: it must cost
        # no more than a comparable plain call, within noise.  A 500ns
        # absolute ceiling guards the property even if the baseline
        # no-op is optimized away.
        assert med <= max(0.02 * base + floor, 5e-7), (med, base, floor)
        assert cachekeys.registered_count() == 0  # still inert


class TestKeyharnessTier1:
    def test_quick_slice(self, tmp_path):
        """The bounded tier-1 slice of the key-mutation harness: AOT +
        autotune key fields, the invalidate contract, and the pair
        program (the full sweep incl. subprocess restart legs is `make
        keyharness` / -m slow below)."""
        from tests import keyharness

        results = keyharness.run(str(tmp_path), quick=True)
        assert set(results) == {
            "aot_key_fields",
            "autotune_key_fields",
            "invalidate_derived_contract",
            "pairs_program_key",
        }
        assert results["invalidate_derived_contract"]["value_attrs"] >= 10


@pytest.mark.slow
class TestKeyharnessFull:
    def test_full_sweep(self, tmp_path):
        from tests import keyharness

        results = keyharness.run(str(tmp_path), quick=False)
        assert "aot_restart_subprocess" in results
        assert "registry_census" in results
        assert "sharded_program_key" in results


class TestLintBudget:
    def test_seven_legs_stay_under_wall_clock_budget(self):
        """The combined `make lint` static legs (jaxlint + locklint +
        shapelint + cachelint + planlint + statelint + wirelint,
        in-process over their Makefile paths) must stay inside one
        minute — the seven-leg lint is part of `make check`'s inner
        loop and a slow linter stops being run."""
        import importlib

        t0 = time.perf_counter()
        jaxlint = importlib.import_module("jaxlint")
        locklint = importlib.import_module("locklint")
        shapelint = importlib.import_module("shapelint")
        planlint = importlib.import_module("planlint")
        statelint = importlib.import_module("statelint")
        wirelint = importlib.import_module("wirelint")
        jax_paths = [
            os.path.join(REPO, "cyclonus_tpu", p)
            for p in (
                "engine", "telemetry", "worker", "analysis", "probe",
                "perfobs", "serve", "tiers", "chaos", "linter", "recipes",
            )
        ]
        for f in jaxlint.iter_py_files(jax_paths):
            jaxlint.lint_file(f)
        locklint.lint_paths([os.path.join(REPO, "cyclonus_tpu")])
        shapelint.lint_paths(
            [
                os.path.join(REPO, "cyclonus_tpu", p)
                for p in (
                    "engine", "analysis", os.path.join("worker", "model.py"),
                    "perfobs", "serve", "tiers", "chaos", "linter", "recipes",
                )
            ]
        )
        cachelint.lint_paths(CACHE_PACKAGES)
        planlint.lint_paths(
            [
                os.path.join(REPO, "cyclonus_tpu", p)
                for p in ("engine", "serve", "tiers")
            ]
        )
        statelint.lint_paths(
            [
                os.path.join(REPO, "cyclonus_tpu", p)
                for p in ("serve", "audit")
            ]
        )
        wirelint.lint_paths(
            [
                os.path.join(REPO, "cyclonus_tpu", p)
                for p in ("worker", "serve")
            ]
        )
        elapsed = time.perf_counter() - t0
        assert elapsed < 60.0, f"seven lint legs took {elapsed:.1f}s"
