"""tools/shapelint.py + utils/contracts.py tests: seeded-violation gates
for SC001-SC004 (each defect class must be caught, each suppression
honored), the clean-run + annotation-count acceptance gate over the
engine/analysis/worker-model paths, the runtime contract twin
(CYCLONUS_SHAPE_CHECK=1 catches a deliberately mis-shaped encoding in a
subprocess; zero overhead when off, pinned by the paired-median
differential), the ip-except mask-guard regression, and the wire-drift
static check."""

import os
import subprocess
import sys
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import shapelint

PRELUDE = """
    import numpy as np
    from dataclasses import dataclass
    from cyclonus_tpu.utils import contracts


    @contracts.checked
    @dataclass
    class Enc:
        ids: np.ndarray = contracts.tensor("(N, L) int32", sentinel="-1=pad")
        ips: np.ndarray = contracts.tensor(
            "(N,) uint32", sentinel="0=invalid", mask="ip_valid"
        )
        ip_valid: np.ndarray = contracts.tensor("(N,) bool")
"""


def _lint_source(tmp_path, source: str, prelude: str = PRELUDE):
    p = tmp_path / "mod.py"
    p.write_text(textwrap.dedent(prelude).lstrip() + textwrap.dedent(source))
    findings, _stats = shapelint.lint_paths([str(p)])
    return findings


def _codes(findings):
    return [f.code for f in findings]


class TestSC001ShapeContract:
    def test_wrong_rank_at_constructor(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def build(n):
                return Enc(
                    ids=np.zeros((n,), dtype=np.int32),
                    ips=np.zeros((n,), np.uint32),
                    ip_valid=np.ones(n, dtype=bool),
                )
            """,
        )
        assert _codes(findings) == ["SC001"]
        assert "rank" in findings[0].message

    def test_wrong_dtype_through_call_site_inference(self, tmp_path):
        """One level of return inference: the helper's dtype travels to
        the constructor check."""
        findings = _lint_source(
            tmp_path,
            """
            def helper(n):
                return np.full((n, 4), -1, dtype=np.float32)

            def build(n):
                return Enc(
                    ids=helper(n),
                    ips=np.zeros((n,), np.uint32),
                    ip_valid=np.ones(n, dtype=bool),
                )
            """,
        )
        assert _codes(findings) == ["SC001"]
        assert "float32" in findings[0].message

    def test_consistent_build_is_clean(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def helper(n):
                return np.full((n, 4), -1, dtype=np.int32)

            def build(n):
                return Enc(
                    ids=helper(n),
                    ips=np.zeros((n,), np.uint32),
                    ip_valid=np.ones(n, dtype=bool),
                )
            """,
        )
        assert findings == []

    def test_rank_changing_implicit_broadcast(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def f(
                a,  # shape: (N,) int32
                b,  # shape: (N, L) int32
            ):
                return a == b
            """,
        )
        assert _codes(findings) == ["SC001"]
        assert "broadcast" in findings[0].message

    def test_explicit_index_marks_intent(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def f(
                a,  # shape: (N,) int32
                b,  # shape: (N, L) int32
            ):
                return a[:, None] == b
            """,
        )
        assert findings == []

    def test_suppression(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def build(n):
                return Enc(
                    ids=np.zeros((n,), dtype=np.int32),  # shapelint: ignore[SC001]
                    ips=np.zeros((n,), np.uint32),
                    ip_valid=np.ones(n, dtype=bool),
                )
            """,
        )
        assert findings == []


class TestSC002DtypePromotion:
    def test_cross_signedness_compare(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def f(n):
                a = np.zeros((n,), dtype=np.uint32)
                b = np.zeros((n,), dtype=np.int32)
                return a == b
            """,
        )
        assert _codes(findings) == ["SC002"]
        assert "uint32 vs int32" in findings[0].message

    def test_explicit_cast_is_clean(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def f(n):
                a = np.zeros((n,), dtype=np.uint32)
                b = np.zeros((n,), dtype=np.int32)
                return a == b.astype(np.uint32)
            """,
        )
        assert findings == []

    def test_declared_dtypes_cross_module_fields(self, tmp_path):
        """The contract registry feeds the dtype check: dict-key access
        to a declared field carries its declared dtype."""
        findings = _lint_source(
            tmp_path,
            """
            def f(enc, raw):
                ids = np.zeros((4,), dtype=np.int32)
                return enc["ips"] & ids
            """,
        )
        assert _codes(findings) == ["SC002"]

    def test_bool_arithmetic_upcast(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def f(n):
                a = np.zeros((n,), dtype=bool)
                b = np.ones((n,), dtype=bool)
                return a + b
            """,
        )
        assert _codes(findings) == ["SC002"]
        assert "bool" in findings[0].message

    def test_bare_float_literal(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def f():
                return np.array([0.5, 1.5])
            """,
        )
        assert _codes(findings) == ["SC002"]
        assert "float" in findings[0].message

    def test_pinned_dtype_is_clean(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def f():
                return np.array([0.5, 1.5], dtype=np.float32)
            """,
        )
        assert findings == []

    def test_suppression(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def f(n):
                a = np.zeros((n,), dtype=np.uint32)
                b = np.zeros((n,), dtype=np.int32)
                return a == b  # shapelint: ignore[SC002]
            """,
        )
        assert findings == []


class TestSC003Sentinel:
    def test_masked_compare_without_mask(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def f(enc, raw):
                return enc.ips == raw
            """,
        )
        assert _codes(findings) == ["SC003"]
        assert "ip_valid" in findings[0].message

    def test_mask_in_same_statement_is_clean(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def f(enc, raw):
                return (enc.ips == raw) & enc.ip_valid
            """,
        )
        assert findings == []

    def test_wrong_sentinel_fill(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def build(n):
                return Enc(
                    ids=np.full((n, 4), -2, dtype=np.int32),
                    ips=np.zeros((n,), np.uint32),
                    ip_valid=np.ones(n, dtype=bool),
                )
            """,
        )
        assert _codes(findings) == ["SC003"]
        assert "-2" in findings[0].message

    def test_suppression(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def f(enc, raw):
                return enc.ips == raw  # shapelint: ignore[SC003]
            """,
        )
        assert findings == []


class TestSC004TileAlignment:
    def test_misaligned_literal_lane_dim(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def make(pl):
                return pl.BlockSpec((8, 100), lambda i: (i, 0))
            """,
            prelude="",
        )
        assert _codes(findings) == ["SC004"]
        assert "100" in findings[0].message

    def test_unprovable_round_math_lane_dim(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def make(pl, n):
                g = -(-n // 96) * 96
                return pl.BlockSpec((8, g), lambda i: (i, 0))
            """,
            prelude="",
        )
        assert _codes(findings) == ["SC004"]

    def test_correct_round_up_is_clean(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def make(pl, n):
                g = -(-n // 128) * 128
                return pl.BlockSpec((8, g), lambda i: (i, 0))
            """,
            prelude="",
        )
        assert findings == []

    def test_round_up_through_helper_and_unpack(self, tmp_path):
        """The prover follows one level of call returns, including
        tuple unpacking and `x *= 2` augmentation (the _tiles_for
        shape)."""
        findings = _lint_source(
            tmp_path,
            """
            BS = 512

            def tiles(n):
                bs = BS
                if n > bs:
                    bs *= 2
                return bs, 128

            def make(pl, n):
                bs, kt = tiles(n)
                return pl.BlockSpec((kt, bs), lambda i: (i, 0))
            """,
            prelude="",
        )
        assert findings == []

    def test_tile_comment_assertion(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def f(n):
                w = n + 128 - n % 128  # tile: 128
                return w
            """,
            prelude="",
        )
        assert _codes(findings) == ["SC004"]
        assert "tile: 128" in findings[0].message

    def test_tile_comment_discharged(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def f(n):
                w = ((n + 127) // 128) * 128  # tile: 128
                return w
            """,
            prelude="",
        )
        assert findings == []

    def test_suppression(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def make(pl):
                return pl.BlockSpec((8, 100), lambda i: (i, 0))  # shapelint: ignore[SC004]
            """,
            prelude="",
        )
        assert findings == []


class TestSC004PackedLaneArithmetic:
    """The packed-word (32-per-word) round-ups of the bit-packed kernel
    must be prover-discharged like the 128-lane lane_round_up — both
    locally and THROUGH IMPORTS (the cross-file registry resolution)."""

    def _lint_two(self, tmp_path, a_src, b_src):
        import textwrap

        (tmp_path / "enc.py").write_text(textwrap.dedent(a_src))
        (tmp_path / "use.py").write_text(textwrap.dedent(b_src))
        findings, _stats = shapelint.lint_paths(
            [str(tmp_path / "enc.py"), str(tmp_path / "use.py")]
        )
        return findings

    def test_packed_round_up_discharges_locally(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            PACK_BITS = 32

            def packed_words(n):
                return -(-max(int(n), 1) // PACK_BITS)

            def f(t):
                total = packed_words(t) * PACK_BITS  # tile: 32
                return total
            """,
            prelude="",
        )
        assert findings == []

    def test_packed_round_up_discharges_through_import(self, tmp_path):
        findings = self._lint_two(
            tmp_path,
            """
            PACK_BITS = 32

            def packed_words(n):
                return -(-max(int(n), 1) // PACK_BITS)
            """,
            """
            from enc import PACK_BITS, packed_words

            def f(t):
                total = packed_words(t) * PACK_BITS  # tile: 32
                return total
            """,
        )
        assert findings == []

    def test_imported_helper_proves_lane_dim(self, tmp_path):
        # a BlockSpec lane dim built from an IMPORTED round-up helper
        # (the pallas_kernel.lane_round_up pattern used cross-module)
        findings = self._lint_two(
            tmp_path,
            """
            def lane_round_up(n):
                return -(-max(int(n), 1) // 128) * 128
            """,
            """
            from enc import lane_round_up

            def make(pl, w):
                lanes = lane_round_up(w + 1)  # tile: 128
                return pl.BlockSpec((8, lanes), lambda i: (i, 0))
            """,
        )
        assert findings == []

    def test_hand_rolled_packed_round_up_flags(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            def f(t):
                total = t + 32 - t % 32  # tile: 32
                return total
            """,
            prelude="",
        )
        assert _codes(findings) == ["SC004"]
        assert "tile: 32" in findings[0].message

    def test_imported_const_wrong_multiple_flags(self, tmp_path):
        # cross-file constants must prove the RIGHT divisibility, not
        # rubber-stamp: words * 32 is not a multiple of 128
        findings = self._lint_two(
            tmp_path,
            """
            PACK_BITS = 32
            """,
            """
            from enc import PACK_BITS

            def f(w):
                bits = w * PACK_BITS  # tile: 128
                return bits
            """,
        )
        assert _codes(findings) == ["SC004"]

    def test_live_packed_annotations_discharge(self):
        # the real engine modules: the packed helpers' own `# tile: 32`
        # assertions must hold with zero SC004 findings
        findings, stats = shapelint.lint_paths(
            [
                os.path.join(REPO, "cyclonus_tpu", "engine", f)
                for f in ("encoding.py", "kernel.py", "pallas_kernel.py")
            ]
        )
        assert [f for f in findings if f.code == "SC004"] == []


class TestWireDrift:
    WIRE_PRELUDE = """
        from typing import ClassVar, Dict
        from cyclonus_tpu.utils import contracts
    """

    def test_unconditional_optional_and_missing_required(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            class Msg:
                WIRE: ClassVar[Dict[str, contracts.WireField]] = {
                    "A": contracts.wire(str),
                    "B": contracts.wire(float, optional=True),
                    "C": contracts.wire(str),
                }

                def to_dict(self):
                    return {"A": self.a, "B": self.b, "X": 1}
            """,
            prelude=self.WIRE_PRELUDE,
        )
        assert _codes(findings) == ["SC001", "SC001", "SC001"]
        msgs = " ".join(f.message for f in findings)
        assert "'X'" in msgs and "'B'" in msgs and "'C'" in msgs

    def test_compliant_emit_is_clean(self, tmp_path):
        findings = _lint_source(
            tmp_path,
            """
            class Msg:
                WIRE: ClassVar[Dict[str, contracts.WireField]] = {
                    "A": contracts.wire(str),
                    "B": contracts.wire(float, optional=True),
                }

                def to_dict(self):
                    d = {"A": self.a}
                    if self.b is not None:
                        d["B"] = self.b
                    return d
            """,
            prelude=self.WIRE_PRELUDE,
        )
        assert findings == []

    def test_worker_model_optional_field_drift_is_caught(self, tmp_path):
        """The compat gate the wire suite relies on: emitting
        Result.LatencyMs unconditionally (an optional-field contract
        change) must be flagged when worker/model.py drifts.  The WIRE
        tables are registry projections now (worker/wireregistry.py),
        not literals shapelint can extract — so the gate on the REAL
        model moved to wirelint's WR001; this test pins it against a
        drifted copy of the real tree (model + registry + golden)."""
        import sys as _sys

        _sys.path.insert(0, os.path.join(REPO, "tools"))
        import wirelint

        worker = os.path.join(REPO, "cyclonus_tpu", "worker")
        src = open(os.path.join(worker, "model.py")).read()
        drifted = src.replace(
            "        if self.latency_ms is not None:\n"
            "            d[\"LatencyMs\"] = self.latency_ms\n",
            "        d[\"LatencyMs\"] = self.latency_ms\n",
        )
        assert drifted != src, "model.py emit site moved; update this test"
        pkg = tmp_path / "worker_drifted"
        pkg.mkdir()
        (pkg / "model.py").write_text(drifted)
        for name in ("wireregistry.py", "wire_schema.json"):
            (pkg / name).write_text(open(os.path.join(worker, name)).read())
        findings, _ = wirelint.lint_paths([str(pkg)])
        assert any(
            f.code == "WR001" and "LatencyMs" in f.message
            and "unconditionally" in f.message for f in findings
        ), findings


class TestCleanRun:
    PATHS = [
        os.path.join(REPO, "cyclonus_tpu", "engine"),
        os.path.join(REPO, "cyclonus_tpu", "analysis"),
        os.path.join(REPO, "cyclonus_tpu", "worker", "model.py"),
    ]

    def test_pipeline_is_clean_with_live_annotations(self):
        """The acceptance gate: shapelint exits clean over the encoding
        -> kernel pipeline + wire model with >= 20 live contract
        annotations (ISSUE 5 floor; the codebase carries far more)."""
        findings, stats = shapelint.lint_paths(self.PATHS)
        assert findings == [], "\n".join(f.render() for f in findings)
        assert stats["contracts"] >= 20, stats

    def test_cli_exit_status(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "shapelint.py"),
             "cyclonus_tpu/engine", "cyclonus_tpu/analysis",
             "cyclonus_tpu/worker/model.py"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "contract annotation(s)" in proc.stderr


class TestRuntimeContracts:
    def test_violation_fires_in_checked_subprocess(self):
        """CYCLONUS_SHAPE_CHECK=1: a deliberately mis-shaped encoding
        raises ContractViolation naming the field path and the observed
        shape/dtype; a real encode stays clean and the contract-check
        counter registers."""
        code = textwrap.dedent(
            """
            import numpy as np
            from cyclonus_tpu.engine.encoding import (
                ClusterEncoding, _Vocab, encode_policy,
            )
            from cyclonus_tpu.matcher.core import Policy
            from cyclonus_tpu.utils.contracts import ContractViolation

            enc = encode_policy(
                Policy(),
                [("ns", "a", {"app": "x"}, "10.0.0.1"), ("ns", "b", {}, "zz")],
                {"ns": {"team": "t"}},
            )
            assert enc.cluster.pod_ip_valid.tolist() == [True, False]
            from cyclonus_tpu.telemetry.metrics import REGISTRY
            text = REGISTRY.render_prometheus() if hasattr(
                REGISTRY, "render_prometheus") else ""
            try:
                ClusterEncoding(
                    vocab=_Vocab(), pod_keys=["ns/a"],
                    pod_ns_id=np.zeros((1, 2), np.int32),  # rank 2, declared (N,)
                    pod_kv=np.full((1, 1), -1, np.int32),
                    pod_key=np.full((1, 1), -1, np.int32),
                    pod_ip=np.zeros(1, np.uint32),
                    pod_ip_valid=np.zeros(1, bool),
                    pod_ips=["10.0.0.1"],
                    ns_kv=np.full((1, 1), -1, np.int32),
                    ns_key=np.full((1, 1), -1, np.int32),
                )
            except ContractViolation as e:
                assert "ClusterEncoding.pod_ns_id" in str(e), e
                assert "(1, 2)" in str(e), e
                print("VIOLATION-OK")
            else:
                raise SystemExit("mis-shaped encoding did not raise")
            """
        )
        env = dict(os.environ, CYCLONUS_SHAPE_CHECK="1", JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-c", code],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "VIOLATION-OK" in proc.stdout

    def test_wire_check_fires_in_checked_subprocess(self):
        code = textwrap.dedent(
            """
            from cyclonus_tpu.worker.model import Request
            from cyclonus_tpu.utils.contracts import ContractViolation
            try:
                Request.from_dict(
                    {"Key": "k", "Protocol": "tcp", "Host": "h", "Port": "80"}
                )
            except ContractViolation as e:
                assert "Request.Port" in str(e), e
                print("WIRE-VIOLATION-OK")
            else:
                raise SystemExit("wrong wire type did not raise")
            """
        )
        env = dict(os.environ, CYCLONUS_SHAPE_CHECK="1", JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-c", code],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "WIRE-VIOLATION-OK" in proc.stdout

    def test_check_off_returns_classes_untouched(self):
        from cyclonus_tpu.engine import encoding
        from cyclonus_tpu.utils import contracts

        assert not contracts.CHECK  # the test process never sets the var
        # checked() returned the classes untouched: the dataclass
        # __init__ is not wrapped (functools.wraps would leave
        # __wrapped__ behind), and args() returned original functions
        from cyclonus_tpu.engine import kernel

        for cls in (
            encoding.ClusterEncoding,
            encoding._DirectionEncoding,
            encoding.PolicyEncoding,
        ):
            assert not hasattr(cls.__init__, "__wrapped__"), cls
        for fn in (
            kernel.selector_match,
            kernel.direction_precompute,
            kernel.port_spec_allows,
        ):
            assert not hasattr(fn, "__wrapped__"), fn
            assert hasattr(fn, "__tensor_contracts__")  # lint metadata rides

    def test_zero_overhead_when_off(self):
        """<2% on dataclass construction: the contracts-annotated class
        vs a structurally identical plain dataclass.  With checking off
        `checked` returns the class untouched, so both loops run the
        same bytecode — pinned with the same paired-median differential
        as the guards overhead test (budget 2% or the measurement's own
        noise floor, whichever is larger)."""
        import statistics
        from dataclasses import dataclass

        import numpy as np

        from cyclonus_tpu.utils import contracts

        @contracts.checked
        @dataclass
        class Annotated:
            a: np.ndarray = contracts.tensor("(N, L) int32", sentinel="-1=pad")
            b: np.ndarray = contracts.tensor("(N,) uint32")
            c: np.ndarray = contracts.tensor("(N,) bool")

        @dataclass
        class Plain:
            a: np.ndarray
            b: np.ndarray
            c: np.ndarray

        a = np.full((8, 4), -1, np.int32)
        b = np.zeros(8, np.uint32)
        c = np.zeros(8, bool)
        reps = 20000

        def timed(cls):
            t0 = time.perf_counter()
            for _ in range(reps):
                cls(a=a, b=b, c=c)
            return (time.perf_counter() - t0) / reps

        timed(Annotated), timed(Plain)  # warm both code paths
        diffs, plains = [], []
        for i in range(21):
            if i % 2 == 0:
                tg = timed(Annotated)
                tp = timed(Plain)
            else:
                tp = timed(Plain)
                tg = timed(Annotated)
            diffs.append(tg - tp)
            plains.append(tp)
        med = statistics.median(diffs)
        overhead = max(med, 0.0)
        t_plain = statistics.median(plains)
        mad = statistics.median(abs(d - med) for d in diffs)
        noise_floor = 4 * mad / (len(diffs) ** 0.5)
        budget = max(0.02 * t_plain, noise_floor) + 5e-9
        assert overhead < budget, (
            f"contracts cost {overhead * 1e9:.1f} ns/init "
            f"({100 * overhead / t_plain:.2f}% of {t_plain * 1e9:.0f} ns; "
            f"budget {budget * 1e9:.1f} ns)"
        )


class TestIpExceptMaskRegression:
    def test_invalid_pod_never_matches_ip_peer(self):
        """Regression for the in_except mask-guard (the SC003 finding
        the pod_ip contract surfaced in kernel.direction_precompute):
        an ip peer with an except block must (a) block excepted valid
        pods, (b) allow non-excepted valid pods, and (c) never match a
        pod whose IP failed to parse — including via the except term,
        whose old form compared the 0-sentinel as a real address."""
        import jax.numpy as jnp
        import numpy as np

        from cyclonus_tpu.engine.encoding import PEER_IP
        from cyclonus_tpu.engine.kernel import direction_precompute

        # peer 0: 10.0.0.0/8 except 10.1.0.0/16 (and an adversarial
        # peer 1: 0.0.0.0/0 except 0.0.0.0/0, whose except row would
        # "match" the 0-sentinel of an invalid pod)
        enc = {
            "target_ns": jnp.array([0], jnp.int32),
            "target_sel": jnp.array([0], jnp.int32),
            "peer_target": jnp.array([0, 0], jnp.int32),
            "peer_kind": jnp.array([PEER_IP, PEER_IP], jnp.int32),
            "peer_ns_kind": jnp.array([2, 2], jnp.int32),
            "peer_ns_id": jnp.array([-1, -1], jnp.int32),
            "peer_ns_sel": jnp.array([-1, -1], jnp.int32),
            "peer_pod_kind": jnp.array([0, 0], jnp.int32),
            "peer_pod_sel": jnp.array([-1, -1], jnp.int32),
            "ip_base": jnp.array([0x0A000000, 0], jnp.uint32),
            "ip_mask": jnp.array([0xFF000000, 0], jnp.uint32),
            "ip_is_v4": jnp.array([True, True]),
            "ex_base": jnp.array([[0x0A010000], [0]], jnp.uint32),
            "ex_mask": jnp.array([[0xFFFF0000], [0]], jnp.uint32),
            "ex_valid": jnp.array([[True], [True]]),
        }
        pods = ["10.1.2.3", "10.2.2.2", "<unparseable>"]
        pod_ip = np.array([0x0A010203, 0x0A020202, 0], np.uint32)
        pod_ip_valid = np.array([True, True, False])
        pre = direction_precompute(
            enc,
            jnp.ones((1, 3), bool),
            jnp.ones((1, 1), bool),
            jnp.zeros(3, jnp.int32),
            jnp.asarray(pod_ip),
            jnp.asarray(pod_ip_valid),
        )
        got = np.asarray(pre["peer_match"])
        # peer 0: excepted / allowed / invalid
        assert got[0].tolist() == [False, True, False], (pods, got)
        # peer 1: everything in-cidr is excepted; the invalid pod must
        # be False through BOTH terms, not "in cidr but also in except"
        assert got[1].tolist() == [False, False, False], (pods, got)


class TestMakefileWiring:
    def test_make_lint_runs_shapelint(self):
        mk = open(os.path.join(REPO, "Makefile")).read()
        assert "shapelint:" in mk
        lint_rule = mk.split("\nlint:", 1)[1].split("\n\n", 1)[0]
        body = mk.split("\nshapelint:", 1)[1].split("\n\n", 1)[0]
        assert "shapelint" in mk.split("\nlint:", 1)[1].splitlines()[0], (
            "make lint must depend on shapelint"
        )
        assert "tools/shapelint.py" in body
        for target in ("cyclonus_tpu/engine", "cyclonus_tpu/analysis",
                       "cyclonus_tpu/worker/model.py"):
            assert target in body
        assert lint_rule is not None


class TestReviewRegressions:
    def test_bool_matmul_is_sc002(self, tmp_path):
        """bool @ bool stays bool in numpy (every nonzero sum collapses
        to True) — the exact hazard audit.py's astype-before-matmul
        comment names."""
        findings = _lint_source(
            tmp_path,
            """
            def f(n):
                a = np.zeros((n, n), dtype=bool)
                b = np.ones((n, n), dtype=bool)
                return a @ b
            """,
        )
        assert _codes(findings) == ["SC002"]
        assert "matmul" in findings[0].message

    def test_parse_spec_rejects_comma_typo(self):
        """'(N L)' must raise at declaration time, not become a wrong
        rank-1 contract the runtime twin then enforces spuriously."""
        import pytest

        from cyclonus_tpu.utils import contracts

        with pytest.raises(ValueError, match="N L"):
            contracts.parse_spec("(N L) int32")

    def test_result_parse_side_type_drift_is_caught(self):
        """Result.from_dict type-checks PRESENT wire keys under
        CYCLONUS_SHAPE_CHECK=1 (tolerating absent ones, per the compat
        rules), symmetric with Request.from_dict."""
        code = textwrap.dedent(
            """
            from cyclonus_tpu.worker.model import Result
            from cyclonus_tpu.utils.contracts import ContractViolation
            # absent optional keys tolerated
            Result.from_dict({
                "Request": {"Key": "k", "Protocol": "tcp", "Host": "h",
                            "Port": 1},
                "Output": "", "Error": "",
            })
            try:
                Result.from_dict({
                    "Request": {"Key": "k", "Protocol": "tcp", "Host": "h",
                                "Port": 1},
                    "Output": 5, "Error": "",
                })
            except ContractViolation as e:
                assert "Result.Output" in str(e), e
                print("RESULT-DRIFT-OK")
            else:
                raise SystemExit("drifted Output type did not raise")
            """
        )
        env = dict(os.environ, CYCLONUS_SHAPE_CHECK="1", JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-c", code],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "RESULT-DRIFT-OK" in proc.stdout
