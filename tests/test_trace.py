"""Trace-timeline layer tests (docs/DESIGN.md "Trace timelines"):

  * event recorder: span enter/exit captured as B/E events, bounded
    ring, enable/disable semantics, in-process ingest dedup;
  * Chrome trace-event export golden shape: required keys on every
    event (ph/ts/pid/tid/name), balanced + monotonically consistent B/E
    pairs, normalized timestamps, process-name metadata;
  * driver→worker context propagation: an in-process batch round trip
    and a REAL worker subprocess both share the driver's trace_id and
    nest under the issuing step's span path;
  * acceptance: `probe --mock --trace-out` writes a loadable Chrome
    trace; `/profile?seconds=N` on the metrics server returns 200 with
    a written profiler artifact;
  * `cyclonus-tpu trace` CLI export + summary modes;
  * metrics server: ephemeral port is reported; a taken port fails with
    MetricsPortBusy / one clean CLI line, not a traceback.
"""

import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import pytest

from cyclonus_tpu import telemetry
from cyclonus_tpu.telemetry import events, trace_export
from cyclonus_tpu.telemetry.spans import adopt, span

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _trace_isolation():
    """Every test starts untraced and leaves nothing active."""
    events.disable()
    events.reset()
    yield
    events.disable()
    events.reset()


def validate_chrome_trace(trace):
    """The golden-shape contract: required keys, and per-(pid, tid)
    balanced B/E pairs whose timestamps are monotonically consistent
    (each E closes the latest open B of the same name, never earlier
    than it)."""
    assert "traceEvents" in trace and "displayTimeUnit" in trace
    stacks = {}
    spans = 0
    for e in trace["traceEvents"]:
        for key in trace_export.CHROME_EVENT_KEYS:
            assert key in e, f"event missing {key}: {e}"
        if e["ph"] == "M":
            continue
        assert e["ph"] in ("B", "E"), f"unexpected phase {e['ph']}"
        assert e["ts"] >= 0
        stack = stacks.setdefault((e["pid"], e["tid"]), [])
        if e["ph"] == "B":
            stack.append(e)
        else:
            assert stack, f"E without open B on {(e['pid'], e['tid'])}: {e}"
            b = stack.pop()
            assert b["name"] == e["name"], f"mismatched pair {b} / {e}"
            assert e["ts"] >= b["ts"], f"E before B: {b} / {e}"
            spans += 1
    for key, stack in stacks.items():
        assert not stack, f"unclosed B events on {key}: {stack}"
    return spans


class TestEventRecorder:
    def test_disabled_by_default_and_costs_nothing(self):
        with span("ev.off"):
            pass
        assert events.entries() == []

    def test_span_enter_exit_captured_with_final_attrs(self):
        tid = events.enable()
        with span("ev.outer", pods=3) as s:
            with span("ev.inner"):
                pass
            s.set(targets=9)
        evts = events.entries()
        assert [e["ph"] for e in evts] == ["B", "B", "E", "E"]
        assert [e["name"] for e in evts] == [
            "ev.outer", "ev.inner", "ev.inner", "ev.outer",
        ]
        assert evts[1]["path"] == "ev.outer/ev.inner"
        assert all(e["trace_id"] == tid for e in evts)
        assert all(e["pid"] == os.getpid() for e in evts)
        # B carries entry attrs; E carries the final (s.set-enriched) view
        assert evts[0]["args"] == {"pods": 3}
        assert evts[3]["args"] == {"pods": 3, "targets": 9}
        assert evts[3]["ts"] >= evts[0]["ts"]

    def test_ring_is_bounded_newest_wins(self):
        events.enable()
        cap = events.RING.maxlen
        for i in range(cap + 10):
            events.record("B", f"n{i}", f"n{i}")
        assert len(events.entries()) == cap
        assert events.entries()[-1]["name"] == f"n{cap + 9}"

    def test_ingest_skips_own_pid_and_junk(self):
        events.enable("t1")
        with span("ev.mine"):
            pass
        own = events.entries()
        assert events.ingest(own) == 0  # in-process worker dedup
        foreign = [dict(e, pid=os.getpid() + 1) for e in own]
        assert events.ingest(foreign) == 2
        assert events.ingest([{"ph": "B"}, "junk", 42]) == 0
        assert len(events.entries()) == 4

    def test_mark_since_window(self):
        events.enable()
        with span("ev.before"):
            pass
        marker = events.mark()
        with span("ev.after"):
            pass
        new = events.since(marker)
        assert [e["name"] for e in new] == ["ev.after", "ev.after"]
        assert events.since(events.mark()) == []

    def test_adopt_nests_under_foreign_path(self):
        events.enable()
        with adopt("driver/step-3"):
            with span("ev.child"):
                pass
        assert events.entries()[0]["path"] == "driver/step-3/ev.child"
        # and the thread's path is restored
        with span("ev.top"):
            pass
        assert events.entries()[-1]["path"] == "ev.top"


class TestChromeExport:
    def test_golden_shape_and_pair_consistency(self):
        events.enable("shape-test")
        with span("exp.a", x=1):
            with span("exp.b"):
                pass
        trace = trace_export.to_chrome_trace()
        assert validate_chrome_trace(trace) == 2
        # JSON-serializable end to end
        rt = json.loads(json.dumps(trace))
        names = [e["name"] for e in rt["traceEvents"] if e["ph"] != "M"]
        assert names == ["exp.a", "exp.b", "exp.b", "exp.a"]
        # normalized timestamps: first event at 0, origin preserved
        first = [e for e in rt["traceEvents"] if e["ph"] == "B"][0]
        assert first["ts"] == 0.0
        assert rt["otherData"]["epoch_origin_s"] > 0
        assert rt["otherData"]["trace_id"] == "shape-test"
        # args carry the span path for navigation
        assert first["args"]["path"] == "exp.a"
        # process-name metadata row present
        metas = [e for e in rt["traceEvents"] if e["ph"] == "M"]
        assert metas and "driver" in metas[0]["args"]["name"]

    def test_trace_id_filter(self):
        events.enable("keep")
        with span("exp.keep"):
            pass
        events.ingest(
            [
                {
                    "ph": "B", "name": "exp.drop", "path": "exp.drop",
                    "ts": 1.0, "pid": os.getpid() + 1, "tid": 1,
                    "trace_id": "other",
                }
            ]
        )
        trace = trace_export.to_chrome_trace(trace_id="keep")
        names = {e["name"] for e in trace["traceEvents"] if e["ph"] != "M"}
        assert names == {"exp.keep"}

    def test_write_and_summarize(self, tmp_path):
        events.enable()
        with span("exp.w"):
            pass
        p = trace_export.write_chrome_trace(str(tmp_path / "t.json"))
        data = json.load(open(p))
        validate_chrome_trace(data)
        text = trace_export.summarize(data)
        assert "exp.w" in text and "1 process(es)" in text
        assert trace_export.summarize({"traceEvents": []}).startswith(
            "(empty trace"
        )


class _WorkerKube:
    """IKubernetes stub whose exec runs the REAL in-process worker, so a
    driver-side batch runner round-trips through the actual wire JSON."""

    def execute_remote_command(self, namespace, pod, container, command):
        from cyclonus_tpu.worker.worker import run_worker

        return run_worker(command[command.index("--jobs") + 1]), "", None


class TestContextPropagation:
    def _jobs(self):
        from cyclonus_tpu.probe.job import Job

        return [
            Job(
                from_key="x/a", from_namespace="x", from_pod="a",
                from_container="cont", to_key="x/b", to_host="127.0.0.1",
                to_namespace="x", resolved_port=1,
                resolved_port_name="p", protocol="TCP",
            )
        ]

    def test_in_process_roundtrip_single_trace_single_ring(self, monkeypatch):
        """Worker spans join the driver's trace_id, nest under the
        issuing step's span path, and are NOT duplicated by ingest when
        the worker ran in-process."""
        from cyclonus_tpu.probe.runner import KubeBatchJobRunner
        from cyclonus_tpu.worker import worker as worker_mod
        from cyclonus_tpu.worker.model import Result

        monkeypatch.setattr(
            worker_mod,
            "_probe_with_retries",
            lambda request: Result(request=request, output="connected"),
        )
        driver_tid = events.enable()
        runner = KubeBatchJobRunner(_WorkerKube())
        with span("interpreter.step", step=0):
            results = runner.run_jobs(self._jobs())
        assert [r.combined for r in results] == ["allowed"]
        evts = events.entries()
        assert all(e["trace_id"] == driver_tid for e in evts)
        worker_evts = [e for e in evts if e["name"].startswith("worker.")]
        assert {e["name"] for e in worker_evts} == {
            "worker.batch", "worker.probe",
        }
        # nesting: worker spans sit under the driver's step span path
        assert all(
            e["path"].startswith("interpreter.step/probe.kube_batch/")
            for e in worker_evts
        )
        # no duplication: exactly one B per span occurrence
        probe_b = [
            e for e in worker_evts
            if e["name"] == "worker.probe" and e["ph"] == "B"
        ]
        assert len(probe_b) == 1
        # the in-process worker must NOT have flipped the process-global
        # role: driver events recorded after the batch stay "driver"
        with span("post.batch"):
            pass
        assert events.entries()[-1]["role"] == "driver"
        validate_chrome_trace(trace_export.to_chrome_trace())

    def test_subprocess_worker_shares_trace_and_merges(self):
        """Acceptance: a REAL worker subprocess records events under the
        driver's trace_id in its own pid, ships them back on the Result
        wire, and the merged export shows both processes."""
        from cyclonus_tpu.worker.model import Batch, Request, Result

        driver_tid = events.enable()
        with span("interpreter.step", step=0):
            parent = "interpreter.step"
            batch = Batch(
                namespace="x", pod="a", container="c",
                requests=[
                    Request(
                        key="x/a->x/b", protocol="tcp",
                        host="127.0.0.1", port=1,
                    )
                ],
                trace_id=driver_tid,
                parent_span=parent,
            )
            env = dict(os.environ, CYCLONUS_CONNECT_NATIVE="1")
            proc = subprocess.run(
                [
                    sys.executable, "-m", "cyclonus_tpu.worker",
                    "--jobs", batch.to_json(),
                ],
                capture_output=True, text=True, timeout=120,
                cwd=REPO, env=env,
            )
        assert proc.returncode == 0, proc.stderr[-500:]
        results = [Result.from_dict(d) for d in json.loads(proc.stdout)]
        shipped = results[0].trace_events
        assert shipped, "worker shipped no trace events"
        assert all(e["trace_id"] == driver_tid for e in shipped)
        assert all(e["pid"] != os.getpid() for e in shipped)
        assert all(e["role"] == "worker" for e in shipped)
        assert all(e["path"].startswith("interpreter.step/") for e in shipped)
        assert events.ingest(shipped) == len(shipped)
        trace = trace_export.to_chrome_trace(trace_id=driver_tid)
        validate_chrome_trace(trace)
        pids = {e["pid"] for e in trace["traceEvents"] if e["ph"] != "M"}
        assert len(pids) == 2, "merged trace must span driver + worker pids"
        metas = {
            e["args"]["name"]
            for e in trace["traceEvents"]
            if e["ph"] == "M" and e["name"] == "process_name"
        }
        assert any("driver" in m for m in metas)
        assert any("worker" in m for m in metas)


class TestProbeTraceOutAcceptance:
    def test_probe_trace_out_writes_merged_chrome_trace(self, tmp_path):
        """Acceptance: a simulated probe run with --trace-out produces
        Chrome trace-event JSON whose driver events share one trace_id
        and include the case/step/probe/engine span hierarchy."""
        from cyclonus_tpu.cli.root import main

        out = str(tmp_path / "run.json")
        rc = main(
            [
                "probe", "--mock", "--perfect-cni", "--ignore-loopback",
                "--trace-out", out,
            ]
        )
        assert rc == 0
        trace = json.load(open(out))
        assert validate_chrome_trace(trace) > 0
        names = {e["name"] for e in trace["traceEvents"] if e["ph"] != "M"}
        for expected in (
            "probe.run", "interpreter.case", "interpreter.step",
            "interpreter.probe", "probe.simulated",
        ):
            assert expected in names, f"{expected} missing from timeline"
        ids = {
            e["args"].get("trace_id")
            for e in trace["traceEvents"]
            if e["ph"] != "M" and e["args"].get("trace_id")
        }
        assert trace["otherData"]["trace_id"] is not None
        assert len(trace["otherData"]["trace_ids"]) == 1
        # nested paths are navigable: the probe span sits under the run
        probe_paths = [
            e["args"]["path"]
            for e in trace["traceEvents"]
            if e.get("name") == "interpreter.probe"
        ]
        assert probe_paths and all(
            p.startswith("probe.run/interpreter.case/interpreter.step")
            for p in probe_paths
        )


class TestProfileEndpoint:
    def test_profile_returns_artifact(self):
        """Acceptance: /profile?seconds=N returns 200 with a profiler
        artifact directory that exists and contains capture files."""
        from cyclonus_tpu.telemetry.server import (
            start_metrics_server,
            stop_metrics_server,
        )

        srv = start_metrics_server(0)
        try:
            assert srv.port != 0  # the BOUND ephemeral port is reported
            with urllib.request.urlopen(
                srv.url + "/profile?seconds=0.2", timeout=180
            ) as resp:
                assert resp.status == 200
                body = json.loads(resp.read())
            assert body["seconds"] == 0.2
            artifact = body["artifact"]
            assert os.path.isdir(artifact)
            files = [
                os.path.join(dp, f)
                for dp, _, fs in os.walk(artifact)
                for f in fs
            ]
            assert files, "profiler left no artifact files"
        finally:
            stop_metrics_server()

    def test_profile_rejects_bad_seconds(self):
        from cyclonus_tpu.telemetry.server import (
            start_metrics_server,
            stop_metrics_server,
        )

        srv = start_metrics_server(0)
        try:
            for q in ("seconds=abc", "seconds=0", "seconds=9999"):
                with pytest.raises(urllib.error.HTTPError) as exc:
                    urllib.request.urlopen(
                        f"{srv.url}/profile?{q}", timeout=30
                    )
                assert exc.value.code == 400
        finally:
            stop_metrics_server()

    def test_profile_rejects_concurrent_capture(self):
        """The jax profiler is a process singleton: while one capture
        holds _PROFILE_LOCK, a second /profile must answer 409 (typed
        refusal) instead of queueing behind or corrupting the capture."""
        import cyclonus_tpu.telemetry.server as tserver
        from cyclonus_tpu.telemetry.server import (
            start_metrics_server,
            stop_metrics_server,
        )

        srv = start_metrics_server(0)
        assert tserver._PROFILE_LOCK.acquire(blocking=False)
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    srv.url + "/profile?seconds=0.2", timeout=30
                )
            assert exc.value.code == 409
            body = json.loads(exc.value.read())
            assert "already running" in body["error"]
        finally:
            tserver._PROFILE_LOCK.release()
            stop_metrics_server()
        # the refusal released nothing it didn't take: a fresh capture
        # still acquires cleanly
        assert tserver._PROFILE_LOCK.acquire(blocking=False)
        tserver._PROFILE_LOCK.release()


class TestMetricsPortBusy:
    def test_server_raises_one_line_error(self):
        from cyclonus_tpu.telemetry.server import (
            MetricsPortBusy,
            MetricsServer,
        )

        first = MetricsServer(0)
        try:
            with pytest.raises(MetricsPortBusy) as exc:
                MetricsServer(first.port)
            msg = str(exc.value)
            assert str(first.port) in msg and "\n" not in msg
        finally:
            first.close()

    def test_cli_exits_cleanly_on_taken_port(self):
        from cyclonus_tpu.cli.probe_cmd import _start_metrics
        from cyclonus_tpu.telemetry.server import (
            MetricsServer,
            active_server,
        )

        assert active_server() is None, "leaked metrics server"
        blocker = MetricsServer(0)
        try:
            args = type("A", (), {"metrics_port": blocker.port})()
            with pytest.raises(SystemExit) as exc:
                _start_metrics(args)
            assert "already in use" in str(exc.value)
        finally:
            blocker.close()


class TestTraceCLI:
    def test_export_and_summary_modes(self, tmp_path, capsys):
        from cyclonus_tpu.cli.root import main

        events.enable("cli-test")
        with span("cli.span"):
            pass
        out = str(tmp_path / "cli.json")
        assert main(["trace", "--out", out]) == 0
        capsys.readouterr()
        trace = json.load(open(out))
        validate_chrome_trace(trace)
        assert main(["trace", "--input", out]) == 0
        text = capsys.readouterr().out
        assert "cli.span" in text and "trace_id=cli-test" in text

    def test_stdout_export_is_valid_json(self, capsys):
        from cyclonus_tpu.cli.root import main

        events.enable()
        with span("cli.stdout"):
            pass
        assert main(["trace"]) == 0
        trace = json.loads(capsys.readouterr().out)
        validate_chrome_trace(trace)


class TestResetSemantics:
    def test_telemetry_reset_clears_event_window(self):
        events.enable()
        with span("rst.a"):
            pass
        assert events.entries()
        telemetry.reset()
        assert events.entries() == []
        # the trace stays ACTIVE: reset starts an empty timeline, not an
        # untraced process
        with span("rst.b"):
            pass
        assert [e["name"] for e in events.entries()] == ["rst.b", "rst.b"]
