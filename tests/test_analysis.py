"""Analysis-subsystem tests: rule firing masks, shadowing/redundancy
audit, policy-set diff/equivalence, and the scalar-oracle cross-checks —
the acceptance gates of the audit tentpole:

  * a hand-built shadowed rule is flagged (and its coverers named);
  * diff of a policy set against itself is empty;
  * diff against a one-rule perturbation localizes the changed cells;
  * every claim survives the oracle cross-check on all examples.py
    fixtures (audit_policy_set raises on a refuted claim).
"""

import numpy as np
import pytest

from cyclonus_tpu.analysis import (
    audit_policy_set,
    derive_port_cases,
    diff_policy_sets,
    policy_without_rule,
    synthesize_cluster,
)
from cyclonus_tpu.engine import PortCase, TpuPolicyEngine
from cyclonus_tpu.kube.examples import all_examples
from cyclonus_tpu.kube.netpol import (
    IntOrString,
    LabelSelector,
    NetworkPolicy,
    NetworkPolicyIngressRule,
    NetworkPolicyPeer,
    NetworkPolicyPort,
    NetworkPolicySpec,
)
from cyclonus_tpu.kube.pathological import (
    ALLOW_ALL_INGRESS,
    ALLOW_MATCHING_PODS_IN_POLICY_NAMESPACE_PEER,
    LABELS_CD,
    NAMESPACE,
    SELECTOR_EMPTY,
    SELECTOR_GH,
)
from cyclonus_tpu.matcher import build_network_policies

CASES = [PortCase(80, "", "TCP"), PortCase(53, "", "UDP")]


def _pathological_cluster():
    pods = [
        (NAMESPACE, "plain", {}, "10.0.1.1"),
        (NAMESPACE, "cd", dict(LABELS_CD), "10.0.1.2"),
        ("other", "out", {}, "10.0.2.1"),
    ]
    namespaces = {NAMESPACE: {"ns": NAMESPACE}, "other": {"ns": "other"}}
    return pods, namespaces


def _ingress_policy(name, peers, ports=None, namespace=NAMESPACE,
                    pod_selector=SELECTOR_EMPTY):
    return NetworkPolicy(
        name=name,
        namespace=namespace,
        spec=NetworkPolicySpec(
            pod_selector=pod_selector,
            policy_types=["Ingress"],
            ingress=[NetworkPolicyIngressRule(from_=peers, ports=ports or [])],
        ),
    )


class TestAudit:
    def test_pathological_shadowed_rule(self):
        """ALLOW_ALL_INGRESS (all peers) + a narrow pod-selector rule on
        the same target: the narrow rule's firing mask is subsumed —
        flagged shadowed, coverer + source policies named."""
        narrow = _ingress_policy(
            "narrow", [ALLOW_MATCHING_PODS_IN_POLICY_NAMESPACE_PEER]
        )
        policy = build_network_policies(False, [ALLOW_ALL_INGRESS, narrow])
        pods, namespaces = _pathological_cluster()
        report = audit_policy_set(policy, pods, namespaces, CASES)
        shadowed = [f for f in report.findings if f.kind == "shadowed"]
        assert len(shadowed) == 1
        f = shadowed[0]
        assert f.rule.direction == "ingress"
        assert f.oracle == "confirmed"
        assert f.fire_cells > 0
        assert f.rule.policies == (f"{NAMESPACE}/narrow",)
        # the all-peers rule covers it, and its source policy is named
        assert any(
            f"{NAMESPACE}/allow-all-ingress" in c.policies
            for c in f.covered_by
        )

    def test_never_firing_rule(self):
        """A peer selector matching no pod of the cluster never fires."""
        dead = _ingress_policy(
            "dead", [NetworkPolicyPeer(pod_selector=SELECTOR_GH)]
        )
        policy = build_network_policies(False, [dead])
        pods, namespaces = _pathological_cluster()  # no {g: g} pod anywhere
        report = audit_policy_set(policy, pods, namespaces, CASES)
        assert [f.kind for f in report.findings] == ["never-fires"]
        assert report.findings[0].oracle == "confirmed"

    def test_live_rules_not_flagged(self):
        """Two disjoint narrow rules both fire uniquely: no findings."""
        a = _ingress_policy(
            "only", [ALLOW_MATCHING_PODS_IN_POLICY_NAMESPACE_PEER]
        )
        policy = build_network_policies(False, [a])
        pods, namespaces = _pathological_cluster()
        report = audit_policy_set(policy, pods, namespaces, CASES)
        assert report.findings == []
        assert report.n_rules["ingress"] == 1

    def test_port_shadowing(self):
        """Same peer twice — all ports vs port 80 only: the port-80 rule
        is shadowed (every cell it fires on, the all-port rule fires)."""
        wide = _ingress_policy("wide", [NetworkPolicyPeer()])
        narrow = _ingress_policy(
            "narrow-port",
            [NetworkPolicyPeer()],
            ports=[NetworkPolicyPort(protocol="TCP", port=IntOrString(80))],
        )
        policy = build_network_policies(False, [wide, narrow])
        pods, namespaces = _pathological_cluster()
        report = audit_policy_set(policy, pods, namespaces, CASES)
        shadowed = [f for f in report.findings if f.kind == "shadowed"]
        assert len(shadowed) == 1
        assert "narrow-port" in shadowed[0].rule.policies[0]

    def test_examples_fixtures_oracle_checked(self):
        """Every examples.py fixture audits clean through the oracle
        cross-check (audit_policy_set raises on any refuted claim)."""
        policy = build_network_policies(False, all_examples())
        pods, namespaces = synthesize_cluster(policy)
        cases = derive_port_cases(policy)
        report = audit_policy_set(
            policy, pods, namespaces, cases, oracle_samples=4
        )
        assert report.oracle_checked == sum(
            1 for f in report.findings if f.oracle == "confirmed"
        )
        assert all(
            f.oracle == "confirmed" for f in report.findings
        ), report.table()
        assert sum(report.n_rules.values()) > 10

    def test_firing_components_reconstruct_grid(self):
        """The rank-1 firing-mask factors reconstruct the direction
        verdicts exactly: allowed = ~has_target | OR_p fire[p]."""
        policy = build_network_policies(False, all_examples()[:8])
        pods, namespaces = synthesize_cluster(policy)
        cases = derive_port_cases(policy)[:3]
        engine = TpuPolicyEngine(policy, pods, namespaces)
        comp = engine.firing_components(cases)
        grid = engine.evaluate_grid(cases)
        n = len(pods)
        for direction, got in (
            ("ingress", np.swapaxes(np.asarray(grid.ingress), 1, 2)),
            ("egress", np.asarray(grid.egress)),
        ):
            c = comp[direction]
            a, b, cq = c["rule_tmatch"], c["peer_match"], c["pport"]
            # fire[p, n, m, q] -> any over p; n = target side, m = peer side
            fire_any = np.einsum("pn,pm,pq->nmq", a, b, cq) > 0
            allowed = (~c["has_target"][:, None, None]) | fire_any
            if direction == "ingress":
                # target side is the DESTINATION: [dst, src, q] -> [q, src, dst]
                want = np.moveaxis(allowed, -1, 0).swapaxes(1, 2)
            else:
                want = np.moveaxis(allowed, -1, 0)
            np.testing.assert_array_equal(want, got, err_msg=direction)

    def test_audit_grid_cap(self):
        policy = build_network_policies(False, [ALLOW_ALL_INGRESS])
        pods = [(NAMESPACE, f"p{i}", {}, f"10.0.0.{i}") for i in range(4)]
        with pytest.raises(ValueError, match="exceeds"):
            audit_policy_set(
                policy, pods * 3000, {NAMESPACE: {}}, CASES
            )


class TestDiff:
    def test_self_diff_empty(self):
        policy = build_network_policies(False, all_examples())
        pods, namespaces = synthesize_cluster(policy)
        cases = derive_port_cases(policy)
        report = diff_policy_sets(policy, policy, pods, namespaces, cases)
        assert report.equivalent
        assert report.n_diff == {"ingress": 0, "egress": 0, "combined": 0}
        assert report.cells == []
        assert report.oracle_checked > 0

    def test_one_rule_perturbation_localizes(self):
        """Removing the single live ingress rule of the app=web target
        flips exactly the cells into that target's pods — diff reports
        them, nothing else, and egress never differs."""
        web = LabelSelector.make(match_labels={"app": "web"})
        client = NetworkPolicyPeer(
            pod_selector=LabelSelector.make(match_labels={"app": "client"})
        )
        pol = _ingress_policy(
            "web-in", [client], namespace="default", pod_selector=web
        )
        policy_a = build_network_policies(False, [pol])
        # perturbation: strip ingress rule (t0, r0) -> deny-all target
        policy_b = policy_without_rule(policy_a, "ingress", 0, 0)
        pods = [
            ("default", "web", {"app": "web"}, "10.0.0.1"),
            ("default", "client", {"app": "client"}, "10.0.0.2"),
            ("default", "other", {}, "10.0.0.3"),
        ]
        namespaces = {"default": {}}
        report = diff_policy_sets(
            policy_a, policy_b, pods, namespaces, CASES
        )
        assert not report.equivalent
        assert report.n_diff["egress"] == 0
        assert report.n_diff["ingress"] > 0
        assert len(report.cells) == report.n_diff["ingress"]
        # every differing cell lands on the perturbed target's pod, and
        # only where the removed rule fired (src=client)
        for cell in report.cells:
            assert cell.dst == "default/web"
            assert cell.src == "default/client"
            assert cell.a[0] and not cell.b[0]  # ingress allowed -> denied

    def test_diff_oracle_samples_cover_both_sides(self):
        policy_a = build_network_policies(False, [ALLOW_ALL_INGRESS])
        policy_b = build_network_policies(False, [])
        pods, namespaces = _pathological_cluster()
        report = diff_policy_sets(policy_a, policy_b, pods, namespaces, CASES)
        # allow-all vs no-policy: both all-allow -> equivalent grids
        assert report.equivalent


SHADOW_YAML = """\
apiVersion: networking.k8s.io/v1
kind: NetworkPolicy
metadata:
  name: web-allow
  namespace: default
spec:
  podSelector: {}
  policyTypes: ["Ingress"]
  ingress:
    - from:
        - podSelector: {}
    - from:
        - podSelector:
            matchLabels:
              app: web
      ports:
        - protocol: TCP
          port: 80
"""


class TestCli:
    def _run(self, capsys, argv):
        from cyclonus_tpu.cli.root import main

        rc = main(argv)
        out = capsys.readouterr().out
        return rc, out

    def test_audit_flags_shadowed_rule(self, tmp_path, capsys):
        p = tmp_path / "shadow.yaml"
        p.write_text(SHADOW_YAML)
        rc, out = self._run(
            capsys,
            ["analyze", "--mode", "audit", "--policy-path", str(p),
             "--simplify-policies", "false"],
        )
        assert rc == 0
        assert "shadowed" in out
        assert "default/web-allow" in out
        assert "confirmed" in out

    def test_diff_identical_sets_zero_cells(self, tmp_path, capsys):
        p = tmp_path / "shadow.yaml"
        p.write_text(SHADOW_YAML)
        rc, out = self._run(
            capsys,
            ["analyze", "--mode", "diff", "--policy-path", str(p),
             "--policy-path2", str(p), "--simplify-policies", "false"],
        )
        assert rc == 0
        assert "EQUIVALENT: 0 of" in out

    def test_diff_perturbed_set_reports_cells(self, tmp_path, capsys):
        a = tmp_path / "a.yaml"
        a.write_text(SHADOW_YAML)
        b = tmp_path / "b.yaml"
        # drop the broad allow-all rule: verdicts must differ
        b.write_text(
            SHADOW_YAML.replace(
                "    - from:\n        - podSelector: {}\n", "", 1
            )
        )
        rc, out = self._run(
            capsys,
            ["analyze", "--mode", "diff", "--policy-path", str(a),
             "--policy-path2", str(b), "--simplify-policies", "false"],
        )
        assert rc == 0
        assert "DIFFER" in out
        assert "oracle-checked" in out


class TestInputs:
    def test_derive_port_cases(self):
        pol = _ingress_policy(
            "ports",
            [NetworkPolicyPeer()],
            ports=[
                NetworkPolicyPort(protocol="TCP", port=IntOrString(8080)),
                NetworkPolicyPort(protocol="UDP", port=IntOrString("dns")),
            ],
        )
        policy = build_network_policies(False, [pol])
        cases = derive_port_cases(policy)
        assert PortCase(8080, "", "TCP") in cases
        assert PortCase(0, "dns", "UDP") in cases
        assert PortCase(80, "", "TCP") in cases  # baseline
        assert any(c.port == 65432 for c in cases)  # sentinel
        assert len(cases) == len(set(cases))

    def test_synthesize_cluster_covers_selectors(self):
        policy = build_network_policies(False, all_examples())
        pods, namespaces = synthesize_cluster(policy)
        assert pods and namespaces
        assert len(pods) <= 48
        # every pod namespace exists in the namespace map
        assert {p[0] for p in pods} <= set(namespaces)
        # distinct IPs
        ips = [p[3] for p in pods]
        assert len(ips) == len(set(ips))
