"""End-to-end tests of the loopback cluster (kube/loopback.py): pods as
REAL processes on dedicated 127.x.y.z addresses, probes as REAL TCP
connects / UDP datagrams (source-bound, so enforcement keys on true peer
IPs), the in-pod batch prober as a REAL worker subprocess.  The
environment's substitute for the reference's KinD flow
(hack/kind/run-cyclonus.sh — no docker/kind/netfilter exists here; see
docs/LOOPBACK.md)."""

import pytest

from cyclonus_tpu.connectivity import Interpreter, InterpreterConfig
from cyclonus_tpu.generator import TestCaseGenerator, create_policy, read_network_policies
from cyclonus_tpu.generator.tags import StringSet
from cyclonus_tpu.generator.testcase import TestCase, TestStep
from cyclonus_tpu.kube.loopback import LoopbackKubernetes, native_probe
from cyclonus_tpu.kube.netpol import (
    IntOrString,
    LabelSelector,
    NetworkPolicy,
    NetworkPolicyIngressRule,
    NetworkPolicyPeer,
    NetworkPolicySpec,
)
from cyclonus_tpu.probe.probeconfig import PROBE_MODE_SERVICE_NAME, ProbeConfig
from cyclonus_tpu.probe.resources import Resources


def small_cluster(lb, namespaces=("x", "y"), pods=("a", "b")):
    return Resources.new_default(
        lb,
        list(namespaces),
        list(pods),
        [80, 81],
        ["TCP", "UDP"],
        pod_creation_timeout_seconds=15,
    )


class TestLoopbackSockets:
    def test_enforcement_over_real_sockets(self):
        """Allow / deny / unserved-port / source-attribution semantics,
        each observed through an actual socket operation."""
        with LoopbackKubernetes() as lb:
            small_cluster(lb)
            pa, pb = lb.get_pod("x", "a"), lb.get_pod("y", "b")
            assert pa.pod_ip.startswith("127.") and pb.pod_ip.startswith("127.")

            # no policies: served combos answer, unserved port is a REAL
            # kernel refusal (no process listens there)
            assert native_probe(pb.pod_ip, 80, "TCP", source_ip=pa.pod_ip) is None
            assert native_probe(pb.pod_ip, 81, "UDP", source_ip=pa.pod_ip) is None
            err = native_probe(pb.pod_ip, 99, "TCP", source_ip=pa.pod_ip)
            assert err and "refused" in err.lower()

            # deny-all-ingress in y: a->b blocked on both protocols, the
            # reverse direction (ns x has no policy) stays open — the
            # server can only distinguish these via true source IPs
            lb.create_network_policy(
                NetworkPolicy(
                    name="deny",
                    namespace="y",
                    spec=NetworkPolicySpec(
                        pod_selector=LabelSelector.make(),
                        policy_types=["Ingress"],
                    ),
                )
            )
            assert native_probe(pb.pod_ip, 80, "TCP", source_ip=pa.pod_ip) == "closed without ack"
            assert native_probe(pb.pod_ip, 80, "UDP", source_ip=pa.pod_ip) == "timeout"
            assert native_probe(pa.pod_ip, 80, "TCP", source_ip=pb.pod_ip) is None

            # allow from pod a only: label-selector enforcement per peer
            lb.update_network_policy(
                NetworkPolicy(
                    name="deny",
                    namespace="y",
                    spec=NetworkPolicySpec(
                        pod_selector=LabelSelector.make(),
                        policy_types=["Ingress"],
                        ingress=[
                            NetworkPolicyIngressRule(
                                ports=[],
                                from_=[
                                    NetworkPolicyPeer(
                                        pod_selector=LabelSelector.make(
                                            match_labels={"pod": "a"}
                                        ),
                                        namespace_selector=LabelSelector.make(),
                                    )
                                ],
                            )
                        ],
                    ),
                )
            )
            assert native_probe(pb.pod_ip, 80, "TCP", source_ip=pa.pod_ip) is None
            b_self = lb.get_pod("x", "b")
            assert (
                native_probe(pb.pod_ip, 80, "TCP", source_ip=b_self.pod_ip)
                == "closed without ack"
            )

    def test_pod_lifecycle_frees_address(self):
        """delete_pod kills the server process; its ports refuse.  Also:
        a probe from a NON-pod source (unbound client = 127.0.0.1) is
        denied — the verdict map only contains pod addresses."""
        with LoopbackKubernetes() as lb:
            small_cluster(lb, namespaces=("x",), pods=("a", "b"))
            pa, pb = lb.get_pod("x", "a"), lb.get_pod("x", "b")
            ip = pb.pod_ip
            assert native_probe(ip, 80, "TCP", source_ip=pa.pod_ip) is None
            assert native_probe(ip, 80, "TCP") == "closed without ack"
            lb.delete_pod("x", "b")
            err = native_probe(ip, 80, "TCP", source_ip=pa.pod_ip)
            assert err and ("refused" in err.lower() or "timeout" in err)

    def test_worker_subprocess_batch(self):
        """The real in-pod worker: a subprocess speaking the JSON batch
        protocol over native sockets, mixed verdicts in one batch."""
        import json

        with LoopbackKubernetes() as lb:
            small_cluster(lb)
            pa, pb = lb.get_pod("x", "a"), lb.get_pod("y", "b")
            lb.create_network_policy(
                NetworkPolicy(
                    name="deny",
                    namespace="y",
                    spec=NetworkPolicySpec(
                        pod_selector=LabelSelector.make(),
                        policy_types=["Ingress"],
                    ),
                )
            )
            batch = json.dumps(
                {
                    "Namespace": "x",
                    "Pod": "a",
                    "Container": "cont-80-tcp",
                    "Requests": [
                        {"Key": "blocked", "Protocol": "tcp", "Host": pb.pod_ip, "Port": 80},
                        {"Key": "open", "Protocol": "tcp", "Host": pa.pod_ip, "Port": 80},
                    ],
                }
            )
            out, _err_out, err = lb.execute_remote_command(
                "x", "a", "cont-80-tcp", ["/worker", "--jobs", batch]
            )
            assert err is None
            results = {r["Request"]["Key"]: r for r in json.loads(out)}
            assert results["blocked"]["Error"] != ""
            assert results["open"]["Error"] == ""
            assert results["open"]["Output"] == "connected"


def loopback_interpreter(lb, resources, batch_jobs=False):
    return Interpreter(
        lb,
        resources,
        InterpreterConfig(
            reset_cluster_before_test_case=True,
            verify_cluster_state_before_test_case=True,
            kube_probe_retries=0,
            perturbation_wait_seconds=0,
            batch_jobs=batch_jobs,
            simulated_engine="oracle",
            pod_wait_timeout_seconds=15,
        ),
    )


class TestLoopbackInterpreter:
    @pytest.mark.parametrize("batch_jobs", [False, True])
    def test_one_off_probe_matches_simulated(self, batch_jobs):
        """The full interpreter loop over real sockets: apply example
        policies, probe every pod pair via the kube (exec) path —
        per-job agnhost style or the batch worker — and require the
        real table to equal the simulated one (result.passed())."""
        with LoopbackKubernetes() as lb:
            resources = Resources.new_default(
                lb,
                ["x", "y", "z"],
                ["a", "b"],
                [80, 81],
                ["TCP", "UDP"],
                pod_creation_timeout_seconds=15,
                batch_jobs=batch_jobs,
            )
            policies = [
                # deny-all ingress in y + allow back only from x/a pods
                NetworkPolicy(
                    name="deny-all-y",
                    namespace="y",
                    spec=NetworkPolicySpec(
                        pod_selector=LabelSelector.make(),
                        policy_types=["Ingress"],
                    ),
                ),
                NetworkPolicy(
                    name="allow-a-to-y",
                    namespace="y",
                    spec=NetworkPolicySpec(
                        pod_selector=LabelSelector.make(),
                        policy_types=["Ingress"],
                        ingress=[
                            NetworkPolicyIngressRule(
                                ports=[],
                                from_=[
                                    NetworkPolicyPeer(
                                        pod_selector=LabelSelector.make(
                                            match_labels={"pod": "a"}
                                        ),
                                        namespace_selector=LabelSelector.make(
                                            match_labels={"ns": "x"}
                                        ),
                                    )
                                ],
                            )
                        ],
                    ),
                ),
            ]
            actions = [read_network_policies(["x", "y", "z"])]
            for policy in policies:
                actions.append(create_policy(policy))
            case = TestCase(
                description="loopback one-off",
                tags=StringSet(),
                steps=[
                    TestStep(
                        probe=ProbeConfig.port_protocol_config(
                            IntOrString(80), "TCP", PROBE_MODE_SERVICE_NAME
                        ),
                        actions=actions,
                    )
                ],
            )
            result = loopback_interpreter(
                lb, resources, batch_jobs=batch_jobs
            ).execute_test_case(case)
            assert result.err is None, result.err
            assert result.passed(ignore_loopback=False), "real != simulated"


@pytest.mark.fuzz
class TestLoopbackFuzz:
    def test_random_policies_real_sockets(self):
        """Randomized policy sets through the interpreter over the
        loopback cluster: every seed's REAL-socket table (per-job exec
        path) must equal the simulated table.  The real-network twin of
        the oracle/kernel fuzz sweep (test_engine_parity.run_fuzz_seed);
        one shared cluster, reset between cases by the interpreter."""
        import random

        from test_engine_parity import random_policy

        with LoopbackKubernetes() as lb:
            resources = Resources.new_default(
                lb,
                ["x", "y", "z"],
                ["a", "b"],
                [80, 81],
                ["TCP", "UDP"],
                pod_creation_timeout_seconds=15,
            )
            interpreter = loopback_interpreter(lb, resources)
            keys = ["pod", "app", "tier", "ns", "team"]
            values = ["a", "b", "c", "web", "db", "x", "y", "z", "blue", "red"]
            failures = []
            for seed in range(6):
                rng = random.Random(1000 + seed)
                policies = [
                    random_policy(rng, i, ["x", "y", "z"], keys, values)
                    for i in range(rng.randrange(1, 4))
                ]
                actions = [read_network_policies(["x", "y", "z"])]
                actions.extend(create_policy(p) for p in policies)
                case = TestCase(
                    description=f"loopback fuzz seed {seed}",
                    tags=StringSet(),
                    steps=[
                        TestStep(
                            probe=ProbeConfig.port_protocol_config(
                                IntOrString(80), "TCP", PROBE_MODE_SERVICE_NAME
                            ),
                            actions=actions,
                        ),
                        TestStep(
                            probe=ProbeConfig.port_protocol_config(
                                IntOrString(81), "UDP", PROBE_MODE_SERVICE_NAME
                            ),
                            actions=[],
                        ),
                    ],
                )
                result = interpreter.execute_test_case(case)
                if result.err is not None or not result.passed(ignore_loopback=False):
                    failures.append((seed, str(result.err)))
            assert not failures, failures


@pytest.mark.conformance
class TestLoopbackConformance:
    def test_conflict_cases(self, tmp_path):
        """The 16 conflict-family conformance cases through the
        interpreter over the loopback cluster — the KinD-flow analog
        (`--include conflict`, journaled).  The committed artifact
        artifacts/loopback-conformance-journal.jsonl comes from the same
        flow via `generate --loopback`."""
        from cyclonus_tpu.connectivity.journal import Journal

        with LoopbackKubernetes() as lb:
            resources = Resources.new_default(
                lb,
                ["x", "y", "z"],
                ["a", "b", "c"],
                [80, 81],
                ["TCP", "UDP"],
                pod_creation_timeout_seconds=15,
            )
            zc = resources.get_pod("z", "c")
            generator = TestCaseGenerator(
                allow_dns=True,
                pod_ip=zc.ip,
                namespaces=["x", "y", "z"],
                tags=["conflict"],
                excluded_tags=["multi-peer", "upstream-e2e", "example"],
            )
            cases = generator.generate_test_cases()
            assert len(cases) == 16
            journal = Journal(str(tmp_path / "journal.jsonl"))
            interpreter = loopback_interpreter(lb, resources)
            failed = []
            for i, tc in enumerate(cases):
                result = interpreter.execute_test_case(tc)
                ok = result.passed(ignore_loopback=False)
                journal.record(
                    tc.description,
                    passed=ok,
                    step_count=len(result.steps),
                    tags=tc.tags.keys_sorted(),
                    error=str(result.err) if result.err else "",
                    key=f"{i}:{tc.description}",
                )
                if not ok:
                    failed.append(tc.description)
            assert not failed, failed
