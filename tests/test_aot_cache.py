"""Persistent AOT executable cache (cyclonus_tpu/engine/aot_cache.py):
the zero-recompile restart contract, and the corrupt/stale/concurrent
degradation discipline (docs/DESIGN.md "Cold start & chaos")."""

import json
import os
import pickle
import subprocess
import sys

import pytest

from cyclonus_tpu.engine import aot_cache

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# a small engine driven end to end in a FRESH interpreter: build,
# evaluate grid + pairs + counts, print the verdict digest + the AOT
# counters + the engine span counts as one JSON line
_DRIVER = """
import json, os, random, sys
import numpy as np
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from bench import build_synthetic
from cyclonus_tpu import telemetry
from cyclonus_tpu.engine import PortCase, TpuPolicyEngine, aot_cache
from cyclonus_tpu.matcher import build_network_policies

pods, namespaces, policies = build_synthetic(40, 10, random.Random(3))
policy = build_network_policies(True, policies)
engine = TpuPolicyEngine(policy, pods, namespaces)
cases = [PortCase(80, "serve-80-tcp", "TCP")]
grid = np.asarray(engine.evaluate_grid(cases).combined)
counts = engine.evaluate_grid_counts(cases, backend="pallas")
pairs = engine.evaluate_pairs(cases, [(0, 1), (2, 3)])
spans = telemetry.SPANS.stats()
from cyclonus_tpu.telemetry import instruments as ti
kernel_traces = sum(
    s0.get("value", 0)
    for s0 in ti.KERNEL_TRACES.snapshot().get("samples", [])
)
print(json.dumps({{
    "digest": int(grid.sum()),
    "counts": counts,
    "pairs": int(pairs.sum()),
    "aot": aot_cache.counters(),
    "dispatch_spans": spans.get("engine.dispatch", {{}}).get("count", 0),
    "kernel_traces": kernel_traces,
}}))
"""


def _run_driver(cache_dir, extra_env=None):
    env = dict(os.environ)
    env["CYCLONUS_AOT_CACHE"] = str(cache_dir)
    env["CYCLONUS_AUTOTUNE_CACHE"] = "0"
    # isolate from any developer-level JAX compilation cache so the
    # measured compile counts are the AOT layer's alone
    env["CYCLONUS_JAX_CACHE"] = "0"
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, "-c", _DRIVER.format(repo=REPO)],
        capture_output=True,
        text=True,
        timeout=300,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stdout[-1500:] + proc.stderr[-1500:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


class TestRestartContract:
    def test_restart_adopts_executables_with_zero_compiles(self, tmp_path):
        """THE cold-start acceptance gate: a fresh process against a
        warm cache adopts every covered executable — hits > 0, fresh
        compiles == 0 — and produces bit-identical results."""
        cache = tmp_path / "aot"
        first = _run_driver(cache)
        assert first["aot"]["compiles"] > 0  # cold: paid real compiles
        assert first["aot"]["hits"] == 0
        assert first["aot"]["stores"] > 0
        second = _run_driver(cache)
        # zero-recompile adoption: every program the first process
        # persisted is adopted, nothing compiles fresh
        assert second["aot"]["compiles"] == 0, second["aot"]
        assert second["aot"]["misses"] == 0, second["aot"]
        assert second["aot"]["adopted"] >= first["aot"]["stores"]
        # identical verdicts through the adopted executables
        assert second["digest"] == first["digest"]
        assert second["counts"] == first["counts"]
        assert second["pairs"] == first["pairs"]
        # the engine still dispatched the same evaluations (the spans
        # prove the warm path ran, it didn't skip work)
        assert second["dispatch_spans"] == first["dispatch_spans"]
        # and the kernel trace counters stay FLAT: adopted executables
        # never re-enter the python kernel builders
        assert first["kernel_traces"] > 0
        assert second["kernel_traces"] == 0, second

    def test_poisoned_entries_degrade_to_fresh_compile(self, tmp_path):
        """Corrupt bytes, truncation, and version skew each degrade to
        a fresh compile — never a raise, never a wrong verdict."""
        cache = tmp_path / "aot"
        first = _run_driver(cache)
        entries = sorted(p for p in cache.iterdir() if p.suffix == ".aotx")
        assert entries, "no cache entries written"
        for i, path in enumerate(entries):
            if i % 3 == 0:
                path.write_bytes(b"\xffgarbage" * 100)
            elif i % 3 == 1:
                path.write_bytes(path.read_bytes()[: max(1, path.stat().st_size // 2)])
            else:
                path.write_bytes(
                    pickle.dumps({"v": 999, "key": "nope", "payload": b""})
                )
        third = _run_driver(cache)
        assert third["digest"] == first["digest"]
        assert third["counts"] == first["counts"]
        # every poisoned entry was rejected and recompiled fresh
        assert third["aot"]["compiles"] > 0
        assert third["aot"]["hits"] == 0

    def test_concurrently_written_cache_stays_loadable(self, tmp_path):
        """Two processes warming the same cache dir concurrently must
        both finish and leave a cache a third process fully adopts
        (per-entry atomic replace: same-key racers both wrote a valid
        executable)."""
        cache = tmp_path / "aot"
        env = dict(os.environ)
        env["CYCLONUS_AOT_CACHE"] = str(cache)
        env["CYCLONUS_AUTOTUNE_CACHE"] = "0"
        env["CYCLONUS_JAX_CACHE"] = "0"
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _DRIVER.format(repo=REPO)],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                cwd=REPO,
                env=env,
            )
            for _ in range(2)
        ]
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, out[-800:] + err[-800:]
            outs.append(json.loads(out.strip().splitlines()[-1]))
        assert outs[0]["digest"] == outs[1]["digest"]
        adopter = _run_driver(cache)
        assert adopter["aot"]["compiles"] == 0, adopter["aot"]
        assert adopter["digest"] == outs[0]["digest"]


class TestCacheModule:
    def test_disabled_by_env(self, monkeypatch):
        monkeypatch.setenv("CYCLONUS_AOT_CACHE", "0")
        assert aot_cache.cache_dir() is None
        assert aot_cache.load("anything") is None
        assert aot_cache.store("anything", object()) is False

    def test_load_never_raises_on_garbage(self, tmp_path, monkeypatch):
        monkeypatch.setenv("CYCLONUS_AOT_CACHE", str(tmp_path))
        key = aot_cache.make_key("t", "sig")
        path = aot_cache._entry_path(str(tmp_path), key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(b"not a pickle at all")
        assert aot_cache.load(key) is None

    def test_key_collision_rejected_by_embedded_key(self, tmp_path, monkeypatch):
        """An entry whose embedded key differs from the requested key
        (digest collision / copied file) is stale, not loadable."""
        monkeypatch.setenv("CYCLONUS_AOT_CACHE", str(tmp_path))
        key = aot_cache.make_key("t", "sig")
        path = aot_cache._entry_path(str(tmp_path), key)
        with open(path, "wb") as f:
            pickle.dump(
                {
                    "v": aot_cache.CACHE_VERSION,
                    "key": "some-other-key",
                    "payload": b"",
                    "in_tree": None,
                    "out_tree": None,
                },
                f,
            )
        assert aot_cache.load(key) is None

    def test_store_unserializable_returns_false(self, tmp_path, monkeypatch):
        monkeypatch.setenv("CYCLONUS_AOT_CACHE", str(tmp_path))

        class NotCompiled:
            pass

        assert aot_cache.store(aot_cache.make_key("t", "s"), NotCompiled()) is False

    def test_make_key_varies_by_all_dimensions(self):
        base = aot_cache.make_key("a", "s", schedule="single", plan="p")
        assert aot_cache.make_key("b", "s", schedule="single", plan="p") != base
        assert aot_cache.make_key("a", "t", schedule="single", plan="p") != base
        assert aot_cache.make_key("a", "s", schedule="ring", plan="p") != base
        assert aot_cache.make_key("a", "s", schedule="single", plan="q") != base

    def test_platform_stamp_covers_jaxlib_independently(self):
        """Key-omission regression (tools/cachelint.py audit): the
        serialized payload is a JAXLIB binary, and jaxlib can be pinned
        independently of jax — a jaxlib-only upgrade must invalidate,
        not adopt."""
        import jax
        import jaxlib

        stamp = aot_cache.platform_stamp()
        assert f"jax={jax.__version__}" in stamp
        assert "jaxlib=" in stamp
        base_key = aot_cache.make_key("a", "s")
        orig = jaxlib.__version__
        try:
            jaxlib.__version__ = orig + ".post1"
            assert aot_cache.platform_stamp() != stamp
            # and the full key follows the stamp: a jaxlib-only bump
            # must miss every persisted executable
            assert aot_cache.make_key("a", "s") != base_key
        finally:
            jaxlib.__version__ = orig
        assert aot_cache.platform_stamp() == stamp  # revert hits
        assert aot_cache.make_key("a", "s") == base_key

    def test_aot_program_round_trip_in_process(self, tmp_path, monkeypatch):
        """AotProgram stores on first call and a FRESH wrapper adopts
        from disk (load path exercised without a subprocess)."""
        import jax
        import jax.numpy as jnp

        from cyclonus_tpu.telemetry import instruments as ti

        monkeypatch.setenv("CYCLONUS_AOT_CACHE", str(tmp_path))
        jitted = jax.jit(lambda x: x * 3 + 1)
        x = jnp.arange(8, dtype=jnp.int32)
        p1 = aot_cache.AotProgram("t.roundtrip", jitted, plan="unit")
        out1 = p1(x)
        hits0 = ti.AOT_CACHE.value(outcome="hit")
        p2 = aot_cache.AotProgram("t.roundtrip", jitted, plan="unit")
        out2 = p2(x)
        assert ti.AOT_CACHE.value(outcome="hit") == hits0 + 1
        assert (out1 == out2).all()

    def test_aot_program_falls_back_on_unlowerable(self, tmp_path, monkeypatch):
        """A wrapped callable without .lower (or whose lowering fails)
        pins the fallback and still answers."""
        monkeypatch.setenv("CYCLONUS_AOT_CACHE", str(tmp_path))

        def plain(x):
            return x + 1

        p = aot_cache.AotProgram("t.fallback", plain, plan="unit")
        assert p(1) == 2
        assert p(2) == 3  # fallback pinned, still works

    def test_counters_schema(self):
        c = aot_cache.counters()
        for k in ("hits", "misses", "adopted", "stores", "compiles", "dir"):
            assert k in c
        assert c["adopted"] == c["hits"]


@pytest.mark.slow
class TestRestartContractSharded:
    def test_sharded_program_adopts_on_restart(self, tmp_path):
        """The cached ring shard_map program rides the same cache."""
        driver = """
import json, os, random, sys
import numpy as np
sys.path.insert(0, {repo!r})
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
    " --xla_force_host_platform_device_count=8").strip()
import jax
jax.config.update("jax_platforms", "cpu")
from bench import build_synthetic
from cyclonus_tpu.engine import PortCase, TpuPolicyEngine, aot_cache
from cyclonus_tpu.matcher import build_network_policies

pods, namespaces, policies = build_synthetic(40, 10, random.Random(3))
policy = build_network_policies(True, policies)
engine = TpuPolicyEngine(policy, pods, namespaces)
cases = [PortCase(80, "serve-80-tcp", "TCP")]
g = np.asarray(engine.evaluate_grid_sharded(cases, schedule="ring").combined)
print(json.dumps({{"digest": int(g.sum()), "aot": aot_cache.counters()}}))
"""
        env_common = {
            "CYCLONUS_AOT_CACHE": str(tmp_path / "aot"),
            "CYCLONUS_AUTOTUNE_CACHE": "0",
            "CYCLONUS_JAX_CACHE": "0",
        }

        def run():
            env = dict(os.environ)
            env.update(env_common)
            proc = subprocess.run(
                [sys.executable, "-c", driver.format(repo=REPO)],
                capture_output=True, text=True, timeout=300, cwd=REPO,
                env=env,
            )
            assert proc.returncode == 0, proc.stdout[-800:] + proc.stderr[-800:]
            return json.loads(proc.stdout.strip().splitlines()[-1])

        first = run()
        assert first["aot"]["stores"] > 0
        second = run()
        assert second["aot"]["compiles"] == 0, second["aot"]
        assert second["digest"] == first["digest"]
