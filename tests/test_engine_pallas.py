"""Parity gate for the fused Pallas verdict+count kernel
(engine/pallas_kernel.py): counts must equal the oracle-checked
single-device kernel's sums exactly.  On CPU the kernel runs in Pallas
interpret mode; on TPU it compiles via Mosaic — same program either way.
"""

import numpy as np
import pytest

from cyclonus_tpu.engine import PortCase, TpuPolicyEngine

from test_engine_tiled import CASES, fuzz_problem, full_grids


class TestPallasCounts:
    @pytest.mark.parametrize("seed", range(4))
    def test_counts_match_kernel(self, seed):
        policy, pods, namespaces = fuzz_problem(seed, n_extra_pods=6)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        ing, egr, comb = full_grids(engine, CASES)
        counts = engine.evaluate_grid_counts(CASES, backend="pallas")
        assert counts["ingress"] == int(ing.sum())
        assert counts["egress"] == int(egr.sum())
        assert counts["combined"] == int(comb.sum())
        assert counts["cells"] == ing.size

    def test_single_port_case(self):
        policy, pods, namespaces = fuzz_problem(11)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        cases = [PortCase(80, "serve-80-tcp", "TCP")]
        ing, egr, comb = full_grids(engine, cases)
        counts = engine.evaluate_grid_counts(cases, backend="pallas")
        assert counts["combined"] == int(comb.sum())

    def test_matches_xla_backend(self):
        policy, pods, namespaces = fuzz_problem(12, n_extra_pods=9)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        a = engine.evaluate_grid_counts(CASES, block=8, backend="xla")
        b = engine.evaluate_grid_counts(CASES, backend="pallas")
        assert a == b

    def test_pre_cache_state_machine(self, monkeypatch):
        """The device-resident precompute cache: populated on the second
        consecutive evaluation of one case set, hit thereafter, evicted
        after two consecutive other-set evaluations — with identical
        counts on every path, and a byte estimate that matches the real
        pytree."""
        import cyclonus_tpu.engine.api as api

        policy, pods, namespaces = fuzz_problem(14, n_extra_pods=8)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        A = CASES
        B = [PortCase(81, "", "UDP")]
        C = [PortCase(9999, "", "TCP")]
        want_a = engine.evaluate_grid_counts(A, backend="xla")
        # 1st A: fused path, no cache; 2nd A: split path populates it
        assert engine.evaluate_grid_counts(A, backend="pallas") == want_a
        assert engine._pre_cache is None
        assert engine.evaluate_grid_counts(A, backend="pallas") == want_a
        assert engine._pre_cache is not None
        # estimate matches the cached pytree (has_target [N] x2 is the
        # only leaf it ignores)
        import jax

        actual = sum(
            x.nbytes for x in jax.tree_util.tree_leaves(engine._pre_cache[1])
        )
        n = engine._tensors["pod_ns_id"].shape[0]
        assert engine._pre_bytes_estimate(len(A)) == actual - 2 * n
        # cache hit
        assert engine.evaluate_grid_counts(A, backend="pallas") == want_a
        assert engine._pre_cache_misses == 0
        # one other-set call must NOT evict (A/B alternation)
        want_b = engine.evaluate_grid_counts(B, backend="xla")
        assert engine.evaluate_grid_counts(B, backend="pallas") == want_b
        assert engine._pre_cache is not None
        assert engine.evaluate_grid_counts(A, backend="pallas") == want_a
        # B seen again: the split path REPLACES the cached set with B's
        # (alternating sets each get cached when re-seen, never thrash)
        assert engine.evaluate_grid_counts(B, backend="pallas") == want_b
        assert engine._pre_cache is not None
        tallow_key = "tallow_pk" if engine._pack else "tallow_bf"
        assert engine._pre_cache[1]["egress"][tallow_key].shape[-1] == len(B)
        # two consecutive distinct foreign sets evict outright
        want_c = engine.evaluate_grid_counts(C, backend="xla")
        assert engine.evaluate_grid_counts(A, backend="pallas") == want_a
        assert engine.evaluate_grid_counts(C, backend="pallas") == want_c
        assert engine._pre_cache is None

    def test_pre_cache_size_gate_and_opt_out(self, monkeypatch):
        """An over-cap estimate keeps the engine on the fused path (no
        split compile, no pin); CYCLONUS_PRE_CACHE=0 disables caching."""
        import cyclonus_tpu.engine.api as api

        policy, pods, namespaces = fuzz_problem(15, n_extra_pods=8)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        want = engine.evaluate_grid_counts(CASES, backend="xla")
        monkeypatch.setattr(api, "_PRE_CACHE_MAX_BYTES", 0)
        for _ in range(3):
            assert engine.evaluate_grid_counts(CASES, backend="pallas") == want
        assert engine._pre_cache is None

        monkeypatch.undo()
        monkeypatch.setenv("CYCLONUS_PRE_CACHE", "0")
        engine2 = TpuPolicyEngine(policy, pods, namespaces)
        for _ in range(3):
            assert engine2.evaluate_grid_counts(CASES, backend="pallas") == want
        assert engine2._pre_cache is None

    def test_bf16_operand_mode(self, monkeypatch):
        """The CYCLONUS_PALLAS_DTYPE=bf16 fallback (f32 accumulators)
        must count identically to the default int8 path.  The env var is
        read at trace time, so clear jit caches around the flip."""
        import jax

        policy, pods, namespaces = fuzz_problem(13, n_extra_pods=7)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        want = engine.evaluate_grid_counts(CASES, backend="pallas")
        monkeypatch.setenv("CYCLONUS_PALLAS_DTYPE", "bf16")
        jax.clear_caches()
        try:
            engine2 = TpuPolicyEngine(policy, pods, namespaces)
            got = engine2.evaluate_grid_counts(CASES, backend="pallas")
        finally:
            monkeypatch.undo()
            jax.clear_caches()
        assert got == want

    def test_unequal_direction_chunks(self, monkeypatch):
        """Regression: with different target-axis chunk counts per
        direction (n_k_e != n_k_i), the clamped index maps refetch the
        shorter direction's last chunk and the per-direction guards must
        skip accumulating it.  Shrinking KT forces multiple chunks from a
        small fixture; an ingress-heavy and an egress-heavy policy set
        exercise both orderings."""
        import jax

        import cyclonus_tpu.engine.pallas_kernel as pk
        from cyclonus_tpu.kube.netpol import (
            IntOrString,
            LabelSelector,
            NetworkPolicyEgressRule,
            NetworkPolicyIngressRule,
            NetworkPolicyPeer,
            NetworkPolicyPort,
        )
        from cyclonus_tpu.matcher import build_network_policies
        from test_engine_parity import default_cluster, mkpol

        pods, namespaces = default_cluster()

        def mk_dir_policies(n_ing, n_eg):
            out = []
            for i in range(n_ing):
                out.append(mkpol(
                    f"in{i}", "x",
                    LabelSelector.make(match_labels={"pod": "abc"[i % 3], "i": str(i)}),
                    ["Ingress"],
                    ingress=[NetworkPolicyIngressRule(
                        ports=[NetworkPolicyPort(protocol="TCP", port=IntOrString(80))],
                        from_=[NetworkPolicyPeer(pod_selector=LabelSelector.make())],
                    )],
                ))
            for i in range(n_eg):
                out.append(mkpol(
                    f"eg{i}", "y",
                    LabelSelector.make(match_labels={"pod": "abc"[i % 3], "e": str(i)}),
                    ["Egress"],
                    egress=[NetworkPolicyEgressRule(
                        ports=[],
                        to=[NetworkPolicyPeer(pod_selector=LabelSelector.make())],
                    )],
                ))
            return out

        # these fixtures' targets mostly match no pod; dead-target
        # compaction would collapse them to a single chunk and make the
        # multi-chunk path untested
        monkeypatch.setenv("CYCLONUS_COMPACT", "0")
        # KT is a lane dimension (min 128); >128 targets on one side
        # yields n_k 2 vs 1
        monkeypatch.setattr(pk, "KT", 128)
        try:
            for n_ing, n_eg in [(150, 3), (3, 150)]:
                policy = build_network_policies(True, mk_dir_policies(n_ing, n_eg))
                engine = TpuPolicyEngine(policy, pods, namespaces)
                want = engine.evaluate_grid_counts(CASES, block=8, backend="xla")
                jax.clear_caches()  # KT is read at trace time, not cached on
                got = engine.evaluate_grid_counts(CASES, backend="pallas")
                assert got == want, (n_ing, n_eg, got, want)
        finally:
            jax.clear_caches()

    def test_unequal_src_dst_tiles(self, monkeypatch):
        """Regression: with BS != BD the pod axis must pad to a COMMON
        multiple — independent rounding silently dropped trailing dst
        rows (caught as a count mismatch in a 100k tile-size sweep)."""
        import cyclonus_tpu.engine.pallas_kernel as pk

        policy, pods, namespaces = fuzz_problem(13, n_extra_pods=10)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        want = engine.evaluate_grid_counts(CASES, block=8, backend="xla")
        import jax

        try:
            for bs, bd in [(256, 512), (512, 256)]:
                monkeypatch.setattr(pk, "BS", bs)
                monkeypatch.setattr(pk, "BD", bd)
                # BS/BD are read at trace time but are NOT part of the jit
                # cache key; identical input shapes would silently reuse
                # the previous configuration's executable
                jax.clear_caches()
                got = engine.evaluate_grid_counts(CASES, backend="pallas")
                assert got == want, (bs, bd, got, want)
        finally:
            # don't leave a non-default-tiling executable in the global
            # cache for later tests with identical input shapes
            jax.clear_caches()

    def test_doubled_src_tile_path(self):
        """A >512-pod cluster with small T-chunks takes the bs=1024
        doubled-src-tile configuration (_tiles_for) — the asymmetric
        bs != bd index maps, nz reshapes, and epilogue flush must still
        count exactly (every other test cluster is far below one tile)."""
        import random

        import bench as bench_mod
        from cyclonus_tpu.engine.pallas_kernel import _tiles_for
        from cyclonus_tpu.matcher import build_network_policies

        rng = random.Random(31)
        pods, namespaces, policies = bench_mod.build_synthetic(600, 60, rng)
        policy = build_network_policies(True, policies)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        for d in ("ingress", "egress"):
            assert engine._tensors[d]["target_ns"].shape[0] + 1 <= 128
        assert _tiles_for(128, 128, 600) == (1024, 512)  # the tested config
        want = engine.evaluate_grid_counts(CASES, block=64, backend="xla")
        got = engine.evaluate_grid_counts(CASES, backend="pallas")
        assert got == want

    def test_rect_non_prefix_masks(self):
        """The RECTANGULAR kernel (verdict_counts_pallas_rect) — the
        per-device program of the mesh fast path: Ns != Nd and validity
        as arbitrary per-side masks (a shard's rows are a window of the
        global pod axis, not a prefix, and dead pods can sit anywhere).
        Pinned against the oracle-checked single-device grids restricted
        to the same window/masks."""
        import numpy as np

        from cyclonus_tpu.engine.pallas_kernel import (
            sum_partials,
            verdict_counts_pallas_rect,
        )
        from cyclonus_tpu.engine.tiled import _precompute_jit

        policy, pods, namespaces = fuzz_problem(16, n_extra_pods=10)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        n = len(pods)
        n_b = engine._tensors["pod_ns_id"].shape[0]  # bucketed axis
        assert n_b > n  # pad rows in play
        pre = _precompute_jit(engine._tensors_with_cases(CASES))
        e, ig = pre["egress"], pre["ingress"]
        ing, egr, comb = full_grids(engine, CASES)  # [Q, N, N] real pods

        base = np.arange(n_b) < n
        q = len(CASES)

        for src0, holes_src, holes_dst in [
            (3, [4, 7], [0, 5]),  # src window into the axis, holes both sides
            (0, [], [1, 2, 9]),  # full src, dst holes only
            (n - 2, [n - 1], []),  # window straddling the real/pad boundary
        ]:
            src_ok = base.copy()
            src_ok[holes_src] = False
            dst_ok = base.copy()
            dst_ok[holes_dst] = False
            partials = verdict_counts_pallas_rect(
                e["tmatch"][:, src0:],
                e["has_target"][src0:],
                e["tallow_bf"],
                ig["tmatch"],
                ig["has_target"],
                ig["tallow_bf"][:, src0:],
                valid_src=src_ok[src0:],
                valid_dst=dst_ok,
                interpret=True,
            )
            got = sum_partials(partials, q, 0)
            srcsel = [s for s in range(src0, n) if src_ok[s]]
            dstsel = [d for d in range(n) if dst_ok[d]]
            sel = np.ix_(range(q), srcsel, dstsel)
            sel_t = np.ix_(range(q), dstsel, srcsel)  # ingress is [Q, dst, src]
            assert got["ingress"] == int(ing[sel_t].sum()), (src0, holes_src, holes_dst)
            assert got["egress"] == int(egr[sel].sum()), (src0, holes_src, holes_dst)
            assert got["combined"] == int(comb[sel].sum()), (src0, holes_src, holes_dst)

    def test_rect_dst_window(self):
        """Rect with the DST side windowed/masked instead (Ns > Nd): the
        opposite orientation of the mesh path's slicing."""
        import numpy as np

        from cyclonus_tpu.engine.pallas_kernel import (
            sum_partials,
            verdict_counts_pallas_rect,
        )
        from cyclonus_tpu.engine.tiled import _precompute_jit

        policy, pods, namespaces = fuzz_problem(17, n_extra_pods=9)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        n = len(pods)
        n_b = engine._tensors["pod_ns_id"].shape[0]
        pre = _precompute_jit(engine._tensors_with_cases(CASES))
        e, ig = pre["egress"], pre["ingress"]
        ing, egr, comb = full_grids(engine, CASES)

        dst0 = 2
        base = np.arange(n_b) < n
        src_ok = base.copy()
        src_ok[[6]] = False
        dst_ok = base.copy()
        dst_ok[[3, 8]] = False
        q = len(CASES)
        partials = verdict_counts_pallas_rect(
            e["tmatch"],
            e["has_target"],
            e["tallow_bf"][:, dst0:],
            ig["tmatch"][:, dst0:],
            ig["has_target"][dst0:],
            ig["tallow_bf"],
            valid_src=src_ok,
            valid_dst=dst_ok[dst0:],
            interpret=True,
        )
        got = sum_partials(partials, q, 0)
        srcsel = [s for s in range(n) if src_ok[s]]
        dstsel = [d for d in range(dst0, n) if dst_ok[d]]
        sel = np.ix_(range(q), srcsel, dstsel)
        sel_t = np.ix_(range(q), dstsel, srcsel)
        assert got["ingress"] == int(ing[sel_t].sum())
        assert got["egress"] == int(egr[sel].sum())
        assert got["combined"] == int(comb[sel].sum())

    def test_dtype_flip_without_cache_clear(self):
        """CYCLONUS_PALLAS_DTYPE is now resolved OUTSIDE the jit and
        passed as a static argument: flipping it mid-process retraces
        instead of silently reusing the previous dtype's executable — no
        jax.clear_caches() around this test, which is the point."""
        from cyclonus_tpu.engine.pallas_kernel import (
            sum_partials,
            verdict_counts_pallas_rect,
        )
        from cyclonus_tpu.engine.tiled import _precompute_jit

        policy, pods, namespaces = fuzz_problem(18, n_extra_pods=5)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        pre = _precompute_jit(engine._tensors_with_cases(CASES))
        e, ig = pre["egress"], pre["ingress"]
        args = (
            e["tmatch"], e["has_target"], e["tallow_bf"],
            ig["tmatch"], ig["has_target"], ig["tallow_bf"],
        )
        q = len(CASES)
        got = {
            od: sum_partials(
                verdict_counts_pallas_rect(
                    *args, interpret=True, operand_dtype=od
                ),
                q,
                0,
            )
            for od in ("int8", "bf16", "int8")
        }
        assert got["int8"] == got["bf16"]

    def _slab_case(self, policy, pods, namespaces, bs, bd, w, n_pods=None):
        """Run the slab kernel (interpret) on an engine's precompute and
        pin its counts against the oracle-checked full grids."""
        import numpy as np

        from cyclonus_tpu.engine.pallas_kernel import (
            slab_windows,
            sum_partials,
            verdict_counts_pallas_slab,
        )
        from cyclonus_tpu.engine.tiled import _precompute_jit

        engine = TpuPolicyEngine(policy, pods, namespaces)
        n = len(pods) if n_pods is None else n_pods
        pre = _precompute_jit(engine._tensors_with_cases(CASES))
        e, ig = pre["egress"], pre["ingress"]
        n_b = engine._tensors["pod_ns_id"].shape[0]
        valid = np.arange(n_b) < n
        tm_e = np.asarray(e["tmatch"]) & valid[None, :]
        tm_i = np.asarray(ig["tmatch"]) & valid[None, :]
        t0_e, ok_e = slab_windows(tm_e, bs, w)
        t0_i, ok_i = slab_windows(tm_i, bd, w)
        assert ok_e and ok_i, "fixture must be slab-eligible"
        partials = verdict_counts_pallas_slab(
            e["tmatch"], e["has_target"], e["tallow_bf"],
            ig["tmatch"], ig["has_target"], ig["tallow_bf"],
            t0_e, t0_i, n,
            interpret=True, bs=bs, bd=bd, w=w,
        )
        got = sum_partials(partials, len(CASES), 0)
        ing, egr, comb = full_grids(engine, CASES)
        sel = [s for s in range(min(n, len(pods)))]
        q = len(CASES)
        ix = np.ix_(range(q), sel, sel)
        assert got["ingress"] == int(ing[ix].sum())
        assert got["egress"] == int(egr[ix].sum())
        assert got["combined"] == int(comb[ix].sum())

    @pytest.mark.parametrize("seed", [30, 31, 32])
    def test_slab_counts_match_kernel(self, seed):
        """Per-tile target-slab kernel parity on fuzzed problems: tiny
        tiles force multiple slabs, windows land mid-axis."""
        policy, pods, namespaces = fuzz_problem(seed, n_extra_pods=9)
        self._slab_case(policy, pods, namespaces, bs=8, bd=4, w=8)

    def test_slab_validity_prefix(self):
        """Validity cut below the real pod count: trailing pods must
        contribute nothing on either axis (epilogue OR-terms included)."""
        policy, pods, namespaces = fuzz_problem(33, n_extra_pods=10)
        self._slab_case(policy, pods, namespaces, bs=8, bd=8, w=8, n_pods=len(pods) - 3)

    def test_slab_multi_namespace_sorted(self):
        """An ns-SORTED multi-namespace cluster — the production regime:
        narrow per-tile windows over a longer target axis, windows
        differing per tile, plus the bs != bd asymmetric layout."""
        import random

        import bench as bench_mod
        from cyclonus_tpu.matcher import build_network_policies

        rng = random.Random(77)
        pods, namespaces, policies = bench_mod.build_synthetic(2000, 100, rng)
        pods = sorted(pods, key=lambda p: p[0])  # ns-sort, like the packed path
        policy = build_network_policies(True, policies)
        self._slab_case(policy, pods, namespaces, bs=256, bd=128, w=64)

    def test_slab_api_path(self, monkeypatch):
        """CYCLONUS_PALLAS_SLAB=1 routes the packed counts path through
        the slab kernel (tiny tile overrides so a fuzz cluster spans
        multiple tiles), identical counts on cold, split/pre-cache, and
        cached evaluations; an ineligible width gate falls back to the
        chunked kernels with counts unchanged."""
        import cyclonus_tpu.engine.pallas_kernel as pk

        monkeypatch.setenv("CYCLONUS_PACK", "0")
        monkeypatch.setenv("CYCLONUS_PALLAS_SLAB", "1")
        monkeypatch.setattr(pk, "SLAB_BS", 8)
        monkeypatch.setattr(pk, "SLAB_BD", 8)
        monkeypatch.setattr(pk, "SLAB_W", 8)
        policy, pods, namespaces = fuzz_problem(34, n_extra_pods=10)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        want = engine.evaluate_grid_counts(CASES, backend="xla")
        got = engine.evaluate_grid_counts(CASES, backend="pallas")
        assert isinstance(engine._slab_plan_state, dict)  # plan engaged
        assert got == want
        # 2nd/3rd evaluations take the split + pre-cache paths
        assert engine.evaluate_grid_counts(CASES, backend="pallas") == want
        assert engine.evaluate_grid_counts(CASES, backend="pallas") == want

        # deterministic width-gate fallback: two same-namespace targets
        # that both match pods occupy two rows of one tile's window, so
        # W=1 is ALWAYS ineligible — the plan must come back None and
        # the chunked kernels must produce identical counts
        from cyclonus_tpu.kube.netpol import LabelSelector
        from cyclonus_tpu.matcher import build_network_policies

        from test_engine_parity import default_cluster, mkpol

        monkeypatch.setattr(pk, "SLAB_W", 1)
        d_pods, d_ns = default_cluster()
        policy2 = build_network_policies(
            True,
            [
                mkpol("p1", "x", LabelSelector.make(match_labels={"pod": "a"}),
                      ["Ingress"], ingress=[]),
                mkpol("p2", "x", LabelSelector.make(match_labels={"pod": "b"}),
                      ["Ingress"], ingress=[]),
            ],
        )
        engine2 = TpuPolicyEngine(policy2, d_pods, d_ns)
        want2 = engine2.evaluate_grid_counts(CASES, backend="xla")
        assert engine2.evaluate_grid_counts(CASES, backend="pallas") == want2
        assert engine2._slab_plan_state is None  # gate rejected W=1

    def test_slab_autotune_mechanics(self, monkeypatch):
        """_autotune_slab times both steady-state programs from the
        pinned precompute, records a boolean choice, and returns
        partials identical to either path (the perf decision itself is
        TPU-side; this pins the mechanics)."""
        import numpy as np

        import cyclonus_tpu.engine.pallas_kernel as pk
        from cyclonus_tpu.engine.pallas_kernel import sum_partials

        monkeypatch.setenv("CYCLONUS_PACK", "0")
        monkeypatch.setenv("CYCLONUS_PALLAS_SLAB", "1")
        monkeypatch.setattr(pk, "SLAB_BS", 8)
        monkeypatch.setattr(pk, "SLAB_BD", 8)
        monkeypatch.setattr(pk, "SLAB_W", 8)
        policy, pods, namespaces = fuzz_problem(35, n_extra_pods=10)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        want = engine.evaluate_grid_counts(CASES, backend="xla")
        for _ in range(3):  # reach the pinned-precompute steady state
            assert engine.evaluate_grid_counts(CASES, backend="pallas") == want
        assert engine._pre_cache is not None
        engine._slab_choice = None
        key = engine._steady_state_args(CASES)[0]
        partials = engine._autotune_slab(np.int32(len(pods)), key)
        assert engine._slab_choice in (True, False)
        # the candidate leg built and cached the gathered slab operands
        assert engine._slab_ops_cache is not None
        assert engine._slab_ops_cache[0] == key
        got = sum_partials(np.asarray(partials), len(CASES), len(pods))
        for k in ("ingress", "egress", "combined"):
            assert got[k] == want[k]
        # later calls run the recorded winner
        assert engine.evaluate_grid_counts(CASES, backend="pallas") == want

    def test_slab_autotune_candidate_failure_rejects(self, monkeypatch):
        """A slab program that fails to compile/run must reject ITSELF
        in the autotune — choice False, default result returned, no
        exception — because the autotune is where an unproven kernel
        runs unforced and must never take down the proven path."""
        import numpy as np

        import cyclonus_tpu.engine.pallas_kernel as pk
        from cyclonus_tpu.engine.pallas_kernel import sum_partials

        monkeypatch.setenv("CYCLONUS_PACK", "0")
        monkeypatch.setenv("CYCLONUS_PALLAS_SLAB", "1")
        monkeypatch.setattr(pk, "SLAB_BS", 8)
        monkeypatch.setattr(pk, "SLAB_BD", 8)
        monkeypatch.setattr(pk, "SLAB_W", 8)
        policy, pods, namespaces = fuzz_problem(37, n_extra_pods=9)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        want = engine.evaluate_grid_counts(CASES, backend="xla")
        for _ in range(3):
            assert engine.evaluate_grid_counts(CASES, backend="pallas") == want
        assert engine._pre_cache is not None
        engine._slab_choice = None
        key = engine._steady_state_args(CASES)[0]

        def failing_slab(ops):
            raise RuntimeError("mosaic compile failure (simulated)")

        monkeypatch.setattr(
            engine, "_counts_from_slab_ops_jit", failing_slab
        )
        partials = engine._autotune_slab(np.int32(len(pods)), key)
        assert engine._slab_choice is False
        # a rejected candidate must not leave its operands pinned
        assert engine._slab_ops_cache is None
        got = sum_partials(np.asarray(partials), len(CASES), len(pods))
        for k in ("ingress", "egress", "combined"):
            assert got[k] == want[k]
        # the rejection sticks: later calls run the default path without
        # touching the failing slab leg
        assert engine.evaluate_grid_counts(CASES, backend="pallas") == want

        # a HANGING candidate (wedged remote compile) must also reject
        # via the bounded leg, not stall the caller
        import time as _t

        def hanging_slab(ops):
            _t.sleep(30)

        monkeypatch.setattr(
            engine, "_counts_from_slab_ops_jit", hanging_slab
        )
        monkeypatch.setenv("CYCLONUS_AUTOTUNE_TIMEOUT_S", "0.5")
        engine._slab_choice = None
        t0 = _t.time()
        partials = engine._autotune_slab(np.int32(len(pods)), key)
        assert _t.time() - t0 < 10
        assert engine._slab_choice is False
        got = sum_partials(np.asarray(partials), len(CASES), len(pods))
        assert got["combined"] == want["combined"]

    def test_slab_autotune_rejection_telemetry_and_orphan_gating(
        self, monkeypatch
    ):
        """A rejected candidate must leave telemetry (WHY there are no
        timed legs), and after a TIMEOUT the next dispatch must gate on
        the abandoned thread: wait briefly for it, count the overlap if
        it is still in flight, and never let its stray execution race a
        real dispatch unrecorded."""
        import threading
        import time as _t

        import cyclonus_tpu.engine.pallas_kernel as pk

        monkeypatch.setenv("CYCLONUS_PACK", "0")
        monkeypatch.setenv("CYCLONUS_PALLAS_SLAB", "1")
        monkeypatch.setattr(pk, "SLAB_BS", 8)
        monkeypatch.setattr(pk, "SLAB_BD", 8)
        monkeypatch.setattr(pk, "SLAB_W", 8)
        policy, pods, namespaces = fuzz_problem(38, n_extra_pods=9)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        want = engine.evaluate_grid_counts(CASES, backend="xla")
        for _ in range(3):
            assert engine.evaluate_grid_counts(CASES, backend="pallas") == want
        assert engine._pre_cache is not None
        real_slab = engine._counts_from_slab_ops_jit
        key = engine._steady_state_args(CASES)[0]

        # --- error branch: telemetry, no orphan ---
        def failing(ops):
            raise RuntimeError("mosaic compile failure (simulated)")

        monkeypatch.setattr(engine, "_counts_from_slab_ops_jit", failing)
        engine._slab_choice = None
        engine._autotune_slab(np.int32(len(pods)), key)
        tel = engine._slab_autotune
        assert tel["candidate"] == "error"
        assert "mosaic compile failure" in tel["candidate_error"]
        assert "default_s" in tel
        assert engine._autotune_orphan is None

        # --- timeout branch: orphan gates the next dispatch ---
        release = threading.Event()

        def hanging(ops):
            release.wait(30)
            return real_slab(ops)

        monkeypatch.setattr(engine, "_counts_from_slab_ops_jit", hanging)
        monkeypatch.setenv("CYCLONUS_AUTOTUNE_TIMEOUT_S", "0.3")
        engine._slab_choice = None
        engine._autotune_slab(np.int32(len(pods)), key)
        assert engine._slab_autotune["candidate"] == "timeout"
        assert engine._autotune_orphan is not None

        # a dispatch while the orphan is live: brief wait times out,
        # overlap counted, orphan kept for the non-blocking next check
        monkeypatch.setenv("CYCLONUS_AUTOTUNE_DRAIN_S", "0.2")
        monkeypatch.setattr(
            engine, "_counts_from_slab_ops_jit", real_slab
        )
        assert engine.evaluate_grid_counts(CASES, backend="pallas") == want
        assert engine._slab_autotune["orphan_overlap_dispatches"] == 1
        assert engine._autotune_orphan is not None

        # once the orphan finishes, the next dispatch clears it without
        # further counting
        release.set()
        deadline = _t.time() + 10
        while not engine._autotune_orphan["event"].is_set():
            assert _t.time() < deadline
            _t.sleep(0.02)
        assert engine.evaluate_grid_counts(CASES, backend="pallas") == want
        assert engine._autotune_orphan is None
        assert engine._slab_autotune["orphan_overlap_dispatches"] == 1

    def test_slab_ops_cache_lifecycle(self, monkeypatch):
        """The gathered slab operands are built once per pinned case set
        (forced mode dispatches from the cache), reused by identity on
        repeat dispatches, and evicted WITH the pre-cache."""
        import cyclonus_tpu.engine.pallas_kernel as pk

        monkeypatch.setenv("CYCLONUS_PACK", "0")
        monkeypatch.setenv("CYCLONUS_PALLAS_SLAB", "1")
        monkeypatch.setattr(pk, "SLAB_BS", 8)
        monkeypatch.setattr(pk, "SLAB_BD", 8)
        monkeypatch.setattr(pk, "SLAB_W", 8)
        policy, pods, namespaces = fuzz_problem(41, n_extra_pods=8)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        want = engine.evaluate_grid_counts(CASES, backend="xla")
        for _ in range(3):
            assert engine.evaluate_grid_counts(CASES, backend="pallas") == want
        # steady state + forced choice => the dispatch rides the cache
        assert engine._slab_choice is True
        assert engine._slab_ops_cache is not None
        ops_first = engine._slab_ops_cache[1]
        assert engine.evaluate_grid_counts(CASES, backend="pallas") == want
        assert engine._slab_ops_cache[1] is ops_first  # reused, not rebuilt
        # two consecutive other-set evaluations evict pre AND slab ops
        other = [PortCase(9999, "", "TCP")]
        want_other = engine.evaluate_grid_counts(other, backend="xla")
        assert engine.evaluate_grid_counts(other, backend="pallas") == want_other
        assert engine.evaluate_grid_counts(other, backend="pallas") == want_other
        assert engine._slab_ops_cache is None or (
            engine._slab_ops_cache[0] != engine._steady_state_args(CASES)[0]
        )

    def test_counts_pipelined_eval(self):
        """counts_pipelined_eval_s: None before the pinned-precompute
        steady state, then (seconds, counts) with counts identical to
        the sync path — the device-throughput leg the bench records."""
        policy, pods, namespaces = fuzz_problem(40, n_extra_pods=7)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        want = engine.evaluate_grid_counts(CASES, backend="xla")
        assert engine.counts_pipelined_eval_s(CASES) is None  # cold
        for _ in range(3):
            assert engine.evaluate_grid_counts(CASES, backend="pallas") == want
        got = engine.counts_pipelined_eval_s(CASES, reps=3)
        assert got is not None
        dt, counts = got
        assert dt > 0
        assert counts == want
        # a different case set is not at steady state
        other = [PortCase(9999, "", "TCP")]
        assert engine.counts_pipelined_eval_s(other) is None

    def test_slab_auto_mode_needs_tpu(self, monkeypatch):
        """The default 'auto' mode never engages off TPU (interpret-mode
        timing is meaningless): no plan, default kernels, counts
        unchanged."""
        import jax

        import cyclonus_tpu.engine.pallas_kernel as pk

        if jax.default_backend() == "tpu":
            pytest.skip("off-TPU behavior; suite running on real TPU")
        monkeypatch.delenv("CYCLONUS_PALLAS_SLAB", raising=False)
        monkeypatch.setattr(pk, "SLAB_BS", 8)
        monkeypatch.setattr(pk, "SLAB_BD", 8)
        policy, pods, namespaces = fuzz_problem(36, n_extra_pods=8)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        want = engine.evaluate_grid_counts(CASES, backend="xla")
        assert engine.evaluate_grid_counts(CASES, backend="pallas") == want
        assert engine._slab_plan_state is None
        assert engine._slab_choice is None

    def test_slab_windows_eligibility(self):
        """slab_windows: window starts and the ineligibility verdict for
        scattered (non-local) target structure."""
        import numpy as np

        from cyclonus_tpu.engine.pallas_kernel import slab_windows

        tm = np.zeros((40, 8), dtype=bool)
        tm[3, 0] = tm[5, 1] = True  # tile 0 (cols 0-3): rows 3..5
        tm[20, 4] = tm[24, 7] = True  # tile 1: rows 20..24
        t0, ok = slab_windows(tm, tile=4, w=8)
        assert ok
        assert list(t0) == [3, 20]
        # scatter one tile's matches past the window
        tm[35, 2] = True  # tile 0 now spans 3..35 > 8
        _t0, ok = slab_windows(tm, tile=4, w=8)
        assert not ok
        # empty tmatch: trivially eligible
        t0, ok = slab_windows(np.zeros((0, 8), dtype=bool), tile=4, w=8)
        assert ok

    def test_selector_match_np_twin(self):
        """The numpy selector evaluator that drives dead-target compaction
        must agree with the device kernel op for op — fuzzed over random
        selector tables (incl. matchExpressions) and label sets."""
        import numpy as np

        from cyclonus_tpu.engine.api import _selector_match_np
        from cyclonus_tpu.engine.kernel import selector_match

        rng = np.random.default_rng(7)
        for _ in range(20):
            s, r, e, v, n, l = (
                rng.integers(1, 6),
                rng.integers(1, 4),
                rng.integers(1, 4),
                rng.integers(1, 4),
                rng.integers(1, 12),
                rng.integers(1, 5),
            )
            args = (
                rng.integers(-1, 6, size=(s, r)).astype(np.int32),
                rng.integers(0, 5, size=(s, e)).astype(np.int32),
                rng.integers(-1, 5, size=(s, e)).astype(np.int32),
                rng.integers(-1, 6, size=(s, e, v)).astype(np.int32),
                rng.integers(-1, 6, size=(n, l)).astype(np.int32),
                rng.integers(-1, 5, size=(n, l)).astype(np.int32),
            )
            got = _selector_match_np(*args)
            want = np.asarray(selector_match(*args))
            assert np.array_equal(got, want)

    def test_no_policies_all_allow(self):
        """With zero policies every pod is target-free: the pseudo-target
        fold must produce all-allow counts for exactly the valid pods
        (pads contribute nothing)."""
        from cyclonus_tpu.matcher import build_network_policies

        from test_engine_parity import default_cluster

        pods, namespaces = default_cluster()
        policy = build_network_policies(True, [])
        engine = TpuPolicyEngine(policy, pods, namespaces)
        counts = engine.evaluate_grid_counts(CASES, backend="pallas")
        n = len(pods)
        full = n * n * len(CASES)
        assert counts == {
            "ingress": full,
            "egress": full,
            "combined": full,
            "cells": full,
        }

    def test_one_empty_direction(self):
        """Ingress-only policies leave the egress target axis empty
        (T_e = 0): its padded pseudo-row chunk must still produce the
        all-allow egress verdicts for valid pods."""
        from cyclonus_tpu.kube.netpol import LabelSelector
        from cyclonus_tpu.matcher import build_network_policies

        from test_engine_parity import default_cluster, mkpol

        pods, namespaces = default_cluster()
        policy = build_network_policies(
            True,
            [
                mkpol(
                    "deny-in",
                    "x",
                    LabelSelector.make(match_labels={"pod": "a"}),
                    ["Ingress"],
                    ingress=[],
                )
            ],
        )
        engine = TpuPolicyEngine(policy, pods, namespaces)
        want = engine.evaluate_grid_counts(CASES, block=8, backend="xla")
        got = engine.evaluate_grid_counts(CASES, backend="pallas")
        assert got == want
        n = len(pods)
        assert got["egress"] == n * n * len(CASES)  # no egress targets


class TestSlabLayout:
    def test_slab_w_aug_alignment_arbitrary_w(self):
        """slab_w_aug must land on the dtype sublane tile for ANY w
        override (not just tile-aligned ones), with room for the window
        plus the OR-term row."""
        from cyclonus_tpu.engine.pallas_kernel import slab_w_aug

        for od, tile in (("int8", 32), ("bf16", 16)):
            for w in (1, 17, 32, 100, 128, 129, 257):
                aug = slab_w_aug(od, w)
                assert aug % tile == 0, (od, w, aug)
                assert aug >= w + 1, (od, w, aug)
                # minimal: no more than one extra tile of padding
                assert aug < w + 1 + tile, (od, w, aug)

    def test_slab_w_aug_default_unchanged(self):
        """Tile-aligned defaults keep the historical layout (the
        persistent compile cache keys on these shapes)."""
        from cyclonus_tpu.engine.pallas_kernel import SLAB_W, slab_w_aug

        assert SLAB_W % 32 == 0
        assert slab_w_aug("int8") == SLAB_W + 32
        assert slab_w_aug("bf16") == SLAB_W + 16

    def test_slab_budget_counts_bytes_not_elements(self, monkeypatch):
        """api._slab_plan must scale its HBM estimate by the operand
        itemsize: with bf16 operands the same element count is twice
        the bytes, so a budget that admits an int8 plan at the edge
        must reject the bf16 one."""
        from cyclonus_tpu.engine.pallas_kernel import (
            SLAB_BD,
            SLAB_BS,
            slab_w_aug,
        )
        from cyclonus_tpu.matcher import build_network_policies
        from test_engine_parity import mkpol
        from cyclonus_tpu.kube.netpol import (
            LabelSelector,
            NetworkPolicyIngressRule,
        )

        n = 4 * SLAB_BS  # spans >= 2 src tiles so the plan engages
        pods = [("x", f"p{i}", {"pod": "a"}, f"10.0.{i // 250}.{i % 250}")
                for i in range(n)]
        namespaces = {"x": {"ns": "x"}}
        policy = build_network_policies(
            True,
            [mkpol("allow", "x", LabelSelector.make(), ["Ingress"],
                   ingress=[NetworkPolicyIngressRule()])],
        )
        monkeypatch.setenv("CYCLONUS_PACK", "0")
        monkeypatch.setenv("CYCLONUS_PALLAS_SLAB", "1")
        # this test pins the slab BYTE accounting with an exact budget;
        # class compression would add its aux/index bytes to the same
        # budget (its own test: test_engine_classes.py) and skew the
        # equality below
        monkeypatch.setenv("CYCLONUS_CLASS_COMPRESS", "0")

        monkeypatch.setenv("CYCLONUS_PALLAS_DTYPE", "int8")
        engine = TpuPolicyEngine(policy, pods, namespaces)
        n_b = int(engine._tensors["pod_ns_id"].shape[0])
        n_tiles = -(-n_b // SLAB_BS) + -(-n_b // SLAB_BD)
        elems = n_tiles * slab_w_aug("int8") * n_b
        ns = engine._tensors["pod_ns_id"]
        key = np.where(ns < 0, np.iinfo(np.int32).max, ns)
        perm = np.argsort(key, kind="stable").astype(np.int32)

        # budget admitting 2 cases of int8 exactly
        budget = 2 * elems
        monkeypatch.setenv("CYCLONUS_SLAB_MAX_BYTES", str(budget))
        assert engine._slab_plan(perm) is not None

        # same ELEMENT budget under bf16 must be rejected (2x the bytes)
        monkeypatch.setenv("CYCLONUS_PALLAS_DTYPE", "bf16")
        bf16_elems = n_tiles * slab_w_aug("bf16") * n_b
        monkeypatch.setenv("CYCLONUS_SLAB_MAX_BYTES", str(2 * bf16_elems))
        engine2 = TpuPolicyEngine(policy, pods, namespaces)
        assert engine2._slab_plan(perm) is None
