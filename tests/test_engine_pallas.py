"""Parity gate for the fused Pallas verdict+count kernel
(engine/pallas_kernel.py): counts must equal the oracle-checked
single-device kernel's sums exactly.  On CPU the kernel runs in Pallas
interpret mode; on TPU it compiles via Mosaic — same program either way.
"""

import numpy as np
import pytest

from cyclonus_tpu.engine import PortCase, TpuPolicyEngine

from test_engine_tiled import CASES, fuzz_problem, full_grids


class TestPallasCounts:
    @pytest.mark.parametrize("seed", range(4))
    def test_counts_match_kernel(self, seed):
        policy, pods, namespaces = fuzz_problem(seed, n_extra_pods=6)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        ing, egr, comb = full_grids(engine, CASES)
        counts = engine.evaluate_grid_counts(CASES, backend="pallas")
        assert counts["ingress"] == int(ing.sum())
        assert counts["egress"] == int(egr.sum())
        assert counts["combined"] == int(comb.sum())
        assert counts["cells"] == ing.size

    def test_single_port_case(self):
        policy, pods, namespaces = fuzz_problem(11)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        cases = [PortCase(80, "serve-80-tcp", "TCP")]
        ing, egr, comb = full_grids(engine, cases)
        counts = engine.evaluate_grid_counts(cases, backend="pallas")
        assert counts["combined"] == int(comb.sum())

    def test_matches_xla_backend(self):
        policy, pods, namespaces = fuzz_problem(12, n_extra_pods=9)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        a = engine.evaluate_grid_counts(CASES, block=8, backend="xla")
        b = engine.evaluate_grid_counts(CASES, backend="pallas")
        assert a == b

    def test_unequal_src_dst_tiles(self, monkeypatch):
        """Regression: with BS != BD the pod axis must pad to a COMMON
        multiple — independent rounding silently dropped trailing dst
        rows (caught as a count mismatch in a 100k tile-size sweep)."""
        import cyclonus_tpu.engine.pallas_kernel as pk

        policy, pods, namespaces = fuzz_problem(13, n_extra_pods=10)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        want = engine.evaluate_grid_counts(CASES, block=8, backend="xla")
        import jax

        try:
            for bs, bd in [(256, 512), (512, 256)]:
                monkeypatch.setattr(pk, "BS", bs)
                monkeypatch.setattr(pk, "BD", bd)
                # BS/BD are read at trace time but are NOT part of the jit
                # cache key; identical input shapes would silently reuse
                # the previous configuration's executable
                jax.clear_caches()
                got = engine.evaluate_grid_counts(CASES, backend="pallas")
                assert got == want, (bs, bd, got, want)
        finally:
            # don't leave a non-default-tiling executable in the global
            # cache for later tests with identical input shapes
            jax.clear_caches()
