"""Parity gate for the tiled/streaming evaluation paths (engine/tiled.py):
counts, streamed blocks, and point-pair verdicts must agree exactly with
the single-device kernel (itself oracle-checked by test_engine_parity.py),
across fuzzed policy sets, odd block sizes (pad rows in play), and the
IPv6 host-fallback path.
"""

import random

import numpy as np
import pytest

from cyclonus_tpu.engine import PortCase, TpuPolicyEngine
from cyclonus_tpu.matcher import build_network_policies

from test_engine_parity import (
    default_cluster,
    mkpol,
    oracle_grid,
    random_policy,
)
from cyclonus_tpu.kube.netpol import (
    IPBlock,
    LabelSelector,
    NetworkPolicyIngressRule,
    NetworkPolicyPeer,
)

CASES = [
    PortCase(80, "serve-80-tcp", "TCP"),
    PortCase(81, "serve-81-udp", "UDP"),
]


def fuzz_problem(seed, n_extra_pods=0):
    rng = random.Random(seed)
    nss = ["x", "y", "z"]
    keys = ["pod", "app", "ns", "team"]
    values = ["a", "b", "c", "x", "y", "z", "blue", "red"]
    pods, namespaces = default_cluster()
    # fuzzed team labels layer ON TOP of the defaults so namespace-selector
    # peers on "team" genuinely discriminate
    for ns in nss:
        namespaces[ns] = {"ns": ns, "team": rng.choice(["blue", "red"])}
    for i in range(n_extra_pods):
        ns = rng.choice(nss)
        pods.append(
            (ns, f"extra-{i}", {"app": rng.choice(values)}, f"192.168.2.{i + 1}")
        )
    policies = [
        random_policy(rng, i, nss, keys, values)
        for i in range(rng.randrange(2, 6))
    ]
    return build_network_policies(True, policies), pods, namespaces


def full_grids(engine, cases):
    g = engine.evaluate_grid(cases)
    return (
        np.asarray(g.ingress),
        np.asarray(g.egress),
        np.asarray(g.combined),
    )


class TestTiledCounts:
    @pytest.mark.parametrize("seed,block", [(0, 4), (1, 5), (2, 16), (3, 64)])
    def test_counts_match_kernel(self, seed, block):
        policy, pods, namespaces = fuzz_problem(seed, n_extra_pods=7)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        ing, egr, comb = full_grids(engine, CASES)
        counts = engine.evaluate_grid_counts(CASES, block=block, backend="xla")
        assert counts["ingress"] == int(ing.sum())
        assert counts["egress"] == int(egr.sum())
        assert counts["combined"] == int(comb.sum())
        assert counts["cells"] == ing.size

    def test_counts_empty(self):
        policy, pods, namespaces = fuzz_problem(0)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        assert engine.evaluate_grid_counts([]) == {
            "ingress": 0,
            "egress": 0,
            "combined": 0,
            "cells": 0,
        }

    @pytest.mark.parametrize("seed,block", [(20, 2), (21, 8)])
    def test_counts_ring_match_kernel(self, seed, block):
        """Ring-rotation counts (both axes sharded, ppermute per step)
        must equal the single-device kernel's sums."""
        policy, pods, namespaces = fuzz_problem(seed, n_extra_pods=13)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        ing, egr, comb = full_grids(engine, CASES)
        counts = engine.evaluate_grid_counts_ring(CASES, block=block)
        assert counts["ingress"] == int(ing.sum())
        assert counts["egress"] == int(egr.sum())
        assert counts["combined"] == int(comb.sum())

    @pytest.mark.parametrize("seed,block", [(22, 2), (23, 8)])
    def test_counts_ring2d_match_kernel(self, seed, block):
        """Hierarchical (dcn, ici) ring counts — ICI hops within a host
        round, one DCN hop per round — must equal the single-device
        kernel's sums.  On the virtual 8-device CPU mesh the default
        factoring is 2 hosts x 4 chips, so both axes actually rotate."""
        policy, pods, namespaces = fuzz_problem(seed, n_extra_pods=13)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        ing, egr, comb = full_grids(engine, CASES)
        counts = engine.evaluate_grid_counts_ring2d(CASES, block=block)
        assert counts["ingress"] == int(ing.sum())
        assert counts["egress"] == int(egr.sum())
        assert counts["combined"] == int(comb.sum())

    @pytest.mark.parametrize("n_dev", [2, 4, 8])
    def test_ring_per_device_shard_shapes(self, n_dev, monkeypatch):
        """The ring path's O(N / n_dev) per-device memory claim, asserted
        structurally: inside the shard_map'd per-device function, the pod
        arrays must arrive with exactly n_padded / n_dev rows.  Recorded
        by intercepting the per-device _precompute during tracing."""
        import jax
        import numpy as np
        from jax.sharding import Mesh

        import cyclonus_tpu.engine.tiled as tiled

        policy, pods, namespaces = fuzz_problem(25, n_extra_pods=13)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        cpu = jax.devices("cpu")
        if len(cpu) < n_dev:
            pytest.skip(f"needs {n_dev} CPU devices, have {len(cpu)}")
        mesh = Mesh(np.array(cpu[:n_dev]), ("x",))

        seen = []
        real_precompute = tiled._precompute

        def recording_precompute(tensors, *args, **kwargs):
            seen.append(int(tensors["pod_kv"].shape[0]))
            return real_precompute(tensors, *args, **kwargs)

        monkeypatch.setattr(tiled, "_precompute", recording_precompute)
        block = 4
        want = engine.evaluate_grid_counts(CASES, block=block, backend="xla")
        got = engine.evaluate_grid_counts_ring(CASES, block=block, mesh=mesh)
        assert got == want
        # ring path: per-device pod rows = n_padded / n_dev exactly.
        # The engine's tensors arrive shape-BUCKETED (api._bucket_pods),
        # so mesh padding starts from the bucketed axis, not n_pods.
        # (Only the ring call's trace is asserted — the single-device
        # reference's module-level jit may be cached from earlier tests
        # and then never calls the recorder.)
        n_bucketed = engine._tensors["pod_ns_id"].shape[0]
        # mirror _mesh_counts_setup's block clamp: the engine shrinks the
        # tile height so every device gets at least one tile
        n = engine.encoding.cluster.n_pods
        block_eff = min(block, max(n // n_dev, 1))
        granule = n_dev * block_eff
        n_padded = -(-n_bucketed // granule) * granule
        assert seen, "ring path never traced (unexpected jit cache hit)"
        assert seen[-1] == n_padded // n_dev
        assert seen[-1] < n_bucketed  # strictly smaller than the full axis

    def test_counts_ring2d_explicit_mesh(self):
        """A caller-provided 4x2 mesh (4 'hosts' x 2 'chips') exercises a
        DCN axis longer than the ICI axis."""
        import jax
        import numpy as np
        from jax.sharding import Mesh

        policy, pods, namespaces = fuzz_problem(24, n_extra_pods=9)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        cpu = jax.devices("cpu")
        if len(cpu) < 8:
            pytest.skip(f"needs an 8-device CPU mesh, have {len(cpu)}")
        devs = np.array(cpu[:8]).reshape(4, 2)
        mesh = Mesh(devs, ("dcn", "ici"))
        want = engine.evaluate_grid_counts(CASES, block=4, backend="xla")
        got = engine.evaluate_grid_counts_ring2d(CASES, block=4, mesh=mesh)
        assert got == want

    def test_counts_ring_ipv6_host_rows(self):
        """host_ip_match rows are pod-axis sharded in the ring path — on
        BOTH sides: the ingress policy patches the local (peer) view, the
        egress policy's patched rows are baked into the tallow bundle
        that rotates around the ring."""
        from cyclonus_tpu.kube.netpol import (
            IPBlock,
            LabelSelector,
            NetworkPolicyEgressRule,
            NetworkPolicyIngressRule,
            NetworkPolicyPeer,
        )
        from cyclonus_tpu.matcher import build_network_policies
        from test_engine_parity import default_cluster, mkpol

        pods, namespaces = default_cluster()
        pods = [
            (ns, name, labels, ip if i % 2 else f"2001:db8::{i + 1}")
            for i, (ns, name, labels, ip) in enumerate(pods)
        ]
        v6_peer = NetworkPolicyPeer(ip_block=IPBlock.make("2001:db8::/112", []))
        pol_i = mkpol(
            "v6-in",
            "x",
            LabelSelector.make(),
            ["Ingress"],
            ingress=[NetworkPolicyIngressRule(ports=[], from_=[v6_peer])],
        )
        pol_e = mkpol(
            "v6-eg",
            "y",
            LabelSelector.make(),
            ["Egress"],
            egress=[NetworkPolicyEgressRule(ports=[], to=[v6_peer])],
        )
        policy = build_network_policies(True, [pol_i, pol_e])
        engine = TpuPolicyEngine(policy, pods, namespaces)
        ing, egr, comb = full_grids(engine, CASES)
        counts = engine.evaluate_grid_counts_ring(CASES, block=2)
        assert counts["combined"] == int(comb.sum())
        assert counts["ingress"] == int(ing.sum())

    @pytest.mark.parametrize("seed,block", [(7, 2), (8, 16)])
    def test_counts_sharded_match_kernel(self, seed, block):
        """Mesh-parallel counts over the virtual multi-device mesh must
        equal the single-device kernel's sums (pad rows per device)."""
        policy, pods, namespaces = fuzz_problem(seed, n_extra_pods=11)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        ing, egr, comb = full_grids(engine, CASES)
        counts = engine.evaluate_grid_counts_sharded(CASES, block=block)
        assert counts["ingress"] == int(ing.sum())
        assert counts["egress"] == int(egr.sum())
        assert counts["combined"] == int(comb.sum())

    @pytest.mark.parametrize("seed,block", [(9, 2), (10, 8)])
    def test_counts_sharded_pallas_kernel(self, seed, block):
        """The production multi-chip FAST path: kernel="pallas" forces
        the fused rectangular verdict+count kernel per device (interpret
        mode on the CPU mesh, Mosaic-compiled on TPU) — pinned against
        the single-device kernel exactly like the xla tile loop, and
        against the xla mesh path's full result dict."""
        policy, pods, namespaces = fuzz_problem(seed, n_extra_pods=11)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        ing, egr, comb = full_grids(engine, CASES)
        counts = engine.evaluate_grid_counts_sharded(
            CASES, block=block, kernel="pallas"
        )
        assert counts["ingress"] == int(ing.sum())
        assert counts["egress"] == int(egr.sum())
        assert counts["combined"] == int(comb.sum())
        assert counts == engine.evaluate_grid_counts_sharded(
            CASES, block=block, kernel="xla"
        )


class TestTiledBlocks:
    # (7, 3): 14 pods bucket to a 16-row pod axis — a block size that
    # doesn't divide it used to yield pad rows mislabeled as real rows
    @pytest.mark.parametrize("seed,block", [(4, 4), (5, 7), (6, 32), (7, 3)])
    def test_blocks_match_kernel(self, seed, block):
        policy, pods, namespaces = fuzz_problem(seed, n_extra_pods=5)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        ing, egr, comb = full_grids(engine, CASES)  # [Q, N, N]
        n = len(pods)
        seen = 0
        for start, b_ing, b_egr, b_comb in engine.iter_grid_blocks(
            CASES, block=block
        ):
            b = b_egr.shape[0]
            # block layout: [b, N, Q]; full-grid: ingress [Q, dst, src],
            # egress/combined [Q, src, dst]
            np.testing.assert_array_equal(
                b_egr, np.moveaxis(egr[:, start : start + b, :], 0, -1)
            )
            np.testing.assert_array_equal(
                b_comb, np.moveaxis(comb[:, start : start + b, :], 0, -1)
            )
            np.testing.assert_array_equal(
                b_ing,
                np.moveaxis(ing[:, :, start : start + b], 0, -1).transpose(
                    1, 0, 2
                ),
            )
            seen += b
        assert seen == n


class TestPairs:
    @pytest.mark.parametrize("seed", range(4))
    def test_pairs_match_oracle(self, seed):
        policy, pods, namespaces = fuzz_problem(100 + seed, n_extra_pods=3)
        engine = TpuPolicyEngine(policy, pods, namespaces)
        rng = random.Random(seed)
        n = len(pods)
        pairs = [(rng.randrange(n), rng.randrange(n)) for _ in range(40)]
        got = engine.evaluate_pairs(CASES, pairs)  # [K, Q, 3]
        expected = oracle_grid(policy, pods, namespaces, CASES)
        for k, (s, d) in enumerate(pairs):
            for qi in range(len(CASES)):
                exp = expected[(qi, s, d)]
                assert tuple(bool(x) for x in got[k, qi]) == exp, (
                    f"pair ({s},{d}) case {qi}: engine="
                    f"{tuple(got[k, qi])} oracle={exp}"
                )

    def test_pairs_ipv6_host_fallback(self):
        # IPv6 IPBlock forces host-evaluated peer rows; the pairs kernel
        # must re-index them by original pod row
        pods, namespaces = default_cluster()
        pods = [
            (ns, name, labels, ip if i % 2 else f"2001:db8::{i + 1}")
            for i, (ns, name, labels, ip) in enumerate(pods)
        ]
        pol = mkpol(
            "v6",
            "x",
            LabelSelector.make(),
            ["Ingress"],
            ingress=[
                NetworkPolicyIngressRule(
                    ports=[],
                    from_=[
                        NetworkPolicyPeer(
                            ip_block=IPBlock.make("2001:db8::/112", [])
                        )
                    ],
                )
            ],
        )
        policy = build_network_policies(True, [pol])
        engine = TpuPolicyEngine(policy, pods, namespaces)
        ing, egr, comb = full_grids(engine, CASES)
        n = len(pods)
        pairs = [(s, d) for s in range(n) for d in range(n)]
        got = engine.evaluate_pairs(CASES, pairs)
        for k, (s, d) in enumerate(pairs):
            for qi in range(len(CASES)):
                assert bool(got[k, qi, 0]) == bool(ing[qi, d, s])
                assert bool(got[k, qi, 1]) == bool(egr[qi, s, d])
                assert bool(got[k, qi, 2]) == bool(comb[qi, s, d])
