"""State-surface harness: the dynamic proof behind tools/statelint.py
(docs/DESIGN.md "State discipline"), mirroring tests/planharness.py's
role for the dispatch lint.

The static pass proves the declared state registry
(cyclonus_tpu/serve/stateregistry.py) agrees with the code: every
registered field is mutated only on the guarded commit path, rides the
rollback snapshot, the digest canonicalization, the ``note_epoch``
audit snapshot, the ``state()`` payload, and a wire Delta kind.  This
harness proves the declarations PREDICT live behavior: it arms the
registry call recorder (CYCLONUS_STATEHARNESS=1, read once at import —
the strip contract), drives every registered field's delta kinds
through a real VerdictService, and asserts

  * the epoch state digest CHANGES for every committed kind (digest
    coverage is live, not just declared — statelint ST003's dynamic
    twin),
  * a forced mid-apply failure (chaos point ``delta_apply``) rolls the
    digest back to the pre-batch value through the registry-driven
    snapshot/restore pair (ST002's dynamic twin),
  * the epoch advances exactly once per committed batch and not at all
    for rejected or dropped batches (ST004's dynamic twin),
  * every declared kind round-trips the wire Delta envelope (ST005's
    dynamic twin),

plus the planted "forgotten field" leg: a snapshot stripped of a
registered field makes ``restore`` raise KeyError, an ``audit_state``
dict stripped of one makes ``note_epoch`` raise TypeError, and a
canonicalization stripped of one digests a BANP change EQUAL — the
exact silent-coverage-loss statelint ST002/ST003 exist to prevent,
proven fireable at runtime and not just in the linter's fixtures.

The quick slice runs in tier-1 (via tests/test_statelint.py, planlint's
subprocess pattern); ``--full`` (``make stateharness``) adds the
scaled parity sweep.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Callable, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the recorder is armed at stateregistry IMPORT (strip contract) — set
# the flag before any cyclonus_tpu import, plus the standalone-run env
# the pytest path gets from tests/conftest.py
os.environ["CYCLONUS_STATEHARNESS"] = "1"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("CYCLONUS_AUTOTUNE_CACHE", "0")
os.environ.setdefault("CYCLONUS_AOT_CACHE", "0")


class HarnessFailure(AssertionError):
    """A live state surface diverged from the registry's declaration;
    the message names the scenario and the divergence."""


def _check(cond: bool, scenario: str, detail: str) -> None:
    if not cond:
        raise HarnessFailure(f"{scenario}: {detail}")


# --- delta payload factories ------------------------------------------------


def _np_dict(name: str, ns: str, app: str) -> Dict:
    """A minimal compilable NetworkPolicy payload."""
    return {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "NetworkPolicy",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "podSelector": {},
            "policyTypes": ["Ingress"],
            "ingress": [
                {"from": [{"podSelector": {"matchLabels": {"app": app}}}]}
            ],
        },
    }


def _anp_dict(name: str, priority: int) -> Dict:
    from cyclonus_tpu.tiers.model import (
        AdminNetworkPolicy,
        TierRule,
        TierScope,
    )

    return AdminNetworkPolicy(
        name=name, priority=priority, subject=TierScope(),
        ingress=[TierRule(action="Allow", peers=[TierScope()])],
    ).to_dict()


def _banp_dict() -> Dict:
    from cyclonus_tpu.tiers.model import (
        BaselineAdminNetworkPolicy,
        TierRule,
        TierScope,
    )

    return BaselineAdminNetworkPolicy(
        subject=TierScope(),
        ingress=[TierRule(action="Deny", peers=[TierScope()])],
    ).to_dict()


def _kind_delta(kind: str):
    """A representative, state-CHANGING Delta for each registered kind
    against the Ctx fixture (pods pod-0..N-1 in ns0/ns1; deltas are
    ordered so upserts precede their deletes)."""
    from cyclonus_tpu.worker.model import Delta

    table = {
        "pod_add": Delta(
            kind="pod_add", namespace="ns0", name="harness-pod",
            labels={"app": "app1", "pod": "p99", "tier": "tier1"},
            ip="10.99.0.1",
        ),
        "pod_labels": Delta(
            kind="pod_labels", namespace="ns0", name="harness-pod",
            labels={"app": "app2", "pod": "p99", "tier": "tier2"},
        ),
        "pod_remove": Delta(
            kind="pod_remove", namespace="ns0", name="harness-pod",
        ),
        "ns_labels": Delta(
            kind="ns_labels", namespace="ns0",
            labels={"ns": "ns0", "team": "team9"},
        ),
        "policy_upsert": Delta(
            kind="policy_upsert", namespace="ns0", name="harness-np",
            policy=_np_dict("harness-np", "ns0", "app1"),
        ),
        "policy_delete": Delta(
            kind="policy_delete", namespace="ns0", name="harness-np",
        ),
        "anp_upsert": Delta(
            kind="anp_upsert", name="harness-anp",
            policy=_anp_dict("harness-anp", 10),
        ),
        "anp_delete": Delta(kind="anp_delete", name="harness-anp"),
        "banp_upsert": Delta(kind="banp_upsert", policy=_banp_dict()),
        "banp_delete": Delta(kind="banp_delete"),
    }
    return table[kind]


class Ctx:
    """Shared scenario context: a small live service (8 pods across 2
    namespaces — every registered field populated or populatable inside
    the tier-1 budget), its audit controller (synchronous drain — no
    worker thread), and the covered field/kind census."""

    def __init__(self, seed: int):
        self.seed = seed
        self._svc = None
        self._aud = None
        self.covered_fields: set = set()
        self.covered_kinds: set = set()

    def service(self):
        if self._svc is None:
            from cyclonus_tpu.audit import AuditController
            from cyclonus_tpu.cli.serve_cmd import synthetic_cluster
            from cyclonus_tpu.serve import VerdictService

            pods, namespaces = synthetic_cluster(8, 2, self.seed)
            self._aud = AuditController(
                rate=0.0, seed=7, digest_rows=4, start_worker=False
            )
            self._svc = VerdictService(
                pods, namespaces, [], audit=self._aud
            )
        return self._svc

    @property
    def audit(self):
        self.service()
        return self._aud

    def digest(self) -> str:
        """The state digest computed HERE, directly from the service's
        authoritative dicts — independent of the audit plane, so the
        rollback leg does not trust the surface under test."""
        from cyclonus_tpu.audit import digest as dg

        svc = self.service()
        return dg.state_digest(dg.canonical_state(
            svc.pods, svc.namespaces, svc.netpols, svc.anps, svc.banp
        ))

    def drain_calls(self) -> List[str]:
        from cyclonus_tpu.serve import stateregistry

        return stateregistry.drain()


# --- scenarios --------------------------------------------------------------


def scenario_field_kind_digests(ctx: Ctx) -> Dict:
    """Every registered field's every delta kind, committed through the
    live service: the state digest must CHANGE, the epoch must advance
    exactly once, the state() payload must reflect the field, and the
    commit must route through the registry's snapshot + audit_state
    helpers (the recorder proves the path is registry-driven, not a
    drifted hand-rolled copy)."""
    from cyclonus_tpu.serve import stateregistry

    svc = ctx.service()
    batches = 0
    for f in stateregistry.FIELDS:
        for kind in f.kinds:
            pre_digest = ctx.digest()
            pre_epoch = svc.epoch
            ctx.drain_calls()
            report = svc.apply([_kind_delta(kind)])
            calls = ctx.drain_calls()
            _check(
                report["applied"] == 1 and not report["rejected"],
                f"digest.{kind}", f"delta rejected: {report}",
            )
            _check(
                ctx.digest() != pre_digest, f"digest.{kind}",
                f"state digest unchanged across a committed {kind} "
                f"(field {f.name!r} lost digest coverage)",
            )
            _check(
                svc.epoch == pre_epoch + 1, f"digest.{kind}",
                f"epoch {pre_epoch} -> {svc.epoch} (want exactly +1)",
            )
            _check(
                "snapshot" in calls and "audit_state" in calls,
                f"digest.{kind}",
                f"commit did not route through the registry helpers "
                f"(recorded {calls})",
            )
            st = svc.state()
            _check(
                f.state_key in st, f"digest.{kind}",
                f"state() payload lost registered key {f.state_key!r}",
            )
            ctx.covered_fields.add(f.name)
            ctx.covered_kinds.add(kind)
            batches += 1
    # the state() exposure is registry-driven end to end: counts match
    # the live dicts for every field
    st = svc.state()
    counts = stateregistry.state_counts(svc)
    for key, want in counts.items():
        _check(
            st[key] == want, "digest.state_counts",
            f"state()[{key!r}] = {st[key]!r} != registry count {want!r}",
        )
    return {"batches": batches}


def scenario_rollback_restores_digest(ctx: Ctx) -> Dict:
    """A fault injected mid-apply — after the authoritative dicts
    mutated, before the engine saw anything — must roll the DIGEST back
    to the pre-batch value via the registry snapshot/restore pair, leave
    the epoch untouched, and let the next clean batch commit."""
    from cyclonus_tpu import chaos
    from cyclonus_tpu.worker.model import Delta

    svc = ctx.service()
    delta = Delta(
        kind="ns_labels", namespace="ns1",
        labels={"ns": "ns1", "team": "chaos"},
    )
    pre_digest = ctx.digest()
    pre_epoch = svc.epoch
    ctx.drain_calls()
    tok = chaos.reset("delta_apply:1")
    try:
        raised = False
        try:
            svc.apply([delta])
        except chaos.ChaosError:
            raised = True
        _check(raised, "rollback", "injected delta_apply fault never fired")
        calls = ctx.drain_calls()
        _check(
            "snapshot" in calls and "restore" in calls, "rollback",
            f"dropped batch did not route through registry "
            f"snapshot/restore (recorded {calls})",
        )
        _check(
            ctx.digest() == pre_digest, "rollback",
            "state digest NOT rolled back to the pre-batch value",
        )
        _check(
            svc.epoch == pre_epoch, "rollback",
            f"epoch advanced through a dropped batch "
            f"({pre_epoch} -> {svc.epoch})",
        )
    finally:
        chaos.disarm(tok)
    report = svc.apply([delta])
    _check(
        report["epoch"] == pre_epoch + 1 and ctx.digest() != pre_digest,
        "rollback", f"post-fault apply did not commit cleanly: {report}",
    )
    return {"faults": 1}


def scenario_epoch_once_per_batch(ctx: Ctx) -> Dict:
    """One committed batch spanning several fields advances the epoch
    exactly once; an all-rejected batch advances it not at all."""
    svc = ctx.service()
    from cyclonus_tpu.worker.model import Delta

    pre = svc.epoch
    report = svc.apply([
        _kind_delta("pod_add"),
        _kind_delta("ns_labels"),
        _kind_delta("policy_upsert"),
    ])
    _check(
        not report["rejected"] and svc.epoch == pre + 1, "epoch.batch",
        f"3-delta batch moved epoch {pre} -> {svc.epoch} "
        f"(rejected={report.get('rejected')}, want exactly +1)",
    )
    pre = svc.epoch
    report = svc.apply([Delta(kind="no_such_kind", namespace="ns0")])
    _check(
        len(report["rejected"]) == 1 and svc.epoch == pre, "epoch.rejected",
        f"rejected batch moved epoch {pre} -> {svc.epoch}: {report}",
    )
    # cleanup so later scenarios see the fixture baseline
    svc.apply([_kind_delta("policy_delete"), _kind_delta("pod_remove")])
    return {"batches": 3}


def scenario_wire_roundtrip(ctx: Ctx) -> Dict:
    """Every registry-declared kind is a wire Delta kind and survives
    to_dict -> from_dict intact, carrying its declared payload key —
    and the registry's kind set IS Delta.KINDS, both ways."""
    from cyclonus_tpu.serve import stateregistry
    from cyclonus_tpu.worker.model import Delta

    _check(
        set(stateregistry.delta_kinds()) == set(Delta.KINDS),
        "wire.census",
        f"registry kinds {sorted(stateregistry.delta_kinds())} != "
        f"wire Delta.KINDS {sorted(Delta.KINDS)}",
    )
    for spec in stateregistry.KINDS:
        d = _kind_delta(spec.kind)
        wire = d.to_dict()
        back = Delta.from_dict(wire)
        _check(
            back == d, f"wire.{spec.kind}",
            f"Delta round-trip mutated the payload: {d} -> {back}",
        )
        if spec.payload:
            _check(
                spec.payload in wire, f"wire.{spec.kind}",
                f"declared payload key {spec.payload!r} absent from the "
                f"wire dict {sorted(wire)}",
            )
        ctx.covered_kinds.add(spec.kind)
    return {"kinds": len(stateregistry.KINDS)}


def scenario_audit_digest_coverage(ctx: Ctx) -> Dict:
    """The audit ring's per-epoch digest must separate states differing
    ONLY in tier objects: an anp_upsert (and a banp_upsert) produces a
    digest unequal to the previous epoch's — the replica-comparison
    coverage the registry's digest_key column declares."""
    svc = ctx.service()
    aud = ctx.audit
    for kind, cleanup in (
        ("anp_upsert", "anp_delete"),
        ("banp_upsert", "banp_delete"),
    ):
        svc.apply([_kind_delta(kind)])
        aud.drain()
        digests = aud.digests()
        epoch = svc.epoch
        _check(
            epoch in digests and (epoch - 1) in digests,
            f"audit.{kind}", f"digest ring missing epochs "
            f"{epoch - 1}/{epoch}: have {sorted(digests)}",
        )
        _check(
            digests[epoch]["digest"] != digests[epoch - 1]["digest"],
            f"audit.{kind}",
            f"epoch digest EQUAL across a committed {kind}: two "
            f"replicas differing only in a tier object would compare "
            f"clean",
        )
        svc.apply([_kind_delta(cleanup)])
    return {"kinds": 2}


def scenario_forgotten_field(ctx: Ctx) -> Dict:
    """The planted forgotten-field fixture, live: each of statelint's
    ST002/ST003 failure modes is demonstrably REAL (the guarded
    surfaces fail loudly where the unguarded ones would silently lose
    coverage) and ST005's (an undeclared kind is rejected, never
    half-applied)."""
    from cyclonus_tpu.audit import digest as dg
    from cyclonus_tpu.serve import stateregistry
    from cyclonus_tpu.worker.model import Delta

    svc = ctx.service()
    # ST002's runtime twin: a snapshot missing a registered field makes
    # restore raise KeyError instead of committing poison.  (Restoring
    # from a just-taken snapshot, so the partial writes are no-ops.)
    snap = stateregistry.snapshot(svc)
    forgotten = dict(snap)
    forgotten.pop("banp")
    raised = False
    try:
        stateregistry.restore(svc, forgotten)
    except KeyError:
        raised = True
    _check(
        raised, "forgotten.restore",
        "restore accepted a snapshot missing a registered field",
    )
    stateregistry.restore(svc, snap)
    # ST003's runtime twin #1: an audit_state dict missing a field makes
    # note_epoch raise TypeError (required keyword-only parameter).
    state = stateregistry.audit_state(svc)
    state.pop("banp")
    raised = False
    try:
        ctx.audit.note_epoch(
            svc.epoch, policy=None, tiers=None, **state
        )
    except TypeError:
        raised = True
    _check(
        raised, "forgotten.note_epoch",
        "note_epoch accepted a snapshot missing a registered field",
    )
    # ST003's runtime twin #2: a canonicalization that DROPS a field
    # digests a BANP change equal — the silent coverage loss itself.
    pre_full = ctx.digest()
    pre_canon = dg.canonical_state(
        svc.pods, svc.namespaces, svc.netpols, svc.anps, svc.banp
    )
    pre_canon.pop("banp")
    pre_partial = dg.state_digest(pre_canon)
    svc.apply([_kind_delta("banp_upsert")])
    post_canon = dg.canonical_state(
        svc.pods, svc.namespaces, svc.netpols, svc.anps, svc.banp
    )
    post_canon.pop("banp")
    _check(
        dg.state_digest(post_canon) == pre_partial, "forgotten.digest",
        "the partial-canonicalization control failed (states differ "
        "beyond the BANP)",
    )
    _check(
        ctx.digest() != pre_full, "forgotten.digest",
        "the full digest missed a BANP change",
    )
    svc.apply([_kind_delta("banp_delete")])
    # ST005's runtime twin: a kind with no declared lifecycle is
    # rejected by the validator's Delta.KINDS membership vet.
    report = svc.apply([Delta(kind="tenant_upsert", namespace="ns0")])
    _check(
        len(report["rejected"]) == 1, "forgotten.kind",
        f"undeclared kind was not rejected: {report}",
    )
    return {"legs": 4}


def scenario_scaled_parity(ctx: Ctx) -> Dict:
    """The slow leg (`make stateharness`): a 48-pod service, every
    registered kind committed in sequence, incremental-vs-rebuild
    parity verified after each batch — the registry-driven commit path
    under realistic churn."""
    from cyclonus_tpu.cli.serve_cmd import synthetic_cluster
    from cyclonus_tpu.serve import VerdictService, stateregistry

    pods, namespaces = synthetic_cluster(48, 4, ctx.seed + 1)
    svc = VerdictService(pods, namespaces, [])
    for spec in stateregistry.KINDS:
        pre = svc.epoch
        report = svc.apply([_kind_delta(spec.kind)])
        _check(
            not report["rejected"] and svc.epoch == pre + 1,
            f"scaled.{spec.kind}", f"batch did not commit: {report}",
        )
        # raises AssertionError on any incremental-vs-rebuild mismatch
        parity = svc.verify_parity(oracle_samples=4)
        _check(
            parity["cells"] > 0, f"scaled.{spec.kind}",
            f"parity sweep checked nothing: {parity}",
        )
    return {"batches": len(stateregistry.KINDS)}


#: (name, fn, in_quick_slice)
SCENARIOS: List[Tuple[str, Callable[[Ctx], Dict], bool]] = [
    ("field_kind_digests", scenario_field_kind_digests, True),
    ("rollback_restores_digest", scenario_rollback_restores_digest, True),
    ("epoch_once_per_batch", scenario_epoch_once_per_batch, True),
    ("wire_roundtrip", scenario_wire_roundtrip, True),
    ("audit_digest_coverage", scenario_audit_digest_coverage, True),
    ("forgotten_field", scenario_forgotten_field, True),
    ("scaled_parity", scenario_scaled_parity, False),
]


def coverage_census(ctx: Ctx) -> Dict:
    """Every registered field and declared kind must have been driven
    through the live service — the acceptance gate ISSUE 19 names."""
    from cyclonus_tpu.serve import stateregistry

    missing_fields = sorted(
        f.name for f in stateregistry.FIELDS
        if f.name not in ctx.covered_fields
    )
    missing_kinds = sorted(
        k.kind for k in stateregistry.KINDS
        if k.kind not in ctx.covered_kinds
    )
    _check(
        not missing_fields and not missing_kinds, "coverage",
        f"registered surface never exercised: fields={missing_fields} "
        f"kinds={missing_kinds}",
    )
    return {
        "fields": len(ctx.covered_fields),
        "kinds": len(ctx.covered_kinds),
    }


def run(
    *,
    quick: bool = True,
    only: Optional[List[str]] = None,
    seed: int = 0,
    log: Optional[Callable[[str], None]] = None,
) -> Dict[str, Dict]:
    """Run the scenario set; raises HarnessFailure on the first
    divergence.  Returns per-scenario stats."""
    ctx = Ctx(seed)
    results: Dict[str, Dict] = {}
    for name, fn, in_quick in SCENARIOS:
        if only is not None:
            if name not in only:
                continue
        elif quick and not in_quick:
            continue
        stats = fn(ctx)
        results[name] = stats
        if log is not None:
            log(f"stateharness {name}: OK {stats}")
    if only is None:
        results["coverage_census"] = coverage_census(ctx)
        if log is not None:
            log(
                f"stateharness coverage_census: OK "
                f"{results['coverage_census']}"
            )
    return results


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--full", action="store_true", help="all scenarios")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--scenarios", nargs="*", default=None,
        help=f"subset (choices: {[n for n, _f, _q in SCENARIOS]})",
    )
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    results = run(
        quick=not args.full,
        only=args.scenarios,
        seed=args.seed,
        log=print if args.verbose else None,
    )
    print(
        f"stateharness: {len(results)} scenario(s) passed "
        f"({', '.join(sorted(results))})",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
